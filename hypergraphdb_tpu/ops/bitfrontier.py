"""Bit-packed BFS frontiers — the 10M-atom-scale traversal engine.

Round-1's dense ``(K, N)`` bool frontiers (``ops/frontier.py``) cannot reach
BASELINE config-4 scale: at K=1024 seeds over N=10M atoms they need 10 GB per
bool array + 41 GB of int32 levels, vs 16 GB HBM on a v5e chip. This module
keeps the same GraphBLAS push-BFS semantics (SimpleALGenerator neighbor rule,
``HGBreadthFirstTraversal.java:49-66``) but stores every per-seed bitmap as
**bit-packed uint32 words** — a 32× cut — and bounds transients:

- persistent state is ``frontier``/``visited`` of shape (K, W) uint32 with
  ``W = ceil((N+1)/32)``: 1.28 GB total at K=1024, N=10M;
- the scatter destination is the only dense bool array, (K_block, M); K is
  processed in ``k_block``-sized blocks so it stays ~1-2 GB;
- edge relations stream through a ``lax.scan`` in ``edge_chunk`` slices, so
  the per-edge gather transient is (K_block, edge_chunk) instead of
  (K_block, E);
- levels, when requested, are int8 (max 127 hops — plenty; the reference's
  ``maxDistance`` defaults are single digits).

Edges touched per seed (the benchmark's edges/s numerator) fall out of the
scatter loop for free: each incidence entry whose source bit is live is
counted as it is gathered — no separate O(K·N) degree pass.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot

WORD = 32


def words_for(nbits: int) -> int:
    """uint32 words needed to hold ``nbits`` bits."""
    return (nbits + WORD - 1) // WORD


# ------------------------------------------------------------------ bit ops


def pack_bits(bits: jax.Array) -> jax.Array:
    """(..., M) bool with M % 32 == 0 → (..., M//32) uint32."""
    *lead, m = bits.shape
    w = m // WORD
    chunks = bits.reshape(*lead, w, WORD).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(WORD, dtype=jnp.uint32)
    )
    return (chunks * weights).sum(axis=-1, dtype=jnp.uint32)


def unpack_bits(packed: jax.Array) -> jax.Array:
    """(..., W) uint32 → (..., W*32) bool."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[..., None] >> shifts) & jnp.uint32(1)
    *lead, w, _ = bits.shape
    return bits.astype(bool).reshape(*lead, w * WORD)


def test_bits(packed: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather bits: packed (..., W) uint32, idx (I,) int32 → (..., I) bool."""
    word = packed[..., idx >> 5]
    shift = (idx & 31).astype(jnp.uint32)
    return ((word >> shift) & jnp.uint32(1)).astype(bool)


def popcount(packed: jax.Array, axis=-1) -> jax.Array:
    """Population count summed along ``axis`` (int32)."""
    return jax.lax.population_count(packed).astype(jnp.int32).sum(axis=axis)


def valid_word_mask(n_valid: int, w: int, offset: int = 0) -> np.ndarray:
    """(w,) uint32 mask with bit j of word i set iff
    ``offset + i*32 + j < n_valid`` — clears the dummy row and pad bits.

    Host-side (numpy) reference for the device mask ``bfs_packed_block``
    builds inline with ``pack_bits`` — kept for host callers/tests; not on
    the traced BFS path (hglint HG103)."""
    ids = offset + np.arange(w * WORD, dtype=np.int64)
    bits = ids < n_valid
    return np.packbits(
        bits.reshape(w, WORD), axis=-1, bitorder="little"
    ).view("<u4").reshape(w)


# ------------------------------------------------------------------ kernels


def _scatter_relation(
    src: jax.Array,       # (C, chunk) int32 — message source ids (global)
    dst: jax.Array,       # (C, chunk) int32 — destination ids (local to dest)
    f_packed: jax.Array,  # (K, W_src) uint32 — source bitmaps
    m_dest: int,          # destination bool width
    count: bool,
    varying_axis: Optional[str] = None,
) -> tuple[jax.Array, jax.Array]:
    """Stream edge chunks: OR source bits into a dense bool destination.

    Returns (packed destination (K, m_dest//32) uint32, per-seed live-edge
    counts (K,) int32 — zeros when ``count`` is False).

    ``varying_axis``: when called inside a ``shard_map`` body over a mesh
    axis, the scan carry accumulates the device-local edge slice, so the
    replicated zero init must be cast to axis-varying.
    """
    K = f_packed.shape[0]

    def body(carry, sd):
        dest, cnt = carry
        s, d = sd
        bit = test_bits(f_packed, s)          # (K, chunk)
        dest = dest.at[:, d].max(bit)
        if count:
            cnt = cnt + bit.sum(axis=1, dtype=jnp.int32)
        return (dest, cnt), None

    init = (
        jnp.zeros((K, m_dest), dtype=bool),
        jnp.zeros((K,), dtype=jnp.int32),
    )
    if varying_axis is not None and hasattr(jax.lax, "pcast"):
        # jax >= 0.6 tracks varying-ness explicitly; 0.4.x shard_map has no
        # varying type system, so a replicated init is accepted as-is
        init = jax.lax.pcast(init, (varying_axis,), to="varying")
    (dest, cnt), _ = jax.lax.scan(body, init, (src, dst))
    return pack_bits(dest), cnt


class PackedBFSResult(NamedTuple):
    visited: jax.Array        # (K, W) uint32 — packed reachable-set bitmaps
    edges_touched: jax.Array  # (K,) int32 — incidence entries with live source
    levels: Optional[jax.Array]  # (K, M) int8 or None — hop distance, -1 unreached


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((8,), "int32")),
    statics={"max_hops": 2, "edge_chunk": 64, "with_levels": False},
)
@partial(
    jax.jit,
    static_argnames=("max_hops", "edge_chunk", "with_levels"),
)
def bfs_packed_block(
    dev: DeviceSnapshot,
    seeds: jax.Array,     # (K,) int32
    max_hops: int,
    edge_chunk: int = 1 << 19,
    with_levels: bool = False,
) -> PackedBFSResult:
    """One seed-block of bit-packed multi-hop BFS, single device.

    The whole loop is one XLA program: per hop, two edge-relation scans
    (atom→link, link→target) each ending in a bit-pack — no host syncs,
    mirroring ``ops.frontier.bfs_levels`` at 1/32 the state footprint.

    ``max_hops`` is capped at 127 so levels fit int8 (the reference's
    ``maxDistance`` is single digits in practice).
    """
    if max_hops > 127:
        raise ValueError("bfs_packed: max_hops > 127 would overflow int8 levels")
    K = seeds.shape[0]
    N = dev.num_atoms
    w = words_for(N + 1)
    m = w * WORD

    def chunked(a):
        e = a.shape[0]
        pad = (-e) % edge_chunk
        if pad:
            a = jnp.concatenate([a, jnp.full((pad,), N, dtype=a.dtype)])
        return a.reshape(-1, edge_chunk)

    inc_src = chunked(dev.inc_src)
    inc_links = chunked(dev.inc_links)
    tgt_src = chunked(dev.tgt_src)
    tgt_flat = chunked(dev.tgt_flat)

    # clears dummy slot N + pad bits; built with jnp so tracing stays free
    # of host numpy work (hglint HG103) — XLA folds it to a constant
    valid = pack_bits(jnp.arange(m, dtype=jnp.int32) < N)

    frontier = jnp.zeros((K, w), dtype=jnp.uint32)
    bitv = jnp.left_shift(jnp.uint32(1), (seeds & 31).astype(jnp.uint32))
    frontier = frontier.at[jnp.arange(K), seeds >> 5].max(bitv)
    visited = frontier
    if with_levels:
        levels = jnp.where(unpack_bits(frontier), 0, -1).astype(jnp.int8)
    else:
        levels = jnp.zeros((), dtype=jnp.int8)

    def body(i, state):
        frontier, visited, counts, levels = state
        link_packed, c = _scatter_relation(
            inc_src, inc_links, frontier, m, count=True
        )
        nbr_packed, _ = _scatter_relation(
            tgt_src, tgt_flat, link_packed, m, count=False
        )
        nxt = nbr_packed & valid & ~visited
        if with_levels:
            levels = jnp.where(
                unpack_bits(nxt), (i + 1).astype(jnp.int8), levels
            )
        return nxt, visited | nxt, counts + c, levels

    frontier, visited, counts, levels = jax.lax.fori_loop(
        0, max_hops, body,
        (frontier, visited, jnp.zeros((K,), dtype=jnp.int32), levels),
    )
    return PackedBFSResult(
        visited, counts, levels if with_levels else None
    )


# ------------------------------------------------------------------ host API


def bfs_packed(
    snap: CSRSnapshot,
    seeds: np.ndarray,
    max_hops: int,
    k_block: int = 256,
    edge_chunk: int = 1 << 19,
    with_levels: bool = False,
) -> tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Blocked driver: K seeds in ``k_block`` slices so the dense scatter
    transient stays ~``k_block × N`` bytes regardless of K.

    Returns (visited_packed (K, W) uint32, edges_touched (K,) int64,
    levels (K, N+1) int8 or None).
    """
    dev = snap.device
    seeds = np.asarray(seeds, dtype=np.int32)
    K = len(seeds)
    vis_out, cnt_out, lev_out = [], [], []
    for s in range(0, K, k_block):
        block = seeds[s : s + k_block]
        pad = k_block - len(block)
        if pad:
            block = np.concatenate([block, np.zeros(pad, dtype=np.int32)])
        res = bfs_packed_block(
            dev, jnp.asarray(block), max_hops,
            edge_chunk=edge_chunk, with_levels=with_levels,
        )
        take = k_block - pad
        vis_out.append(np.asarray(res.visited)[:take])
        cnt_out.append(np.asarray(res.edges_touched)[:take])
        if with_levels:
            lev_out.append(np.asarray(res.levels)[:take])
    visited = np.concatenate(vis_out)
    counts = np.concatenate(cnt_out).astype(np.int64)
    levels = (
        np.concatenate(lev_out)[:, : snap.num_atoms + 1]
        if with_levels else None
    )
    return visited, counts, levels


def unpack_visited(visited_packed: np.ndarray, n: int) -> np.ndarray:
    """(K, W) uint32 → (K, n) bool on host (numpy, no device round-trip)."""
    bits = np.unpackbits(
        visited_packed.view(np.uint8).reshape(len(visited_packed), -1),
        axis=1, bitorder="little",
    )
    return bits[:, :n].astype(bool)


# ------------------------------------------------------------------ planning


def bfs_memory_bytes(
    n_atoms: int,
    e_inc: int,
    e_tgt: int,
    k_block: int = 256,
    n_dev: int = 1,
    edge_chunk: int = 1 << 19,
    with_levels: bool = False,
) -> dict:
    """Per-device HBM budget of the packed BFS at a given scale — the
    planning contract VERDICT r1 asked for (config-4 must fit under a v5e
    chip's 16 GB). Pure arithmetic; a unit test pins the config-4 numbers."""
    w_full = words_for(n_atoms + 1)
    n_loc = -(-(n_atoms + 1) // (n_dev * 128)) * 128
    w_loc = n_loc // WORD if n_dev > 1 else w_full
    m_loc = n_loc if n_dev > 1 else w_full * WORD
    state = 3 * k_block * w_loc * 4            # frontier, visited, next (packed)
    gathered = 2 * k_block * w_full * 4        # all-gathered packed bitmaps
    scatter_dest = k_block * m_loc             # dense bool destination
    edge_transient = k_block * edge_chunk * 5  # gathered words + bool bits
    edges = (e_inc + e_tgt) * 2 * 4 // n_dev   # COO src+dst per relation
    atoms = (n_atoms // n_dev) * (4 * 3 + 1 + 8)  # type/arity/offsets,flag,rank
    levels = k_block * m_loc if with_levels else 0
    total = (
        state + gathered + scatter_dest + edge_transient + edges + atoms
        + levels
    )
    return {
        "state": state, "gathered": gathered, "scatter_dest": scatter_dest,
        "edge_transient": edge_transient, "edges": edges, "atoms": atoms,
        "levels": levels, "total": total,
    }
