"""Device plane: CSR snapshots, frontier/set kernels, incremental overlays,
Pallas kernels, and snapshot checkpointing (SURVEY §7 device design)."""

from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot
from hypergraphdb_tpu.ops.frontier import bfs_levels, expand_frontier
from hypergraphdb_tpu.ops.bitfrontier import (
    bfs_memory_bytes,
    bfs_packed,
    unpack_visited,
)
from hypergraphdb_tpu.ops.ellbfs import PullBFSResult, bfs_pull, visited_rows
from hypergraphdb_tpu.ops.incremental import (
    PinnedView,
    SnapshotManager,
    bfs_levels_delta,
)
from hypergraphdb_tpu.ops.aot_cache import AOTCache
from hypergraphdb_tpu.ops.pallas_bfs import bfs_pull_fused, pallas_bfs_ok
from hypergraphdb_tpu.ops.serving import (
    bfs_serve_batch,
    bfs_serve_batch_fused,
    pattern_serve_batch,
)
from hypergraphdb_tpu.ops.setops import (
    and_incident_pattern,
    collect_pattern,
    execute_pattern,
    plan_pattern,
)
from hypergraphdb_tpu.ops.checkpoint import (
    copy_subgraph,
    export_graph,
    import_graph,
    load_snapshot,
    save_snapshot,
)

__all__ = [
    "AOTCache",
    "CSRSnapshot",
    "DeviceSnapshot",
    "PinnedView",
    "PullBFSResult",
    "SnapshotManager",
    "bfs_pull_fused",
    "bfs_serve_batch",
    "bfs_serve_batch_fused",
    "pallas_bfs_ok",
    "pattern_serve_batch",
    "and_incident_pattern",
    "bfs_levels",
    "bfs_pull",
    "collect_pattern",
    "execute_pattern",
    "plan_pattern",
    "visited_rows",
    "bfs_memory_bytes",
    "bfs_packed",
    "unpack_visited",
    "bfs_levels_delta",
    "copy_subgraph",
    "expand_frontier",
    "export_graph",
    "import_graph",
    "load_snapshot",
    "save_snapshot",
]
