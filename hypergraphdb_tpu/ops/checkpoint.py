"""Checkpoint / export: device-snapshot persistence and logical graph dumps.

SURVEY §5 "Checkpoint / resume": the reference's durability is transactional
storage + BDB checkpoints, and its logical transfer format is the subgraph
stream (``storage/HGStoreSubgraph.java``, ``peer/SubgraphManager.java:57``).
Here:

- :func:`save_snapshot` / :func:`load_snapshot` — persist a packed CSR
  snapshot as one compressed ``.npz`` (the orbax-style device-array
  checkpoint: reload and serve queries without re-packing the store);
- :func:`export_graph` / :func:`import_graph` — the logical dump: every
  atom as (type name, value bytes, targets), streaming JSONL. Imports
  translate handles, so it doubles as the subgraph-transfer format;
- :func:`copy_subgraph` — ``CopyGraphTraversal`` analogue: copy the
  reachable closure of root atoms into another graph.
"""

from __future__ import annotations

import base64
import json
import logging
import os
from typing import Optional, Sequence

import numpy as np

from hypergraphdb_tpu.fault import global_faults
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

#: process fault registry, bound once (singleton contract — the enabled
#: gate is ONE attribute read per crash point)
_FAULTS = global_faults()

_log = logging.getLogger("hypergraphdb_tpu.ops.checkpoint")


# ------------------------------------------------------------- device snapshot


def _npz_path(path: str) -> str:
    # np.savez appends ".npz" when missing but np.load does not — normalize
    return path if path.endswith(".npz") else path + ".npz"


def _plans_path(path: str) -> str:
    return _npz_path(path)[:-4] + ".plans.npz"


def _atomic_write(path: str, writer, crash_point: str) -> None:
    """Crash-atomic publish: write a same-directory tmp, fsync, then
    ``os.replace`` — a death at ANY instant (including the registered
    ``crash_point`` between write and publish, which the recovery drill
    arms with :class:`~hypergraphdb_tpu.fault.InjectedCrash`) leaves
    either the old complete file or the new complete file on disk, never
    a torn one. An ordinary write failure cleans the tmp up; a simulated
    crash (``BaseException``) leaves it behind exactly like a real kill
    would — loaders never look at ``*.tmp``, and the next save overwrites
    it."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        if _FAULTS.enabled:
            _FAULTS.check(crash_point, path=path)
        os.replace(tmp, path)
    except Exception:
        # ordinary failure: clean up. A simulated kill (InjectedCrash is
        # a BaseException) skips this on purpose — a real crash leaves
        # its tmp behind too, and loaders never read *.tmp
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_snapshot(snap: CSRSnapshot, path: str,
                  with_plans: bool = False) -> None:
    """Persist the CSR arrays; ``with_plans=True`` additionally writes the
    pull-BFS plan pyramid next to the npz (``<path>.plans.npz``), so a
    reopened session skips the plan rebuild (the reference never rebuilds
    its indexes on open either — ``HGStore.java:282``).

    Both files publish crash-atomically (tmp + ``os.replace``): a save
    that dies mid-write leaves the PREVIOUS checkpoint fully loadable.
    The npz replaces first, the sidecar second — a crash between the two
    leaves a fingerprint-mismatched sidecar, which the loader treats as
    absent (quiet plan rebuild), so every interleaving is safe."""
    by_type_keys = np.asarray(sorted(snap.by_type), dtype=np.int64)
    arrays = {
        "version": np.asarray([snap.version], dtype=np.int64),
        "num_atoms": np.asarray([snap.num_atoms], dtype=np.int64),
        "n_edges": np.asarray([snap.n_edges_inc, snap.n_edges_tgt],
                              dtype=np.int64),
        "inc_offsets": snap.inc_offsets,
        "inc_links": snap.inc_links,
        "inc_src": snap.inc_src,
        "tgt_offsets": snap.tgt_offsets,
        "tgt_flat": snap.tgt_flat,
        "tgt_src": snap.tgt_src,
        "type_of": snap.type_of,
        "is_link": snap.is_link,
        "arity": snap.arity,
        "value_rank": snap.value_rank,
        "value_kind": snap.value_kind,
        "by_type_keys": by_type_keys,
    }
    for k in by_type_keys.tolist():
        arrays[f"bt_{k}"] = snap.by_type[int(k)]
    _atomic_write(
        _npz_path(path),
        lambda f: np.savez_compressed(f, **arrays),
        "ckpt.save_npz",
    )
    pp = _plans_path(path)
    if with_plans:
        from hypergraphdb_tpu.ops.ellbfs import (
            plans_for, save_plans, snapshot_fingerprint)

        plans = plans_for(snap)
        fp = snapshot_fingerprint(snap)
        _atomic_write(
            pp,
            lambda f: save_plans(plans, f, fingerprint=fp),
            "ckpt.save_plans",
        )
    elif os.path.exists(pp):
        # overwriting a snapshot without plans must not leave a stale
        # sidecar behind for the loader to pick up (a crash between the
        # npz replace and this remove leaves a fingerprint-mismatched
        # sidecar — treated as absent on load)
        os.remove(pp)


def load_snapshot(path: str) -> CSRSnapshot:
    """Restore a snapshot; a sibling ``.plans.npz`` (see
    :func:`save_snapshot`) is attached so ``plans_for`` is a no-op.

    Sidecar triage: a STALE sidecar (well-formed, wrong fingerprint or
    plan format — the overwrite-without-plans / crash-between-replaces
    shapes) rebuilds quietly by design; a CORRUPT/unreadable sidecar is a
    real fault — logged, counted (``fault.sidecar_corrupt``), and then
    rebuilt the same way (plans are derived data; the snapshot itself is
    intact)."""
    with np.load(_npz_path(path)) as z:
        snap = _snapshot_from_npz(z)
    pp = _plans_path(path)
    if os.path.exists(pp):
        from hypergraphdb_tpu.ops.ellbfs import (
            StalePlans, load_plans, snapshot_fingerprint)

        try:
            plans = load_plans(
                pp, expect_fingerprint=snapshot_fingerprint(snap)
            )
            object.__setattr__(snap, "_pull_plans", plans)
        except StalePlans:
            pass  # another snapshot's plans (by design) → plans_for rebuilds
        except Exception:
            from hypergraphdb_tpu.obs.flight import global_flight
            from hypergraphdb_tpu.utils.metrics import global_metrics

            _log.warning(
                "checkpoint sidecar %s is corrupt/unreadable; plans will "
                "be rebuilt", pp, exc_info=True,
            )
            global_metrics.incr("fault.sidecar_corrupt")
            # a corrupt sidecar on reopen is the durable trace of a
            # crash/bit-rot — incident: dump what this process saw
            global_flight().incident("sidecar_corrupt", path=str(pp))
    return snap


def _snapshot_from_npz(z) -> CSRSnapshot:
    by_type = {
        int(k): z[f"bt_{int(k)}"] for k in z["by_type_keys"].tolist()
    }
    return CSRSnapshot(
        version=int(z["version"][0]),
        num_atoms=int(z["num_atoms"][0]),
        inc_offsets=z["inc_offsets"],
        inc_links=z["inc_links"],
        inc_src=z["inc_src"],
        tgt_offsets=z["tgt_offsets"],
        tgt_flat=z["tgt_flat"],
        tgt_src=z["tgt_src"],
        type_of=z["type_of"],
        is_link=z["is_link"],
        arity=z["arity"],
        value_rank=z["value_rank"],
        # absent in pre-r4 checkpoints: default to zeros (kind "unknown")
        value_kind=(
            z["value_kind"] if "value_kind" in z
            else np.zeros(len(z["value_rank"]), dtype=np.uint8)
        ),
        by_type=by_type,
        n_edges_inc=int(z["n_edges"][0]),
        n_edges_tgt=int(z["n_edges"][1]),
    )


# ------------------------------------------------------------- logical dumps


def _atom_record(graph, h: int) -> Optional[dict]:
    rec = graph.store.get_link(h)
    if rec is None or len(rec) < 3:
        return None
    type_handle, value_handle, flags = rec[0], rec[1], rec[2]
    try:
        # get_type (not name_of) so persisted-but-unregistered type atoms
        # recover via the reopen path instead of silently dropping atoms
        type_name = graph.typesystem.get_type(type_handle).name
    except Exception:
        return None
    data = graph.store.get_data(value_handle) if value_handle >= 0 else None
    return {
        "h": int(h),
        "type": type_name,
        "v": base64.b64encode(data).decode("ascii") if data is not None else None,
        "link": bool(flags & 1),
        "t": [int(t) for t in rec[3:]],
    }


def export_graph(graph, path: str) -> int:
    """Stream every atom (handle order — targets precede their links) to a
    JSONL file. Returns the number of atoms exported."""
    n = 0
    with open(path, "w", encoding="utf-8") as f:
        for h in graph.atoms():
            w = _atom_record(graph, int(h))
            if w is None:
                continue
            f.write(json.dumps(w) + "\n")
            n += 1
    return n


def _import_record(graph, w: dict, mapping: dict[int, int]) -> Optional[int]:
    # type atoms are re-created by the destination's own bootstrap; remap
    if w["type"] == "top":
        if w["v"] is not None:
            name = graph.typesystem.top.make(base64.b64decode(w["v"]))
            try:
                mapping[w["h"]] = int(graph.typesystem.handle_of(name))
            except Exception:  # hglint: disable=HG1005
                pass  # type not registered at the destination; links to it
                # (rare) will fail loudly at the mapping lookup
        return None
    atype = graph.typesystem.get_type(w["type"])
    value = atype.make(base64.b64decode(w["v"])) if w["v"] is not None else None
    try:
        targets = [mapping[t] for t in w["t"]]
    except KeyError as e:
        raise KeyError(
            f"import of atom {w['h']} references target {e.args[0]} that "
            "was not importable (its type is unknown here?)"
        ) from e
    if w["link"]:
        nh = graph.add_link(targets, value=value, type=w["type"])
    else:
        nh = graph.add_node(value, type=w["type"])
    mapping[w["h"]] = int(nh)
    return int(nh)


def import_graph(graph, path: str) -> dict[int, int]:
    """Load a JSONL dump; returns the old-handle → new-handle mapping.

    The whole import runs in ONE transaction: a mid-import failure (bad
    record, unknown type, unresolvable target) rolls back every atom added
    so far instead of leaving a partially imported graph (ADVICE r2)."""
    mapping: dict[int, int] = {}

    def run() -> None:
        mapping.clear()  # retry-safe
        with open(path, encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    _import_record(graph, json.loads(line), mapping)

    graph.txman.transact(run)
    return mapping


def copy_subgraph(src, dst, roots: Sequence[int],
                  max_distance: Optional[int] = None) -> dict[int, int]:
    """Copy the traversal closure of ``roots`` from ``src`` into ``dst``
    (``CopyGraphTraversal.java:27`` semantics): every reached atom plus the
    target closure needed to rebuild its links. Returns handle mapping."""
    from hypergraphdb_tpu.algorithms.traversals import HGBreadthFirstTraversal

    wanted: set[int] = set(int(r) for r in roots)
    for r in roots:
        for link, a in HGBreadthFirstTraversal(src, int(r),
                                               max_distance=max_distance):
            wanted.add(int(a))
            if link is not None:
                wanted.add(int(link))  # the connecting links travel too
    # expand to the full target closure so links never dangle
    frontier = list(wanted)
    while frontier:
        h = frontier.pop()
        rec = src.store.get_link(h)
        if rec is None:
            continue
        for t in rec[3:]:
            if int(t) not in wanted:
                wanted.add(int(t))
                frontier.append(int(t))
    mapping: dict[int, int] = {}
    for h in sorted(wanted):  # ascending: targets precede links
        w = _atom_record(src, h)
        if w is not None:
            _import_record(dst, w, mapping)
    return mapping
