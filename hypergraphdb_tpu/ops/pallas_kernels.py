"""Pallas TPU kernels for the sorted-set hot ops.

The n-way intersection (``ZigZagIntersectionResult.java:37-75``) is served
by XLA ``searchsorted`` in ``ops/setops.py``. Binary search is log-depth
gather traffic, which the VPU dislikes; for the row sizes a hypergraph
produces (incidence rows up to a few thousand entries) a **brute-force
tiled compare** is faster on TPU: every base element is compared against
every element of the other sets in (8,128)-shaped VMEM tiles — pure
vector compares, zero gathers, perfectly lane-aligned.

``membership_mask_pallas(base (Lb,), others (M, Lo)) -> int32 (Lb,)``
computes ``base[i] ∈ others[j]  ∀j`` — the n-way AND-membership at the
heart of ``And(incident, incident, ...)``. Complexity O(Lb·M·Lo) compares
vs O(Lb·M·log Lo) for binary search; the crossover favors this kernel
while rows fit VMEM (guarded by ``fits_vmem``).

CPU tests run the same kernel in interpreter mode; ``setops.
device_intersect_sorted`` auto-picks it on TPU when shapes qualify.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hypergraphdb_tpu.ops.setops import _bucket

SENTINEL = np.int32(np.iinfo(np.int32).max)

#: base tile: 8 sublanes × 128 lanes of int32
_TILE_ROWS = 8
_LANES = 128
_TILE = _TILE_ROWS * _LANES


def _kernel(base_ref, other_ref, out_ref, cur_ref):
    """Grid = (base tiles i, other sets j, lane chunks k), k fastest.

    ``cur_ref`` (VMEM scratch, persistent across sequential grid steps)
    accumulates "found in other j" over its chunks; at each j's last chunk
    it ANDs into the output tile. Mosaic dislikes dynamic unaligned row
    loads, so the (j, k) iteration lives in the grid — every load is a
    statically-shaped aligned block."""
    j = pl.program_id(1)
    k = pl.program_id(2)
    nk = pl.num_programs(2)
    b = base_ref[:]  # (8, 128) int32

    @pl.when(k == 0)
    def _():
        cur_ref[:] = jnp.zeros_like(cur_ref)

    c = other_ref[0, 0, :]  # (128,) chunk of other set j
    eq = jnp.any(b[:, :, None] == c[None, None, :], axis=-1)
    cur_ref[:] = cur_ref[:] | eq.astype(jnp.int32)

    @pl.when(k == nk - 1)
    def _():
        found = cur_ref[:]

        @pl.when(j == 0)
        def _():
            out_ref[:] = (b != SENTINEL).astype(jnp.int32) & found

        @pl.when(j > 0)
        def _():
            out_ref[:] = out_ref[:] & found


@functools.partial(jax.jit, static_argnames=("interpret",))
def _membership_call(base2d: jax.Array, others: jax.Array,
                     interpret: bool = False) -> jax.Array:
    rows = base2d.shape[0]
    m, lo = others.shape
    nk = lo // _LANES
    grid = (rows // _TILE_ROWS, m, nk)
    # chunk-per-row 3D view: block (1, 1, 128) satisfies the TPU block
    # constraint because the middle dim is the FULL array dim
    others3d = others.reshape(m * nk, 1, _LANES)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_ROWS, _LANES), lambda i, j, k: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, _LANES), lambda i, j, k: (j * nk + k, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_ROWS, _LANES), lambda i, j, k: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(base2d.shape, jnp.int32),
        scratch_shapes=[pltpu.VMEM((_TILE_ROWS, _LANES), jnp.int32)],
        interpret=interpret,
    )(base2d, others3d)


def fits_vmem(lb: int, m: int, lo: int, budget_bytes: int = 8 << 20) -> bool:
    """Conservative VMEM guard: others + one base tile must fit."""
    return (m * lo + _TILE) * 4 <= budget_bytes


def membership_mask_pallas(
    base: jax.Array, others: jax.Array, interpret: bool = False
) -> jax.Array:
    """``mask[i] = base[i] ∈ others[j] for every j`` (SENTINEL-aware).

    ``base`` (Lb,) and ``others`` (M, Lo) are SENTINEL-padded sorted int32;
    Lb and Lo are padded up to tile multiples here. Returns bool (Lb,).
    """
    lb = base.shape[0]
    # power-of-two buckets on BOTH dims: bounds the number of distinct
    # kernel shapes (each distinct shape is a fresh Mosaic compile)
    lb_pad = _bucket(lb, minimum=_TILE) - lb
    if lb_pad:
        base = jnp.concatenate(
            [base, jnp.full((lb_pad,), SENTINEL, dtype=base.dtype)]
        )
    lo_pad = _bucket(others.shape[1], minimum=_LANES) - others.shape[1]
    if lo_pad:
        others = jnp.concatenate(
            [others,
             jnp.full((others.shape[0], lo_pad), SENTINEL, others.dtype)],
            axis=1,
        )
    base2d = base.reshape(-1, _LANES)
    out = _membership_call(base2d, others, interpret=interpret)
    return out.reshape(-1)[:lb] > 0


def intersect_sorted_pallas(arrays, interpret: bool = False) -> np.ndarray:
    """n-way sorted intersection via the membership kernel; same contract
    as ``setops.device_intersect_sorted`` (host int64 arrays in/out)."""
    arrays = sorted(arrays, key=len)
    base = np.asarray(arrays[0], dtype=np.int32)
    others_list = arrays[1:]
    if not others_list:
        return np.asarray(arrays[0], dtype=np.int64)
    lo = _bucket(max((len(a) for a in others_list), default=1),
                 minimum=_LANES)
    others = np.full((len(others_list), lo), SENTINEL, dtype=np.int32)
    for i, a in enumerate(others_list):
        others[i, : len(a)] = a
    mask = membership_mask_pallas(
        jnp.asarray(base), jnp.asarray(others), interpret=interpret
    )
    return base[np.asarray(mask)].astype(np.int64)
