"""Batched serving entry points: fixed-shape micro-batch kernels.

The serving runtime (``hypergraphdb_tpu/serve``) coalesces independent
caller requests into shape-bucketed device batches. These are the two
kernels it dispatches — both return **compact** per-request results
(counts + the first ``top_r`` matches) so the host link carries
O(K · top_r) per batch instead of O(K · N):

- :func:`bfs_serve_batch` — K-seed BFS over the incremental
  (base ∪ delta) pair (``ops/incremental.bfs_levels_delta`` semantics),
  compacted on device to per-seed reach counts + the ``top_r`` smallest
  reached atom ids.
- :func:`pattern_serve_batch` — K conjunctive incident patterns
  (``And(Incident(a), Incident(b), ..., [AtomType])``) via the hub-proof
  ELL intersection (``ops/setops.incident_intersection_ell``), with a
  PER-REQUEST type filter (``type_vec`` lane < 0 = no type constraint) so
  one compiled program serves typed and untyped queries in the same
  micro-batch — a scalar ``type_handle`` would force one batch group per
  type and starve coalescing.

Both kernels tolerate padding lanes natively: pad BFS seeds with the
dummy row id (``dev.num_atoms`` — reaches nothing), pad pattern anchors
with the dummy row (empty incidence — zero candidates). Pad-lane outputs
are well-defined garbage the runtime discards by lane index.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops import pallas_bfs as _pbfs
from hypergraphdb_tpu.ops.incremental import DeviceDelta, bfs_levels_delta
from hypergraphdb_tpu.ops.setops import SENTINEL, incident_intersection_ell
from hypergraphdb_tpu.ops.snapshot import DeviceSnapshot

#: ``type_vec`` lane value meaning "no type constraint for this request"
NO_TYPE = -1


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.device_delta_exemplar(),
                    hgverify.sds((8,), "int32")),
    statics={"max_hops": 2, "top_r": 4},
)
@partial(jax.jit, static_argnames=("max_hops", "top_r"))
def bfs_serve_batch(
    dev: DeviceSnapshot,
    delta: DeviceDelta,
    seeds: jax.Array,   # (K,) int32 — pad lanes carry dev.num_atoms
    max_hops: int,
    top_r: int,
) -> tuple[jax.Array, jax.Array]:
    """K-seed BFS over base ∪ delta with on-device result compaction.

    Returns ``(counts (K,) int32, first_r (K, top_r) int32)``: per-seed
    |visited| (INCLUDING the live seed — ``ops/ellbfs`` reach-count
    convention) and the ``top_r`` smallest reached atom ids in ascending
    order, SENTINEL-padded past the count. A request whose full result set
    exceeds ``top_r`` is flagged truncated by the runtime
    (``counts > top_r``)."""
    _, visited = bfs_levels_delta(
        dev, delta, seeds, max_hops, with_levels=False
    )
    counts = visited.sum(axis=1).astype(jnp.int32)
    n1 = dev.type_of.shape[0]
    ids = jnp.arange(n1, dtype=jnp.int32)
    masked = jnp.where(visited, ids[None, :], SENTINEL)
    # top_k of the negation = the top_r SMALLEST reached ids; re-negating
    # flips the descending sort back to ascending
    first_r = -jax.lax.top_k(-masked, top_r)[0]
    return counts, first_r


@hgverify.entry(
    shapes=_pbfs.exemplar_shapes,
    statics={
        "geom": _pbfs.EXEMPLAR_GEOM,
        "kwp": 128, "max_hops": 2, "top_r": 4, "interpret": True,
    },
)
@partial(jax.jit, static_argnames=(
    "geom", "kwp", "max_hops", "top_r", "widths1", "widths2", "interpret",
))
def bfs_serve_batch_fused(
    fused: "_pbfs.DeviceFusedPlan",
    seeds: jax.Array,          # (K,) int32 — pad lanes carry n_atoms
    n_atoms: jax.Array,        # scalar int32
    overlay: "_pbfs.OverlayArrays" = None,
    *,
    geom: "_pbfs.FusedGeom",
    kwp: int,
    max_hops: int,
    top_r: int,
    widths1: tuple = None,
    widths2: tuple = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """The fused-kernel twin of :func:`bfs_serve_batch`: same
    ``(counts, first_r)`` contract, computed from the transposed-bitmap
    Pallas hop chain (``ops/pallas_bfs``) instead of the dense
    ``bfs_levels_delta`` sweep. Delta-added edges ride the ``overlay``
    pull plan (host-built per delta refresh); tombstones do NOT — the
    executor declines to route here while any tombstone is pending
    (composed fused adjacency cannot neutralize a dead link). Pad lanes
    keep their dummy-row seed bit, matching the dense path's
    well-defined-garbage contract lane for lane."""
    visited, _, reach = _pbfs._bfs_fused(
        fused, seeds, n_atoms, geom, kwp, max_hops,
        count_edges=False, clear_dummy=False, overlay=overlay,
        widths1=widths1, widths2=widths2, interpret=interpret,
    )
    K = seeds.shape[0]
    counts = reach[:K]
    first_r = _pbfs.first_r_from_bitmap(visited, n_atoms + 1, top_r, K)
    return counts, first_r


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((32, 4), "int32"),
                    hgverify.sds((4, 2), "int32"),
                    hgverify.sds((4,), "int32")),
    statics={"pad_len": 8, "top_r": 4},
)
@partial(jax.jit, static_argnames=("pad_len", "top_r"))
def pattern_serve_batch(
    dev: DeviceSnapshot,
    tgt_ell: jax.Array,   # (N+1, W) int32 ELL targets (ops/setops.ell_targets)
    anchors: jax.Array,   # (K, P) int32 — anchors[:, 0] has the SMALLEST row
    type_vec: jax.Array,  # (K,) int32 — per-request type handle, NO_TYPE = any
    pad_len: int,
    top_r: int,
) -> tuple[jax.Array, jax.Array]:
    """K conjunctive incident patterns with per-request type filters.

    Returns ``(counts (K,) int32, first_r (K, top_r) int32)``: per-query
    survivor count and the first ``top_r`` matching link ids ascending,
    SENTINEL-padded. Links live in the BASE snapshot only — the serving
    runtime merges the delta memtable host-side (the LSM read-correction of
    ``query/compiler.DeviceValueConjPlan``)."""
    rows0, mask = incident_intersection_ell(
        dev, tgt_ell, anchors, pad_len, None
    )
    safe = jnp.where(rows0 == SENTINEL, 0, rows0)
    want = type_vec[:, None]
    mask = mask & ((want < 0) | (dev.type_of[safe] == want))
    counts = mask.sum(axis=1).astype(jnp.int32)
    ranked = jnp.where(mask, rows0, SENTINEL)
    first_r = jax.lax.sort(ranked, dimension=1)[:, :top_r]
    return counts, first_r
