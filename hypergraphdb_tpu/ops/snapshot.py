"""CSRSnapshot — the immutable device-resident image of the hypergraph.

The central TPU-native idea (SURVEY §7 design stance): the mutable,
transactional store lives on host; queries and traversals run against an
**immutable CSR snapshot in HBM**. A snapshot is a long-lived read
transaction — MVCC maps onto versioned snapshots instead of pointer-chased
B-trees (the reference reads incidence sets through BDB cursors,
``BJEStorageImplementation.java:307``; here they are two flat gather-friendly
arrays).

Layout (all int32, padded to lane multiples, ``N = id_space`` = one past the
largest atom handle, with one extra dummy row ``N`` used as scatter/gather
dump for padding):

- ``inc_offsets[N+2]``, ``inc_links[E_inc]`` — incidence CSR: links pointing
  at each atom (sorted per row).
- ``inc_src[E_inc]`` — row id per entry (the "COO expansion" that makes the
  whole incidence relation one scatter op).
- ``tgt_offsets[N+2]``, ``tgt_flat[E_tgt]``, ``tgt_src[E_tgt]`` — target CSR:
  the ordered target tuple of each link atom.
- ``type_of[N+1]`` — type handle per atom (-1 for dead ids).
- ``is_link[N+1]`` — link flag per atom.
- ``arity[N+1]`` — target count per atom.
- ``value_rank[N+1]`` (uint64) — order-preserving 64-bit rank of each atom's
  value key PAYLOAD (``utils/ordered_bytes.rank64`` over the key minus its
  kind byte), enabling device-side value comparisons without host payloads
  (SURVEY §7 hard part 3). For fixed-width kinds (int/float/bool/time the
  payload is ≤ 8 bytes) the rank is EXACT — device eq/range filters need no
  host verification; variable-width kinds (str/bytes) tie on rank equality.
- ``value_kind[N+1]`` (uint8) — the kind byte of each atom's value key, so
  rank comparisons never cross kinds (ranks of different kinds are
  incomparable once the kind prefix is stripped).
- ``by_type``: type handle → sorted array of atom ids (the device form of
  the by-type system index).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property
from typing import Optional

import numpy as np

from hypergraphdb_tpu.utils.ordered_bytes import rank64, rank_ambiguous

#: sentinel for padded entries in id arrays
PAD = np.int32(-1)


def _register_device_snapshot_pytree() -> None:
    """Register DeviceSnapshot as a jax pytree so jitted kernels can take it
    directly, regardless of which ops module is imported first."""
    import jax

    jax.tree_util.register_pytree_node(
        DeviceSnapshot,
        lambda s: (
            (
                s.inc_offsets, s.inc_links, s.inc_src,
                s.tgt_offsets, s.tgt_flat, s.tgt_src,
                s.type_of, s.is_link, s.arity,
                s.value_rank_hi, s.value_rank_lo, s.value_kind,
            ),
            s.num_atoms,
        ),
        lambda aux, ch: DeviceSnapshot(aux, *ch),
    )


def _pad_to(arr: np.ndarray, multiple: int, fill) -> np.ndarray:
    n = len(arr)
    m = ((n + multiple - 1) // multiple) * multiple if n else multiple
    if m == n:
        return arr
    out = np.full(m, fill, dtype=arr.dtype)
    out[:n] = arr
    return out


def _group_by_type(type_of_n: np.ndarray) -> dict[int, np.ndarray]:
    """type handle → sorted array of atom ids (device by-type index form)."""
    by_type: dict[int, np.ndarray] = {}
    live = type_of_n >= 0
    if live.any():
        th_arr = type_of_n[live]
        id_arr = np.nonzero(live)[0].astype(np.int32)
        order = np.lexsort((id_arr, th_arr))
        th_sorted, id_sorted = th_arr[order], id_arr[order]
        uniq, starts = np.unique(th_sorted, return_index=True)
        bounds = np.append(starts, len(th_sorted))
        for i, t in enumerate(uniq.tolist()):
            by_type[int(t)] = id_sorted[bounds[i] : bounds[i + 1]].copy()
    return by_type


def _incidence_transpose(
    tgt_src: np.ndarray, tgt_flat: np.ndarray, N: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Incidence CSR derived as the TRANSPOSE of the target relation: entry
    (t ← l) for every (l → t) edge, deduped, each row sorted by link id.
    Returns (inc_offsets (N+2,) int32, inc_links, inc_src)."""
    if len(tgt_flat):
        pair_order = np.lexsort((tgt_src, tgt_flat))
        pt = tgt_flat[pair_order].astype(np.int64)
        pl = tgt_src[pair_order].astype(np.int64)
        keep = np.ones(len(pt), dtype=bool)
        keep[1:] = (pt[1:] != pt[:-1]) | (pl[1:] != pl[:-1])
        pt, pl = pt[keep], pl[keep]
    else:
        pt = pl = np.empty(0, dtype=np.int64)
    inc_counts = np.bincount(pt, minlength=N + 1)
    inc_offsets = np.zeros(N + 2, dtype=np.int32)
    np.cumsum(inc_counts, out=inc_offsets[1 : N + 2])
    return inc_offsets, pl.astype(np.int32), pt.astype(np.int32)


@dataclass
class CSRSnapshot:
    version: int
    num_atoms: int          # id space size (N); row N is the dummy slot
    inc_offsets: np.ndarray
    inc_links: np.ndarray
    inc_src: np.ndarray
    tgt_offsets: np.ndarray
    tgt_flat: np.ndarray
    tgt_src: np.ndarray
    type_of: np.ndarray
    is_link: np.ndarray
    arity: np.ndarray
    value_rank: np.ndarray
    value_kind: np.ndarray = field(default_factory=lambda: np.empty(0, np.uint8))
    #: (N+1,) uint64 — SECOND rank word (key payload bytes 8..16), the
    #: hgindex tie-break for variable-width kinds; empty on snapshots
    #: packed before the column existed (consumers treat empty as
    #: "no tie-break: var-width columns stay host-served"). HOST-side
    #: only — DeviceSnapshot's pytree is unchanged; the device twin
    #: rides each ValueIndexColumn's rank2 words instead.
    value_rank2: np.ndarray = field(
        default_factory=lambda: np.empty(0, np.uint64))
    #: (N+1,) bool — True where the atom's 128-bit rank pair is NOT a
    #: faithful stand-in for its full key (payload >16 bytes, or NUL in
    #: the first 16 — ``utils/ordered_bytes.rank_ambiguous``). Only
    #: consulted for variable-width kinds; fixed-width exactness is a
    #: property of the KIND, not the atom.
    value_ambig: np.ndarray = field(
        default_factory=lambda: np.empty(0, bool))
    by_type: dict[int, np.ndarray] = field(default_factory=dict)
    n_edges_inc: int = 0    # real (unpadded) incidence entries
    n_edges_tgt: int = 0    # real (unpadded) target entries

    @staticmethod
    def from_tables(
        type_of: np.ndarray,      # (N,) int32 type handle per atom, -1 dead
        is_link: np.ndarray,      # (N,) bool
        tgt_offsets: np.ndarray,  # (N+1,) int — target CSR offsets
        tgt_flat: np.ndarray,     # (E,) int — ordered targets per link
        value_rank: Optional[np.ndarray] = None,  # (N,) uint64 payload ranks
        value_kind: Optional[np.ndarray] = None,  # (N,) uint8 kind bytes
        value_rank2: Optional[np.ndarray] = None,  # (N,) uint64 tie-break word
        value_ambig: Optional[np.ndarray] = None,  # (N,) bool rank ambiguity
        version: int = 0,
        pad_multiple: int = 128,
    ) -> "CSRSnapshot":
        """Assemble a snapshot directly from columnar tables — the
        dataset-scale bulk path (the analogue of the reference's
        subgraph-as-stream loading, ``storage/RAMStorageGraph.java``),
        bypassing per-atom store writes entirely. Used by the benchmark
        generators to build 10M-atom graphs in seconds; ``pack`` routes
        through the same assembly."""
        N = len(type_of)
        type_col = np.full(N + 1, -1, dtype=np.int32)
        type_col[:N] = type_of
        link_col = np.zeros(N + 1, dtype=bool)
        link_col[:N] = is_link
        arity = np.zeros(N + 1, dtype=np.int32)
        lens = np.asarray(tgt_offsets[1:]) - np.asarray(tgt_offsets[:-1])
        arity[:N] = lens.astype(np.int32)
        rank_col = np.zeros(N + 1, dtype=np.uint64)
        if value_rank is not None:
            rank_col[:N] = value_rank
        kind_col = np.zeros(N + 1, dtype=np.uint8)
        if value_kind is not None:
            kind_col[:N] = value_kind
        rank2_col = np.zeros(N + 1, dtype=np.uint64)
        if value_rank2 is not None:
            rank2_col[:N] = value_rank2
        ambig_col = np.zeros(N + 1, dtype=bool)
        if value_ambig is not None:
            ambig_col[:N] = value_ambig
        elif value_kind is not None and value_rank2 is None:
            # rank-only callers (the bulk bench path) carry no keys to
            # derive the tie-break from: variable-width atoms must stay
            # rank-ambiguous (→ host-served windows), preserving the
            # pre-tie-break behavior instead of guessing exactness
            from hypergraphdb_tpu.storage.value_index import FIXED_WIDTH_KINDS

            fixed = np.isin(
                kind_col[:N],
                np.frombuffer(bytes(FIXED_WIDTH_KINDS), dtype=np.uint8))
            ambig_col[:N] = (kind_col[:N] != 0) & ~fixed
        off = np.zeros(N + 2, dtype=np.int32)
        off[1 : N + 1] = np.asarray(tgt_offsets[1:], dtype=np.int32)
        off[N + 1] = off[N]
        tgt_flat = np.asarray(tgt_flat, dtype=np.int32)
        tgt_src = np.repeat(
            np.arange(N, dtype=np.int32), lens.astype(np.int64)
        )
        inc_offsets, inc_links, inc_src = _incidence_transpose(
            tgt_src, tgt_flat, N
        )
        e_inc, e_tgt = len(inc_links), len(tgt_flat)
        return CSRSnapshot(
            version=version,
            num_atoms=N,
            inc_offsets=inc_offsets,
            inc_links=_pad_to(inc_links, pad_multiple, N),
            inc_src=_pad_to(inc_src, pad_multiple, N),
            tgt_offsets=off,
            tgt_flat=_pad_to(tgt_flat, pad_multiple, N),
            tgt_src=_pad_to(tgt_src, pad_multiple, N),
            type_of=type_col,
            is_link=link_col,
            arity=arity,
            value_rank=rank_col,
            value_kind=kind_col,
            value_rank2=rank2_col,
            value_ambig=ambig_col,
            by_type=_group_by_type(type_col[:N]),
            n_edges_inc=e_inc,
            n_edges_tgt=e_tgt,
        )

    # ------------------------------------------------------------------ pack
    @staticmethod
    def extract_tables(graph, value_ranks: bool = True) -> dict:
        """Read the committed store into raw host tables — the ONLY part of
        packing that must see a consistent store state. Background
        compaction (``ops/incremental.SnapshotManager``) holds the commit
        lock just for this extraction and runs the expensive CSR assembly
        (``pack(tables=...)``) lock-free."""
        backend = graph.backend
        ids, offsets, flat = backend.bulk_links()
        value_items = None
        if value_ranks:
            try:
                from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

                idx = backend.get_index(IDX_BY_VALUE, create=False)
                if idx is not None:
                    value_items = list(idx.bulk_items())
            except Exception:
                value_items = None
        peek = int(graph.handles.peek) if hasattr(graph.handles, "peek") else 0
        return {
            "ids": np.asarray(ids, dtype=np.int64),
            "offsets": np.asarray(offsets, dtype=np.int64),
            "flat": np.asarray(flat, dtype=np.int64),
            "peek": max(peek, int(backend.max_handle())),
            "value_items": value_items,
        }

    @staticmethod
    def pack(graph, version: Optional[int] = None, pad_multiple: int = 128,
             capacity: Optional[int] = None, value_ranks: bool = True,
             tables: Optional[dict] = None,
             ) -> "CSRSnapshot":
        """Pack the committed store into CSR arrays (the ``storage/tpu-jax``
        snapshot step from BASELINE.json's north star).

        ``capacity`` over-allocates the id space so atoms added AFTER the
        pack still fit in this snapshot's bitmap width — the prerequisite
        for delta overlays (``ops/incremental.py``): base and delta share
        one frontier shape, so no recompilation on ingest. ``tables`` (from
        :meth:`extract_tables`) lets callers separate the store read from
        the assembly."""
        if tables is None:
            tables = CSRSnapshot.extract_tables(graph, value_ranks)
        ids = tables["ids"]
        offsets = tables["offsets"]
        flat = tables["flat"]
        n = tables["peek"]
        if capacity is not None:
            n = max(n, int(capacity))
        N = n  # id space; dummy row is N

        type_of = np.full(N + 1, -1, dtype=np.int32)
        is_link = np.zeros(N + 1, dtype=bool)
        arity = np.zeros(N + 1, dtype=np.int32)
        value_rank = np.zeros(N + 1, dtype=np.uint64)
        value_kind = np.zeros(N + 1, dtype=np.uint8)
        value_rank2 = np.zeros(N + 1, dtype=np.uint64)
        value_ambig = np.zeros(N + 1, dtype=bool)

        # fully vectorized record decode (the 10M-atom scale path — no
        # per-atom Python): record layout is (type, value, flags, *targets),
        # see core/graph.py
        starts = offsets[:-1]
        lens = offsets[1:] - starts
        ok = lens >= 3
        vids = ids[ok]
        vstarts = starts[ok]
        vlens = lens[ok]
        type_of[vids] = flat[vstarts].astype(np.int32)
        value_handles = flat[vstarts + 1]
        is_link[vids] = (flat[vstarts + 2].astype(np.int64) & 1).astype(bool)
        arities = (vlens - 3).astype(np.int32)
        arity[vids] = arities

        # target COO: for record j, positions vstarts[j]+3 .. end
        rec_of = np.repeat(np.arange(len(vids)), vlens)
        pos_in_rec = np.arange(len(rec_of)) - np.repeat(
            np.cumsum(vlens) - vlens, vlens
        )
        tmask = pos_in_rec >= 3
        rec_sel = rec_of[tmask]
        tgt_flat_coo = flat[
            np.repeat(vstarts, vlens)[tmask] + pos_in_rec[tmask]
        ].astype(np.int32)
        tgt_src_coo = vids[rec_sel].astype(np.int32)

        # target CSR grouped by source link (records already id-ascending)
        tgt_counts = np.zeros(N + 1, dtype=np.int64)
        tgt_counts[vids] = arities
        tgt_offsets = np.zeros(N + 2, dtype=np.int32)
        np.cumsum(tgt_counts, out=tgt_offsets[1 : N + 2])
        e_tgt = len(tgt_flat_coo)
        tgt_flat_arr = tgt_flat_coo
        tgt_src_arr = tgt_src_coo

        # incidence CSR = transpose of the target relation (shared helper)
        inc_offsets, inc_links_arr, inc_src_arr = _incidence_transpose(
            tgt_src_coo, tgt_flat_coo, N
        )
        e_inc = len(inc_links_arr)

        # value ranks via the by-value system index: one rank64 per DISTINCT
        # key (values repeat heavily in real graphs), scattered to handles.
        # The kind byte is stripped into its own column so the 8 rank bytes
        # all carry payload — exact (tie-free) for fixed-width kinds.
        if tables["value_items"] is not None:
            # lazy import keeps ops/ free of module-level storage deps
            from hypergraphdb_tpu.storage.value_index import FIXED_WIDTH_KINDS

            for key, hs in tables["value_items"]:
                sel = hs[hs <= N]
                payload = key[1:]
                value_rank[sel] = rank64(payload)
                value_kind[sel] = key[0] if key else 0
                # the hgindex tie-break pair: second word + ambiguity bit
                # (payload beyond 16 bytes, or NUL among the first 16 —
                # there zero-padding stops being a faithful order/identity
                # map and the window must host-serve). Fixed-width kinds
                # are NEVER ambiguous: their 8-byte payload fits the first
                # rank word entirely, NUL bytes and all.
                value_rank2[sel] = rank64(payload[8:16])
                if key and key[0] not in FIXED_WIDTH_KINDS:
                    value_ambig[sel] = rank_ambiguous(payload)

        # pad edge arrays to lane multiples; padded entries point at the
        # dummy row N (whose frontier/visited value is always False)
        inc_links_p = _pad_to(inc_links_arr, pad_multiple, N)
        inc_src_p = _pad_to(inc_src_arr, pad_multiple, N)
        tgt_flat_p = _pad_to(tgt_flat_arr, pad_multiple, N)
        tgt_src_p = _pad_to(tgt_src_arr, pad_multiple, N)

        # by-type sorted id arrays (device form of the by-type index)
        by_type = _group_by_type(type_of[:N])

        return CSRSnapshot(
            version=version if version is not None else getattr(
                graph, "_mutations", 0
            ),
            num_atoms=N,
            inc_offsets=inc_offsets,
            inc_links=inc_links_p,
            inc_src=inc_src_p,
            tgt_offsets=tgt_offsets,
            tgt_flat=tgt_flat_p,
            tgt_src=tgt_src_p,
            type_of=type_of,
            is_link=is_link,
            arity=arity,
            value_rank=value_rank,
            value_kind=value_kind,
            value_rank2=value_rank2,
            value_ambig=value_ambig,
            by_type=by_type,
            n_edges_inc=e_inc,
            n_edges_tgt=e_tgt,
        )

    # ------------------------------------------------------------------ host views
    def incidence_row(self, atom: int) -> np.ndarray:
        s, e = int(self.inc_offsets[atom]), int(self.inc_offsets[atom + 1])
        return self.inc_links[s:e]

    def targets_row(self, atom: int) -> np.ndarray:
        s, e = int(self.tgt_offsets[atom]), int(self.tgt_offsets[atom + 1])
        return self.tgt_flat[s:e]

    def type_set(self, type_handle: int) -> np.ndarray:
        return self.by_type.get(int(type_handle), np.empty(0, dtype=np.int32))

    # ------------------------------------------------------------------ device
    @cached_property
    def device(self) -> "DeviceSnapshot":
        """Transfer to the default device (HBM) once; cached."""
        return DeviceSnapshot.from_host(self)


@dataclass
class DeviceSnapshot:
    """The jnp-array twin of a CSRSnapshot, resident in device memory."""

    num_atoms: int
    inc_offsets: "jax.Array"  # noqa: F821
    inc_links: "jax.Array"  # noqa: F821
    inc_src: "jax.Array"  # noqa: F821
    tgt_offsets: "jax.Array"  # noqa: F821
    tgt_flat: "jax.Array"  # noqa: F821
    tgt_src: "jax.Array"  # noqa: F821
    type_of: "jax.Array"  # noqa: F821
    is_link: "jax.Array"  # noqa: F821
    arity: "jax.Array"  # noqa: F821
    # the 64-bit order-preserving value ranks, split into two uint32 words
    # (compare lexicographically hi-then-lo): jnp.asarray would silently
    # truncate uint64 to its LOW 32 bits under default x64-disabled JAX,
    # destroying the ordering
    value_rank_hi: "jax.Array"  # noqa: F821
    value_rank_lo: "jax.Array"  # noqa: F821
    value_kind: "jax.Array"  # noqa: F821 — uint8 kind byte per atom

    @staticmethod
    def from_host(snap: CSRSnapshot) -> "DeviceSnapshot":
        import jax.numpy as jnp

        return DeviceSnapshot(
            num_atoms=snap.num_atoms,
            inc_offsets=jnp.asarray(snap.inc_offsets),
            inc_links=jnp.asarray(snap.inc_links),
            inc_src=jnp.asarray(snap.inc_src),
            tgt_offsets=jnp.asarray(snap.tgt_offsets),
            tgt_flat=jnp.asarray(snap.tgt_flat),
            tgt_src=jnp.asarray(snap.tgt_src),
            type_of=jnp.asarray(snap.type_of),
            is_link=jnp.asarray(snap.is_link),
            arity=jnp.asarray(snap.arity),
            value_rank_hi=jnp.asarray(
                (snap.value_rank >> np.uint64(32)).astype(np.uint32)
            ),
            value_rank_lo=jnp.asarray(
                (snap.value_rank & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            ),
            value_kind=jnp.asarray(
                snap.value_kind
                if len(snap.value_kind) == snap.num_atoms + 1
                else np.zeros(snap.num_atoms + 1, dtype=np.uint8)
            ),
        )


_register_device_snapshot_pytree()
