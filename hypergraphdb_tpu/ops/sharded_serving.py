"""Multi-chip serving kernels: the mesh-sharded twins of ``ops/serving``.

The serving runtime's batched kernels (``bfs_serve_batch`` /
``pattern_serve_batch`` / the ``ops/join`` lane executor) each run on ONE
chip; these route the same micro-batch contracts through ``shard_map``
programs over the device mesh (``parallel.sharded.AXIS``), so a serve
bucket's work spreads across every chip of a pod and the pinned snapshot
no longer has to fit one chip's HBM:

- :func:`bfs_serve_batch_sharded` — K-seed BFS over the ROW-SHARDED
  (base ∪ delta) pair (``parallel.sharded.bfs_packed_sharded_delta``:
  per hop, two all-gathers of packed frontier words cross ICI), with the
  result compaction ALSO on the mesh: each device counts + top-``r``'s
  its own row range, counts ``psum`` up, and the per-device candidate
  windows ``all_gather`` + merge into the global ``top_r`` smallest ids
  — O(K · n_dev · top_r) ints on ICI however large the graph.
- :func:`pattern_serve_batch_sharded` — K conjunctive incident patterns,
  CANDIDATE-sharded: the smallest anchor's incidence row (host-gathered
  per lane, its target tuples and type labels riding along) splits
  across devices along the candidate axis; each device membership-tests
  its slice against every other anchor in O(L_loc · P · W) contiguous
  work, then the same psum + all-gather-merge compaction. No
  device-resident ELL matrix at all — the only per-batch device state is
  O(K · pad · W).
- :func:`execute_join_sharded` — the PR-10 worst-case-optimal join lane
  executor, LANE-sharded: one ``shard_map`` program runs the whole
  multiway-intersection step chain on each device for its K/n_dev lanes
  of the batch (relations replicated — sharding the relations themselves
  is the ROADMAP follow-up), counts/truncation/tuple windows reassembled
  along the lane axis.

All three keep the single-chip kernels' result contracts bit-for-bit
(compact ``(counts, first_r)`` / ``JoinExecution``), so the serving
runtime's collect path — including the host-side LSM memtable
corrections, which stay exactly as they are — needs no sharded variant.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.bitfrontier import unpack_bits
from hypergraphdb_tpu.ops.setops import ELL_MAX_WIDTH, SENTINEL, _bucket
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
from hypergraphdb_tpu.parallel.sharded import (
    _SHARD_MAP_KW,
    AXIS,
    ShardedDelta,
    ShardedSnapshot,
    bfs_packed_sharded_delta,
    shard_map,
)


#: one carrier per DISTINCT mesh topology — keyed by (axis names,
#: device ids), NOT id(mesh): recycled runtimes mint a fresh Mesh object
#: per executor, and an identity key would pin every dead mesh (plus its
#: device-resident carrier arrays) for the life of the process
_CARRIERS: dict = {}


def mesh_carrier(mesh) -> ShardedSnapshot:
    """A MINIMAL ShardedSnapshot whose only job is carrying ``mesh``
    into kernels that need no row-sharded state (the pattern lanes: all
    real operands are host-assembled per batch). Constant shapes, so
    prewarm and dispatch share one compiled program and one AOT key —
    and a pattern-only pod never pays the O(E) sharded-base build."""
    key = (tuple(mesh.axis_names),
           tuple(int(d.id) for d in mesh.devices.flat))
    hit = _CARRIERS.get(key)
    if hit is not None:
        return hit
    n_dev = int(mesh.devices.size)
    n_loc = 128
    n_pad = n_dev * n_loc
    from jax.sharding import NamedSharding

    shard = NamedSharding(mesh, P(AXIS))

    def put(a):
        return jax.device_put(jnp.asarray(a), shard)

    carrier = ShardedSnapshot(
        mesh=mesh, num_atoms=n_pad - 1, n_loc=n_loc, edge_chunk=8,
        inc_src=put(np.zeros(n_dev * 8, np.int32)),
        inc_dst=put(np.zeros(n_dev * 8, np.int32)),
        tgt_src=put(np.zeros(n_dev * 8, np.int32)),
        tgt_dst=put(np.zeros(n_dev * 8, np.int32)),
        type_of=put(np.zeros(n_pad, np.int32)),
        is_link=put(np.zeros(n_pad, bool)),
        arity=put(np.zeros(n_pad, np.int32)),
        value_rank_hi=put(np.zeros(n_pad, np.uint32)),
        value_rank_lo=put(np.zeros(n_pad, np.uint32)),
    )
    _CARRIERS[key] = carrier
    return carrier


def _merge_first_r(local_first: jax.Array, top_r: int) -> jax.Array:
    """All-gather each device's ascending candidate window and merge to
    the global ``top_r`` smallest (SENTINEL-padded): the one collective
    the compaction epilogues share. Runs INSIDE a shard_map region."""
    cand = jax.lax.all_gather(local_first, AXIS, axis=1, tiled=True)
    short = top_r - cand.shape[1]
    if short > 0:  # tiny graphs: fewer candidate slots than top_r
        cand = jnp.concatenate(
            [cand, jnp.full((cand.shape[0], short), SENTINEL, cand.dtype)],
            axis=1,
        )
    # top_k of the negation = the top_r SMALLEST; re-negating restores
    # ascending order (the ops/serving compaction idiom)
    return -jax.lax.top_k(-cand, top_r)[0]


@hgverify.entry(
    shapes=lambda: (hgverify.sharded_snapshot_exemplar(),
                    hgverify.sharded_delta_exemplar(),
                    hgverify.sds((8,), "int32")),
    statics={"max_hops": 2, "top_r": 4},
    mesh=(AXIS,),
)
@partial(jax.jit, static_argnames=("max_hops", "top_r"))
def bfs_serve_batch_sharded(
    sdev: ShardedSnapshot,
    sdelta: ShardedDelta,
    seeds: jax.Array,   # (K,) int32 — pad lanes carry sdev.num_atoms
    max_hops: int,
    top_r: int,
) -> tuple[jax.Array, jax.Array]:
    """The mesh twin of ``ops.serving.bfs_serve_batch``: same
    ``(counts (K,) int32, first_r (K, top_r) int32)`` contract, computed
    from the row-sharded packed BFS. Pad lanes (dummy-row seeds) reach
    nothing — the dummy row is outside every device's live mask."""
    visited_p, _, _ = bfs_packed_sharded_delta(
        sdev, sdelta, seeds, max_hops, with_levels=False
    )
    n_loc = sdev.n_loc
    k_loc = min(top_r, n_loc)

    def compact(vis_loc):
        # vis_loc (K, n_loc/WORD): this device's row range of the packed
        # visited bitmaps (live-masked by the BFS program)
        row_start = jax.lax.axis_index(AXIS).astype(jnp.int32) * n_loc
        bits = unpack_bits(vis_loc)                       # (K, n_loc)
        counts = jax.lax.psum(
            bits.sum(axis=1).astype(jnp.int32), AXIS
        )
        ids = row_start + jnp.arange(n_loc, dtype=jnp.int32)
        masked = jnp.where(bits, ids[None, :], SENTINEL)
        local_first = -jax.lax.top_k(-masked, k_loc)[0]
        return counts, _merge_first_r(local_first, top_r)

    fn = shard_map(
        compact, mesh=sdev.mesh,
        in_specs=(P(None, AXIS),), out_specs=(P(), P()),
        **_SHARD_MAP_KW,
    )
    return fn(visited_p)


# --------------------------------------------------------------------------
# candidate-sharded conjunctive patterns
# --------------------------------------------------------------------------


def pattern_host_rows(
    snap: CSRSnapshot,
    anchors: np.ndarray,   # (K, P) int64/int32 — [:, 0] has the SMALLEST row
    pad_len: int,
    n_dev: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side batch assembly for :func:`pattern_serve_batch_sharded`:
    per lane, the smallest anchor's incidence row (the candidate set),
    each candidate's type label, and each candidate's target tuple —
    gathered from the CSR's HOST arrays, so no (N+1, W) ELL matrix ever
    occupies device memory. The candidate axis is rounded up to a
    multiple of ``n_dev`` (the shard_map split). Returns
    ``(rows0 (K, L) int32 SENTINEL-padded, row0_types (K, L) int32,
    tgt_tuples (K, L, W) int32 -1-padded)``."""
    anchors = np.asarray(anchors, dtype=np.int64)
    K = anchors.shape[0]
    N = snap.num_atoms
    L = max(int(pad_len), 1)
    L = -(-L // n_dev) * n_dev
    a0 = np.clip(anchors[:, 0], 0, N)
    off = snap.inc_offsets
    starts = off[a0].astype(np.int64)
    lens = off[a0 + 1].astype(np.int64) - starts
    lane = np.arange(L, dtype=np.int64)
    have = lane[None, :] < np.minimum(lens, L)[:, None]
    idx = np.minimum(starts[:, None] + lane[None, :],
                     max(len(snap.inc_links) - 1, 0))
    rows0 = np.where(have, snap.inc_links[idx] if len(snap.inc_links)
                     else 0, SENTINEL).astype(np.int32)
    safe = np.where(have, rows0, N).astype(np.int64)  # dummy row: empty
    row0_types = np.where(have, snap.type_of[safe], -1).astype(np.int32)
    W = _bucket(max(int(snap.arity[: N + 1].max(initial=0)), 1), minimum=2)
    tstart = snap.tgt_offsets[safe].astype(np.int64)          # (K, L)
    tlen = snap.tgt_offsets[safe + 1].astype(np.int64) - tstart
    wlane = np.arange(W, dtype=np.int64)
    tvalid = wlane[None, None, :] < tlen[:, :, None]
    tidx = np.minimum(tstart[:, :, None] + wlane[None, None, :],
                      max(len(snap.tgt_flat) - 1, 0))
    tgt = np.where(tvalid, snap.tgt_flat[tidx] if len(snap.tgt_flat)
                   else 0, -1).astype(np.int32)
    return rows0, row0_types, tgt


@hgverify.entry(
    shapes=lambda: (hgverify.sharded_snapshot_exemplar(),
                    hgverify.sds((8, 16), "int32"),
                    hgverify.sds((8, 16), "int32"),
                    hgverify.sds((8, 16, 4), "int32"),
                    hgverify.sds((8, 2), "int32"),
                    hgverify.sds((8,), "int32")),
    statics={"top_r": 4},
    mesh=(AXIS,),
)
@partial(jax.jit, static_argnames=("top_r",))
def pattern_serve_batch_sharded(
    sdev: ShardedSnapshot,    # mesh carrier; its arrays are unused (DCE'd)
    rows0: jax.Array,         # (K, L) int32 — candidate link ids, SENTINEL pad
    row0_types: jax.Array,    # (K, L) int32 — candidates' type handles
    tgt_tuples: jax.Array,    # (K, L, W) int32 — candidates' target tuples
    anchors: jax.Array,       # (K, P) int32 — [:, 0] is the candidate row
    type_vec: jax.Array,      # (K,) int32 — per-request type, < 0 = any
    top_r: int,
) -> tuple[jax.Array, jax.Array]:
    """The mesh twin of ``ops.serving.pattern_serve_batch``: candidates
    split across devices along L; each device target-tuple-membership
    tests its slice against anchors 1..P-1 and type-filters with the
    labels that rode along — then counts ``psum`` and the per-device
    ``top_r`` windows all-gather-merge. ``L`` must be a multiple of the
    mesh size (``pattern_host_rows`` rounds it)."""
    L = rows0.shape[1]
    k_loc = min(top_r, max(L // int(sdev.mesh.devices.size), 1))

    def local(r0, rt, tg, anc, tv):
        mask = r0 != SENTINEL
        for p in range(1, anc.shape[1]):
            mask = mask & jnp.any(tg == anc[:, p, None, None], axis=-1)
        mask = mask & ((tv[:, None] < 0) | (rt == tv[:, None]))
        counts = jax.lax.psum(mask.sum(axis=1).astype(jnp.int32), AXIS)
        ranked = jnp.where(mask, r0, SENTINEL)
        local_first = -jax.lax.top_k(-ranked, k_loc)[0]
        return counts, _merge_first_r(local_first, top_r)

    fn = shard_map(
        local, mesh=sdev.mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(None, AXIS, None),
                  P(), P()),
        out_specs=(P(), P()),
        **_SHARD_MAP_KW,
    )
    return fn(rows0, row0_types, tgt_tuples, anchors, type_vec)


def pattern_sharded_ok(snap: CSRSnapshot) -> bool:
    """Route gate: the host-assembled target tuples use the same arity
    cap as the single-chip ELL path (wider links fall back to host)."""
    N = snap.num_atoms
    return int(snap.arity[: N + 1].max(initial=0)) <= ELL_MAX_WIDTH


# --------------------------------------------------------------------------
# lane-sharded join execution
# --------------------------------------------------------------------------


def execute_join_sharded(
    snap: CSRSnapshot,
    sdev: ShardedSnapshot,
    plan,                    # join/planner.JoinPlan
    consts: np.ndarray,      # (K, n_consts) int32
    *,
    top_r: int = 16,
    n_real: int = None,
    row_cap: int = None,
    pad_cap: int = None,
    slot_budget: int = None,
):
    """The mesh twin of ``ops.join.execute_join`` for the serving lanes:
    ONE shard_map program runs the plan's whole expand-step chain per
    device over its K/n_dev lanes (``K`` must divide by the mesh size —
    the serve buckets do), with the same pad/row-bucket schedule
    arithmetic applied to the per-device lane count. Relations are
    replicated across the mesh in this v1 (each chip holds the full CSR
    for the join path; sharding the relations is the ROADMAP follow-up)
    — what the mesh buys today is the step chain's candidate expansion
    and intersection running n_dev-wide. Returns an
    ``ops.join.JoinExecution`` with the lane axis reassembled, same
    counts/trunc/tuples contract as the single-chip executor."""
    from hypergraphdb_tpu.ops.join import (
        DEFAULT_PAD_CAP,
        DEFAULT_ROW_CAP,
        DEFAULT_SLOT_BUDGET,
        JoinExecution,
        _rel_arrays,
        _rel_host_offsets,
        join_expand_step,
        join_finalize,
    )

    row_cap = DEFAULT_ROW_CAP if row_cap is None else row_cap
    pad_cap = DEFAULT_PAD_CAP if pad_cap is None else pad_cap
    slot_budget = DEFAULT_SLOT_BUDGET if slot_budget is None else slot_budget
    mesh = sdev.mesh
    n_dev = int(mesh.devices.size)
    dev = snap.device
    K, A = (int(consts.shape[0]), int(consts.shape[1]))
    if K % n_dev:
        raise ValueError(
            f"lane count {K} must divide by the mesh size {n_dev}"
        )
    k_loc = K // n_dev
    n_real = K if n_real is None else int(n_real)
    consts = np.ascontiguousarray(consts, dtype=np.int32)
    consts_dev = jnp.asarray(consts) if A else jnp.zeros((K, 0), jnp.int32)

    # the per-step schedule (pads, row buckets, relation arrays, statics)
    # is host-computed ONCE for the whole batch — identical on every
    # device, with row buckets sized to the per-device lane count
    sched = []
    rels: list = []          # flat replicated array operands

    def rel_slot(arrs) -> tuple:
        idx = []
        for a in arrs:
            for i, have in enumerate(rels):
                if have is a:
                    idx.append(i)
                    break
            else:
                rels.append(a)
                idx.append(len(rels) - 1)
        return tuple(idx)

    R = k_loc
    for s in plan.steps:
        if s.source_key.kind == "const":
            off_h = _rel_host_offsets(snap, s.source_rel)
            real = consts[:n_real]
            keys = np.clip(real[:, s.source_key.index], 0, snap.num_atoms)
            w = int(np.max(off_h[keys + 1] - off_h[keys], initial=1))
        else:
            w = 4 * (int(s.width_est) + 1)
        pad = _bucket(
            max(min(w, pad_cap, max(slot_budget // max(R, 1), 8)), 1),
            minimum=8,
        )
        rows_out = min(_bucket(R * pad), row_cap, R * pad)
        exp_ix = rel_slot(_rel_arrays(snap, dev, s.source_rel))
        filt_sel = []
        filt_ix = []
        for f in s.filters:
            fo, ff = _rel_arrays(snap, dev, f.rel)
            filt_sel.append((f.rev, f.key.kind, f.key.index))
            filt_ix.append(rel_slot((fo, ff)))
        sched.append({
            "exp_ix": exp_ix, "filt_ix": tuple(filt_ix),
            "exp_sel": (s.source_key.kind, s.source_key.index),
            "filt_sel": tuple(filt_sel),
            "type_handle": (-1 if s.type_handle is None
                            else int(s.type_handle)),
            "pad": pad, "rows_out": rows_out, "dedupe": s.dedupe,
        })
        R = rows_out
    type_ix = rel_slot((dev.type_of,))[0]
    sort_cols = tuple(plan.order.index(v) for v in plan.sig.vars)
    n_cols0 = 0

    def lane_prog(consts_loc, *rel_ops):
        lane_base = jax.lax.axis_index(AXIS).astype(jnp.int32) * k_loc
        cols = jnp.zeros((k_loc, n_cols0), jnp.int32)
        lanes = jnp.arange(k_loc, dtype=jnp.int32)          # LOCAL lanes
        valid = (lane_base + lanes) < n_real
        counts = jnp.zeros(k_loc, jnp.int32)
        trunc = jnp.zeros(k_loc, bool)
        for st in sched:
            n_dist = int(cols.shape[1]) if plan.distinct else 0
            cols, lanes, valid, counts, step_trunc = join_expand_step(
                rel_ops[st["exp_ix"][0]], rel_ops[st["exp_ix"][1]],
                cols, lanes, valid, consts_loc,
                tuple(rel_ops[i] for i, _ in st["filt_ix"]),
                tuple(rel_ops[j] for _, j in st["filt_ix"]),
                rel_ops[type_ix],
                exp_sel=st["exp_sel"], filt_sel=st["filt_sel"],
                type_handle=st["type_handle"],
                pad=st["pad"], rows_out=st["rows_out"], n_lanes=k_loc,
                n_distinct_cols=n_dist,
                distinct_consts=plan.distinct and A > 0,
                dedupe=st["dedupe"],
            )
            trunc = trunc | step_trunc
        tuples = join_finalize(cols, lanes, valid, top_r=top_r,
                               n_lanes=k_loc, sort_cols=sort_cols)
        return counts, trunc, tuples

    fn = shard_map(
        lane_prog, mesh=mesh,
        in_specs=(P(AXIS),) + (P(),) * len(rels),
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
        **_SHARD_MAP_KW,
    )
    counts, trunc, tuples = fn(consts_dev, *rels)
    return JoinExecution(order=plan.order, counts=counts, trunc=trunc,
                         tuples=tuples)
