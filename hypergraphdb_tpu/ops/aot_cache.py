"""Persistent AOT lowering cache: compile once per (entry, shape-bucket).

The serving runtime compiles one executable per (kernel entry, shape
bucket, statics). Those compiles are pure functions of the traced program
— nothing about them depends on graph *content* — yet every fresh process
pays them again: BENCH r04 burned 18.7 s of ``plan_build``-adjacent
compile time, and a cold ``ServeRuntime`` spends its first deadline
windows inside XLA instead of serving. This module makes the compile a
cache lookup: ``jax.jit(...).lower().compile()`` products are serialized
(``jax.experimental.serialize_executable``) into a **fingerprinted
on-disk directory** and loaded back in milliseconds.

Key anatomy (see README "Fused BFS kernel & AOT cache"):

- the cache **directory** is fingerprinted by environment —
  ``<root>/<jax-version>_<backend>/`` — so upgrading jax or moving
  between backends can never replay a stale executable;
- the **entry file name** is ``<entry>__<sha256 of (entry, arg avals,
  statics, content_key)>.aot``; avals cover every dynamic argument's
  shape/dtype (the shape bucket), statics are the jit-static kwargs, and
  ``content_key`` is the caller's optional data fingerprint (serving
  passes ``ellbfs.snapshot_fingerprint``-style keys when results must be
  pinned to a snapshot generation);
- each file carries a JSON header (format version, jax/backend versions,
  entry, content_key, wall compile seconds) ahead of the pickled
  executable payload.

Invalidation rules, mirroring ``ellbfs.StalePlans``:

- a WELL-FORMED entry whose header disagrees (format bump, jax/backend
  version, content_key) raises :class:`StaleEntry` internally and is
  treated as a quiet miss → rebuild (counted in ``stats.stale``);
- an unreadable/corrupt file is logged at WARNING, counted in
  ``stats.corrupt``, and rebuilt — a damaged cache must never take the
  process down;
- stores are write-then-rename, so a crashed writer leaves no torn entry.

``JAX_PLATFORMS=cpu`` behavior: everything works (CPU executables
serialize fine), so the lifecycle is testable in tier-1; only the
*callers'* Pallas gates differ per backend.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger("hypergraphdb_tpu.aot")

#: bumped when the on-disk layout changes; mismatched entries are stale
FORMAT = 1

_MAGIC = b"HGAOT1\n"


class StaleEntry(ValueError):
    """Well-formed cache entry for a different environment/content —
    the quiet-rebuild case, deliberately distinct from a corrupt file."""


@dataclass
class AOTStats:
    """Counters of one cache instance. ``hits``/``misses`` count compile
    avoidance (a memory hit after a disk hit is still a hit — the point
    is whether XLA ran); the rest classify why a miss happened."""

    hits: int = 0
    misses: int = 0
    disk_hits: int = 0      # hits served by deserializing from disk
    mem_hits: int = 0       # hits served by the in-process memo
    stale: int = 0
    corrupt: int = 0
    puts: int = 0
    gc_removed: int = 0     # superseded entries deleted by the open sweep
    compile_s: float = 0.0  # wall seconds spent actually compiling

    def as_dict(self) -> dict:
        return {
            "hits": self.hits, "misses": self.misses,
            "disk_hits": self.disk_hits, "mem_hits": self.mem_hits,
            "stale": self.stale, "corrupt": self.corrupt,
            "puts": self.puts, "gc_removed": self.gc_removed,
            "compile_s": round(self.compile_s, 3),
        }


def env_fingerprint(backend: Optional[str] = None) -> str:
    """The environment half of the key: jax version + backend platform.
    Anything that changes the emitted executable format must be here."""
    import jax

    return f"jax{jax.__version__}_{backend or jax.default_backend()}"


def _is_device(obj: Any) -> bool:
    try:
        import jax

        return isinstance(obj, jax.Device)
    except Exception:  # noqa: BLE001 - exotic jax versions
        return type(obj).__name__ == "Device"


def _dumps(obj: Any) -> bytes:
    """Pickle with jax ``Device`` objects swapped for their ids: the
    in/out treedefs of MESH-sharded entries carry the ``Mesh`` (and so
    its device array) in pytree aux data, and devices are process
    handles no pickler can serialize. The env fingerprint already pins
    the backend, so re-resolving by id at load time is exact."""
    import io

    buf = io.BytesIO()
    p = pickle.Pickler(buf)

    def persistent_id(o):
        if _is_device(o):
            return ("hg_device", int(o.id))
        return None

    p.persistent_id = persistent_id
    p.dump(obj)
    return buf.getvalue()


def _loads(data: bytes) -> Any:
    import io

    up = pickle.Unpickler(io.BytesIO(data))

    def persistent_load(pid):
        kind, did = pid
        if kind != "hg_device":
            raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")
        import jax

        for d in jax.devices():
            if int(d.id) == int(did):
                return d
        # fewer/different devices than the writer: a stale-shaped entry,
        # surfaced as unreadable → quiet rebuild
        raise pickle.UnpicklingError(f"device id {did} not present")

    up.persistent_load = persistent_load
    return up.load()


def _aval_sig(x: Any) -> str:
    import jax

    leaves = jax.tree_util.tree_leaves(x)
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        parts.append(f"{tuple(shape) if shape is not None else ()}:{dtype}")
    return ";".join(parts)


@dataclass
class AOTCache:
    """One fingerprinted cache directory + an in-process compiled memo.

    Thread-safety: lookups and stores are idempotent (same key → same
    executable) and writes are atomic renames, so concurrent runtimes
    sharing a directory at worst duplicate a compile.
    """

    root: str
    content_key: str = ""
    backend: Optional[str] = None
    stats: AOTStats = field(default_factory=AOTStats)
    #: open-time GC bounds (ROADMAP 4f): superseded content generations'
    #: files older than ``gc_max_age_s`` are deleted, and oldest-first
    #: beyond ``gc_max_bytes`` of directory total — a long-lived replica
    #: otherwise accumulates multi-MB orphaned executables across every
    #: compaction generation. ``gc_max_age_s=None`` disables the sweep.
    gc_max_age_s: Optional[float] = 7 * 86400.0
    gc_max_bytes: int = 256 * 1024 * 1024

    def __post_init__(self):
        self.dir = os.path.join(self.root, env_fingerprint(self.backend))
        os.makedirs(self.dir, exist_ok=True)
        self._mem: dict[str, Any] = {}
        if self.gc_max_age_s is not None:
            try:
                self.gc()
            except Exception:  # noqa: BLE001 - a broken sweep never gates
                log.warning("aot cache gc failed in %s", self.dir,
                            exc_info=True)

    # -- open-time GC ---------------------------------------------------------
    def _entry_content_key(self, path: str) -> Optional[str]:
        """The entry's header content_key, reading ONLY magic + header
        line (never the multi-MB payload); None for unreadable files —
        those would be rebuilt on load anyway, so GC treats them as
        superseded."""
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    return None
                header = json.loads(f.readline().decode("utf-8"))
            return str(header.get("content_key", ""))
        except Exception:  # noqa: BLE001 - damaged header
            return None

    def gc(self, now: Optional[float] = None) -> int:
        """Sweep the cache directory (called at open): delete entries of
        SUPERSEDED content generations — files whose header content_key
        differs from this cache's — once older than ``gc_max_age_s``,
        then oldest-superseded-first while the directory's total size
        exceeds ``gc_max_bytes``. Current-generation entries are never
        touched (the prewarm relies on them), and abandoned ``*.tmp.*``
        writer leftovers past the age bound go too. Returns how many
        files were removed (also counted in ``stats.gc_removed``)."""
        if self.gc_max_age_s is None:
            # the documented off switch — without this, a MANUAL gc()
            # would read None as age 0 and delete every superseded entry
            # plus any tmp a concurrent writer is mid-writing
            return 0
        if now is None:
            now = time.time()
        removed = 0
        superseded: list[tuple[float, int, str]] = []  # (mtime, size, path)
        total = 0
        for name in os.listdir(self.dir):
            path = os.path.join(self.dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if ".tmp." in name:  # crashed writer's leftover
                if now - st.st_mtime > self.gc_max_age_s:
                    try:
                        os.unlink(path)
                        removed += 1
                    except OSError:
                        pass
                continue
            if not name.endswith(".aot"):
                continue
            total += st.st_size
            ck = self._entry_content_key(path)
            if ck != self.content_key:
                superseded.append((st.st_mtime, st.st_size, path))
        superseded.sort()  # oldest first
        for mtime, size, path in superseded:
            if (now - mtime <= self.gc_max_age_s
                    and total <= self.gc_max_bytes):
                continue  # young AND within budget: keep for now
            try:
                os.unlink(path)
            except OSError:
                continue
            removed += 1
            total -= size
        self.stats.gc_removed += removed
        if removed:
            log.info("aot cache gc: removed %d superseded entries from %s",
                     removed, self.dir)
        return removed

    # -- keys -----------------------------------------------------------------
    def key_for(self, entry: str, args: tuple, statics: dict) -> str:
        h = hashlib.sha256()
        h.update(entry.encode())
        h.update(_aval_sig(args).encode())
        h.update(repr(sorted(statics.items())).encode())
        h.update(self.content_key.encode())
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in entry)[:80]
        return f"{safe}__{h.hexdigest()[:24]}"

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.aot")

    # -- the one entry point --------------------------------------------------
    def get_or_compile(self, entry: str, jit_fn, args: tuple,
                       statics: Optional[dict] = None,
                       persist: bool = True):
        """The compiled executable for ``jit_fn(*args, **statics)`` —
        memory, then disk, then a real ``lower().compile()`` persisted
        for next time. Returns the compiled object; call it with the
        DYNAMIC args only (statics are baked in).

        ``persist=False`` memoizes a fresh compile in-process only:
        dispatch-time shapes the prewarm didn't cover (e.g. a resized
        delta bucket) would otherwise mint a new multi-MB disk entry per
        shape generation, synchronously, on a serving thread — and
        nothing evicts the superseded files."""
        statics = statics or {}
        key = self.key_for(entry, args, statics)
        compiled = self._mem.get(key)
        if compiled is not None:
            self.stats.hits += 1
            self.stats.mem_hits += 1
            return compiled
        compiled = self._load(key)
        if compiled is not None:
            self.stats.hits += 1
            self.stats.disk_hits += 1
            self._mem[key] = compiled
            return compiled
        self.stats.misses += 1
        t0 = time.perf_counter()
        compiled = jit_fn.lower(*args, **statics).compile()
        dt = time.perf_counter() - t0
        self.stats.compile_s += dt
        self._mem[key] = compiled
        if persist:
            self._store(key, entry, compiled, compile_s=dt)
        return compiled

    def warm(self, entry: str, jit_fn, args: tuple,
             statics: Optional[dict] = None) -> bool:
        """Pre-compile one bucket; True when it was already cached."""
        before = self.stats.hits
        self.get_or_compile(entry, jit_fn, args, statics)
        return self.stats.hits > before

    # -- disk -----------------------------------------------------------------
    def _load(self, key: str):
        path = self._path(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                magic = f.read(len(_MAGIC))
                if magic != _MAGIC:
                    raise OSError(f"bad magic {magic!r}")
                header = json.loads(f.readline().decode("utf-8"))
                self._check_header(header, path)
                payload, in_tree, out_tree = _loads(f.read())
        except StaleEntry as e:
            # a different environment/content wrote this — quiet rebuild,
            # exactly the ellbfs.StalePlans discipline
            log.debug("aot cache stale: %s", e)
            self.stats.stale += 1
            return None
        except Exception as e:  # noqa: BLE001 - any damage → rebuild
            log.warning("aot cache entry %s unreadable (%s: %s) — "
                        "rebuilding", path, type(e).__name__, e)
            self.stats.corrupt += 1
            return None
        try:
            from jax.experimental import serialize_executable as se

            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 - runtime rejected the blob
            log.warning("aot cache entry %s failed to deserialize (%s: %s)"
                        " — rebuilding", path, type(e).__name__, e)
            self.stats.corrupt += 1
            return None

    def _check_header(self, header: dict, path: str) -> None:
        import jax

        if header.get("format") != FORMAT:
            raise StaleEntry(f"{path}: format {header.get('format')} != "
                             f"{FORMAT}")
        if header.get("env") != env_fingerprint(self.backend):
            raise StaleEntry(f"{path}: env {header.get('env')!r} != "
                             f"{env_fingerprint(self.backend)!r}")
        if header.get("content_key", "") != self.content_key:
            raise StaleEntry(
                f"{path}: content_key {header.get('content_key')!r} does "
                f"not match ({self.content_key!r}) — stale cache entry"
            )
        _ = jax  # imported for symmetry with env_fingerprint

    def _store(self, key: str, entry: str, compiled,
               compile_s: float = 0.0) -> None:
        """Best-effort persist (an unwritable cache dir must not fail the
        compile that just succeeded)."""
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            header = {
                "format": FORMAT,
                "env": env_fingerprint(self.backend),
                "entry": entry,
                "content_key": self.content_key,
                "compile_s": round(compile_s, 3),
                "created_unix": int(time.time()),
            }
            path = self._path(key)
            # pid + thread id + monotonic counter: two runtimes in ONE
            # process storing the same key must not interleave into one
            # tmp file (os.replace would publish the torn result)
            import threading

            tmp = (f"{path}.tmp.{os.getpid()}."
                   f"{threading.get_ident()}.{time.monotonic_ns()}")
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write((json.dumps(header) + "\n").encode("utf-8"))
                f.write(_dumps((payload, in_tree, out_tree)))
            os.replace(tmp, path)
            self.stats.puts += 1
        except Exception as e:  # noqa: BLE001
            log.warning("aot cache store failed for %s (%s: %s)",
                        entry, type(e).__name__, e)

#: env var naming the default cache root (the ``HG_PLAN_CACHE`` twin)
CACHE_ENV = "HG_AOT_CACHE"


def default_cache(content_key: str = "") -> Optional[AOTCache]:
    """Cache rooted at ``$HG_AOT_CACHE``, or None when unset."""
    root = os.environ.get(CACHE_ENV)
    if not root:
        return None
    try:
        return AOTCache(root=root, content_key=content_key)
    except OSError as e:  # pragma: no cover - unwritable root
        log.warning("aot cache root %s unusable: %s", root, e)
        return None
