"""Pallas TPU kernel: double-buffered HBM row gather + fixed-width OR.

The pull-BFS reduction (:mod:`hypergraphdb_tpu.ops.ellbfs`) spends its time
gathering Kw-word rows of the transposed visited bitmap through CSR index
plans — the access pattern of the reference's incidence-set walk
(``core/src/java/org/hypergraphdb/algorithms/HGBreadthFirstTraversal.java:49-66``)
re-laid as one row fetch per edge. This module implements that fetch as a
hand-pipelined Pallas kernel: a grid over output blocks, scalar-prefetched
indices, ``D`` in-flight slots of ``w`` single-row async copies each
(double-buffered DMA), and a VPU OR-chain per output chunk.

Measured reality on v5e (microbench, 4M×512B table, 2M random rows, 3 reps):

======================  ==============  ===========
path                    rows/s          effective
======================  ==============  ===========
XLA gather, 128B rows   ~22M            ~2.9 GB/s
XLA gather, 512B rows   ~30M            ~15 GB/s
this kernel, 512B rows  ~29-31M         ~16 GB/s
======================  ==============  ===========

Both paths sit at the chip's ~30M descriptors/s issue floor for
row-granular HBM access; predicating away pad-row fetches or splitting
descriptors across DMA priorities moves nothing (measured 19.5M useful
fetches/s predicated vs 29.4M unpredicated). The lever that actually buys
bandwidth is ROW WIDTH — 512-byte rows (4096-seed blocks) quadruple the
useful bytes per descriptor — which is why ``ellbfs`` carries visited-only
state to fit wide blocks in HBM. The kernel is kept as the default TPU
path at supported widths (it edges out XLA slightly and pins the layout),
with the XLA gather as the fallback everywhere else.

Constraints (Mosaic, this toolchain): rows must be a multiple of 128 lanes
(Kw % 128 == 0 — narrower VMEM blocks fail to compile), and the
scalar-prefetched index segment must fit the 1 MB SMEM, so long index
arrays are processed in ``SEG``-index segments under ``lax.scan``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hypergraphdb_tpu import verify as hgverify

try:  # DMA priorities landed after 0.4.x; harmless to drop when absent
    import inspect

    _COPY_PRIORITY = "priority" in inspect.signature(
        pltpu.AsyncCopyDescriptor.start
    ).parameters
except Exception:  # pragma: no cover - defensive: API moved
    _COPY_PRIORITY = False

#: per-core SMEM budget the scalar-prefetched index segment must fit
#: (matches hglint HG503's model of PrefetchScalarGridSpec operands)
SMEM_BUDGET = 1 << 20
#: indices per pallas_call: 512 KB of the 1 MB SMEM budget
SEG = 1 << 17
# import-time twin of the hglint HG503 contract: one int32 index segment
# must leave SMEM headroom for Mosaic's own scalar state — a SEG bump that
# blows the budget should fail here, not in opaque Mosaic allocation
# (a real raise, not an assert: the guard must survive `python -O`)
if SEG * 4 > SMEM_BUDGET // 2:
    raise ValueError(
        "pallas_gather.SEG: scalar-prefetch segment exceeds half the "
        "SMEM budget"
    )
#: output chunks per grid step
G = 256
#: in-flight DMA slots (D*w outstanding row copies)
D = 16
#: below this many indices the XLA gather's lower fixed cost wins
MIN_INDICES = 1 << 15
#: per-core VMEM budget the kernel's working set must fit (see
#: ``_vmem_bytes``); matches hglint HG501's default budget
VMEM_BUDGET = 16 << 20


def _vmem_bytes(w: int, Kw: int) -> int:
    """Static VMEM working set of one ``_call``: the (G, Kw) uint32 output
    window double-buffered across grid steps + the (D*w, Kw) uint32 DMA
    row scratch. ``w``/``Kw`` are runtime-chosen, so hglint HG502 cannot
    fold this bound — this guard enforces it instead (the kernel would
    otherwise die in Mosaic allocation with an opaque error, or only on
    hardware while CPU interpret tests pass)."""
    return 4 * Kw * (2 * G + D * w)


def _kernel(idx_ref, values, out_ref, rows, sems, *, w, Kw):
    g = pl.program_id(0)

    def start(c, slot):
        base = g * G * w + c * w
        rbase = slot * w
        for j in range(w):
            copy = pltpu.make_async_copy(
                values.at[pl.ds(idx_ref[base + j], 1), :],
                rows.at[pl.ds(rbase + j, 1), :],
                sems.at[slot],
            )
            if _COPY_PRIORITY:
                copy.start(priority=j % 2)
            else:
                copy.start()

    for p in range(D):
        start(p, p)

    def body(c, _):
        slot = jax.lax.rem(c, D)
        pltpu.make_async_copy(
            rows.at[pl.ds(slot * w, w), :],
            rows.at[pl.ds(slot * w, w), :],
            sems.at[slot],
        ).wait()
        base = slot * w
        res = rows[pl.ds(base, 1), :]
        for j in range(1, w):
            res = res | rows[pl.ds(base + j, 1), :]
        out_ref[pl.ds(c, 1), :] = res

        @pl.when(c + D < G)
        def _():
            start(c + D, slot)

        return 0

    jax.lax.fori_loop(0, G, body, 0)


def _call(seg_idx: jax.Array, values: jax.Array, w: int,
          interpret: bool) -> jax.Array:
    Kw = values.shape[1]
    n_out = seg_idx.shape[0] // w
    # budget enforced by gather_or's _vmem_bytes guard (runtime shapes)
    return pl.pallas_call(  # hglint: disable=HG502
        functools.partial(_kernel, w=w, Kw=Kw),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_out // G,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((G, Kw), lambda i, s: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((D * w, Kw), jnp.uint32),
                            pltpu.SemaphoreType.DMA((D,))],
        ),
        out_shape=jax.ShapeDtypeStruct((n_out, Kw), jnp.uint32),
        interpret=interpret,
    )(seg_idx, values)


@hgverify.entry(
    shapes=lambda: (hgverify.sds((8, 128), "uint32"),
                    hgverify.sds((2048,), "int32")),
    statics={"w": 8, "interpret": True},
)
def gather_or(values: jax.Array, idx: jax.Array, w: int,
              interpret: bool = False) -> jax.Array:
    """``OR over groups of w``: returns ``(len(idx)//w, Kw)`` uint32 where
    row c = OR of ``values[idx[c*w : (c+1)*w]]``. ``len(idx) % w == 0`` and
    ``Kw % 128 == 0`` required. Trace-safe (callable under jit)."""
    E = idx.shape[0]
    Kw = values.shape[1]
    if E % w or Kw % 128:
        raise ValueError(f"gather_or: need len(idx) % {w} == 0 and "
                         f"Kw % 128 == 0, got E={E} Kw={Kw}")
    if SEG % (G * w):
        # segmenting slices idx in SEG blocks of whole G-chunk groups; a
        # width that doesn't divide them would truncate the grid to zero
        # and return an unwritten buffer
        raise ValueError(f"gather_or: w={w} must divide SEG/G={SEG // G}")
    if _vmem_bytes(w, Kw) > VMEM_BUDGET:
        raise ValueError(
            f"gather_or: VMEM working set {_vmem_bytes(w, Kw)} B "
            f"(w={w}, Kw={Kw}) exceeds the {VMEM_BUDGET} B per-core "
            f"budget; narrow the rows or fall back to the XLA gather"
        )
    n_out = E // w
    # pad to whole G-chunk blocks (pad chunks gather row 0 and are sliced
    # off — chunks are independent, so garbage rows never mix in)
    blk = G * w
    seg_pad = min(SEG, _ceil(E, blk))
    E_pad = _ceil(E, seg_pad)
    if E_pad != E:
        idx = jnp.concatenate(
            [idx, jnp.zeros((E_pad - E,), dtype=idx.dtype)]
        )
    if E_pad <= SEG:
        out = _call(idx, values, w, interpret)
    else:
        _, outs = jax.lax.scan(
            lambda c, s: (c, _call(s, values, w, interpret)),
            None, idx.reshape(E_pad // SEG, SEG),
        )
        out = outs.reshape(E_pad // w, Kw)
    return out[:n_out] if E_pad != E else out


def _ceil(x: int, m: int) -> int:
    return -(-x // m) * m


_PREFLIGHT: dict[str, bool] = {}


def pallas_ok() -> bool:
    """True when the kernel compiles and runs on the default backend —
    probed once with a tiny instance, cached. Guarded by
    ``HG_PALLAS_GATHER`` (default on)."""
    if os.environ.get("HG_PALLAS_GATHER", "1") in ("0", "false", "no"):
        return False
    backend = jax.default_backend()
    hit = _PREFLIGHT.get(backend)
    if hit is not None:
        return hit
    if backend != "tpu":
        _PREFLIGHT[backend] = False
        return False
    try:
        vals = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)
        idx = jnp.asarray(np.tile(np.arange(8, dtype=np.int32), G))
        out = gather_or(vals, idx, 8)
        expect = np.bitwise_or.reduce(
            np.asarray(vals)[np.asarray(idx)].reshape(-1, 8, 128), axis=1
        )
        ok = bool(np.array_equal(np.asarray(out), expect))
    except Exception:  # noqa: BLE001 - any compile/runtime failure → XLA path
        ok = False
    _PREFLIGHT[backend] = ok
    return ok
