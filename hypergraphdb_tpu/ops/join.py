"""Batched worst-case-optimal join executor: leapfrog as vector kernels.

The device lowering of ``join/planner.JoinPlan``: a binding table of
variable columns grows one variable per step, exactly the TrieJax
execution model (PAPERS.md — per-variable multiway set intersections)
vectorized the way every kernel in this repo is: K independent requests
ride one padded batch, intersections are branchless binary searches
against CSR rows (``ops/setops.segment_member_mask``'s discipline), and
the binding table lives in **shape buckets** so a long-running server
compiles a bounded program set.

Per step::

    keys    = column j of the table (or a per-request constant)
    cand    = CSR row gather of keys           (K·R, pad)   — expansion
    cand   &= cand ∈ row(other)                per filter   — leapfrog
    cand   &= type/distinct masks
    table'  = compact survivors into the next row bucket

Truncation honesty: a CSR row wider than the expansion pad, or a
compaction that would overflow the row bucket, flags the owning request
in ``trunc`` — its count is then a LOWER bound and its prefix honest,
and the serving tier re-serves exactly that request on host
(``serve/runtime``'s exact-at-collect discipline). Nothing is silently
dropped.

The co-incidence relation (two atoms sharing a link — the pattern edge)
is materialized once per snapshot as :func:`neighbor_csr`, the binary
adjacency the reference's ZigZag join walks through B-tree cursors
(``impl/ZigZagIntersectionResult.java:37-75``), here two flat arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.setops import SENTINEL, _bucket
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

#: exemplar candidate slots (R × pad) of the registered
#: ``join_expand_step`` trace — normalizes the committed hgverify byte
#: budget into the planner's bytes-per-probe cost constant
EXEMPLAR_SLOTS = 8 * 8

#: default binding-table row cap (rows per batch, all requests pooled)
DEFAULT_ROW_CAP = 1 << 15

#: default expansion-pad cap (CSR rows wider than this flag truncation);
#: the effective per-step pad is additionally bounded by ``slot_budget``
#: divided by the live row count, so a wide pad is only ever paid while
#: the table is narrow
DEFAULT_PAD_CAP = 1 << 10

#: default candidate-slot budget per expand step (rows × pad) — the
#: executor's peak-memory bound: 2^25 int32 slots ≈ 128 MB
DEFAULT_SLOT_BUDGET = 1 << 25

#: co-incidence materialization budget, in ordered pairs (Σ arity·(a-1)
#: over links). Past it the relation itself is gigabytes and the build
#: would stall (or OOM) whatever thread asked — callers decline to the
#: host path instead. Override: HG_JOIN_MAX_NBR_PAIRS, hard-clamped
#: below int32 range: the CSR offsets (and the device kernels' gather
#: indices) are int32, so a larger relation would silently wrap —
#: corrupt-but-exact-looking answers, the one failure mode this
#: subsystem's truncation-honest contract forbids.
NBR_MAX_PAIRS = min(
    int(__import__("os").environ.get("HG_JOIN_MAX_NBR_PAIRS", 1 << 28)),
    (1 << 31) - 256,
)


# ---------------------------------------------------------------- nbr CSR


def nbr_pair_count(snap: CSRSnapshot) -> int:
    """Ordered co-incidence pairs the snapshot's links imply (before
    dedupe) — the build cost AND an upper bound on the relation's size,
    O(N) from the arity column."""
    ar = snap.arity[: snap.num_atoms].astype(np.int64)
    return int((ar * np.maximum(ar - 1, 0)).sum())


def neighbor_csr(snap: CSRSnapshot) -> tuple[np.ndarray, np.ndarray]:
    """The co-incidence adjacency as a CSR, cached on the snapshot:
    ``flat[offsets[u]:offsets[u+1]]`` = sorted unique atoms sharing at
    least one link with ``u`` (never ``u`` itself — the relation is
    irreflexive, see ``conditions.CoIncident``). Row ``N`` (the dummy)
    is empty. Built vectorized from the target relation: every link
    contributes all ordered pairs of its distinct targets."""
    cached = getattr(snap, "_nbr_csr", None)
    if cached is not None:
        return cached
    pairs = nbr_pair_count(snap)
    if pairs > NBR_MAX_PAIRS:
        from hypergraphdb_tpu.join.ir import JoinUnsupported

        raise JoinUnsupported(
            f"co-incidence relation would materialize {pairs} pairs "
            f"(budget {NBR_MAX_PAIRS}, HG_JOIN_MAX_NBR_PAIRS); joins on "
            "this snapshot run on the host path"
        )
    N = snap.num_atoms
    e = snap.n_edges_tgt
    t = snap.tgt_flat[:e].astype(np.int64)
    src = snap.tgt_src[:e].astype(np.int64)
    if e:
        # entries are grouped by link (records ascending); for entry i of
        # a link with arity a, pair it with all a entries of that link
        lens_link = np.asarray(
            snap.tgt_offsets[1:] - snap.tgt_offsets[:-1], dtype=np.int64
        )
        a_e = lens_link[src]                       # owning link's arity
        ss_e = snap.tgt_offsets[src].astype(np.int64)  # segment start
        left = np.repeat(t, a_e)
        co_pos = np.repeat(ss_e, a_e) + (
            np.arange(int(a_e.sum()), dtype=np.int64)
            - np.repeat(np.cumsum(a_e) - a_e, a_e)
        )
        right = t[co_pos]
        keep = left != right                       # irreflexive by VALUE
        left, right = left[keep], right[keep]
        order = np.lexsort((right, left))
        left, right = left[order], right[order]
        if len(left):
            uniq = np.ones(len(left), dtype=bool)
            uniq[1:] = (left[1:] != left[:-1]) | (right[1:] != right[:-1])
            left, right = left[uniq], right[uniq]
    else:
        left = right = np.empty(0, dtype=np.int64)
    offsets = np.zeros(N + 2, dtype=np.int32)
    np.cumsum(np.bincount(left, minlength=N + 1), out=offsets[1: N + 2])
    flat = right.astype(np.int32)
    if len(flat) % 128:
        pad = np.full(128 - len(flat) % 128, N, dtype=np.int32)
        flat = np.concatenate([flat, pad])
    elif not len(flat):
        flat = np.full(128, N, dtype=np.int32)
    out = (offsets, flat)
    object.__setattr__(snap, "_nbr_csr", out)
    return out


def neighbor_csr_device(snap: CSRSnapshot):
    """Device twin of :func:`neighbor_csr`, uploaded once per snapshot."""
    cached = getattr(snap, "_nbr_csr_dev", None)
    if cached is not None:
        return cached
    offsets, flat = neighbor_csr(snap)
    out = (jnp.asarray(offsets), jnp.asarray(flat))
    object.__setattr__(snap, "_nbr_csr_dev", out)
    return out


# ---------------------------------------------------------------- kernels


def _member_elementwise(flat, starts, ends, queries):
    """``queries[i, j] ∈ flat[starts[i, j]:ends[i, j]]`` — the
    elementwise-bounds twin of ``setops.segment_member_mask`` (there the
    segment is per ROW; here per element, for reversed membership tests
    whose segment comes from the candidate itself)."""
    emax = flat.shape[0] - 1
    lo = starts.astype(jnp.int32)
    hi = ends.astype(jnp.int32)

    def body(_, state):
        lo, hi = state
        active = lo < hi
        mid = (lo + hi) >> 1
        v = flat[jnp.minimum(mid, emax)]
        go_right = v < queries
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    found = flat[jnp.minimum(lo, emax)]
    return (lo < ends.astype(jnp.int32)) & (found == queries) \
        & (queries != SENTINEL)


@hgverify.entry(
    shapes=lambda: (
        (hgverify.sds((33,), "int32"), hgverify.sds((64,), "int32"),
         hgverify.sds((8, 1), "int32"), hgverify.sds((8,), "int32"),
         hgverify.sds((8,), "bool"), hgverify.sds((4, 2), "int32"),
         (hgverify.sds((33,), "int32"),),
         (hgverify.sds((64,), "int32"),),
         hgverify.sds((32,), "int32")),
        {},
    ),
    statics={
        "exp_sel": ("const", 0),
        "filt_sel": ((False, "col", 0),),
        "type_handle": -1,
        "pad": 8, "rows_out": 16, "n_lanes": 4,
        "n_distinct_cols": 1, "distinct_consts": True, "dedupe": False,
    },
)
@partial(jax.jit, static_argnames=(
    "exp_sel", "filt_sel", "type_handle", "pad", "rows_out", "n_lanes",
    "n_distinct_cols", "distinct_consts", "dedupe", "value_ops",
))
def join_expand_step(
    exp_offsets: jax.Array,   # (N+2,) int32 — expansion CSR offsets
    exp_flat: jax.Array,      # (E,) int32 — expansion CSR payload
    cols: jax.Array,          # (R, T) int32 bound binding columns (T ≥ 0)
    lanes: jax.Array,         # (R,) int32 request lane per binding row
    valid: jax.Array,         # (R,) bool
    consts: jax.Array,        # (n_lanes, A) int32 per-request constants
    filt_offsets: tuple,      # one (N+2,) per membership filter
    filt_flats: tuple,        # one (E',) per membership filter
    type_of: jax.Array,       # (N+1,) int32
    value_cols: Optional[tuple] = None,  # (rank_hi, rank_lo, kind) (N+1,)
    value_win: Optional[jax.Array] = None,  # (5,) uint32: kind + bound words
    *,
    exp_sel: tuple,           # ("col", j) | ("const", slot)
    filt_sel: tuple,          # ((rev, "col"|"const", idx), ...)
    type_handle: int,         # -1 = unconstrained
    pad: int,                 # expansion width bucket
    rows_out: int,            # binding-row bucket after this step
    n_lanes: int,             # request lanes (K)
    n_distinct_cols: int,     # earlier columns candidates must differ from
    distinct_consts: bool,    # candidates must differ from every constant
    dedupe: bool,             # expansion rows may repeat values (tgt)
    value_ops: Optional[tuple] = None,  # (lo_op|None, hi_op|None) — a
    # value-rank window on THIS step's candidates (the hgindex planner
    # hook: a value predicate pruning the intersection instead of
    # post-filtering the result); None keeps the trace unchanged
) -> tuple:
    """Bind ONE variable for every binding row of a K-request batch:
    expand candidates from the keyed CSR row, leapfrog-intersect against
    the filter relations, and compact survivors into the next row
    bucket. Returns ``(cols', lanes', valid', lane_counts, lane_trunc)``
    — counts are THIS step's exact per-request survivor totals (counted
    before compaction, so a bucket overflow never corrupts them);
    ``lane_trunc`` flags requests whose expansion row overflowed ``pad``
    or whose survivors overflowed ``rows_out``."""
    R, T = cols.shape
    dummy = type_of.shape[0] - 1

    def key_of(sel):
        kind, idx = sel
        k = cols[:, idx] if kind == "col" else consts[lanes, idx]
        return jnp.where(valid, k, dummy)

    key = key_of(exp_sel)
    starts = exp_offsets[key]
    ends = exp_offsets[key + 1]
    widths = ends - starts
    over_row = (widths > pad) & valid
    lane_ix = jnp.arange(pad, dtype=jnp.int32)
    cmask = lane_ix[None, :] < jnp.minimum(widths, pad)[:, None]
    idx = jnp.minimum(starts[:, None] + lane_ix[None, :],
                      exp_flat.shape[0] - 1)
    cand = jnp.where(cmask, exp_flat[idx], SENTINEL)
    cmask = cmask & valid[:, None]
    if dedupe:
        # target tuples may repeat a value; keep the first occurrence so
        # binding rows stay DISTINCT tuples. Sort-based — stable argsort
        # keeps equal values in position order, so marking each sorted
        # element equal to its predecessor drops every occurrence but
        # the first at O(pad·log pad) per row (a pairwise compare would
        # be O(pad²) elements and a (pad, pad) constant — at the
        # one-shot path's wide pads, gigabytes)
        ord_ = jnp.argsort(cand, axis=1)
        sc = jnp.take_along_axis(cand, ord_, axis=1)
        dup_sorted = jnp.concatenate(
            [jnp.zeros((R, 1), bool), sc[:, 1:] == sc[:, :-1]], axis=1
        )
        dup = jnp.zeros_like(dup_sorted).at[
            jnp.arange(R, dtype=jnp.int32)[:, None], ord_
        ].set(dup_sorted)
        cmask = cmask & ~dup
    safe = jnp.where(cmask, cand, dummy)
    for (rev, kind, kidx), off_f, flat_f in zip(
        filt_sel, filt_offsets, filt_flats
    ):
        o = key_of((kind, kidx))
        if not rev:
            # candidate ∈ row(key): per-row segment, shared bounds
            from hypergraphdb_tpu.ops.setops import segment_member_mask

            cmask = cmask & segment_member_mask(
                flat_f, off_f[o], off_f[o + 1], cand
            )
        else:
            # key ∈ row(candidate): per-element segments
            qo = jnp.broadcast_to(o[:, None], cand.shape)
            cmask = cmask & _member_elementwise(
                flat_f, off_f[safe], off_f[safe + 1], qo
            )
    if type_handle >= 0:
        cmask = cmask & (type_of[safe] == type_handle)
    if value_ops is not None:
        # rank-window leapfrog: gather each candidate's order-preserving
        # value rank words + kind byte and compare against the window —
        # pure vector compute, applied BEFORE compaction so out-of-range
        # candidates never occupy binding rows (``ops/setops``'s rank
        # convention: 64-bit ranks as two uint32 words, hi then lo;
        # cross-kind comparisons are always False)
        vh = value_cols[0][safe]
        vl = value_cols[1][safe]
        vk = value_cols[2][safe].astype(jnp.uint32)
        cmask = cmask & (vk == value_win[0])
        lo_op, hi_op = value_ops
        if lo_op is not None:
            gt = (vh > value_win[1]) | ((vh == value_win[1])
                                        & (vl > value_win[2]))
            eq = (vh == value_win[1]) & (vl == value_win[2])
            cmask = cmask & (gt | eq if lo_op == "gte" else gt)
        if hi_op is not None:
            gt = (vh > value_win[3]) | ((vh == value_win[3])
                                        & (vl > value_win[4]))
            eq = (vh == value_win[3]) & (vl == value_win[4])
            cmask = cmask & (~gt if hi_op == "lte" else ~gt & ~eq)
    for j in range(n_distinct_cols):
        cmask = cmask & (cand != cols[:, j, None])
    if distinct_consts:
        for s in range(consts.shape[1]):
            cmask = cmask & (cand != consts[lanes, s][:, None])
    lane_counts = jnp.zeros(n_lanes, jnp.int32).at[lanes].add(
        cmask.sum(axis=1, dtype=jnp.int32)
    )
    # compaction: survivors first (stable — canonical row order is
    # preserved), into the next bucket
    flat_mask = cmask.reshape(-1)
    src_row = jnp.repeat(jnp.arange(R, dtype=jnp.int32), pad)
    order = jnp.argsort(~flat_mask)
    sel = order[:rows_out]
    new_valid = flat_mask[sel]
    rsel = src_row[sel]
    new_cols = jnp.concatenate(
        [cols[rsel], cand.reshape(-1)[sel][:, None]], axis=1
    )
    new_lanes = lanes[rsel]
    dropped = order[rows_out:]
    trunc_i = jnp.zeros(n_lanes, jnp.int32)
    trunc_i = trunc_i.at[lanes[src_row[dropped]]].add(
        flat_mask[dropped].astype(jnp.int32), mode="drop"
    )
    trunc_i = trunc_i.at[lanes].add(over_row.astype(jnp.int32))
    return new_cols, new_lanes, new_valid, lane_counts, trunc_i > 0


@hgverify.entry(
    shapes=lambda: (hgverify.sds((16, 2), "int32"),
                    hgverify.sds((16,), "int32"),
                    hgverify.sds((16,), "bool")),
    statics={"top_r": 4, "n_lanes": 4, "sort_cols": (0, 1)},
)
@partial(jax.jit, static_argnames=("top_r", "n_lanes", "sort_cols"))
def join_finalize(
    cols: jax.Array,   # (R, V) int32 complete binding rows
    lanes: jax.Array,  # (R,) int32
    valid: jax.Array,  # (R,) bool
    *,
    top_r: int,
    n_lanes: int,
    sort_cols: tuple,  # column indices in sort priority (highest first)
) -> jax.Array:
    """Compact per-request result prefixes: the first ``top_r`` binding
    tuples of every lane, ascending lexicographically by ``sort_cols``
    (the caller passes the REQUEST's variable order mapped onto the
    plan's column layout, so prefixes read canonically however the
    planner reordered) — ``(n_lanes, top_r, V)`` int32, -1-padded. The
    download per batch is O(K · top_r · V) however large the binding
    table ran."""
    R, V = cols.shape
    lane_k = jnp.where(valid, lanes, n_lanes)
    order = jnp.arange(R, dtype=jnp.int32)
    for j in reversed(sort_cols):
        order = order[jnp.argsort(cols[order, j])]
    order = order[jnp.argsort(lane_k[order])]
    sl = lane_k[order]
    idx = jnp.arange(R, dtype=jnp.int32)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sl[1:] != sl[:-1]]
    )
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, idx, 0)
    )
    pos = idx - seg_start
    rows = cols[order]
    out = jnp.full((n_lanes, top_r, V), -1, jnp.int32)
    return out.at[sl, pos].set(rows, mode="drop")


# ---------------------------------------------------------------- execution


@dataclass
class JoinExecution:
    """Async device handles of one executed join batch — pair with
    ``np.asarray`` / :meth:`full_bindings` to sync. ``counts[k]`` is
    exact unless ``trunc[k]`` (then a lower bound — the serving tier
    re-serves that request on host)."""

    order: tuple
    counts: jax.Array                  # (K,) int32
    trunc: jax.Array                   # (K,) bool
    tuples: Optional[jax.Array] = None  # (K, top_r, V) int32, -1 pad
    cols: Optional[jax.Array] = None    # full mode: final binding rows
    lanes: Optional[jax.Array] = None
    valid: Optional[jax.Array] = None

    def full_bindings(self, lane: int) -> np.ndarray:
        """All complete binding rows of one request lane, host-side —
        (n, V) int64 in canonical (table) order."""
        if self.cols is None:
            raise ValueError("execute_join(full=True) required")
        cols = np.asarray(self.cols)
        keep = np.asarray(self.valid) & (np.asarray(self.lanes) == lane)
        return cols[keep].astype(np.int64)


def _rel_arrays(snap: CSRSnapshot, dev, rel: str):
    if rel == "co":
        return neighbor_csr_device(snap)
    if rel == "inc":
        return dev.inc_offsets, dev.inc_links
    return dev.tgt_offsets, dev.tgt_flat


def _rel_host_offsets(snap: CSRSnapshot, rel: str):
    if rel == "co":
        return neighbor_csr(snap)[0]
    if rel == "inc":
        return snap.inc_offsets
    return snap.tgt_offsets


def _rel_max_width(snap: CSRSnapshot, rel: str) -> int:
    """The relation's widest row — a per-(snapshot, relation) invariant,
    cached like ``_nbr_csr``: recomputing the O(N) diff+max per step per
    dispatch would charge pure host bookkeeping to every timed device
    window (the c7 bench runs 64 dispatches per rep)."""
    cache = getattr(snap, "_join_wmax", None)
    if cache is None:
        cache = {}
        object.__setattr__(snap, "_join_wmax", cache)
    if rel not in cache:
        off = np.asarray(_rel_host_offsets(snap, rel)[: snap.num_atoms + 1],
                         dtype=np.int64)
        cache[rel] = int(np.max(np.diff(off), initial=1))
    return cache[rel]


def execute_join(
    snap: CSRSnapshot,
    plan,                    # join/planner.JoinPlan
    consts: np.ndarray,      # (K, n_consts) int32 — per-request constants
    *,
    top_r: int = 16,
    full: bool = False,      # keep the final binding table downloadable
    count_only: bool = False,
    seeds: Optional[np.ndarray] = None,  # pre-bound var-0 candidates
    row_cap: int = DEFAULT_ROW_CAP,
    pad_cap: int = DEFAULT_PAD_CAP,
    var_pad_max: bool = False,
    n_real: Optional[int] = None,
    slot_budget: int = DEFAULT_SLOT_BUDGET,
    value_windows: Optional[dict] = None,
) -> JoinExecution:
    """Run ``plan`` for K same-signature requests in one batched pass —
    async (no host sync; every return field is a device handle).

    Shape policy (the compile-bounding half of the contract): expansion
    pads for constant-keyed steps come from the BATCH's actual maximum
    row width, power-of-two bucketed and capped at ``pad_cap``;
    variable-keyed steps use the plan's estimate bucket
    (``var_pad_max=True`` pays the relation's true max row width instead
    — the exact-count mode the c7 bench runs). Row buckets grow
    multiplicatively and cap at ``row_cap``. Anything the caps cut
    off surfaces per request in ``trunc`` — never silently.

    ``seeds`` replaces the first step: the given ids become the var-0
    binding column of ONE request lane (the benchmark's global-counting
    mode — chunk the id space, sum the counts).

    ``value_windows`` maps a plan variable to a value-rank window
    ``(kind, lo_rank, lo_op, hi_rank, hi_op)`` (64-bit ranks, ops
    gt/gte/lt/lte, None = open) applied as a candidate filter INSIDE the
    step binding that variable — the hgindex planner hook: a value
    predicate prunes the intersection instead of post-filtering, so
    out-of-window candidates never cost binding rows. Callers own kind
    exactness (fixed-width kinds only; rank ties on variable-width kinds
    would silently drop true matches)."""
    dev = snap.device
    K, A = (int(consts.shape[0]), int(consts.shape[1]))
    consts = np.ascontiguousarray(consts, dtype=np.int32)
    consts_dev = jnp.asarray(consts) if A else jnp.zeros((K, 0), jnp.int32)
    if seeds is None:
        cols = jnp.zeros((K, 0), jnp.int32)
        lanes = jnp.arange(K, dtype=jnp.int32)
        # pad lanes (serving's pad-to-bucket shapes) start invalid: they
        # cost their slots but never gather, count, or truncate
        valid = (jnp.ones(K, bool) if n_real is None
                 else jnp.arange(K, dtype=jnp.int32) < int(n_real))
        steps = plan.steps
    else:
        if K != 1:
            raise ValueError("seeds mode is single-lane (K == 1)")
        seeds = np.asarray(seeds, dtype=np.int32)
        cols = jnp.asarray(seeds)[:, None]
        lanes = jnp.zeros(len(seeds), jnp.int32)
        valid = jnp.ones(len(seeds), bool)
        steps = plan.steps[1:]
    trunc = jnp.zeros(K, bool)
    # a 1-variable plan in seeds mode has no steps left: the seeds ARE
    # the complete bindings
    counts = (jnp.zeros(K, jnp.int32).at[lanes].add(valid.astype(jnp.int32))
              if seeds is not None and not steps
              else jnp.zeros(K, jnp.int32))
    vwindows = value_windows or {}
    for s in steps:
        R = int(cols.shape[0])
        if s.source_key.kind == "const":
            off_h = _rel_host_offsets(snap, s.source_rel)
            # real lanes only: zero-filled pad lanes would price every
            # sparse batch's pad by atom 0's row (a hub in age-ordered
            # id spaces)
            real = consts if n_real is None else consts[:n_real]
            keys = np.clip(real[:, s.source_key.index], 0, snap.num_atoms)
            w = int(np.max(off_h[keys + 1] - off_h[keys], initial=1))
        elif var_pad_max:
            # exact-count mode (bench): pay the relation's true max row
            # width so only the pad_cap itself can truncate
            w = _rel_max_width(snap, s.source_rel)
        else:
            # the estimate is a relation AVERAGE; 4× headroom keeps
            # ordinary rows in-pad (hubs past it flag trunc honestly)
            w = 4 * (int(s.width_est) + 1)
        # the pad is additionally bounded by the candidate-slot budget
        # (R × pad is the step's peak tensor): a one-row table may pay a
        # six-figure pad (wide one-shot anchors), a deep table only a
        # narrow one — constant memory either way
        pad = _bucket(
            max(min(w, pad_cap, max(slot_budget // max(R, 1), 8)), 1),
            minimum=8,
        )
        rows_out = min(_bucket(R * pad), row_cap, R * pad)
        exp_off, exp_flat = _rel_arrays(snap, dev, s.source_rel)
        filt_sel = []
        filt_offs = []
        filt_flats = []
        for f in s.filters:
            fo, ff = _rel_arrays(snap, dev, f.rel)
            filt_sel.append((f.rev, f.key.kind, f.key.index))
            filt_offs.append(fo)
            filt_flats.append(ff)
        n_dist = int(cols.shape[1]) if plan.distinct else 0
        win = vwindows.get(s.var)
        vcols = vwin = None
        vops = None
        if win is not None:
            kind, lo_r, lo_op, hi_r, hi_op = win
            vcols = (dev.value_rank_hi, dev.value_rank_lo, dev.value_kind)
            words = np.asarray(
                [int(kind),
                 (lo_r or 0) >> 32, (lo_r or 0) & 0xFFFFFFFF,
                 (hi_r or 0) >> 32, (hi_r or 0) & 0xFFFFFFFF],
                dtype=np.uint64,
            ).astype(np.uint32)
            vwin = jnp.asarray(words)
            vops = (lo_op, hi_op)
        cols, lanes, valid, counts, step_trunc = join_expand_step(
            exp_off, exp_flat, cols, lanes, valid, consts_dev,
            tuple(filt_offs), tuple(filt_flats), dev.type_of,
            vcols, vwin,
            exp_sel=(s.source_key.kind, s.source_key.index),
            filt_sel=tuple(filt_sel),
            type_handle=(-1 if s.type_handle is None
                         else int(s.type_handle)),
            pad=pad, rows_out=rows_out, n_lanes=K,
            n_distinct_cols=n_dist,
            distinct_consts=plan.distinct and A > 0,
            dedupe=s.dedupe,
            value_ops=vops,
        )
        trunc = trunc | step_trunc
    out = JoinExecution(order=plan.order, counts=counts, trunc=trunc)
    if count_only:
        return out
    if top_r > 0:
        sort_cols = tuple(
            plan.order.index(v) for v in plan.sig.vars
        )
        out.tuples = join_finalize(cols, lanes, valid,
                                   top_r=top_r, n_lanes=K,
                                   sort_cols=sort_cols)
    if full:
        out.cols, out.lanes, out.valid = cols, lanes, valid
    return out
