"""Batched worst-case-optimal join executor: leapfrog as vector kernels.

The device lowering of ``join/planner.JoinPlan``: a binding table of
variable columns grows one variable per step, exactly the TrieJax
execution model (PAPERS.md — per-variable multiway set intersections)
vectorized the way every kernel in this repo is: K independent requests
ride one padded batch, intersections are branchless binary searches
against CSR rows (``ops/setops.segment_member_mask``'s discipline), and
the binding table lives in **shape buckets** so a long-running server
compiles a bounded program set.

Per step::

    keys    = column j of the table (or a per-request constant)
    cand    = CSR row gather of keys           (K·R, pad)   — expansion
    cand   &= cand ∈ row(other)                per filter   — leapfrog
    cand   &= type/distinct masks
    table'  = compact survivors into the next row bucket

Truncation honesty: a CSR row wider than the expansion pad, or a
compaction that would overflow the row bucket, flags the owning request
in ``trunc`` — its count is then a LOWER bound and its prefix honest,
and the serving tier re-serves exactly that request on host
(``serve/runtime``'s exact-at-collect discipline). Nothing is silently
dropped.

Join engine v2 (this module's three upgrades, all behind
:func:`execute_join`'s existing contract):

* **Degree-split plans** — lanes whose const-keyed rows exceed the hub
  threshold (``join/planner.hub_lane_mask``) run their whole step chain
  through :func:`join_hub_expand`, a chunked dense-frontier kernel that
  streams a row of ANY width in fixed ``block``-wide tiles (an ELL-style
  expand with the same leapfrog filters per tile) — hub anchors stop
  truncating on expansion width entirely; tail lanes keep the single-
  gather fast path with pads sized to TAIL widths only.
* **Factorized trie relations** — :func:`factorized_relations` builds a
  prefix-grouped encoding of the co-incidence/target CSRs once per
  pinned epoch (cached on the snapshot beside the existing device
  twins): identical rows collapse to one stored group (TrieJax's shared
  trie prefix — every member of a link shares the link's target run),
  so K lanes probing equal rows touch one HBM copy. The co groups store
  CLOSED rows (self included — that is what makes same-link rows equal);
  the kernels re-impose irreflexivity with a one-compare mask.
* **Bushy GHD bags** — ``join/planner.BushyJoinPlan`` chains execute
  each variable-connected component as its own bag (small intermediate
  tables, materialized on device) and :func:`join_bag_join` joins bag
  outputs onto the spine with the cross-component distinctness masks.

The co-incidence relation (two atoms sharing a link — the pattern edge)
is materialized once per snapshot as :func:`neighbor_csr`, the binary
adjacency the reference's ZigZag join walks through B-tree cursors
(``impl/ZigZagIntersectionResult.java:37-75``), here two flat arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.setops import SENTINEL, _bucket
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

#: exemplar candidate slots (R × pad) of the registered
#: ``join_expand_step`` trace — normalizes the committed hgverify byte
#: budget into the planner's bytes-per-probe cost constant
EXEMPLAR_SLOTS = 8 * 8

#: default binding-table row cap (rows per batch, all requests pooled)
DEFAULT_ROW_CAP = 1 << 15

#: default expansion-pad cap (CSR rows wider than this flag truncation);
#: the effective per-step pad is additionally bounded by ``slot_budget``
#: divided by the live row count, so a wide pad is only ever paid while
#: the table is narrow
DEFAULT_PAD_CAP = 1 << 10

#: default candidate-slot budget per expand step (rows × pad) — the
#: executor's peak-memory bound: 2^25 int32 slots ≈ 128 MB
DEFAULT_SLOT_BUDGET = 1 << 25

#: default dense-frontier chunk width of the hub chain: a hub row is
#: streamed ``HUB_BLOCK`` candidates per tile however wide it is, so the
#: hub path's peak tensor is rows × block — never rows × row-width
DEFAULT_HUB_BLOCK = 1 << 9

#: co-incidence materialization budget, in ordered pairs (Σ arity·(a-1)
#: over links). Past it the relation itself is gigabytes and the build
#: would stall (or OOM) whatever thread asked — callers decline to the
#: host path instead. Override: HG_JOIN_MAX_NBR_PAIRS, hard-clamped
#: below int32 range: the CSR offsets (and the device kernels' gather
#: indices) are int32, so a larger relation would silently wrap —
#: corrupt-but-exact-looking answers, the one failure mode this
#: subsystem's truncation-honest contract forbids.
NBR_MAX_PAIRS = min(
    int(__import__("os").environ.get("HG_JOIN_MAX_NBR_PAIRS", 1 << 28)),
    (1 << 31) - 256,
)


# ---------------------------------------------------------------- nbr CSR


def nbr_pair_count(snap: CSRSnapshot) -> int:
    """Ordered co-incidence pairs the snapshot's links imply (before
    dedupe) — the build cost AND an upper bound on the relation's size,
    O(N) from the arity column."""
    ar = snap.arity[: snap.num_atoms].astype(np.int64)
    return int((ar * np.maximum(ar - 1, 0)).sum())


def neighbor_csr(snap: CSRSnapshot) -> tuple[np.ndarray, np.ndarray]:
    """The co-incidence adjacency as a CSR, cached on the snapshot:
    ``flat[offsets[u]:offsets[u+1]]`` = sorted unique atoms sharing at
    least one link with ``u`` (never ``u`` itself — the relation is
    irreflexive, see ``conditions.CoIncident``). Row ``N`` (the dummy)
    is empty. Built vectorized from the target relation: every link
    contributes all ordered pairs of its distinct targets."""
    cached = getattr(snap, "_nbr_csr", None)
    if cached is not None:
        return cached
    pairs = nbr_pair_count(snap)
    if pairs > NBR_MAX_PAIRS:
        from hypergraphdb_tpu.join.ir import JoinUnsupported

        raise JoinUnsupported(
            f"co-incidence relation would materialize {pairs} pairs "
            f"(budget {NBR_MAX_PAIRS}, HG_JOIN_MAX_NBR_PAIRS); joins on "
            "this snapshot run on the host path"
        )
    N = snap.num_atoms
    e = snap.n_edges_tgt
    t = snap.tgt_flat[:e].astype(np.int64)
    src = snap.tgt_src[:e].astype(np.int64)
    if e:
        # entries are grouped by link (records ascending); for entry i of
        # a link with arity a, pair it with all a entries of that link
        lens_link = np.asarray(
            snap.tgt_offsets[1:] - snap.tgt_offsets[:-1], dtype=np.int64
        )
        a_e = lens_link[src]                       # owning link's arity
        ss_e = snap.tgt_offsets[src].astype(np.int64)  # segment start
        left = np.repeat(t, a_e)
        co_pos = np.repeat(ss_e, a_e) + (
            np.arange(int(a_e.sum()), dtype=np.int64)
            - np.repeat(np.cumsum(a_e) - a_e, a_e)
        )
        right = t[co_pos]
        keep = left != right                       # irreflexive by VALUE
        left, right = left[keep], right[keep]
        order = np.lexsort((right, left))
        left, right = left[order], right[order]
        if len(left):
            uniq = np.ones(len(left), dtype=bool)
            uniq[1:] = (left[1:] != left[:-1]) | (right[1:] != right[:-1])
            left, right = left[uniq], right[uniq]
    else:
        left = right = np.empty(0, dtype=np.int64)
    offsets = np.zeros(N + 2, dtype=np.int32)
    np.cumsum(np.bincount(left, minlength=N + 1), out=offsets[1: N + 2])
    flat = right.astype(np.int32)
    if len(flat) % 128:
        pad = np.full(128 - len(flat) % 128, N, dtype=np.int32)
        flat = np.concatenate([flat, pad])
    elif not len(flat):
        flat = np.full(128, N, dtype=np.int32)
    out = (offsets, flat)
    object.__setattr__(snap, "_nbr_csr", out)
    return out


def neighbor_csr_device(snap: CSRSnapshot):
    """Device twin of :func:`neighbor_csr`, uploaded once per snapshot."""
    cached = getattr(snap, "_nbr_csr_dev", None)
    if cached is not None:
        return cached
    offsets, flat = neighbor_csr(snap)
    out = (jnp.asarray(offsets), jnp.asarray(flat))
    object.__setattr__(snap, "_nbr_csr_dev", out)
    return out


# ------------------------------------------------------- factorized relations


@dataclass(frozen=True)
class FactorizedRelation:
    """A prefix-grouped (trie-style) row encoding of one CSR relation:
    identical rows collapse into one stored GROUP, so the flat payload
    holds each shared prefix run once instead of once per owning row
    (TrieJax's compressed-trie trick flattened to two levels). Row
    lookup is one extra indirection: ``flat[offsets[group_of[u]]:
    offsets[group_of[u] + 1]]``. Group 0 is the empty row (the dummy row
    maps there). ``closed=True`` marks the co relation's convention:
    rows INCLUDE the owning atom — that is what makes every member of a
    single shared link carry an identical row — and the kernels restore
    irreflexivity with a one-compare mask."""

    group_of: np.ndarray     # (N+1,) int32 — row -> group id
    offsets: np.ndarray      # (G+1,) int32 — group extents
    flat: np.ndarray         # (F,) int32 — unique row contents, padded
    n_groups: int
    entries: int             # Σ unique-group widths (pre-pad)
    entries_flat: int        # Σ per-row widths the flat CSR stores
    closed: bool
    max_width: int           # widest group (the var_pad_max bound)


def _group_rows(offsets: np.ndarray, flat: np.ndarray, n_rows: int,
                pad_value: int) -> tuple:
    """Group identical rows of a CSR, vectorized per length class (rows
    of one length form a dense matrix; ``np.unique(axis=0)`` lexsorts
    and collapses it). Returns ``(group_of, grp_offsets, grp_flat)``
    with group 0 reserved for the empty row."""
    offsets = np.asarray(offsets, dtype=np.int64)
    flat = np.asarray(flat, dtype=np.int64)
    lens = offsets[1: n_rows + 1] - offsets[:n_rows]
    group_of = np.zeros(n_rows + 1, dtype=np.int32)   # +1: the dummy row
    uniq_chunks = [np.empty(0, dtype=np.int64)]
    grp_lens: list = [0]                              # group 0 = empty
    next_g = 1
    for length in np.unique(lens):
        L = int(length)
        if L == 0:
            continue
        ids = np.flatnonzero(lens == L)
        mat = flat[offsets[ids][:, None] + np.arange(L, dtype=np.int64)]
        uniq, inv = np.unique(mat, axis=0, return_inverse=True)
        group_of[ids] = next_g + inv.astype(np.int32)
        next_g += len(uniq)
        uniq_chunks.append(uniq.reshape(-1))
        grp_lens.extend([L] * len(uniq))
    grp_offsets = np.zeros(next_g + 1, dtype=np.int32)
    grp_offsets[1:] = np.cumsum(np.asarray(grp_lens, dtype=np.int64))
    grp_flat = np.concatenate(uniq_chunks).astype(np.int32)
    if len(grp_flat) % 128:
        tail = np.full(128 - len(grp_flat) % 128, pad_value,
                       dtype=np.int32)
        grp_flat = np.concatenate([grp_flat, tail])
    elif not len(grp_flat):
        grp_flat = np.full(128, pad_value, dtype=np.int32)
    return group_of, grp_offsets, grp_flat


def _closed_co_csr(snap: CSRSnapshot) -> tuple[np.ndarray, np.ndarray]:
    """The co-incidence CSR with each non-empty row CLOSED under its
    owner (self inserted in sort position) — the content-equalizing
    transform: all k members of one k-ary link then share one row."""
    off, flat = neighbor_csr(snap)
    N = snap.num_atoms
    off64 = off[: N + 1].astype(np.int64)
    w = np.diff(off64)
    n_e = int(off64[N])
    left = np.repeat(np.arange(N, dtype=np.int64), w)
    right = flat[:n_e].astype(np.int64)
    selfs = np.flatnonzero(w > 0).astype(np.int64)
    left = np.concatenate([left, selfs])
    right = np.concatenate([right, selfs])
    order = np.lexsort((right, left))
    left, right = left[order], right[order]
    offsets = np.zeros(N + 2, dtype=np.int64)
    np.cumsum(np.bincount(left, minlength=N + 1),
              out=offsets[1: N + 2])
    return offsets, right


def factorized_relations(snap: CSRSnapshot) -> dict:
    """Build (or return the cached) factorized encodings of the co and
    tgt relations for one snapshot — once per pinned epoch, the
    ``_nbr_csr`` caching idiom. Raises ``JoinUnsupported`` when the co
    relation itself is over the pair budget (the build reads it)."""
    cached = getattr(snap, "_fact_rels", None)
    if cached is not None:
        return cached
    N = snap.num_atoms
    out = {}
    co_off, co_flat = _closed_co_csr(snap)
    g, o, f = _group_rows(co_off, co_flat, N, pad_value=N)
    out["co"] = FactorizedRelation(
        group_of=g, offsets=o, flat=f, n_groups=len(o) - 1,
        entries=int(o[-1]), entries_flat=int(co_off[N + 1]),
        closed=True,
        max_width=int(np.max(np.diff(o.astype(np.int64)), initial=1)),
    )
    e = snap.n_edges_tgt
    g, o, f = _group_rows(snap.tgt_offsets, snap.tgt_flat[:e], N,
                          pad_value=N)
    out["tgt"] = FactorizedRelation(
        group_of=g, offsets=o, flat=f, n_groups=len(o) - 1,
        entries=int(o[-1]), entries_flat=int(e), closed=False,
        max_width=int(np.max(np.diff(o.astype(np.int64)), initial=1)),
    )
    object.__setattr__(snap, "_fact_rels", out)
    return out


def factorized_relations_device(snap: CSRSnapshot) -> dict:
    """Device twins of :func:`factorized_relations`, uploaded once per
    snapshot: ``{rel: (group_of, offsets, flat)}`` jnp arrays."""
    cached = getattr(snap, "_fact_rels_dev", None)
    if cached is not None:
        return cached
    rels = factorized_relations(snap)
    out = {
        rel: (jnp.asarray(fr.group_of), jnp.asarray(fr.offsets),
              jnp.asarray(fr.flat))
        for rel, fr in rels.items()
    }
    object.__setattr__(snap, "_fact_rels_dev", out)
    return out


# ---------------------------------------------------------------- kernels


def _member_elementwise(flat, starts, ends, queries):
    """``queries[i, j] ∈ flat[starts[i, j]:ends[i, j]]`` — the
    elementwise-bounds twin of ``setops.segment_member_mask`` (there the
    segment is per ROW; here per element, for reversed membership tests
    whose segment comes from the candidate itself)."""
    emax = flat.shape[0] - 1
    lo = starts.astype(jnp.int32)
    hi = ends.astype(jnp.int32)

    def body(_, state):
        lo, hi = state
        active = lo < hi
        mid = (lo + hi) >> 1
        v = flat[jnp.minimum(mid, emax)]
        go_right = v < queries
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    found = flat[jnp.minimum(lo, emax)]
    return (lo < ends.astype(jnp.int32)) & (found == queries) \
        & (queries != SENTINEL)


def _norm_filt_sel(filt_sel: tuple) -> tuple:
    """Filter selectors as 4-tuples ``(rev, kind, idx, irref)`` —
    legacy 3-tuple call sites (the sharded lane program) read as
    irref=False."""
    return tuple(
        f if len(f) == 4 else (f[0], f[1], f[2], False) for f in filt_sel
    )


def _seg_of(offsets, group, keys):
    """Segment bounds of ``keys``'s rows, through the factorized group
    indirection when the relation is grouped (``group`` is its
    ``group_of`` column) — the one lookup difference between flat and
    trie-encoded relations."""
    g = keys if group is None else group[keys]
    return offsets[g], offsets[g + 1]


def _filter_masks(cand, cmask, safe, key_of, filt_sel, filt_offsets,
                  filt_flats, filt_groups):
    """The leapfrog intersection masks: one membership probe per filter
    relation, forward (candidate ∈ row(key)) or reversed (key ∈
    row(candidate)); ``irref`` filters additionally re-impose
    irreflexivity over CLOSED factorized co rows."""
    from hypergraphdb_tpu.ops.setops import segment_member_mask

    for (rev, kind, kidx, irref), off_f, flat_f, grp_f in zip(
        filt_sel, filt_offsets, filt_flats, filt_groups
    ):
        o = key_of((kind, kidx))
        if not rev:
            # candidate ∈ row(key): per-row segment, shared bounds
            s, e = _seg_of(off_f, grp_f, o)
            cmask = cmask & segment_member_mask(flat_f, s, e, cand)
            if irref:
                cmask = cmask & (cand != o[:, None])
        else:
            # key ∈ row(candidate): per-element segments
            qo = jnp.broadcast_to(o[:, None], cand.shape)
            s, e = _seg_of(off_f, grp_f, safe)
            cmask = cmask & _member_elementwise(flat_f, s, e, qo)
            if irref:
                cmask = cmask & (qo != safe)
    return cmask


def _value_window_mask(cmask, safe, value_cols, value_win, value_ops):
    """Rank-window leapfrog: gather each candidate's order-preserving
    value rank words + kind byte and compare against the window — pure
    vector compute, applied BEFORE compaction so out-of-range candidates
    never occupy binding rows (``ops/setops``'s rank convention: 64-bit
    ranks as two uint32 words, hi then lo; cross-kind comparisons are
    always False)."""
    vh = value_cols[0][safe]
    vl = value_cols[1][safe]
    vk = value_cols[2][safe].astype(jnp.uint32)
    cmask = cmask & (vk == value_win[0])
    lo_op, hi_op = value_ops
    if lo_op is not None:
        gt = (vh > value_win[1]) | ((vh == value_win[1])
                                    & (vl > value_win[2]))
        eq = (vh == value_win[1]) & (vl == value_win[2])
        cmask = cmask & (gt | eq if lo_op == "gte" else gt)
    if hi_op is not None:
        gt = (vh > value_win[3]) | ((vh == value_win[3])
                                    & (vl > value_win[4]))
        eq = (vh == value_win[3]) & (vl == value_win[4])
        cmask = cmask & (~gt if hi_op == "lte" else ~gt & ~eq)
    return cmask


def _distinct_masks(cmask, cand, cols, consts, lanes, n_distinct_cols,
                    distinct_consts):
    for j in range(n_distinct_cols):
        cmask = cmask & (cand != cols[:, j, None])
    if distinct_consts:
        for s in range(consts.shape[1]):
            cmask = cmask & (cand != consts[lanes, s][:, None])
    return cmask


@hgverify.entry(
    shapes=lambda: (
        (hgverify.sds((33,), "int32"), hgverify.sds((64,), "int32"),
         hgverify.sds((8, 1), "int32"), hgverify.sds((8,), "int32"),
         hgverify.sds((8,), "bool"), hgverify.sds((4, 2), "int32"),
         (hgverify.sds((33,), "int32"),),
         (hgverify.sds((64,), "int32"),),
         hgverify.sds((32,), "int32")),
        {},
    ),
    statics={
        "exp_sel": ("const", 0),
        "filt_sel": ((False, "col", 0, False),),
        "type_handle": -1,
        "pad": 8, "rows_out": 16, "n_lanes": 4,
        "n_distinct_cols": 1, "distinct_consts": True, "dedupe": False,
    },
)
@partial(jax.jit, static_argnames=(
    "exp_sel", "filt_sel", "type_handle", "pad", "rows_out", "n_lanes",
    "n_distinct_cols", "distinct_consts", "dedupe", "value_ops",
    "exp_irref",
))
def join_expand_step(
    exp_offsets: jax.Array,   # (N+2,) int32 — expansion CSR offsets
    exp_flat: jax.Array,      # (E,) int32 — expansion CSR payload
    cols: jax.Array,          # (R, T) int32 bound binding columns (T ≥ 0)
    lanes: jax.Array,         # (R,) int32 request lane per binding row
    valid: jax.Array,         # (R,) bool
    consts: jax.Array,        # (n_lanes, A) int32 per-request constants
    filt_offsets: tuple,      # one (N+2,) per membership filter
    filt_flats: tuple,        # one (E',) per membership filter
    type_of: jax.Array,       # (N+1,) int32
    value_cols: Optional[tuple] = None,  # (rank_hi, rank_lo, kind) (N+1,)
    value_win: Optional[jax.Array] = None,  # (5,) uint32: kind + bound words
    exp_group: Optional[jax.Array] = None,  # (N+1,) int32 — factorized
    # row->group indirection of the expansion relation (None = flat CSR)
    filt_groups: Optional[tuple] = None,    # per-filter group columns
    *,
    exp_sel: tuple,           # ("col", j) | ("const", slot)
    filt_sel: tuple,          # ((rev, "col"|"const", idx[, irref]), ...)
    type_handle: int,         # -1 = unconstrained
    pad: int,                 # expansion width bucket
    rows_out: int,            # binding-row bucket after this step
    n_lanes: int,             # request lanes (K)
    n_distinct_cols: int,     # earlier columns candidates must differ from
    distinct_consts: bool,    # candidates must differ from every constant
    dedupe: bool,             # expansion rows may repeat values (tgt)
    value_ops: Optional[tuple] = None,  # (lo_op|None, hi_op|None) — a
    # value-rank window on THIS step's candidates (the hgindex planner
    # hook: a value predicate pruning the intersection instead of
    # post-filtering the result); None keeps the trace unchanged
    exp_irref: bool = False,  # expansion rows are CLOSED (factorized co):
    # drop the self candidate to restore irreflexive semantics
) -> tuple:
    """Bind ONE variable for every binding row of a K-request batch:
    expand candidates from the keyed CSR row, leapfrog-intersect against
    the filter relations, and compact survivors into the next row
    bucket. Returns ``(cols', lanes', valid', lane_counts, lane_trunc)``
    — counts are THIS step's exact per-request survivor totals (counted
    before compaction, so a bucket overflow never corrupts them);
    ``lane_trunc`` flags requests whose expansion row overflowed ``pad``
    or whose survivors overflowed ``rows_out``."""
    R, T = cols.shape
    dummy = type_of.shape[0] - 1
    filt_sel = _norm_filt_sel(filt_sel)
    if filt_groups is None:
        filt_groups = (None,) * len(filt_sel)

    def key_of(sel):
        kind, idx = sel
        k = cols[:, idx] if kind == "col" else consts[lanes, idx]
        return jnp.where(valid, k, dummy)

    key = key_of(exp_sel)
    starts, ends = _seg_of(exp_offsets, exp_group, key)
    widths = ends - starts
    over_row = (widths > pad) & valid
    lane_ix = jnp.arange(pad, dtype=jnp.int32)
    cmask = lane_ix[None, :] < jnp.minimum(widths, pad)[:, None]
    idx = jnp.minimum(starts[:, None] + lane_ix[None, :],
                      exp_flat.shape[0] - 1)
    cand = jnp.where(cmask, exp_flat[idx], SENTINEL)
    cmask = cmask & valid[:, None]
    if exp_irref:
        cmask = cmask & (cand != key[:, None])
    if dedupe:
        # target tuples may repeat a value; keep the first occurrence so
        # binding rows stay DISTINCT tuples. Sort-based — stable argsort
        # keeps equal values in position order, so marking each sorted
        # element equal to its predecessor drops every occurrence but
        # the first at O(pad·log pad) per row (a pairwise compare would
        # be O(pad²) elements and a (pad, pad) constant — at the
        # one-shot path's wide pads, gigabytes)
        ord_ = jnp.argsort(cand, axis=1)
        sc = jnp.take_along_axis(cand, ord_, axis=1)
        dup_sorted = jnp.concatenate(
            [jnp.zeros((R, 1), bool), sc[:, 1:] == sc[:, :-1]], axis=1
        )
        dup = jnp.zeros_like(dup_sorted).at[
            jnp.arange(R, dtype=jnp.int32)[:, None], ord_
        ].set(dup_sorted)
        cmask = cmask & ~dup
    safe = jnp.where(cmask, cand, dummy)
    cmask = _filter_masks(cand, cmask, safe, key_of, filt_sel,
                          filt_offsets, filt_flats, filt_groups)
    if type_handle >= 0:
        cmask = cmask & (type_of[safe] == type_handle)
    if value_ops is not None:
        cmask = _value_window_mask(cmask, safe, value_cols, value_win,
                                   value_ops)
    cmask = _distinct_masks(cmask, cand, cols, consts, lanes,
                            n_distinct_cols, distinct_consts)
    lane_counts = jnp.zeros(n_lanes, jnp.int32).at[lanes].add(
        cmask.sum(axis=1, dtype=jnp.int32)
    )
    # compaction: survivors first (stable — canonical row order is
    # preserved), into the next bucket
    flat_mask = cmask.reshape(-1)
    src_row = jnp.repeat(jnp.arange(R, dtype=jnp.int32), pad)
    order = jnp.argsort(~flat_mask)
    sel = order[:rows_out]
    new_valid = flat_mask[sel]
    rsel = src_row[sel]
    new_cols = jnp.concatenate(
        [cols[rsel], cand.reshape(-1)[sel][:, None]], axis=1
    )
    new_lanes = lanes[rsel]
    dropped = order[rows_out:]
    trunc_i = jnp.zeros(n_lanes, jnp.int32)
    trunc_i = trunc_i.at[lanes[src_row[dropped]]].add(
        flat_mask[dropped].astype(jnp.int32), mode="drop"
    )
    trunc_i = trunc_i.at[lanes].add(over_row.astype(jnp.int32))
    return new_cols, new_lanes, new_valid, lane_counts, trunc_i > 0


@hgverify.entry(
    shapes=lambda: (
        (hgverify.sds((33,), "int32"), hgverify.sds((64,), "int32"),
         hgverify.sds((8, 1), "int32"), hgverify.sds((8,), "int32"),
         hgverify.sds((8,), "bool"), hgverify.sds((4, 2), "int32"),
         (hgverify.sds((33,), "int32"),),
         (hgverify.sds((64,), "int32"),),
         hgverify.sds((32,), "int32")),
        {},
    ),
    statics={
        "exp_sel": ("const", 0),
        "filt_sel": ((False, "col", 0, False),),
        "type_handle": -1,
        "block": 8, "rows_out": 16, "n_lanes": 4,
        "n_distinct_cols": 1, "distinct_consts": True,
    },
)
@partial(jax.jit, static_argnames=(
    "exp_sel", "filt_sel", "type_handle", "block", "rows_out", "n_lanes",
    "n_distinct_cols", "distinct_consts", "value_ops", "exp_irref",
))
def join_hub_expand(
    exp_offsets: jax.Array,   # (N+2,) int32 — expansion CSR offsets
    exp_flat: jax.Array,      # (E,) int32 — expansion CSR payload
    cols: jax.Array,          # (R, T) int32 bound binding columns
    lanes: jax.Array,         # (R,) int32
    valid: jax.Array,         # (R,) bool
    consts: jax.Array,        # (n_lanes, A) int32
    filt_offsets: tuple,
    filt_flats: tuple,
    type_of: jax.Array,       # (N+1,) int32
    value_cols: Optional[tuple] = None,
    value_win: Optional[jax.Array] = None,
    exp_group: Optional[jax.Array] = None,
    filt_groups: Optional[tuple] = None,
    *,
    exp_sel: tuple,
    filt_sel: tuple,
    type_handle: int,
    block: int,               # dense-frontier chunk width
    rows_out: int,            # pooled survivor bucket
    n_lanes: int,
    n_distinct_cols: int,
    distinct_consts: bool,
    value_ops: Optional[tuple] = None,
    exp_irref: bool = False,
) -> tuple:
    """The degree-split twin of :func:`join_expand_step` for HUB rows: a
    dense-frontier expansion that streams each keyed row in fixed
    ``block``-wide chunks (an on-device while loop over ``⌈w_max/block⌉``
    tiles) instead of one padded gather — a row of ANY width expands
    without width truncation, and the peak tensor is ``R × block``
    however wide the hub is. Filters/type/value/distinct masks apply per
    tile (identical semantics to the tail kernel); survivors stream-
    compact into one pooled ``rows_out`` buffer through a running
    cursor, each lane's survivors arriving in ascending candidate order.
    Returns the same ``(cols', lanes', valid', lane_counts, lane_trunc)``
    contract — ``lane_counts`` stay exact even when the pooled buffer
    overflows (counted per tile, pre-compaction); only ``rows_out``
    overflow can set ``lane_trunc``. No dedupe mode: degree-split plans
    route dedupe (tgt) steps through the tail kernel."""
    R, T = cols.shape
    dummy = type_of.shape[0] - 1
    filt_sel = _norm_filt_sel(filt_sel)
    if filt_groups is None:
        filt_groups = (None,) * len(filt_sel)

    def key_of(sel):
        kind, idx = sel
        k = cols[:, idx] if kind == "col" else consts[lanes, idx]
        return jnp.where(valid, k, dummy)

    key = key_of(exp_sel)
    starts, ends = _seg_of(exp_offsets, exp_group, key)
    widths = jnp.where(valid, ends - starts, 0)
    n_chunks = (jnp.max(widths) + block - 1) // block
    lane_ix = jnp.arange(block, dtype=jnp.int32)
    emax = exp_flat.shape[0] - 1
    slot_ix = jnp.arange(R * block, dtype=jnp.int32)
    src_row = jnp.repeat(jnp.arange(R, dtype=jnp.int32), block)

    out_cols = jnp.zeros((rows_out, T + 1), jnp.int32)
    out_lanes = jnp.full((rows_out,), n_lanes, jnp.int32)
    out_valid = jnp.zeros((rows_out,), bool)
    counts0 = jnp.zeros(n_lanes, jnp.int32)
    dropped0 = jnp.zeros(n_lanes, jnp.int32)

    def body(ci, state):
        out_cols, out_lanes, out_valid, counts, dropped, cursor = state
        base_ix = ci * block + lane_ix
        cmask = base_ix[None, :] < widths[:, None]
        idx = jnp.minimum(starts[:, None] + base_ix[None, :], emax)
        cand = jnp.where(cmask, exp_flat[idx], SENTINEL)
        if exp_irref:
            cmask = cmask & (cand != key[:, None])
        safe = jnp.where(cmask, cand, dummy)
        cmask = _filter_masks(cand, cmask, safe, key_of, filt_sel,
                              filt_offsets, filt_flats, filt_groups)
        if type_handle >= 0:
            cmask = cmask & (type_of[safe] == type_handle)
        if value_ops is not None:
            cmask = _value_window_mask(cmask, safe, value_cols,
                                       value_win, value_ops)
        cmask = _distinct_masks(cmask, cand, cols, consts, lanes,
                                n_distinct_cols, distinct_consts)
        counts = counts.at[lanes].add(cmask.sum(axis=1, dtype=jnp.int32))
        # stream-compact this tile's survivors at the cursor: a stable
        # sort keeps row-major order, so each LANE's survivors land in
        # ascending candidate order across tiles — the pooled prefix is
        # per-lane honest
        flat_mask = cmask.reshape(-1)
        order = jnp.argsort(~flat_mask)
        pos = cursor + slot_ix
        write = flat_mask[order] & (pos < rows_out)
        dst = jnp.where(write, pos, rows_out)
        rsel = src_row[order]
        new_rows = jnp.concatenate(
            [cols[rsel], cand.reshape(-1)[order][:, None]], axis=1
        )
        out_cols = out_cols.at[dst].set(new_rows, mode="drop")
        out_lanes = out_lanes.at[dst].set(lanes[rsel], mode="drop")
        out_valid = out_valid.at[dst].set(write, mode="drop")
        over = flat_mask[order] & (pos >= rows_out)
        dropped = dropped.at[lanes[rsel]].add(
            over.astype(jnp.int32), mode="drop"
        )
        cursor = cursor + flat_mask.sum(dtype=jnp.int32)
        return out_cols, out_lanes, out_valid, counts, dropped, cursor

    out_cols, out_lanes, out_valid, counts, dropped, _ = jax.lax.fori_loop(
        0, n_chunks, body,
        (out_cols, out_lanes, out_valid, counts0, dropped0, jnp.int32(0)),
    )
    return out_cols, out_lanes, out_valid, counts, dropped > 0


@hgverify.entry(
    shapes=lambda: (hgverify.sds((16, 1), "int32"),
                    hgverify.sds((16,), "int32"),
                    hgverify.sds((16,), "bool"),
                    hgverify.sds((16, 1), "int32"),
                    hgverify.sds((16,), "int32"),
                    hgverify.sds((16,), "bool")),
    statics={"pad": 8, "rows_out": 32, "n_lanes": 4, "distinct": True},
)
@partial(jax.jit, static_argnames=("pad", "rows_out", "n_lanes",
                                   "distinct"))
def join_bag_join(
    cols: jax.Array,       # (R1, T1) int32 — spine binding rows
    lanes: jax.Array,      # (R1,) int32
    valid: jax.Array,      # (R1,) bool
    bag_cols: jax.Array,   # (R2, T2) int32 — materialized bag rows
    bag_lanes: jax.Array,  # (R2,) int32
    bag_valid: jax.Array,  # (R2,) bool
    *,
    pad: int,              # bag rows per lane bucket
    rows_out: int,         # joined-row bucket
    n_lanes: int,
    distinct: bool,        # cross-side all-distinct masks
) -> tuple:
    """Join a materialized GHD bag onto the spine table: every spine row
    pairs with its own lane's bag rows (the bushy plan's bag⋈bag step —
    components share no variables, so the join is a per-lane product
    under the cross-side distinctness masks; within-side distinctness
    and constant exclusion were already enforced by each chain). Same
    compaction/trunc/count contract as :func:`join_expand_step`; a lane
    whose bag holds more than ``pad`` rows flags trunc (honest lower
    bound, host re-serve)."""
    R1, T1 = cols.shape
    R2, T2 = bag_cols.shape
    # lane-sort the bag so each lane's rows are one contiguous segment
    bkey = jnp.where(bag_valid, bag_lanes, n_lanes)
    border = jnp.argsort(bkey)
    sb_cols = bag_cols[border]
    sb_key = bkey[border]
    bag_off = jnp.searchsorted(
        sb_key, jnp.arange(n_lanes + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    lane_k = jnp.minimum(jnp.where(valid, lanes, n_lanes), n_lanes - 1)
    starts = bag_off[lane_k]
    bcount = bag_off[lane_k + 1] - starts
    j = jnp.arange(pad, dtype=jnp.int32)
    cmask = (j[None, :] < jnp.minimum(bcount, pad)[:, None]) \
        & valid[:, None]
    over_pad = (bcount > pad) & valid
    bidx = jnp.minimum(starts[:, None] + j[None, :], R2 - 1)
    if distinct:
        for i in range(T1):
            for k in range(T2):
                cmask = cmask & (sb_cols[bidx, k] != cols[:, i, None])
    lane_counts = jnp.zeros(n_lanes, jnp.int32).at[lanes].add(
        cmask.sum(axis=1, dtype=jnp.int32)
    )
    flat_mask = cmask.reshape(-1)
    src_row = jnp.repeat(jnp.arange(R1, dtype=jnp.int32), pad)
    order = jnp.argsort(~flat_mask)
    sel = order[:rows_out]
    new_valid = flat_mask[sel]
    rsel = src_row[sel]
    bsel = bidx.reshape(-1)[sel]
    new_cols = jnp.concatenate([cols[rsel], sb_cols[bsel]], axis=1)
    new_lanes = lanes[rsel]
    dropped = order[rows_out:]
    trunc_i = jnp.zeros(n_lanes, jnp.int32)
    trunc_i = trunc_i.at[lanes[src_row[dropped]]].add(
        flat_mask[dropped].astype(jnp.int32), mode="drop"
    )
    trunc_i = trunc_i.at[lanes].add(over_pad.astype(jnp.int32))
    return new_cols, new_lanes, new_valid, lane_counts, trunc_i > 0


@hgverify.entry(
    shapes=lambda: (hgverify.sds((16, 2), "int32"),
                    hgverify.sds((16,), "int32"),
                    hgverify.sds((16,), "bool")),
    statics={"top_r": 4, "n_lanes": 4, "sort_cols": (0, 1)},
)
@partial(jax.jit, static_argnames=("top_r", "n_lanes", "sort_cols"))
def join_finalize(
    cols: jax.Array,   # (R, V) int32 complete binding rows
    lanes: jax.Array,  # (R,) int32
    valid: jax.Array,  # (R,) bool
    *,
    top_r: int,
    n_lanes: int,
    sort_cols: tuple,  # column indices in sort priority (highest first)
) -> jax.Array:
    """Compact per-request result prefixes: the first ``top_r`` binding
    tuples of every lane, ascending lexicographically by ``sort_cols``
    (the caller passes the REQUEST's variable order mapped onto the
    plan's column layout, so prefixes read canonically however the
    planner reordered) — ``(n_lanes, top_r, V)`` int32, -1-padded. The
    download per batch is O(K · top_r · V) however large the binding
    table ran."""
    R, V = cols.shape
    lane_k = jnp.where(valid, lanes, n_lanes)
    order = jnp.arange(R, dtype=jnp.int32)
    for j in reversed(sort_cols):
        order = order[jnp.argsort(cols[order, j])]
    order = order[jnp.argsort(lane_k[order])]
    sl = lane_k[order]
    idx = jnp.arange(R, dtype=jnp.int32)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sl[1:] != sl[:-1]]
    )
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, idx, 0)
    )
    pos = idx - seg_start
    rows = cols[order]
    out = jnp.full((n_lanes, top_r, V), -1, jnp.int32)
    return out.at[sl, pos].set(rows, mode="drop")


# ---------------------------------------------------------------- execution


@dataclass
class JoinExecution:
    """Async device handles of one executed join batch — pair with
    ``np.asarray`` / :meth:`full_bindings` to sync. ``counts[k]`` is
    exact unless ``trunc[k]`` (then a lower bound — the serving tier
    re-serves that request on host). ``hub_lanes`` counts the real lanes
    the degree-split routed through the dense-frontier hub chain (a
    host-side int, known at launch)."""

    order: tuple
    counts: jax.Array                  # (K,) int32
    trunc: jax.Array                   # (K,) bool
    tuples: Optional[jax.Array] = None  # (K, top_r, V) int32, -1 pad
    cols: Optional[jax.Array] = None    # full mode: final binding rows
    lanes: Optional[jax.Array] = None
    valid: Optional[jax.Array] = None
    hub_lanes: int = 0

    def full_bindings(self, lane: int) -> np.ndarray:
        """All complete binding rows of one request lane, host-side —
        (n, V) int64 in canonical (table) order."""
        if self.cols is None:
            raise ValueError("execute_join(full=True) required")
        cols = np.asarray(self.cols)
        keep = np.asarray(self.valid) & (np.asarray(self.lanes) == lane)
        return cols[keep].astype(np.int64)


def _rel_arrays(snap: CSRSnapshot, dev, rel: str):
    if rel == "co":
        return neighbor_csr_device(snap)
    if rel == "inc":
        return dev.inc_offsets, dev.inc_links
    return dev.tgt_offsets, dev.tgt_flat


def _rel_host_offsets(snap: CSRSnapshot, rel: str):
    if rel == "co":
        return neighbor_csr(snap)[0]
    if rel == "inc":
        return snap.inc_offsets
    return snap.tgt_offsets


def _rel_max_width(snap: CSRSnapshot, rel: str,
                   fact: Optional[dict] = None) -> int:
    """The relation's widest row — a per-(snapshot, relation) invariant,
    cached like ``_nbr_csr``: recomputing the O(N) diff+max per step per
    dispatch would charge pure host bookkeeping to every timed device
    window (the c7 bench runs 64 dispatches per rep). Factorized
    relations answer from their own group extents (closed co rows are
    one wider than flat)."""
    if fact is not None and rel in fact:
        return fact[rel].max_width
    cache = getattr(snap, "_join_wmax", None)
    if cache is None:
        cache = {}
        object.__setattr__(snap, "_join_wmax", cache)
    if rel not in cache:
        off = np.asarray(_rel_host_offsets(snap, rel)[: snap.num_atoms + 1],
                         dtype=np.int64)
        cache[rel] = int(np.max(np.diff(off), initial=1))
    return cache[rel]


def _rel_widths_of(snap: CSRSnapshot, rel: str, keys: np.ndarray,
                   fact: Optional[dict]) -> np.ndarray:
    """Host-side row widths of ``keys`` under the encoding the kernels
    will actually gather from (the pad must cover the CLOSED row when
    the factorized co relation serves the step)."""
    if fact is not None and rel in fact:
        fr = fact[rel]
        g = fr.group_of[np.minimum(keys, len(fr.group_of) - 1)]
        off = fr.offsets.astype(np.int64)
        return off[g + 1] - off[g]
    off_h = np.asarray(_rel_host_offsets(snap, rel), dtype=np.int64)
    return off_h[keys + 1] - off_h[keys]


class _ChainCtx:
    """Shared launch context of one :func:`execute_join` call: the
    device arrays, shape knobs, and factorized twins every chain (tail,
    hub, bag) reads."""

    def __init__(self, snap, dev, K, A, consts, consts_dev, n_real,
                 distinct, row_cap, pad_cap, var_pad_max, slot_budget,
                 vwindows, hub_block, fact, fact_dev):
        self.snap = snap
        self.dev = dev
        self.K = K
        self.A = A
        self.consts = consts
        self.consts_dev = consts_dev
        self.n_real = n_real
        self.distinct = distinct
        self.row_cap = row_cap
        self.pad_cap = pad_cap
        self.var_pad_max = var_pad_max
        self.slot_budget = slot_budget
        self.vwindows = vwindows
        self.hub_block = hub_block
        self.fact = fact
        self.fact_dev = fact_dev

    def rel(self, rel: str):
        """(offsets, flat, group, irref) device arrays of one relation —
        the factorized twin when one is cached (inc is never
        factorized; rev filters ride the inc dual and stay flat)."""
        if self.fact_dev is not None and rel in self.fact_dev:
            g, o, f = self.fact_dev[rel]
            return o, f, g, self.fact[rel].closed
        o, f = _rel_arrays(self.snap, self.dev, rel)
        return o, f, None, False

    def value_window(self, var: str):
        win = self.vwindows.get(var)
        if win is None:
            return None, None, None
        kind, lo_r, lo_op, hi_r, hi_op = win
        vcols = (self.dev.value_rank_hi, self.dev.value_rank_lo,
                 self.dev.value_kind)
        words = np.asarray(
            [int(kind),
             (lo_r or 0) >> 32, (lo_r or 0) & 0xFFFFFFFF,
             (hi_r or 0) >> 32, (hi_r or 0) & 0xFFFFFFFF],
            dtype=np.uint64,
        ).astype(np.uint32)
        return vcols, jnp.asarray(words), (lo_op, hi_op)

    def real_keys(self, step, lane_sel: Optional[np.ndarray]) -> np.ndarray:
        """Clipped const-slot keys of the REAL lanes a pad computation
        may price (optionally a sub-selection — the degree split prices
        tail pads from tail lanes only)."""
        real = (self.consts if self.n_real is None
                else self.consts[: self.n_real])
        if lane_sel is not None:
            real = real[lane_sel[: len(real)]]
        if not len(real):
            return np.zeros(0, dtype=np.int64)
        return np.clip(real[:, step.source_key.index].astype(np.int64),
                       0, self.snap.num_atoms)


def _run_chain(ctx: _ChainCtx, steps, cols, lanes, valid, *,
               hub: bool, lane_sel: Optional[np.ndarray] = None):
    """Run one expand-step chain over an existing binding table. In the
    hub chain, CONST-keyed non-dedupe steps — the ones whose keyed row
    IS a hub row — stream through the chunked dense-frontier kernel
    (width-truncation-free); var-keyed steps (per-row tail-sized
    expansions even on hub lanes) and dedupe steps keep the padded
    single-gather fast path, with pads priced from ``lane_sel``'s lanes
    only. Returns ``(cols, lanes, valid, counts, trunc, final_drop)``
    — ``final_drop`` isolates a LAST-step hub-kernel row-buffer
    overflow: the one truncation class that leaves ``counts`` exact
    (hub-kernel counts accumulate per tile BEFORE compaction and no
    later step consumed the clipped table), so count-only callers need
    not treat it as truncation."""
    K = ctx.K
    trunc = jnp.zeros(K, bool)
    final_drop = jnp.zeros(K, bool)
    counts = jnp.zeros(K, jnp.int32)
    for si, s in enumerate(steps):
        R = int(cols.shape[0])
        exp_off, exp_flat, exp_grp, exp_irref = ctx.rel(s.source_rel)
        filt_sel = []
        filt_offs = []
        filt_flats = []
        filt_grps = []
        for f in s.filters:
            fo, ff, fg, firr = ctx.rel(f.rel)
            filt_sel.append((f.rev, f.key.kind, f.key.index, firr))
            filt_offs.append(fo)
            filt_flats.append(ff)
            filt_grps.append(fg)
        n_dist = int(cols.shape[1]) if ctx.distinct else 0
        vcols, vwin, vops = ctx.value_window(s.var)
        use_hub = hub and not s.dedupe and s.source_key.kind == "const"
        use_row_split = hub and not s.dedupe and \
            s.source_key.kind == "col"
        if use_hub:
            block = _bucket(
                max(min(ctx.hub_block,
                        max(ctx.slot_budget // max(R, 1), 8)), 8),
                minimum=8,
            )
            # survivor bucket sized to what the hub rows can actually
            # mint: on the chain's FIRST step (one table row per lane)
            # that is exactly the SUM of the keyed row widths — half or
            # less of rows × max on skewed batches, and every
            # downstream step's table shrinks with it; mid-chain the
            # per-row bound is rows × the widest keyed row
            keys = ctx.real_keys(s, lane_sel)
            widths_h = _rel_widths_of(ctx.snap, s.source_rel, keys,
                                      ctx.fact)
            w_max = int(np.max(widths_h, initial=1)) if len(keys) else 1
            cap_rows = (int(widths_h.sum()) if int(cols.shape[1]) == 0
                        else max(R, 1) * max(w_max, 1))
            rows_out = min(_bucket(max(cap_rows, 1)), ctx.row_cap)
            cols, lanes, valid, counts, step_trunc = join_hub_expand(
                exp_off, exp_flat, cols, lanes, valid, ctx.consts_dev,
                tuple(filt_offs), tuple(filt_flats), ctx.dev.type_of,
                vcols, vwin, exp_grp, tuple(filt_grps),
                exp_sel=(s.source_key.kind, s.source_key.index),
                filt_sel=tuple(filt_sel),
                type_handle=(-1 if s.type_handle is None
                             else int(s.type_handle)),
                block=block, rows_out=rows_out, n_lanes=K,
                n_distinct_cols=n_dist,
                distinct_consts=ctx.distinct and ctx.A > 0,
                value_ops=vops, exp_irref=exp_irref,
            )
            if si == len(steps) - 1:
                final_drop = final_drop | step_trunc
            else:
                trunc = trunc | step_trunc
            continue
        if s.source_key.kind == "const":
            # real lanes only: zero-filled pad lanes would price every
            # sparse batch's pad by atom 0's row (a hub in age-ordered
            # id spaces); under a degree split, tail lanes only — one
            # hub must not inflate every tail lane's pad
            keys = ctx.real_keys(s, lane_sel)
            w = (int(np.max(_rel_widths_of(ctx.snap, s.source_rel, keys,
                                           ctx.fact), initial=1))
                 if len(keys) else 1)
        elif ctx.var_pad_max:
            # exact-count mode (bench): pay the relation's true max row
            # width so only the pad_cap itself can truncate
            w = _rel_max_width(ctx.snap, s.source_rel, ctx.fact)
        else:
            # the estimate is a relation AVERAGE; 4× headroom keeps
            # ordinary rows in-pad (hubs past it flag trunc honestly)
            w = 4 * (int(s.width_est) + 1)
        # the pad is additionally bounded by the candidate-slot budget
        # (R × pad is the step's peak tensor): a one-row table may pay a
        # six-figure pad (wide one-shot anchors), a deep table only a
        # narrow one — constant memory either way
        pad = _bucket(
            max(min(w, ctx.pad_cap,
                    max(ctx.slot_budget // max(R, 1), 8)), 1),
            minimum=8,
        )
        rows_out = min(_bucket(R * pad), ctx.row_cap, R * pad)
        if use_row_split:
            # hub-VALUED variables: a var-keyed step on the hub chain
            # can bind rows that are themselves hubs (a hub's
            # neighbours include the other hubs), and no pad holds
            # them. Per-ROW width split: rows within the pad keep the
            # single-gather kernel; the (few) wider rows compact into a
            # small bucket and stream through the chunked kernel —
            # compaction overflow is the only remaining truncation.
            dummy_id = ctx.snap.num_atoms
            key_dev = jnp.where(
                valid, cols[:, s.source_key.index], dummy_id
            )
            s_dev, e_dev = _seg_of(exp_off, exp_grp, key_dev)
            wide = valid & ((e_dev - s_dev) > pad)
            wide_bucket = min(_bucket(max(R // 8, 64)), _bucket(R))
            worder = jnp.argsort(~wide)
            wsel = worder[:wide_bucket]
            w_cols, w_lanes = cols[wsel], lanes[wsel]
            w_valid = wide[wsel]
            lost = worder[wide_bucket:]
            wide_over = jnp.zeros(K, jnp.int32).at[lanes[lost]].add(
                wide[lost].astype(jnp.int32), mode="drop"
            ) > 0
            common = dict(
                exp_sel=(s.source_key.kind, s.source_key.index),
                filt_sel=tuple(filt_sel),
                type_handle=(-1 if s.type_handle is None
                             else int(s.type_handle)),
                n_lanes=K, n_distinct_cols=n_dist,
                distinct_consts=ctx.distinct and ctx.A > 0,
                value_ops=vops, exp_irref=exp_irref,
            )
            n_cols, n_lanes_a, n_valid, n_counts, n_trunc = \
                join_expand_step(
                    exp_off, exp_flat, cols, lanes, valid & ~wide,
                    ctx.consts_dev, tuple(filt_offs),
                    tuple(filt_flats), ctx.dev.type_of, vcols, vwin,
                    exp_grp, tuple(filt_grps),
                    pad=pad, rows_out=rows_out, dedupe=False, **common,
                )
            block = _bucket(
                max(min(ctx.hub_block,
                        max(ctx.slot_budget // max(wide_bucket, 1),
                            8)), 8),
                minimum=8,
            )
            rows_out_w = min(
                _bucket(wide_bucket
                        * _rel_max_width(ctx.snap, s.source_rel,
                                         ctx.fact)),
                ctx.row_cap,
            )
            w_cols, w_lanes_a, w_valid, w_counts, w_trunc = \
                join_hub_expand(
                    exp_off, exp_flat, w_cols, w_lanes, w_valid,
                    ctx.consts_dev, tuple(filt_offs),
                    tuple(filt_flats), ctx.dev.type_of, vcols, vwin,
                    exp_grp, tuple(filt_grps),
                    block=block, rows_out=rows_out_w, **common,
                )
            cols = jnp.concatenate([n_cols, w_cols])
            lanes = jnp.concatenate([n_lanes_a, w_lanes_a])
            valid = jnp.concatenate([n_valid, w_valid])
            counts = n_counts + w_counts
            # narrow rows fit the pad by construction and the wide pass
            # never width-truncates: both kernels' flags are pure
            # row-buffer drops (count-preserving on a final step);
            # only the wide-bucket overflow loses candidates outright
            if si == len(steps) - 1:
                final_drop = final_drop | n_trunc | w_trunc
                trunc = trunc | wide_over
            else:
                trunc = trunc | n_trunc | w_trunc | wide_over
            continue
        cols, lanes, valid, counts, step_trunc = join_expand_step(
            exp_off, exp_flat, cols, lanes, valid, ctx.consts_dev,
            tuple(filt_offs), tuple(filt_flats), ctx.dev.type_of,
            vcols, vwin, exp_grp, tuple(filt_grps),
            exp_sel=(s.source_key.kind, s.source_key.index),
            filt_sel=tuple(filt_sel),
            type_handle=(-1 if s.type_handle is None
                         else int(s.type_handle)),
            pad=pad, rows_out=rows_out, n_lanes=K,
            n_distinct_cols=n_dist,
            distinct_consts=ctx.distinct and ctx.A > 0,
            dedupe=s.dedupe,
            value_ops=vops, exp_irref=exp_irref,
        )
        trunc = trunc | step_trunc
    return cols, lanes, valid, counts, trunc, final_drop


def _split_chain(ctx: _ChainCtx, steps, base_valid, hub_mask):
    """One component's chain under the degree split: tail lanes through
    the padded fast path, hub lanes (``hub_mask``) through the chunked
    dense-frontier chain, tables re-pooled afterwards. Returns
    ``(cols, lanes, valid, counts, trunc, final_drop, n_hub)``."""
    K = ctx.K
    cols0 = jnp.zeros((K, 0), jnp.int32)
    lanes0 = jnp.arange(K, dtype=jnp.int32)
    n_hub = int(hub_mask.sum()) if hub_mask is not None else 0
    if not n_hub:
        out = _run_chain(ctx, steps, cols0, lanes0, base_valid, hub=False)
        return (*out, 0)
    hub_dev = jnp.asarray(hub_mask)
    if n_hub >= (ctx.K if ctx.n_real is None else ctx.n_real):
        out = _run_chain(ctx, steps, cols0, lanes0,
                         base_valid & hub_dev, hub=True,
                         lane_sel=hub_mask)
        return (*out, n_hub)
    t_cols, t_lanes, t_valid, t_counts, t_trunc, t_fd = _run_chain(
        ctx, steps, cols0, lanes0, base_valid & ~hub_dev, hub=False,
        lane_sel=~hub_mask,
    )
    h_cols, h_lanes, h_valid, h_counts, h_trunc, h_fd = _run_chain(
        ctx, steps, cols0, lanes0, base_valid & hub_dev, hub=True,
        lane_sel=hub_mask,
    )
    return (
        jnp.concatenate([t_cols, h_cols]),
        jnp.concatenate([t_lanes, h_lanes]),
        jnp.concatenate([t_valid, h_valid]),
        t_counts + h_counts,
        t_trunc | h_trunc,
        t_fd | h_fd,
        n_hub,
    )


def _resolve_factorized(snap: CSRSnapshot, factorized):
    """The per-call factorized-relation decision: ``False`` = flat CSRs,
    ``True`` = build (and cache) the trie encoding now, ``None`` = use
    it only when someone already built it for this snapshot (the serve
    tier builds at plan time / prewarm — ad-hoc callers never pay the
    build implicitly)."""
    if factorized is False:
        return None, None
    if factorized is None and getattr(snap, "_fact_rels", None) is None:
        return None, None
    fact = factorized_relations(snap)
    return fact, factorized_relations_device(snap)


def execute_join(
    snap: CSRSnapshot,
    plan,                    # join/planner.JoinPlan | BushyJoinPlan
    consts: np.ndarray,      # (K, n_consts) int32 — per-request constants
    *,
    top_r: int = 16,
    full: bool = False,      # keep the final binding table downloadable
    count_only: bool = False,
    seeds: Optional[np.ndarray] = None,  # pre-bound var-0 candidates
    row_cap: int = DEFAULT_ROW_CAP,
    pad_cap: int = DEFAULT_PAD_CAP,
    var_pad_max: bool = False,
    n_real: Optional[int] = None,
    slot_budget: int = DEFAULT_SLOT_BUDGET,
    value_windows: Optional[dict] = None,
    hub_split: bool = True,
    hub_threshold: Optional[int] = None,
    hub_block: int = DEFAULT_HUB_BLOCK,
    factorized: Optional[bool] = None,
) -> JoinExecution:
    """Run ``plan`` for K same-signature requests in one batched pass —
    async (no host sync; every return field is a device handle).

    Shape policy (the compile-bounding half of the contract): expansion
    pads for constant-keyed steps come from the BATCH's actual maximum
    row width, power-of-two bucketed and capped at ``pad_cap``;
    variable-keyed steps use the plan's estimate bucket
    (``var_pad_max=True`` pays the relation's true max row width instead
    — the exact-count mode the c7 bench runs). Row buckets grow
    multiplicatively and cap at ``row_cap``. Anything the caps cut
    off surfaces per request in ``trunc`` — never silently.

    ``hub_split=True`` (the degree-split plan, v2's default): lanes
    whose const-keyed rows exceed ``hub_threshold`` (default: the pad
    cap — exactly the lanes the tail pads could never hold) run their
    whole chain through the chunked :func:`join_hub_expand` dense-
    frontier kernel, so hub anchors expand at ANY width without
    truncation; tail lanes keep the padded fast path with pads priced
    from tail widths only. ``factorized`` routes the co/tgt gathers
    through the prefix-grouped trie encoding (None = only when the
    snapshot already carries one — see :func:`factorized_relations`).

    ``seeds`` replaces the first step: the given ids become the var-0
    binding column of ONE request lane (the benchmark's global-counting
    mode — chunk the id space, sum the counts).

    ``value_windows`` maps a plan variable to a value-rank window
    ``(kind, lo_rank, lo_op, hi_rank, hi_op)`` (64-bit ranks, ops
    gt/gte/lt/lte, None = open) applied as a candidate filter INSIDE the
    step binding that variable — the hgindex planner hook: a value
    predicate prunes the intersection instead of post-filtering, so
    out-of-window candidates never cost binding rows. Callers own kind
    exactness (fixed-width kinds only; rank ties on variable-width kinds
    would silently drop true matches)."""
    dev = snap.device
    K, A = (int(consts.shape[0]), int(consts.shape[1]))
    consts = np.ascontiguousarray(consts, dtype=np.int32)
    consts_dev = jnp.asarray(consts) if A else jnp.zeros((K, 0), jnp.int32)
    fact, fact_dev = _resolve_factorized(snap, factorized)
    ctx = _ChainCtx(
        snap, dev, K, A, consts, consts_dev, n_real, plan.distinct,
        row_cap, pad_cap, var_pad_max, slot_budget, value_windows or {},
        hub_block, fact, fact_dev,
    )
    bags = getattr(plan, "bags", None)
    if bags is not None:
        if seeds is not None:
            raise ValueError("seeds mode requires a left-deep plan")
        return _execute_bushy(ctx, plan, top_r=top_r, full=full,
                              count_only=count_only,
                              hub_split=hub_split,
                              hub_threshold=hub_threshold)
    if seeds is None:
        base_valid = (jnp.ones(K, bool) if n_real is None
                      else jnp.arange(K, dtype=jnp.int32) < int(n_real))
        hub_mask = _hub_mask(ctx, plan.steps, hub_split, hub_threshold)
        cols, lanes, valid, counts, trunc, final_drop, n_hub = \
            _split_chain(ctx, plan.steps, base_valid, hub_mask)
    else:
        if K != 1:
            raise ValueError("seeds mode is single-lane (K == 1)")
        seeds = np.asarray(seeds, dtype=np.int32)
        cols = jnp.asarray(seeds)[:, None]
        lanes = jnp.zeros(len(seeds), jnp.int32)
        valid = jnp.ones(len(seeds), bool)
        steps = plan.steps[1:]
        n_hub = 0
        final_drop = jnp.zeros(K, bool)
        # a 1-variable plan in seeds mode has no steps left: the seeds
        # ARE the complete bindings
        if not steps:
            counts = jnp.zeros(K, jnp.int32).at[lanes].add(
                valid.astype(jnp.int32)
            )
            trunc = jnp.zeros(K, bool)
        else:
            cols, lanes, valid, counts, trunc, final_drop = _run_chain(
                ctx, steps, cols, lanes, valid, hub=False
            )
    # count-only callers never download the (clipped) table, and a
    # final-step hub drop leaves counts exact — not a truncation for
    # them; tuple/full consumers still see it flagged (their prefix
    # would be incomplete)
    out = JoinExecution(
        order=plan.order, counts=counts,
        trunc=(trunc if count_only else trunc | final_drop),
        hub_lanes=n_hub,
    )
    if count_only:
        return out
    if top_r > 0:
        sort_cols = tuple(
            plan.order.index(v) for v in plan.sig.vars
        )
        out.tuples = join_finalize(cols, lanes, valid,
                                   top_r=top_r, n_lanes=K,
                                   sort_cols=sort_cols)
    if full:
        out.cols, out.lanes, out.valid = cols, lanes, valid
    return out


def _hub_mask(ctx: _ChainCtx, steps, hub_split: bool,
              hub_threshold: Optional[int]):
    """The planner's degree-split policy applied to this batch's
    constants (``join/planner.hub_lane_mask``), or None when the split
    is off / no lane qualifies."""
    if not hub_split or not steps:
        return None
    from hypergraphdb_tpu.join.planner import hub_lane_mask

    thr = min(hub_threshold if hub_threshold is not None else ctx.pad_cap,
              ctx.pad_cap)
    n_real = ctx.K if ctx.n_real is None else ctx.n_real
    mask = hub_lane_mask(ctx.snap, steps, ctx.consts[:n_real], thr)
    if not mask.any():
        return None
    if len(mask) < ctx.K:
        mask = np.concatenate([mask, np.zeros(ctx.K - len(mask), bool)])
    return mask


def _execute_bushy(ctx: _ChainCtx, plan, *, top_r: int, full: bool,
                   count_only: bool, hub_split: bool,
                   hub_threshold: Optional[int]) -> JoinExecution:
    """The bushy GHD executor: run the spine component's chain, run each
    bag's chain to a small materialized table, then fold bags onto the
    spine with :func:`join_bag_join` (cross-component distinctness at
    each fold). Counts come from the final fold; truncation anywhere —
    spine, a bag chain, a fold's pad or row bucket — flags the owning
    lane honestly."""
    K = ctx.K
    base_valid = (jnp.ones(K, bool) if ctx.n_real is None
                  else jnp.arange(K, dtype=jnp.int32) < int(ctx.n_real))
    hub_mask = _hub_mask(ctx, plan.spine, hub_split, hub_threshold)
    cols, lanes, valid, counts, trunc, s_fd, n_hub = _split_chain(
        ctx, plan.spine, base_valid, hub_mask
    )
    # every chain output feeds a fold here, so a clipped table anywhere
    # undercounts downstream: final-step drops are NOT count-preserving
    # in a bushy plan — fold them into trunc conservatively
    trunc = trunc | s_fd
    for bag in plan.bags:
        b_hub = _hub_mask(ctx, bag.steps, hub_split, hub_threshold)
        b_cols, b_lanes, b_valid, _, b_trunc, b_fd, b_n_hub = \
            _split_chain(ctx, bag.steps, base_valid, b_hub)
        b_trunc = b_trunc | b_fd
        n_hub += b_n_hub
        R1 = int(cols.shape[0])
        R2 = int(b_cols.shape[0])
        pad = _bucket(
            max(min(_bucket(R2),
                    max(ctx.slot_budget // max(R1, 1), 8)), 8),
            minimum=8,
        )
        rows_out = min(_bucket(R1 * pad), ctx.row_cap, R1 * pad)
        cols, lanes, valid, counts, j_trunc = join_bag_join(
            cols, lanes, valid, b_cols, b_lanes, b_valid,
            pad=pad, rows_out=rows_out, n_lanes=K,
            distinct=plan.distinct,
        )
        trunc = trunc | b_trunc | j_trunc
    out = JoinExecution(order=plan.order, counts=counts, trunc=trunc,
                        hub_lanes=n_hub)
    if count_only:
        return out
    if top_r > 0:
        sort_cols = tuple(plan.order.index(v) for v in plan.sig.vars)
        out.tuples = join_finalize(cols, lanes, valid, top_r=top_r,
                                   n_lanes=K, sort_cols=sort_cols)
    if full:
        out.cols, out.lanes, out.valid = cols, lanes, valid
    return out
