"""Sorted-set kernels: batched intersection over CSR rows.

The device replacement for the reference's zig-zag/leapfrog join
(``impl/ZigZagIntersectionResult.java:37-75``: per-candidate B-tree ``goTo``
repositioning — exactly the pointer-chasing BASELINE.json targets). On TPU
the same join is a **vectorized searchsorted**: for K queries at once, gather
each anchor's incidence row into a padded (K, L) matrix and probe membership
with binary search — O(K·L·log L) of pure vector compute, no trees.

Conventions: id arrays are int32, sorted ascending per row, padded with
``SENTINEL`` (int32 max) so padding stays sorted and never matches.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot

SENTINEL = np.int32(np.iinfo(np.int32).max)


def pad_sorted(a: np.ndarray, length: int) -> np.ndarray:
    """Pad a sorted unique int array to ``length`` with SENTINEL."""
    out = np.full(length, SENTINEL, dtype=np.int32)
    out[: len(a)] = a
    return out


def _bucket(n: int, minimum: int = 128) -> int:
    """Round up to a power-of-two bucket (bounds recompilation count)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


# ------------------------------------------------------------------ 1-D ops


@jax.jit
def member_mask(sorted_ref: jax.Array, queries: jax.Array) -> jax.Array:
    """queries ∈ sorted_ref, elementwise. Both may be SENTINEL-padded."""
    pos = jnp.searchsorted(sorted_ref, queries)
    pos = jnp.minimum(pos, sorted_ref.shape[0] - 1)
    return (sorted_ref[pos] == queries) & (queries != SENTINEL)


@jax.jit
def intersect_mask_many(base: jax.Array, others: jax.Array) -> jax.Array:
    """base (L,) vs others (M, L'): mask of base elements present in EVERY
    other set — the n-way And intersection in one fused program."""

    def body(mask, other):
        return mask & member_mask(other, base), None

    init = base != SENTINEL
    mask, _ = jax.lax.scan(body, init, others)
    return mask


# ------------------------------------------------------------------ segment search


@jax.jit
def segment_member_mask(
    flat: jax.Array,     # (E,) — concatenated sorted segments (CSR payload)
    starts: jax.Array,   # (K,) int32 — per-query segment start (inclusive)
    ends: jax.Array,     # (K,) int32 — per-query segment end (exclusive)
    queries: jax.Array,  # (K, L) int32 — SENTINEL-padded probe values
) -> jax.Array:
    """queries[k] ∈ flat[starts[k]:ends[k]], elementwise, WITHOUT gathering
    the segment: a branchless binary search runs directly against the CSR
    flat array with per-row bounds. This is the true vectorized zig-zag
    (``ZigZagIntersectionResult.java:37-75``): probe cost is O(L · log E)
    regardless of how large the probed row is — hub rows cost the same as
    singletons (VERDICT r1 Weak #3)."""
    shape = queries.shape
    lo = jnp.broadcast_to(starts[:, None], shape).astype(jnp.int32)
    hi = jnp.broadcast_to(ends[:, None], shape).astype(jnp.int32)
    emax = flat.shape[0] - 1

    def body(_, state):
        lo, hi = state
        active = lo < hi
        mid = (lo + hi) >> 1
        v = flat[jnp.minimum(mid, emax)]
        go_right = v < queries
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    # 32 rounds bound any int32-indexed segment length
    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    found = flat[jnp.minimum(lo, emax)]
    in_seg = lo < jnp.broadcast_to(ends[:, None], shape)
    return in_seg & (found == queries) & (queries != SENTINEL)


@partial(jax.jit, static_argnames=("pad_len",))
def incident_intersection_zigzag(
    dev: DeviceSnapshot,
    anchors: jax.Array,   # (K, P) int32 — anchors[:, 0] has the SMALLEST row
    pad_len: int,         # bucket of the base (smallest) row lengths
    type_handle: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Conjunctive incident intersection with hub-proof cost: gather only
    the base (smallest) incidence row per query and probe the other
    anchors' rows in place via :func:`segment_member_mask`. Work per query
    is O(pad_len · P · log E) — independent of hub row sizes."""
    rows0, mask = gather_rows(
        dev.inc_offsets, dev.inc_links, anchors[:, 0], pad_len
    )
    P = anchors.shape[1]
    for p in range(1, P):
        a = anchors[:, p]
        mask = mask & segment_member_mask(
            dev.inc_links, dev.inc_offsets[a], dev.inc_offsets[a + 1], rows0
        )
    if type_handle is not None:
        safe = jnp.where(rows0 == SENTINEL, 0, rows0)
        mask = mask & (dev.type_of[safe] == type_handle)
    return rows0, mask


# ------------------------------------------------------------------ CSR rows


def gather_rows(
    offsets: jax.Array, flat: jax.Array, atoms: jax.Array, pad_len: int
) -> tuple[jax.Array, jax.Array]:
    """Gather CSR rows for ``atoms`` into a (K, pad_len) SENTINEL-padded,
    per-row-sorted matrix. Returns (rows, valid_mask)."""
    starts = offsets[atoms]
    lens = offsets[atoms + 1] - starts
    lane = jnp.arange(pad_len, dtype=jnp.int32)
    idx = starts[:, None] + lane[None, :]
    valid = lane[None, :] < lens[:, None]
    idx = jnp.where(valid, idx, 0)
    rows = jnp.where(valid, flat[idx], SENTINEL)
    return rows, valid


@partial(jax.jit, static_argnames=("pad_len",))
def incident_intersection(
    dev: DeviceSnapshot,
    anchors: jax.Array,  # (K, P) int32 anchor atoms per query
    pad_len: int,
    type_handle: Optional[jax.Array] = None,  # scalar int32 or None
) -> tuple[jax.Array, jax.Array]:
    """The conjunctive pattern kernel: for each query k, links incident to
    ALL anchors[k, :] (optionally restricted to a type) — the device form of
    ``And(type, incident, incident, ...)`` (BASELINE config 3).

    Returns (candidates (K, pad_len) int32 rows of anchor-0's incidence,
    mask (K, pad_len) bool of survivors)."""
    rows0, valid0 = gather_rows(dev.inc_offsets, dev.inc_links, anchors[:, 0], pad_len)
    mask = valid0
    P = anchors.shape[1]
    for p in range(1, P):
        rows_p, _ = gather_rows(
            dev.inc_offsets, dev.inc_links, anchors[:, p], pad_len
        )
        mask = mask & jax.vmap(member_mask)(rows_p, rows0)
    if type_handle is not None:
        safe = jnp.where(rows0 == SENTINEL, 0, rows0)
        mask = mask & (dev.type_of[safe] == type_handle)
    return rows0, mask


def and_incident_pattern(
    snap: CSRSnapshot,
    anchor_lists: Sequence[Sequence[int]],
    type_handle: Optional[int] = None,
) -> list[np.ndarray]:
    """Host wrapper: run the conjunctive-pattern kernel for K anchor tuples
    (all the same arity) and return per-query sorted result arrays.

    **Hub-proof dispatch** (VERDICT r1 Weak #3): each query's anchors are
    reordered so the SMALLEST incidence row is the base (intersection is
    commutative); only base rows are gathered — other rows are probed in
    place by segment binary search (:func:`segment_member_mask`). Queries
    batch by the power-of-two bucket of their base-row length, so a zipf
    hub in the anchor set neither sets the pad for other queries nor even
    for its own (the hub row is never the base unless every anchor is a
    hub, and even then it is only probed, not gathered).
    """
    anchors = np.asarray(anchor_lists, dtype=np.int32)
    if anchors.ndim == 1:
        anchors = anchors[None, :]
    lens = snap.inc_offsets[anchors + 1] - snap.inc_offsets[anchors]
    if lens.size:
        order = np.argsort(lens, axis=1, kind="stable")
        anchors = np.take_along_axis(anchors, order, axis=1)
        base_len = np.take_along_axis(lens, order[:, :1], axis=1)[:, 0]
    else:
        base_len = np.zeros(0, dtype=np.int64)
    buckets = np.asarray([_bucket(int(m)) for m in base_len])
    dev = snap.device
    th = None if type_handle is None else jnp.int32(type_handle)
    out: list[Optional[np.ndarray]] = [None] * len(anchors)
    for b in np.unique(buckets):
        sel = np.nonzero(buckets == b)[0]
        rows, mask = incident_intersection_zigzag(
            dev, jnp.asarray(anchors[sel]), int(b), th
        )
        rows = np.asarray(rows)
        mask = np.asarray(mask)
        for j, qi in enumerate(sel.tolist()):
            out[qi] = np.sort(rows[j][mask[j]]).astype(np.int64)
    return out  # type: ignore[return-value]


# ------------------------------------------------------------------ planner hook


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def device_intersect_sorted(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """n-way sorted intersection of host arrays on device — used by the
    query planner for large intersections (``IntersectPlan``).

    On TPU, VMEM-sized inputs take the Pallas tiled-compare kernel
    (~3× the XLA searchsorted path on-device, see ``ops/pallas_kernels``);
    everything else falls back to vectorized searchsorted."""
    arrays = sorted(arrays, key=len)
    base = arrays[0]
    if len(base) == 0:
        return np.empty(0, dtype=np.int64)
    L = _bucket(max(len(a) for a in arrays))
    if len(arrays) > 1 and _on_tpu():
        from hypergraphdb_tpu.ops.pallas_kernels import (
            fits_vmem,
            intersect_sorted_pallas,
        )

        if fits_vmem(len(base), len(arrays) - 1, L):
            try:
                return intersect_sorted_pallas(arrays)
            except Exception:
                import logging

                logging.getLogger("hypergraphdb_tpu.ops").warning(
                    "pallas intersection failed; searchsorted fallback",
                    exc_info=True,
                )
    base_p = pad_sorted(base.astype(np.int32), L)
    others = np.stack([pad_sorted(a.astype(np.int32), L) for a in arrays[1:]])
    mask = np.asarray(intersect_mask_many(jnp.asarray(base_p), jnp.asarray(others)))
    return base_p[mask].astype(np.int64)
