"""Sorted-set kernels: batched intersection over CSR rows.

The device replacement for the reference's zig-zag/leapfrog join
(``impl/ZigZagIntersectionResult.java:37-75``: per-candidate B-tree ``goTo``
repositioning — exactly the pointer-chasing BASELINE.json targets). On TPU
the same join is a **vectorized searchsorted**: for K queries at once, gather
each anchor's incidence row into a padded (K, L) matrix and probe membership
with binary search — O(K·L·log L) of pure vector compute, no trees.

Conventions: id arrays are int32, sorted ascending per row, padded with
``SENTINEL`` (int32 max) so padding stays sorted and never matches.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot

SENTINEL = np.int32(np.iinfo(np.int32).max)


def pad_sorted(a: np.ndarray, length: int) -> np.ndarray:
    """Pad a sorted unique int array to ``length`` with SENTINEL."""
    out = np.full(length, SENTINEL, dtype=np.int32)
    out[: len(a)] = a
    return out


def _bucket(n: int, minimum: int = 128) -> int:
    """Round up to a power-of-two bucket (bounds recompilation count)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


# ------------------------------------------------------------------ 1-D ops


@jax.jit
def member_mask(sorted_ref: jax.Array, queries: jax.Array) -> jax.Array:
    """queries ∈ sorted_ref, elementwise. Both may be SENTINEL-padded."""
    pos = jnp.searchsorted(sorted_ref, queries)
    pos = jnp.minimum(pos, sorted_ref.shape[0] - 1)
    return (sorted_ref[pos] == queries) & (queries != SENTINEL)


@jax.jit
def intersect_mask_many(base: jax.Array, others: jax.Array) -> jax.Array:
    """base (L,) vs others (M, L'): mask of base elements present in EVERY
    other set — the n-way And intersection in one fused program."""

    def body(mask, other):
        return mask & member_mask(other, base), None

    init = base != SENTINEL
    mask, _ = jax.lax.scan(body, init, others)
    return mask


# ------------------------------------------------------------------ CSR rows


def gather_rows(
    offsets: jax.Array, flat: jax.Array, atoms: jax.Array, pad_len: int
) -> tuple[jax.Array, jax.Array]:
    """Gather CSR rows for ``atoms`` into a (K, pad_len) SENTINEL-padded,
    per-row-sorted matrix. Returns (rows, valid_mask)."""
    starts = offsets[atoms]
    lens = offsets[atoms + 1] - starts
    lane = jnp.arange(pad_len, dtype=jnp.int32)
    idx = starts[:, None] + lane[None, :]
    valid = lane[None, :] < lens[:, None]
    idx = jnp.where(valid, idx, 0)
    rows = jnp.where(valid, flat[idx], SENTINEL)
    return rows, valid


@partial(jax.jit, static_argnames=("pad_len",))
def incident_intersection(
    dev: DeviceSnapshot,
    anchors: jax.Array,  # (K, P) int32 anchor atoms per query
    pad_len: int,
    type_handle: Optional[jax.Array] = None,  # scalar int32 or None
) -> tuple[jax.Array, jax.Array]:
    """The conjunctive pattern kernel: for each query k, links incident to
    ALL anchors[k, :] (optionally restricted to a type) — the device form of
    ``And(type, incident, incident, ...)`` (BASELINE config 3).

    Returns (candidates (K, pad_len) int32 rows of anchor-0's incidence,
    mask (K, pad_len) bool of survivors)."""
    rows0, valid0 = gather_rows(dev.inc_offsets, dev.inc_links, anchors[:, 0], pad_len)
    mask = valid0
    P = anchors.shape[1]
    for p in range(1, P):
        rows_p, _ = gather_rows(
            dev.inc_offsets, dev.inc_links, anchors[:, p], pad_len
        )
        mask = mask & jax.vmap(member_mask)(rows_p, rows0)
    if type_handle is not None:
        safe = jnp.where(rows0 == SENTINEL, 0, rows0)
        mask = mask & (dev.type_of[safe] == type_handle)
    return rows0, mask


def and_incident_pattern(
    snap: CSRSnapshot,
    anchor_lists: Sequence[Sequence[int]],
    type_handle: Optional[int] = None,
) -> list[np.ndarray]:
    """Host wrapper: run the conjunctive-pattern kernel for K anchor tuples
    (all the same arity) and return per-query sorted result arrays."""
    anchors = np.asarray(anchor_lists, dtype=np.int32)
    if anchors.ndim == 1:
        anchors = anchors[None, :]
    # bucket the pad length by the largest incidence row over ALL anchor
    # columns — a longer non-base row must not be truncated, or shared links
    # sorting past the pad boundary are silently dropped
    lens = snap.inc_offsets[anchors + 1] - snap.inc_offsets[anchors]
    pad_len = _bucket(int(lens.max()) if lens.size else 1)
    dev = snap.device
    th = None if type_handle is None else jnp.int32(type_handle)
    rows, mask = incident_intersection(dev, jnp.asarray(anchors), pad_len, th)
    rows = np.asarray(rows)
    mask = np.asarray(mask)
    return [np.sort(rows[i][mask[i]]).astype(np.int64) for i in range(len(rows))]


# ------------------------------------------------------------------ planner hook


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def device_intersect_sorted(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """n-way sorted intersection of host arrays on device — used by the
    query planner for large intersections (``IntersectPlan``).

    On TPU, VMEM-sized inputs take the Pallas tiled-compare kernel
    (~3× the XLA searchsorted path on-device, see ``ops/pallas_kernels``);
    everything else falls back to vectorized searchsorted."""
    arrays = sorted(arrays, key=len)
    base = arrays[0]
    if len(base) == 0:
        return np.empty(0, dtype=np.int64)
    L = _bucket(max(len(a) for a in arrays))
    if len(arrays) > 1 and _on_tpu():
        from hypergraphdb_tpu.ops.pallas_kernels import (
            fits_vmem,
            intersect_sorted_pallas,
        )

        if fits_vmem(len(base), len(arrays) - 1, L):
            try:
                return intersect_sorted_pallas(arrays)
            except Exception:
                import logging

                logging.getLogger("hypergraphdb_tpu.ops").warning(
                    "pallas intersection failed; searchsorted fallback",
                    exc_info=True,
                )
    base_p = pad_sorted(base.astype(np.int32), L)
    others = np.stack([pad_sorted(a.astype(np.int32), L) for a in arrays[1:]])
    mask = np.asarray(intersect_mask_many(jnp.asarray(base_p), jnp.asarray(others)))
    return base_p[mask].astype(np.int64)
