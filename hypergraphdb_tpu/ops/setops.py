"""Sorted-set kernels: batched intersection over CSR rows.

The device replacement for the reference's zig-zag/leapfrog join
(``impl/ZigZagIntersectionResult.java:37-75``: per-candidate B-tree ``goTo``
repositioning — exactly the pointer-chasing BASELINE.json targets). On TPU
the same join is a **vectorized searchsorted**: for K queries at once, gather
each anchor's incidence row into a padded (K, L) matrix and probe membership
with binary search — O(K·L·log L) of pure vector compute, no trees.

Conventions: id arrays are int32, sorted ascending per row, padded with
``SENTINEL`` (int32 max) so padding stays sorted and never matches.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot

SENTINEL = np.int32(np.iinfo(np.int32).max)


def pad_sorted(a: np.ndarray, length: int) -> np.ndarray:
    """Pad a sorted unique int array to ``length`` with SENTINEL."""
    out = np.full(length, SENTINEL, dtype=np.int32)
    out[: len(a)] = a
    return out


def _bucket(n: int, minimum: int = 128) -> int:
    """Round up to a power-of-two bucket (bounds recompilation count)."""
    b = minimum
    while b < n:
        b <<= 1
    return b


# ------------------------------------------------------------------ 1-D ops


@hgverify.entry(
    shapes=lambda: (hgverify.sds((16,), "int32"),
                    hgverify.sds((16,), "int32")),
)
@jax.jit
def member_mask(sorted_ref: jax.Array, queries: jax.Array) -> jax.Array:
    """queries ∈ sorted_ref, elementwise. Both may be SENTINEL-padded."""
    pos = jnp.searchsorted(sorted_ref, queries)
    pos = jnp.minimum(pos, sorted_ref.shape[0] - 1)
    return (sorted_ref[pos] == queries) & (queries != SENTINEL)


@jax.jit
def intersect_mask_many(base: jax.Array, others: jax.Array) -> jax.Array:
    """base (L,) vs others (M, L'): mask of base elements present in EVERY
    other set — the n-way And intersection in one fused program."""

    def body(mask, other):
        return mask & member_mask(other, base), None

    init = base != SENTINEL
    mask, _ = jax.lax.scan(body, init, others)
    return mask


# ------------------------------------------------------------------ segment search


@hgverify.entry(
    shapes=lambda: (hgverify.sds((64,), "int32"),
                    hgverify.sds((4,), "int32"),
                    hgverify.sds((4,), "int32"),
                    hgverify.sds((4, 8), "int32")),
)
@jax.jit
def segment_member_mask(
    flat: jax.Array,     # (E,) — concatenated sorted segments (CSR payload)
    starts: jax.Array,   # (K,) int32 — per-query segment start (inclusive)
    ends: jax.Array,     # (K,) int32 — per-query segment end (exclusive)
    queries: jax.Array,  # (K, L) int32 — SENTINEL-padded probe values
) -> jax.Array:
    """queries[k] ∈ flat[starts[k]:ends[k]], elementwise, WITHOUT gathering
    the segment: a branchless binary search runs directly against the CSR
    flat array with per-row bounds. This is the true vectorized zig-zag
    (``ZigZagIntersectionResult.java:37-75``): probe cost is O(L · log E)
    regardless of how large the probed row is — hub rows cost the same as
    singletons (VERDICT r1 Weak #3)."""
    shape = queries.shape
    lo = jnp.broadcast_to(starts[:, None], shape).astype(jnp.int32)
    hi = jnp.broadcast_to(ends[:, None], shape).astype(jnp.int32)
    emax = flat.shape[0] - 1

    def body(_, state):
        lo, hi = state
        active = lo < hi
        mid = (lo + hi) >> 1
        v = flat[jnp.minimum(mid, emax)]
        go_right = v < queries
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    # 32 rounds bound any int32-indexed segment length
    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    found = flat[jnp.minimum(lo, emax)]
    in_seg = lo < jnp.broadcast_to(ends[:, None], shape)
    return in_seg & (found == queries) & (queries != SENTINEL)


@partial(jax.jit, static_argnames=("pad_len",))
def incident_intersection_zigzag(
    dev: DeviceSnapshot,
    anchors: jax.Array,   # (K, P) int32 — anchors[:, 0] has the SMALLEST row
    pad_len: int,         # bucket of the base (smallest) row lengths
    type_handle: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Conjunctive incident intersection with hub-proof cost: gather only
    the base (smallest) incidence row per query and probe the other
    anchors' rows in place via :func:`segment_member_mask`. Work per query
    is O(pad_len · P · log E) — independent of hub row sizes."""
    rows0, mask = gather_rows(
        dev.inc_offsets, dev.inc_links, anchors[:, 0], pad_len
    )
    P = anchors.shape[1]
    for p in range(1, P):
        a = anchors[:, p]
        mask = mask & segment_member_mask(
            dev.inc_links, dev.inc_offsets[a], dev.inc_offsets[a + 1], rows0
        )
    if type_handle is not None:
        safe = jnp.where(rows0 == SENTINEL, 0, rows0)
        mask = mask & (dev.type_of[safe] == type_handle)
    return rows0, mask


# ------------------------------------------------------------------ ELL targets

#: cache marker for snapshots whose max arity exceeds the ELL width cap
_ELL_TOO_WIDE = object()

#: arity cap for the dense ELL targets matrix — one module-wide constant
#: (NOT a per-call knob: the matrix is cached on the snapshot, so differing
#: per-call caps would alias each other's cache entries)
ELL_MAX_WIDTH = 64


def value_columns(snap: CSRSnapshot):
    """Dense (N+1, 4) uint32 row-major pack of [rank_hi, rank_lo, kind, 0]
    — cached on the snapshot. The value kernels gather candidate rows'
    rank words; three separate column gathers cost three descriptor
    streams per candidate, while ONE 16-byte row gather fetches all of
    them (the 'rank columns into the ELL layout' move of VERDICT r4 item
    4 — measured, the value leg was gather-bound, not dispatch-bound).
    The pad lane keeps rows 16-byte aligned."""
    cached = getattr(snap, "_value_cols", None)
    if cached is not None:
        return cached
    n1 = snap.num_atoms + 1
    cols = np.zeros((n1, 4), dtype=np.uint32)
    rank = snap.value_rank[:n1]
    cols[:, 0] = (rank >> np.uint64(32)).astype(np.uint32)
    cols[:, 1] = (rank & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    kind = snap.value_kind
    cols[: len(kind), 2] = kind[:n1].astype(np.uint32)
    dev = jnp.asarray(cols)
    object.__setattr__(snap, "_value_cols", dev)
    return dev


def ell_targets(snap: CSRSnapshot):
    """Dense (N+1, W) int32 ELL matrix of each link's target tuple, padded
    with -1 — cached on the snapshot; ``None`` if any link's arity exceeds
    ``ELL_MAX_WIDTH`` (callers then fall back to the segment-search path).

    Why it exists: the conjunctive pattern ``And(type, incident(a),
    incident(b))`` needs the membership test "is anchor b a target of
    candidate link l". Probing b's incidence row costs O(log deg(b)) scattered
    loads with deg(b) up to millions on hubs; probing l's *target tuple*
    is the SAME predicate but over a row of at most max-arity (~10) entries —
    one contiguous 4·W-byte gather and a vector compare, no search at all.
    This is the hypergraph-native zig-zag: leapfrog on the short side of the
    incidence relation (ref ``impl/ZigZagIntersectionResult.java:37-75``).
    """
    cached = getattr(snap, "_tgt_ell", None)
    if cached is not None:
        return cached if cached is not _ELL_TOO_WIDE else None
    N = snap.num_atoms
    width_needed = int(snap.arity[: N + 1].max(initial=0))
    if width_needed > ELL_MAX_WIDTH:
        object.__setattr__(snap, "_tgt_ell", _ELL_TOO_WIDE)
        return None
    W = _bucket(max(width_needed, 1), minimum=2)
    e_tgt = snap.n_edges_tgt
    src = snap.tgt_src[:e_tgt].astype(np.int64)
    starts = snap.tgt_offsets[src].astype(np.int64)
    lane = np.arange(e_tgt, dtype=np.int64) - starts
    ell = np.full((N + 1) * W, -1, dtype=np.int32)
    ell[src * W + lane] = snap.tgt_flat[:e_tgt]
    dev = jnp.asarray(ell.reshape(N + 1, W))
    object.__setattr__(snap, "_tgt_ell", dev)
    return dev


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((32, 4), "int32"),
                    hgverify.sds((4, 2), "int32")),
    statics={"pad_len": 8},
)
@partial(jax.jit, static_argnames=("pad_len",))
def incident_intersection_ell(
    dev: DeviceSnapshot,
    tgt_ell: jax.Array,   # (N+1, W) int32, -1-padded
    anchors: jax.Array,   # (K, P) int32 — anchors[:, 0] has the SMALLEST row
    pad_len: int,
    type_handle: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Conjunctive incident intersection via target-tuple membership: gather
    the base anchor's incidence row (the smallest, so hub rows are never
    gathered) and, for every other anchor, one W-wide ELL row compare per
    candidate. O(pad_len · P · W) contiguous work, no binary search."""
    rows0, mask = gather_rows(
        dev.inc_offsets, dev.inc_links, anchors[:, 0], pad_len
    )
    safe = jnp.where(mask, rows0, dev.type_of.shape[0] - 1)  # dummy row N
    tg = tgt_ell[safe]  # (K, pad, W)
    P = anchors.shape[1]
    for p in range(1, P):
        mask = mask & jnp.any(tg == anchors[:, p, None, None], axis=-1)
    if type_handle is not None:
        mask = mask & (dev.type_of[safe] == type_handle)
    return rows0, mask


# ------------------------------------------------------------------ CSR rows


def gather_rows(
    offsets: jax.Array, flat: jax.Array, atoms: jax.Array, pad_len: int
) -> tuple[jax.Array, jax.Array]:
    """Gather CSR rows for ``atoms`` into a (K, pad_len) SENTINEL-padded,
    per-row-sorted matrix. Returns (rows, valid_mask)."""
    starts = offsets[atoms]
    lens = offsets[atoms + 1] - starts
    lane = jnp.arange(pad_len, dtype=jnp.int32)
    idx = starts[:, None] + lane[None, :]
    valid = lane[None, :] < lens[:, None]
    idx = jnp.where(valid, idx, 0)
    rows = jnp.where(valid, flat[idx], SENTINEL)
    return rows, valid


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((4, 2), "int32")),
    statics={"pad_len": 8},
)
@partial(jax.jit, static_argnames=("pad_len",))
def incident_intersection(
    dev: DeviceSnapshot,
    anchors: jax.Array,  # (K, P) int32 anchor atoms per query
    pad_len: int,
    type_handle: Optional[jax.Array] = None,  # scalar int32 or None
) -> tuple[jax.Array, jax.Array]:
    """The conjunctive pattern kernel: for each query k, links incident to
    ALL anchors[k, :] (optionally restricted to a type) — the device form of
    ``And(type, incident, incident, ...)`` (BASELINE config 3).

    Returns (candidates (K, pad_len) int32 rows of anchor-0's incidence,
    mask (K, pad_len) bool of survivors)."""
    rows0, valid0 = gather_rows(dev.inc_offsets, dev.inc_links, anchors[:, 0], pad_len)
    mask = valid0
    P = anchors.shape[1]
    for p in range(1, P):
        rows_p, _ = gather_rows(
            dev.inc_offsets, dev.inc_links, anchors[:, p], pad_len
        )
        mask = mask & jax.vmap(member_mask)(rows_p, rows0)
    if type_handle is not None:
        safe = jnp.where(rows0 == SENTINEL, 0, rows0)
        mask = mask & (dev.type_of[safe] == type_handle)
    return rows0, mask


@partial(jax.jit, static_argnames=("pad_len", "op", "exact"))
def incident_value_pattern(
    dev: DeviceSnapshot,
    tgt_ell: jax.Array,    # (N+1, W) int32
    anchors: jax.Array,    # (K, P) int32 — anchors[:, 0] is the base
    pad_len: int,
    kind: jax.Array,       # scalar uint8 — the value kind byte
    rank_hi: jax.Array,    # scalar uint32 — query rank, high word
    rank_lo: jax.Array,    # scalar uint32 — low word
    op: str,               # eq | lt | lte | gt | gte
    exact: bool,           # fixed-width kind: rank order == value order, no ties
    type_handle: Optional[jax.Array] = None,
    vcols: Optional[jax.Array] = None,  # (N+1, 4) value_columns row pack
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Conjunctive incident pattern with a device-side VALUE predicate —
    the pushdown the reference gets from value-indexed conjunctions
    (``cond2qry/AndToQuery.java:102-306``). Value order is compared via the
    order-preserving 64-bit payload ranks (``ops/snapshot.py`` value_rank):
    for fixed-width kinds (``exact=True``) the comparison is the value
    comparison; otherwise rank-ties return in ``tie_mask`` for host
    verification. Returns (candidate rows, definite mask, tie mask).
    ``vcols`` (see :func:`value_columns`) fetches all three rank words in
    one row gather instead of three column gathers."""
    rows0, mask = incident_intersection_ell(
        dev, tgt_ell, anchors, pad_len, type_handle
    )
    safe = jnp.where(mask, rows0, dev.type_of.shape[0] - 1)
    if vcols is not None:
        packed = vcols[safe]
        vh, vl, vk = packed[..., 0], packed[..., 1], packed[..., 2]
    else:
        vh = dev.value_rank_hi[safe]
        vl = dev.value_rank_lo[safe]
        vk = dev.value_kind[safe]
    mask = mask & (vk == kind)
    gt = (vh > rank_hi) | ((vh == rank_hi) & (vl > rank_lo))
    eq = (vh == rank_hi) & (vl == rank_lo)
    if exact:
        keep = {
            "eq": eq,
            "lt": ~gt & ~eq,
            "lte": ~gt,
            "gt": gt,
            "gte": gt | eq,
        }[op]
        return rows0, mask & keep, jnp.zeros_like(mask)
    strict = {
        "eq": jnp.zeros_like(eq),
        "lt": ~gt & ~eq,
        "lte": ~gt & ~eq,
        "gt": gt,
        "gte": gt,
    }[op]
    return rows0, mask & strict, mask & eq


@hgverify.entry(
    shapes=lambda: (
        (hgverify.dev_snapshot_exemplar(),
         hgverify.sds((32, 4), "int32"),
         hgverify.sds((4, 2), "int32")),
        {"kind": hgverify.sds((), "uint8"),
         "lo_hi": hgverify.sds((), "uint32"),
         "lo_lo": hgverify.sds((), "uint32"),
         "hi_hi": hgverify.sds((), "uint32"),
         "hi_lo": hgverify.sds((), "uint32")},
    ),
    statics={"pad_len": 8, "lo_op": "gte", "hi_op": "lt", "exact": True},
)
@partial(jax.jit, static_argnames=("pad_len", "lo_op", "hi_op", "exact"))
def incident_value_range(
    dev: DeviceSnapshot,
    tgt_ell: jax.Array,    # (N+1, W) int32
    anchors: jax.Array,    # (K, P) int32 — anchors[:, 0] is the base
    pad_len: int,
    kind: jax.Array,       # scalar uint8 — the value kind byte
    lo_hi: jax.Array,      # scalar uint32 — lower-bound rank, high word
    lo_lo: jax.Array,      # scalar uint32 — low word
    hi_hi: jax.Array,      # scalar uint32 — upper-bound rank, high word
    hi_lo: jax.Array,      # scalar uint32 — low word
    lo_op: str,            # gt | gte   (lower bound)
    hi_op: str,            # lt | lte   (upper bound)
    exact: bool,
    type_handle: Optional[jax.Array] = None,
    vcols: Optional[jax.Array] = None,  # (N+1, 4) value_columns row pack
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """BOTH value bounds of a range window in ONE launch: the incident
    intersection and the rank gathers run once, where an ``[lo, hi)``
    window previously cost two full :func:`incident_value_pattern` passes
    (VERDICT r4 item 4 — the value path was at half the pattern path's
    speedup precisely because every window paid the membership work
    twice). Per-query survivor counts come back too, so a counting caller
    downloads (K,) int32 per batch, nothing else.

    Returns (candidate rows, definite mask, tie mask, counts). Tie
    semantics mirror :func:`incident_value_pattern`: for variable-width
    kinds rank-ties at EITHER bound return in the tie mask for host
    verification."""
    rows0, mask = incident_intersection_ell(
        dev, tgt_ell, anchors, pad_len, type_handle
    )
    safe = jnp.where(mask, rows0, dev.type_of.shape[0] - 1)
    if vcols is not None:
        packed = vcols[safe]
        vh, vl, vk = packed[..., 0], packed[..., 1], packed[..., 2]
    else:
        vh = dev.value_rank_hi[safe]
        vl = dev.value_rank_lo[safe]
        vk = dev.value_kind[safe]
    mask = mask & (vk == kind)

    def against(rank_hi, rank_lo):
        gt = (vh > rank_hi) | ((vh == rank_hi) & (vl > rank_lo))
        eq = (vh == rank_hi) & (vl == rank_lo)
        return gt, eq

    gt_lo, eq_lo = against(lo_hi, lo_lo)
    gt_hi, eq_hi = against(hi_hi, hi_lo)
    if exact:
        keep_lo = gt_lo | eq_lo if lo_op == "gte" else gt_lo
        keep_hi = ~gt_hi if hi_op == "lte" else ~gt_hi & ~eq_hi
        keep = mask & keep_lo & keep_hi
        counts = keep.sum(axis=1, dtype=jnp.int32)
        return rows0, keep, jnp.zeros_like(keep), counts
    # variable-width kinds: only strictly-inside survivors are definite;
    # a tie at either bound needs the host's byte-wise comparison
    keep = mask & gt_lo & ~gt_hi & ~eq_hi
    tie = mask & (eq_lo | eq_hi)
    counts = keep.sum(axis=1, dtype=jnp.int32)
    return rows0, keep, tie, counts


@partial(jax.jit, static_argnames=("pad_len", "top_r"))
def _pattern_compact(
    dev: DeviceSnapshot,
    tgt_ell: jax.Array,
    anchors: jax.Array,
    pad_len: int,
    top_r: int,
    type_handle: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """ELL pattern kernel + on-device result compaction: returns
    (counts (K,), first_r (K, top_r) survivors in ascending order). The
    download per batch is O(K · top_r) instead of O(K · pad_len) — the
    steady-state serving path (results materialize fully on host only for
    the rare query with more than ``top_r`` matches)."""
    rows0, mask = incident_intersection_ell(
        dev, tgt_ell, anchors, pad_len, type_handle
    )
    counts = mask.sum(axis=1).astype(jnp.int32)
    ranked = jnp.where(mask, rows0, SENTINEL)
    first_r = jax.lax.sort(ranked, dimension=1)[:, :top_r]
    return counts, first_r


@dataclass
class PatternPlan:
    """Compiled + device-staged form of a conjunctive-pattern batch: anchors
    are hub-ordered, bucketed by base-row length, and uploaded once. The
    analogue of the reference's compiled ``HGQuery`` — build once, execute
    many times (``HGQuery.java:172``)."""

    snap: CSRSnapshot
    type_handle: Optional[int]
    n_queries: int
    #: per bucket: (host query indices, device anchors, pad_len)
    buckets: list[tuple[np.ndarray, jax.Array, int]]
    use_ell: bool


def plan_pattern(
    snap: CSRSnapshot,
    anchor_lists: Sequence[Sequence[int]],
    type_handle: Optional[int] = None,
) -> PatternPlan:
    """Order each query's anchors smallest-incidence-first (hub-proof:
    VERDICT r1 Weak #3 — the hub row is never the gathered base), bucket by
    power-of-two base-row length, and stage anchor arrays on device."""
    anchors = np.asarray(anchor_lists, dtype=np.int32)
    if anchors.ndim == 1:
        anchors = anchors[None, :]
    lens = snap.inc_offsets[anchors + 1] - snap.inc_offsets[anchors]
    if lens.size:
        order = np.argsort(lens, axis=1, kind="stable")
        anchors = np.take_along_axis(anchors, order, axis=1)
        base_len = np.take_along_axis(lens, order[:, :1], axis=1)[:, 0]
    else:
        base_len = np.zeros(0, dtype=np.int64)
    buckets_of = np.asarray([_bucket(int(m)) for m in base_len])
    staged = []
    for b in np.unique(buckets_of):
        sel = np.nonzero(buckets_of == b)[0]
        staged.append((sel, jnp.asarray(anchors[sel]), int(b)))
    return PatternPlan(
        snap=snap,
        type_handle=type_handle,
        n_queries=len(anchors),
        buckets=staged,
        use_ell=ell_targets(snap) is not None,
    )


def _dispatch_full(plan: PatternPlan, anchors_dev: jax.Array, pad: int):
    """The shared ell/zigzag kernel selection for full-mask outputs."""
    dev = plan.snap.device
    th = None if plan.type_handle is None else jnp.int32(plan.type_handle)
    ell = ell_targets(plan.snap) if plan.use_ell else None
    if ell is not None:
        return incident_intersection_ell(dev, ell, anchors_dev, pad, th)
    return incident_intersection_zigzag(dev, anchors_dev, pad, th)


def execute_pattern(plan: PatternPlan, top_r: int = 16) -> list[tuple]:
    """Dispatch every bucket asynchronously (no host sync — a round-trip
    per bucket would serialize the device, VERDICT r2 Weak #1) returning
    [(sel, counts_dev, first_r_dev)] handles; pair with
    :func:`collect_pattern`."""
    dev = plan.snap.device
    th = None if plan.type_handle is None else jnp.int32(plan.type_handle)
    ell = ell_targets(plan.snap) if plan.use_ell else None
    pending = []
    for sel, anchors_dev, pad in plan.buckets:
        if ell is not None:
            counts, first_r = _pattern_compact(
                dev, ell, anchors_dev, pad, top_r, th
            )
        else:
            rows, mask = incident_intersection_zigzag(
                dev, anchors_dev, pad, th
            )
            counts = mask.sum(axis=1).astype(jnp.int32)
            first_r = jax.lax.sort(
                jnp.where(mask, rows, SENTINEL), dimension=1
            )[:, :top_r]
        pending.append((sel, counts, first_r))
    return pending


def collect_pattern(plan: PatternPlan, pending: list[tuple]) -> list[np.ndarray]:
    """Sync + materialize per-query sorted result arrays. A bucket holding
    any query whose count exceeds the compact window re-runs whole through
    the full-mask kernel — same shapes as the plan's buckets, so no new
    XLA compilations accumulate in a long-lived server (overflow is rare:
    conjunctive incident patterns have small result sets)."""
    out: list[Optional[np.ndarray]] = [None] * plan.n_queries
    fetched = jax.device_get([(c, f) for _, c, f in pending])
    overflow_qis: set[int] = set()
    for (sel, _, _), (counts, first_r) in zip(pending, fetched):
        top_r = first_r.shape[1]
        over = counts > top_r
        for j, qi in enumerate(sel.tolist()):
            if over[j]:
                overflow_qis.add(qi)
            else:
                out[qi] = first_r[j, : counts[j]].astype(np.int64)
    if overflow_qis:
        for sel, anchors_dev, pad in plan.buckets:
            hit = [j for j, q in enumerate(sel.tolist()) if q in overflow_qis]
            if not hit:
                continue
            rows, mask = _dispatch_full(plan, anchors_dev, pad)
            rows = np.asarray(rows)
            mask = np.asarray(mask)
            for j in hit:
                out[int(sel[j])] = rows[j][mask[j]].astype(np.int64)
    return out  # type: ignore[return-value]


def and_incident_pattern(
    snap: CSRSnapshot,
    anchor_lists: Sequence[Sequence[int]],
    type_handle: Optional[int] = None,
) -> list[np.ndarray]:
    """Run the conjunctive-pattern kernel for K anchor tuples (all the same
    arity) and return per-query sorted result arrays — plan → execute →
    collect in one call. For repeated batches keep the :class:`PatternPlan`
    and call :func:`execute_pattern` directly (the steady-state path the
    benchmark measures)."""
    plan = plan_pattern(snap, anchor_lists, type_handle)
    return collect_pattern(plan, execute_pattern(plan))


# ------------------------------------------------------------------ planner hook


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def device_intersect_sorted(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """n-way sorted intersection of host arrays on device — used by the
    query planner for large intersections (``IntersectPlan``).

    On TPU, VMEM-sized inputs take the Pallas tiled-compare kernel
    (~3× the XLA searchsorted path on-device, see ``ops/pallas_kernels``);
    everything else falls back to vectorized searchsorted."""
    arrays = sorted(arrays, key=len)
    base = arrays[0]
    if len(base) == 0:
        return np.empty(0, dtype=np.int64)
    L = _bucket(max(len(a) for a in arrays))
    if len(arrays) > 1 and _on_tpu():
        from hypergraphdb_tpu.ops.pallas_kernels import (
            fits_vmem,
            intersect_sorted_pallas,
        )

        if fits_vmem(len(base), len(arrays) - 1, L):
            try:
                return intersect_sorted_pallas(arrays)
            except Exception:
                import logging

                logging.getLogger("hypergraphdb_tpu.ops").warning(
                    "pallas intersection failed; searchsorted fallback",
                    exc_info=True,
                )
    base_p = pad_sorted(base.astype(np.int32), L)
    others = np.stack([pad_sorted(a.astype(np.int32), L) for a in arrays[1:]])
    mask = np.asarray(intersect_mask_many(jnp.asarray(base_p), jnp.asarray(others)))
    return base_p[mask].astype(np.int64)
