"""hgindex kernels: batched range / ordered / top-k over sorted value columns.

The device replacement for the host value scan — the last query class the
serve tier still answered by walking the by-value B-tree host-side.
Against a ``storage/value_index.ValueIndexColumn`` (per-kind device
columns sorted by ``(rank, gid)``) a range predicate is two vectorized
binary searches and an ordered/top-k request is a bounded gather off the
window's relevant end — the same sorted-row machinery ``ops/setops``
exploits for intersections, pointed at the VALUE dimension (role-free
indexing, PAPERS.md arXiv:0811.1083).

Two entry points, both K-lane padded like ``ops/serving.bfs_serve_batch``
(pad lanes carry empty windows — well-defined garbage the runtime drops
by lane index):

- :func:`range_probe_batch` — per-lane lexicographic ``searchsorted``
  of the (hi, lo) rank-word bounds over one sorted column; returns the
  ``[lo_idx, hi_idx)`` window per lane (``hi_idx - lo_idx`` is the exact
  unfiltered count).
- :func:`ordered_topk_batch` — range probe over the base AND delta
  columns, bounded candidate gathers (window start for ascending lanes,
  window end for descending), per-lane type and incident-anchor filters
  (the anchor filter is ``setops.segment_member_mask`` against the
  incidence CSR — a value predicate used as a join-atom filter), then an
  on-device merge of the two sorted windows into the ``top_r``
  smallest/largest gids per lane. Truncation-honest: ``covered`` flags
  lanes whose whole window fit the gather pad (counts exact under
  filters); an uncovered+filtered lane is re-served exactly on host.

Rank-word convention throughout: the 128-bit rank pair (payload bytes
0..8 and 8..16 — ``utils/ordered_bytes.rank128``) rides as FOUR uint32
words compared lexicographically hi→lo→hi2→lo2 (the two-word
``ops/snapshot.DeviceSnapshot`` convention, extended for the hgindex
tie-break: rank-tied variable-width windows stay exact on device while
every consulted column is ``device_exact``). Fixed-width kinds carry
zero second words — the 4-word compare degenerates to the old 2-word
one bit-for-bit.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.setops import SENTINEL, segment_member_mask

#: uint32 all-ones — the rank-word pad and the descending-order complement
_U32_MAX = jnp.uint32(0xFFFFFFFF)


def _searchsorted4(col_hi: jax.Array, col_lo: jax.Array,
                   col_hi2: jax.Array, col_lo2: jax.Array,
                   n_real: jax.Array,
                   q_hi: jax.Array, q_lo: jax.Array,
                   q_hi2: jax.Array, q_lo2: jax.Array,
                   right: jax.Array) -> jax.Array:
    """Branchless per-lane binary search of 4-rank-word queries (the
    128-bit pair, hi→lo→hi2→lo2 lexicographic) over one sorted 4-word
    column, bounded by the column's REAL length (pad entries are never
    probed). ``right`` selects the insertion side per lane: False =
    leftmost position (ties insert before), True = rightmost (ties
    insert after) — how inclusive/exclusive bounds become pure data
    instead of program variants. 32 rounds bound any int32-indexed
    column (the ``setops.segment_member_mask`` discipline)."""
    m_max = col_hi.shape[0] - 1
    lo = jnp.zeros(q_hi.shape, dtype=jnp.int32)
    hi = jnp.broadcast_to(n_real.astype(jnp.int32), q_hi.shape)

    def body(_, state):
        lo, hi = state
        active = lo < hi
        mid = (lo + hi) >> 1
        m = jnp.minimum(mid, m_max)
        vh = col_hi[m]
        vl = col_lo[m]
        vh2 = col_hi2[m]
        vl2 = col_lo2[m]
        eq1 = (vh == q_hi) & (vl == q_lo)
        less = ((vh < q_hi) | ((vh == q_hi) & (vl < q_lo))
                | (eq1 & ((vh2 < q_hi2)
                          | ((vh2 == q_hi2) & (vl2 < q_lo2)))))
        eq = eq1 & (vh2 == q_hi2) & (vl2 == q_lo2)
        go_right = less | (right & eq)
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return lo


@hgverify.entry(
    shapes=lambda: (hgverify.sds((64,), "uint32"),
                    hgverify.sds((64,), "uint32"),
                    hgverify.sds((64,), "uint32"),
                    hgverify.sds((64,), "uint32"),
                    hgverify.sds((), "int32"),
                    hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
                    hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
                    hgverify.sds((8,), "bool"),
                    hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
                    hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
                    hgverify.sds((8,), "bool")),
)
@jax.jit
def range_probe_batch(
    col_hi: jax.Array,    # (M,) uint32 — sorted column, rank high words
    col_lo: jax.Array,    # (M,) uint32 — rank low words
    col_hi2: jax.Array,   # (M,) uint32 — SECOND rank word, high half
    col_lo2: jax.Array,   # (M,) uint32 — second rank word, low half
    n_real: jax.Array,    # scalar int32 — real (unpadded) entries
    lo_hi: jax.Array,     # (K,) uint32 — per-lane lower-bound rank words
    lo_lo: jax.Array,
    lo_hi2: jax.Array,    # (K,) uint32 — lower-bound second rank words
    lo_lo2: jax.Array,
    lo_right: jax.Array,  # (K,) bool — True = exclusive lower (gt)
    hi_hi: jax.Array,     # (K,) uint32 — per-lane upper-bound rank words
    hi_lo: jax.Array,
    hi_hi2: jax.Array,    # (K,) uint32 — upper-bound second rank words
    hi_lo2: jax.Array,
    hi_right: jax.Array,  # (K,) bool — True = inclusive upper (lte)
) -> tuple[jax.Array, jax.Array]:
    """K range windows over ONE sorted column in a single launch:
    returns ``(lo_idx, hi_idx)`` (K,) int32 each, clamped so
    ``hi_idx >= lo_idx`` — the exact unfiltered per-lane count is their
    difference, and the pair addresses the gather the ordered kernel (or
    a counting caller, which downloads 2·K int32 and nothing else)
    performs. Fixed-width kinds pass all-zero second words on bounds and
    column — the 4-word search then reproduces the old 2-word one
    exactly. Pad lanes: pass equal bounds (empty window)."""
    lo_idx = _searchsorted4(col_hi, col_lo, col_hi2, col_lo2, n_real,
                            lo_hi, lo_lo, lo_hi2, lo_lo2, lo_right)
    hi_idx = _searchsorted4(col_hi, col_lo, col_hi2, col_lo2, n_real,
                            hi_hi, hi_lo, hi_hi2, hi_lo2, hi_right)
    return lo_idx, jnp.maximum(hi_idx, lo_idx)


def _window_gather(col_hi, col_lo, col_hi2, col_lo2, col_gid,
                   lo_idx, hi_idx, desc, win_pad):
    """Gather up to ``win_pad`` entries per lane off each window's
    RELEVANT end (start for ascending lanes, end for descending) —
    whichever end the top-k lives at. Returns (kh, kl, kh2, kl2, gid,
    valid) of shape (K, win_pad)."""
    m_max = col_hi.shape[0] - 1
    width = hi_idx - lo_idx
    take = jnp.minimum(width, win_pad)
    start = jnp.where(desc, hi_idx - take, lo_idx)
    lane_ix = jnp.arange(win_pad, dtype=jnp.int32)
    idx = start[:, None] + lane_ix[None, :]
    valid = lane_ix[None, :] < take[:, None]
    idx = jnp.minimum(jnp.where(valid, idx, 0), m_max)
    return (col_hi[idx], col_lo[idx], col_hi2[idx], col_lo2[idx],
            col_gid[idx], valid)


@hgverify.entry(
    shapes=lambda: (
        (hgverify.sds((64,), "uint32"), hgverify.sds((64,), "uint32"),
         hgverify.sds((64,), "uint32"), hgverify.sds((64,), "uint32"),
         hgverify.sds((64,), "int32"), hgverify.sds((), "int32"),
         hgverify.sds((32,), "uint32"), hgverify.sds((32,), "uint32"),
         hgverify.sds((32,), "uint32"), hgverify.sds((32,), "uint32"),
         hgverify.sds((32,), "int32"), hgverify.sds((), "int32"),
         hgverify.sds((33,), "int32"),
         hgverify.sds((33,), "int32"), hgverify.sds((64,), "int32"),
         hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
         hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
         hgverify.sds((8,), "bool"),
         hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
         hgverify.sds((8,), "uint32"), hgverify.sds((8,), "uint32"),
         hgverify.sds((8,), "bool"),
         hgverify.sds((8,), "int32"), hgverify.sds((8,), "int32"),
         hgverify.sds((8,), "bool")),
        {},
    ),
    statics={"win_pad": 8, "top_r": 4},
)
@partial(jax.jit, static_argnames=("win_pad", "top_r"))
def ordered_topk_batch(
    col_hi: jax.Array,    # base column (storage/value_index layout)
    col_lo: jax.Array,
    col_hi2: jax.Array,   # base column second rank words
    col_lo2: jax.Array,
    col_gid: jax.Array,
    n_base: jax.Array,    # scalar int32
    d_hi: jax.Array,      # delta column (same layout, may be all-pad)
    d_lo: jax.Array,
    d_hi2: jax.Array,     # delta column second rank words
    d_lo2: jax.Array,
    d_gid: jax.Array,
    n_delta: jax.Array,   # scalar int32
    type_of: jax.Array,   # (N+1,) int32 — per-atom type handles
    inc_offsets: jax.Array,  # (N+2,) int32 — incidence CSR (anchor filter)
    inc_links: jax.Array,    # (E,) int32
    lo_hi: jax.Array,     # per-lane bounds, range_probe_batch conventions
    lo_lo: jax.Array,
    lo_hi2: jax.Array,    # lower-bound second rank words
    lo_lo2: jax.Array,
    lo_right: jax.Array,
    hi_hi: jax.Array,
    hi_lo: jax.Array,
    hi_hi2: jax.Array,    # upper-bound second rank words
    hi_lo2: jax.Array,
    hi_right: jax.Array,
    type_vec: jax.Array,  # (K,) int32 — per-lane type handle, <0 = any
    anchor_vec: jax.Array,  # (K,) int32 — per-lane incident anchor, <0 = none
    desc: jax.Array,      # (K,) bool — True = top-k LARGEST values
    win_pad: int,         # candidate gather width per column (>= top_r)
    top_r: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Range probe → filter → merged top-k, K lanes in one launch.

    Returns ``(counts, first_r, covered, window_total)``:

    - ``window_total`` (K,) int32 — the exact UNFILTERED window size
      (base + delta), straight off the probes;
    - ``covered`` (K,) bool — both windows fit the gather pad, so the
      filtered ``counts`` are exact and ``first_r`` is the complete
      filtered set's prefix; an UNcovered lane is exact only without
      filters (then ``window_total`` is its count and ``first_r`` its
      honest value-ordered prefix — valid because a merge of each
      column's first/last ``win_pad`` dominates any global top-k of
      ``top_r <= win_pad``);
    - ``counts`` (K,) int32 — filtered survivors among gathered
      candidates;
    - ``first_r`` (K, top_r) int32 — gids in the REQUESTED value order
      (ascending rank for ``desc=False`` lanes, descending for
      ``desc=True``; rank ties break toward the smaller gid either way),
      ``SENTINEL``-padded past the count.
    """
    if win_pad < top_r:
        raise ValueError(f"win_pad {win_pad} < top_r {top_r}: the merged "
                         "prefix could miss global top-k entries")
    lo_b, hi_b = range_probe_batch(col_hi, col_lo, col_hi2, col_lo2, n_base,
                                   lo_hi, lo_lo, lo_hi2, lo_lo2, lo_right,
                                   hi_hi, hi_lo, hi_hi2, hi_lo2, hi_right)
    lo_d, hi_d = range_probe_batch(d_hi, d_lo, d_hi2, d_lo2, n_delta,
                                   lo_hi, lo_lo, lo_hi2, lo_lo2, lo_right,
                                   hi_hi, hi_lo, hi_hi2, hi_lo2, hi_right)
    window_total = (hi_b - lo_b) + (hi_d - lo_d)
    covered = ((hi_b - lo_b) <= win_pad) & ((hi_d - lo_d) <= win_pad)

    bh, bl, bh2, bl2, bg, bv = _window_gather(
        col_hi, col_lo, col_hi2, col_lo2, col_gid, lo_b, hi_b, desc, win_pad)
    dh, dl, dh2, dl2, dg, dv = _window_gather(
        d_hi, d_lo, d_hi2, d_lo2, d_gid, lo_d, hi_d, desc, win_pad)
    kh = jnp.concatenate([bh, dh], axis=1)
    kl = jnp.concatenate([bl, dl], axis=1)
    kh2 = jnp.concatenate([bh2, dh2], axis=1)
    kl2 = jnp.concatenate([bl2, dl2], axis=1)
    gid = jnp.concatenate([bg, dg], axis=1)
    valid = jnp.concatenate([bv, dv], axis=1)

    n1 = type_of.shape[0]
    safe = jnp.clip(gid, 0, n1 - 1)
    want = type_vec[:, None]
    valid = valid & ((want < 0) | (type_of[safe] == want))
    # incident-anchor filter: candidate ∈ inc_row(anchor), the in-place
    # segment search of the pattern lanes — a value window acting as a
    # filter on join-atom candidates (and vice versa)
    anchor = jnp.where(anchor_vec < 0, n1 - 1, anchor_vec)  # dummy row
    probe = jnp.where(valid, gid, SENTINEL)
    member = segment_member_mask(
        inc_links, inc_offsets[anchor], inc_offsets[anchor + 1], probe
    )
    valid = valid & ((anchor_vec < 0)[:, None] | member)

    counts = valid.sum(axis=1).astype(jnp.int32)
    # requested order as a pure key transform: complement the rank words
    # on descending lanes (uint32 bitwise not reverses order); gids stay
    # ascending so rank ties break identically either way. Invalid slots
    # get max keys AFTER the transform so they sort last everywhere.
    flip = desc[:, None]
    kh = jnp.where(flip, ~kh, kh)
    kl = jnp.where(flip, ~kl, kl)
    kh2 = jnp.where(flip, ~kh2, kh2)
    kl2 = jnp.where(flip, ~kl2, kl2)
    kh = jnp.where(valid, kh, _U32_MAX)
    kl = jnp.where(valid, kl, _U32_MAX)
    kh2 = jnp.where(valid, kh2, _U32_MAX)
    kl2 = jnp.where(valid, kl2, _U32_MAX)
    gid = jnp.where(valid, gid, SENTINEL)
    _, _, _, _, sorted_gid = jax.lax.sort(
        (kh, kl, kh2, kl2, gid), num_keys=5, dimension=1)
    return counts, sorted_gid[:, :top_r], covered, window_total
