"""Fused Pallas pull-BFS megakernel: a whole hop in one ``pallas_call``.

``ops/ellbfs.py`` made the 3-hop pull BFS *correct at scale* by staging a
hop as four host-sequenced jits (``_stage`` → ``_stage_lvl0_consume`` →
``_stage_upper`` → ``_visited_update``) so the 5-6 GB stage buffers free
between launches. The price is that every hop round-trips link-live and
reach-chunk state through HBM twice and pays four dispatch RTTs — BENCH
r05 measured 13.1B edges/s but only 25 GB/s effective, **3% of the v5e
HBM peak**: the chain is latency-bound, not bandwidth-bound. This is the
materialization-boundary lesson of the EmptyHeaded/TrieJax line (PAPERS):
on accelerators, graph workloads are dominated by where intermediate sets
land, not by FLOPs.

This module removes the boundary. One :func:`pl.pallas_call` per hop runs

- **level expansion**: for every output atom row, a double-buffered
  HBM→VMEM DMA pipeline gathers the visited rows of its *fused
  adjacency* — the host-composed atom→atom relation ``{t : t ∈ tgt(l),
  l ∈ inc(v)}`` (stage 1 ∘ stage 2 of the ellbfs pyramid collapsed into
  one padded chunk plan),
- **visited dedup**: a VPU OR-fold accumulates the gathered rows straight
  into a VMEM-resident output block seeded with the old visited rows
  (OR is the dedup — no sort, no unique, no frontier array), and
- **frontier compaction**: nothing but the new visited block ever leaves
  the chip — the monotone-closure trick of ``ellbfs`` (pull from VISITED,
  frontiers telescope) means the frontier is never materialized at all.

Chunk plans ride scalar prefetch (SMEM), mirroring ``pallas_gather.py``'s
``PrefetchScalarGridSpec`` + DMA-semaphore scaffolding and its
``_vmem_bytes`` budget discipline; hglint HG5xx models the same windows.
Hops chain on-device inside ONE jit (no host sequencing, no stage
buffers: peak state is two visited bitmaps instead of visited + 10.5 GB
of stage chunks), and per-hop degree sums / final reach counts reuse the
``ellbfs`` bit-dot so results are bit-identical to the unfused chain.

Layout: the visited bitmap keeps the transposed ``(rows, Kw)`` uint32
form but rows pad up to ``KWP_MIN = 128`` lanes (512-byte rows — the
measured descriptor-rate lever, and Mosaic's minimum VMEM window width).
Narrow seed blocks (K < 4096) still run fused at 128 lanes; the spare
words are zero and sliced off on exit.

Fallback contract: everything here is gated — :func:`pallas_bfs_ok`
probes the backend once (CPU/older toolchains → False), plan builders
decline geometries whose SMEM/VMEM windows exceed budget, and callers
(``ellbfs.bfs_pull``, ``ops/serving``) keep the unfused chain as the
fallback path, so CPU tier-1 exercises the exact same entry points with
``use_pallas`` resolving to False.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.ellbfs import (
    ReducePlan,
    _apply_plan,
    _bitdot,
    _bitdot_rows,
    _ceil_to,
    _segmented_ranges,
    build_reduce_plan,
)
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

WORD = 32

#: fused-adjacency chunk width (visited rows OR'd per chunk); must divide
#: the DMA slot layout the same way pallas_gather's ``w`` does
W = 8
#: output rows per grid step — one (8, 128) uint32 tile per buffer
B = 8
#: max blocks per segment (grid size of one pallas_call); segments scan
SEG_BLOCKS = 256
#: in-flight DMA slots (D*W outstanding row copies)
D = 8
#: minimum lane width of a visited row (Mosaic VMEM window constraint —
#: narrower blocks fail to compile; also the 512-byte descriptor lever)
KWP_MIN = 128
#: per-core SMEM budget for the scalar-prefetched chunk plan (matches
#: hglint HG503's model); we claim at most half, like pallas_gather.SEG
SMEM_BUDGET = 1 << 20
#: per-core VMEM budget the kernel working set must fit (hglint HG501)
VMEM_BUDGET = 16 << 20
#: upper-level / overlay reduction stream chunk (XLA path)
CHUNK = 1 << 16


def _vmem_bytes(kwp: int, w: int = W) -> int:
    """Static VMEM working set of one hop call: the (B, kwp) old-visited
    and output windows double-buffered across grid steps, plus the
    (D*w, kwp) DMA row scratch. ``kwp`` is runtime-chosen, so hglint
    HG502 cannot fold this bound — this guard enforces it instead.
    ``w`` must be the PLAN's chunk width (``geom.w``), not assumed."""
    return 4 * kwp * (2 * B + 2 * B + D * w)


def _smem_bytes(cap: int, nb: int, w: int = W) -> int:
    """Scalar-prefetch bytes of one hop call: the (cap*w,) int32 fused
    index segment, the (cap,) chunk→row map, and the (nb+1,) block
    bounds. Must leave Mosaic its own SMEM headroom (half budget).
    ``w`` must be the PLAN's chunk width (``geom.w``), not assumed."""
    return 4 * (cap * w + cap + nb + 1)


# ---------------------------------------------------------------- host plans


class FusedGeom(NamedTuple):
    """Static geometry of a fused plan (hashable — rides jit statics)."""

    n_atoms: int     # N; row N is the dummy row
    n_rows: int      # padded row space = n_seg * nb * B; last row is zero
    n_seg: int       # pallas_call segments per hop
    nb: int          # blocks (grid steps) per segment
    cap: int         # chunk capacity per segment
    w: int           # chunk width
    zero_row: int    # guaranteed-all-zero visited row (= n_rows - 1)
    total_entries: int  # real fused-adjacency entries (traffic model)


@dataclass(frozen=True)
class FusedPlan:
    """Host precompute for the fused hop over one snapshot.

    The fused adjacency composes the two ellbfs stages on host: row ``v``
    lists every atom ``t`` with ``t ∈ tgt(l)`` for some incident link
    ``l ∈ inc(v)`` (duplicates kept — OR is idempotent and dedup would
    cost a sort). Rows pad to whole ``w``-chunks (pad entries gather the
    zero row); chunks order row-major, rows tile into ``B``-row blocks,
    blocks into ``nb``-block segments of uniform ``cap`` chunk capacity.
    """

    geom: FusedGeom
    blk_off: np.ndarray     # (n_seg, nb+1) int32 — chunk bounds per block
    chunk_rows: np.ndarray  # (n_seg, cap) int32 — segment-local row per chunk
    idx: np.ndarray         # (n_seg, cap*w) int32 — visited rows to gather
    inc_deg: np.ndarray     # (n_rows,) int32 — incidence degree (edge count)

    @property
    def smem_ok(self) -> bool:
        return _smem_bytes(self.geom.cap, self.geom.nb,
                           self.geom.w) <= SMEM_BUDGET // 2


def build_fused_plan(snap: CSRSnapshot, w: int = W) -> FusedPlan:
    """Compose the snapshot's two CSR stages into the fused chunk plan."""
    N = snap.num_atoms
    n1 = N + 1
    inc_off = np.asarray(snap.inc_offsets[: n1 + 1], dtype=np.int64)
    inc_links = np.asarray(snap.inc_links[: snap.n_edges_inc],
                           dtype=np.int64)
    tgt_off = np.asarray(snap.tgt_offsets[: n1 + 1], dtype=np.int64)
    tgt_flat = np.asarray(snap.tgt_flat[: snap.n_edges_tgt], dtype=np.int32)

    e_inc = len(inc_links)
    # per incidence entry: arity of its link; fused degree per atom = the
    # segment sum over its incidence row (all cumsums — no np.repeat, the
    # plan-build lesson of VERDICT r4)
    ar = tgt_off[inc_links + 1] - tgt_off[inc_links]
    pre = np.zeros(e_inc + 1, dtype=np.int64)
    np.cumsum(ar, out=pre[1:])
    fused_deg = pre[inc_off[1 : n1 + 1]] - pre[inc_off[:n1]]
    nchunk = -(-fused_deg // w)  # ceil; 0 for empty rows

    # row space: n1 atom rows + at least one spare all-zero row, tiled
    # into B-row blocks and nb-block segments
    n_blocks = -(-(n1 + 1) // B)
    nb = min(n_blocks, SEG_BLOCKS)
    n_seg = -(-n_blocks // nb)
    n_rows = n_seg * nb * B
    zero_row = n_rows - 1

    row_chunks = np.zeros(n_rows, dtype=np.int64)
    row_chunks[:n1] = nchunk
    row_chunk_starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(row_chunks, out=row_chunk_starts[1:])
    total_chunks = int(row_chunk_starts[-1])

    # segment tiling: segment s covers rows [s*nb*B, (s+1)*nb*B); its
    # chunk span is the row-chunk-starts slice at those boundaries; cap =
    # the widest segment (uniform shapes keep the per-hop scan traceable)
    rows_per_seg = nb * B
    seg_off = row_chunk_starts[:: rows_per_seg]  # exactly n_seg + 1 entries
    seg_counts = seg_off[1:] - seg_off[:-1]
    cap = max(int(seg_counts.max(initial=0)), 1)

    geom = FusedGeom(
        n_atoms=N, n_rows=n_rows, n_seg=n_seg, nb=nb, cap=cap, w=w,
        zero_row=zero_row, total_entries=int(fused_deg.sum()),
    )
    if _smem_bytes(cap, nb, w) > SMEM_BUDGET // 2:
        # hub rows blow the scalar-prefetch window: decline CHEAPLY,
        # before materializing the O(composition) fused adjacency — on a
        # hub-heavy graph that array can dwarf the CSR itself, and the
        # staged chain is about to serve this snapshot anyway
        empty = np.zeros((0,), dtype=np.int32)
        return FusedPlan(
            geom=geom, blk_off=empty.reshape(0, nb + 1),
            chunk_rows=empty.reshape(0, 0), idx=empty.reshape(0, 0),
            inc_deg=empty,
        )

    # flat level-0 index array, padded per row (pad → zero row)
    idx_flat = np.full(total_chunks * w, zero_row, dtype=np.int32)
    if e_inc:
        # atom id per incidence entry, via boundary marks (O(E) cumsum):
        # atom_of[e] = #{row starts inc_off[1..n1-1] that are <= e}
        marks = np.zeros(e_inc, dtype=np.int64)
        bounds = inc_off[1:n1]
        np.add.at(marks, bounds[bounds < e_inc], 1)
        atom_of = np.cumsum(marks)
        row_pad_starts = row_chunk_starts * w
        dst_start = (
            row_pad_starts[atom_of] + (pre[:e_inc] - pre[inc_off[atom_of]])
        )
        live = np.nonzero(ar)[0]
        if len(live):
            dst = _segmented_ranges(dst_start[live], ar[live])
            src = _segmented_ranges(tgt_off[inc_links[live]], ar[live])
            idx_flat[dst] = tgt_flat[src]

    # chunk → row map (global), via the same boundary-marks trick
    chunk_row_g = np.zeros(max(total_chunks, 1), dtype=np.int64)
    if total_chunks:
        bmarks = np.zeros(total_chunks, dtype=np.int64)
        bounds = row_chunk_starts[1:n_rows]
        np.add.at(bmarks, bounds[bounds < total_chunks], 1)
        chunk_row_g = np.cumsum(bmarks)

    blk_off = np.zeros((n_seg, nb + 1), dtype=np.int32)
    chunk_rows = np.zeros((n_seg, cap), dtype=np.int32)
    idx = np.full((n_seg, cap * w), zero_row, dtype=np.int32)
    for s in range(n_seg):
        c0, c1 = int(seg_off[s]), int(seg_off[s + 1])
        blk_off[s] = (
            row_chunk_starts[s * rows_per_seg : (s + 1) * rows_per_seg + 1 : B]
            - c0
        ).astype(np.int32)
        n_c = c1 - c0
        if n_c:
            chunk_rows[s, :n_c] = (
                chunk_row_g[c0:c1] - s * rows_per_seg
            ).astype(np.int32)
            idx[s, : n_c * w] = idx_flat[c0 * w : c1 * w]

    inc_deg = np.zeros(n_rows, dtype=np.int32)
    inc_deg[:n1] = (inc_off[1 : n1 + 1] - inc_off[:n1]).astype(np.int32)
    inc_deg[N] = 0  # dummy row counts nothing

    return FusedPlan(geom=geom, blk_off=blk_off, chunk_rows=chunk_rows,
                     idx=idx, inc_deg=inc_deg)


def fused_plans_for(snap: CSRSnapshot) -> FusedPlan:
    """Fused plan for a snapshot — memoized on the snapshot object (the
    ``plans_for`` discipline; rebuilt only when the snapshot changes)."""
    plan = getattr(snap, "_fused_plan", None)
    if plan is None:
        plan = build_fused_plan(snap)
        object.__setattr__(snap, "_fused_plan", plan)
    return plan


class DeviceFusedPlan(NamedTuple):
    """Device staging of a :class:`FusedPlan` (a pytree of arrays; the
    static geometry travels separately as a :class:`FusedGeom`)."""

    blk_off: jax.Array
    chunk_rows: jax.Array
    idx: jax.Array
    inc_deg: jax.Array


def device_fused_plan(snap: CSRSnapshot) -> tuple[DeviceFusedPlan, FusedGeom]:
    dev = getattr(snap, "_fused_device", None)
    if dev is None:
        plan = fused_plans_for(snap)
        if plan.blk_off.shape[0] != plan.geom.n_seg:
            # build_fused_plan declined (SMEM window) without
            # materializing the adjacency — callers must gate on
            # plan_supported/fused_ready before staging
            raise ValueError(
                "fused plan declined for this snapshot: "
                + (plan_supported(snap, WORD) or "SMEM window overflow")
            )
        dev = (
            DeviceFusedPlan(
                blk_off=jnp.asarray(plan.blk_off),
                chunk_rows=jnp.asarray(plan.chunk_rows),
                idx=jnp.asarray(plan.idx),
                inc_deg=jnp.asarray(plan.inc_deg),
            ),
            plan.geom,
        )
        object.__setattr__(snap, "_fused_device", dev)
    return dev


# ---------------------------------------------------------------- the kernel


def _hop_kernel(blk_off_ref, chunk_rows_ref, idx_ref, visited_hbm, vis_blk,
                out_ref, rows, sems, *, w, block_rows, d):
    """One grid step = one B-row output block of the new visited bitmap.

    The block's chunk span comes from the scalar-prefetched bounds; each
    chunk is ``w`` single-row async copies into one of ``d`` DMA slots
    (double buffering: chunk c+d streams while chunk c folds), OR-folded
    on the VPU and OR'd into the block-local output row — the old visited
    rows seed the output, so expansion, dedup, and the visited update are
    one pass with nothing intermediate leaving VMEM."""
    b = pl.program_id(0)
    c_lo = blk_off_ref[b]
    c_hi = blk_off_ref[b + 1]
    nc = c_hi - c_lo
    out_ref[...] = vis_blk[...]

    def start(c, slot):
        for j in range(w):
            pltpu.make_async_copy(
                visited_hbm.at[pl.ds(idx_ref[c * w + j], 1), :],
                rows.at[pl.ds(slot * w + j, 1), :],
                sems.at[slot],
            ).start()

    for p in range(d):
        @pl.when(p < nc)
        def _(p=p):
            start(c_lo + p, p)

    def body(i, _):
        slot = jax.lax.rem(i, d)
        pltpu.make_async_copy(
            rows.at[pl.ds(slot * w, w), :],
            rows.at[pl.ds(slot * w, w), :],
            sems.at[slot],
        ).wait()
        base = slot * w
        res = rows[pl.ds(base, 1), :]
        for j in range(1, w):
            res = res | rows[pl.ds(base + j, 1), :]
        r = chunk_rows_ref[c_lo + i] - b * block_rows
        out_ref[pl.ds(r, 1), :] = out_ref[pl.ds(r, 1), :] | res

        @pl.when(i + d < nc)
        def _():
            start(c_lo + i + d, slot)

        return 0

    jax.lax.fori_loop(0, nc, body, 0)


def _hop_call(blk_off_s, chunk_rows_s, idx_s, visited, vis_seg, *,
              nb, w, interpret):
    kwp = visited.shape[1]
    # budget enforced by the callers' _vmem_bytes/_smem_bytes guards
    # (runtime shapes, same discipline as pallas_gather)
    return pl.pallas_call(  # hglint: disable=HG502
        functools.partial(_hop_kernel, w=w, block_rows=B, d=D),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((B, kwp), lambda i, s0, s1, s2: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((B, kwp), lambda i, s0, s1, s2: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((D * w, kwp), jnp.uint32),
                            pltpu.SemaphoreType.DMA((D,))],
        ),
        out_shape=jax.ShapeDtypeStruct((nb * B, kwp), jnp.uint32),
        interpret=interpret,
    )(blk_off_s, chunk_rows_s, idx_s, visited, vis_seg)


def _hop_fused(visited: jax.Array, plan: DeviceFusedPlan, geom: FusedGeom,
               interpret: bool) -> jax.Array:
    """One full hop: new visited = old | fused-adjacency OR-gather."""
    kwp = visited.shape[1]
    rows_per_seg = geom.nb * B
    if geom.n_seg == 1:
        return _hop_call(
            plan.blk_off[0], plan.chunk_rows[0], plan.idx[0],
            visited, visited, nb=geom.nb, w=geom.w, interpret=interpret,
        )

    def body(_, xs):
        off, cr, ix, s = xs
        vis_seg = jax.lax.dynamic_slice(
            visited, (s * rows_per_seg, 0), (rows_per_seg, kwp)
        )
        return None, _hop_call(off, cr, ix, visited, vis_seg,
                               nb=geom.nb, w=geom.w, interpret=interpret)

    _, outs = jax.lax.scan(
        body, None,
        (plan.blk_off, plan.chunk_rows, plan.idx,
         jnp.arange(geom.n_seg, dtype=jnp.int32)),
    )
    return outs.reshape(geom.n_rows, kwp)


# ------------------------------------------------------------- delta overlay


class OverlayArrays(NamedTuple):
    """Device half of a :class:`DeltaOverlayPlan` (pytree of arrays)."""

    levels1: tuple     # stage-1 index pyramid (delta links ← visited rows)
    levels2: tuple     # stage-2 pyramid, level 0 composed into stage-1 space
    out_map: jax.Array  # (A,) int32 — stage-2 concat chunk per delta row
    rows: jax.Array     # (A,) int32 — UNIQUE atom rows gaining delta edges


@dataclass(frozen=True)
class DeltaOverlayPlan:
    """Host plan for the delta COO's pull contribution: the miniature twin
    of ``ellbfs.build_pull_plans`` over ONLY the delta edges, with output
    restricted to the atoms that actually gained incidence — so applying
    the overlay costs O(delta), not O(graph). Built once per device-delta
    refresh (cached on the delta object) from the delta's own padded
    arrays, so it describes exactly what the unfused kernel sees."""

    arrays: OverlayArrays
    widths1: tuple
    widths2: tuple


def overlay_plan_for(delta, n_atoms: int,
                     geom: FusedGeom) -> Optional[DeltaOverlayPlan]:
    """Overlay plan for a DeviceDelta (None = delta carries no edges).
    Raises nothing: any structural surprise simply returns None and the
    caller falls back to the unfused chain."""
    cached = getattr(delta, "_overlay_plan", None)
    if cached is not None:
        plan, key = cached
        if key == (n_atoms, geom.zero_row):
            return plan
    plan = _build_overlay(delta, n_atoms, geom)
    try:
        delta._overlay_plan = (plan, (n_atoms, geom.zero_row))
    except Exception:  # pragma: no cover  # hglint: disable=HG1005
        pass  # frozen delta variants reject the cache slot; rebuilt per call
    return plan


def _build_overlay(delta, n_atoms: int,
                   geom: FusedGeom) -> Optional[DeltaOverlayPlan]:
    tgt_src = np.asarray(delta.tgt_src)
    tgt_flat = np.asarray(delta.tgt_flat)
    inc_links = np.asarray(delta.inc_links)
    inc_src = np.asarray(delta.inc_src)
    real_t = tgt_src != n_atoms       # pad fill is the dummy row id
    real_i = inc_links != n_atoms
    if not real_t.any() or not real_i.any():
        return None

    # stage 1: delta links' target lists as a compact CSR
    ts, tf = tgt_src[real_t], tgt_flat[real_t]
    order = np.argsort(ts, kind="stable")
    ts, tf = ts[order], tf[order]
    links_u, l_counts = np.unique(ts, return_counts=True)
    n_links = len(links_u)
    l_off = np.zeros(n_links + 1, dtype=np.int64)
    np.cumsum(l_counts, out=l_off[1:])
    s1 = build_reduce_plan(l_off, tf, n_links, zero_row=geom.zero_row)

    # stage 2: delta incidence grouped by atom, level 0 composed through
    # stage-1's out_map (the build_pull_plans composition)
    isrc, il = inc_src[real_i], inc_links[real_i]
    order = np.argsort(isrc, kind="stable")
    isrc, il = isrc[order], il[order]
    lpos = np.searchsorted(links_u, il)
    # a delta incidence whose link has no target entries contributes
    # nothing — point it at the stage-1 zero chunk
    bad = (lpos >= n_links) | (links_u[np.minimum(lpos, n_links - 1)] != il)
    lpos = np.where(bad, n_links, lpos)
    atoms_u, a_counts = np.unique(isrc, return_counts=True)
    n_a = len(atoms_u)
    a_off = np.zeros(n_a + 1, dtype=np.int64)
    np.cumsum(a_counts, out=a_off[1:])
    s2 = build_reduce_plan(a_off, lpos, n_a, zero_row=n_links)
    out_map_ext = np.concatenate(
        [s1.out_map, np.asarray([s1.concat_size], dtype=np.int32)]
    )
    lvl0 = out_map_ext[s2.levels[0]]

    arrays = OverlayArrays(
        levels1=tuple(jnp.asarray(l) for l in s1.levels),
        levels2=tuple(jnp.asarray(l)
                      for l in (lvl0,) + s2.levels[1:]),
        out_map=jnp.asarray(s2.out_map),
        rows=jnp.asarray(atoms_u.astype(np.int32)),
    )
    return DeltaOverlayPlan(arrays=arrays, widths1=s1.widths,
                            widths2=s2.widths)


def _overlay_reach(visited: jax.Array, ov: OverlayArrays,
                   widths1: tuple, widths2: tuple) -> jax.Array:
    """The delta edges' pull contribution for ``ov.rows``: (A, Kwp)."""
    buf1 = _apply_plan(visited, ov.levels1, widths1, CHUNK, False)
    buf2 = _apply_plan(buf1, ov.levels2, widths2, CHUNK, False)
    return buf2[ov.out_map]


# --------------------------------------------------------------- fused BFS


def _seed_rows(seeds: jax.Array, n_rows: int, kwp: int) -> jax.Array:
    """Transposed seed bitmap over the fused row space — the
    ``ellbfs._seed_bitmap`` construction at ``kwp`` lane width, WITHOUT
    clearing the dummy row (serve parity keeps pad-lane seed bits; pull
    callers clear it explicitly)."""
    K = seeds.shape[0]
    k = jnp.arange(K, dtype=jnp.int32)
    bit = jnp.left_shift(jnp.uint32(1), (k & 31).astype(jnp.uint32))
    onehot = jnp.zeros((K, kwp), dtype=jnp.uint32).at[k, k >> 5].set(bit)
    return jnp.zeros((n_rows, kwp), dtype=jnp.uint32).at[seeds].add(onehot)


#: the ONE toy fused instance both this module's and ``ops/serving``'s
#: ``@hgverify.entry`` exemplars trace — a plan-layout change edits it
#: here and both harvests follow (no copy-paste drift)
EXEMPLAR_GEOM = FusedGeom(n_atoms=14, n_rows=16, n_seg=1, nb=2, cap=4,
                          w=8, zero_row=15, total_entries=20)


def exemplar_shapes() -> tuple:
    """``(DeviceFusedPlan, seeds, n_atoms)`` avals matching
    :data:`EXEMPLAR_GEOM` — the shared hgverify exemplar builder."""
    return (
        DeviceFusedPlan(
            blk_off=hgverify.sds((1, 3), "int32"),
            chunk_rows=hgverify.sds((1, 4), "int32"),
            idx=hgverify.sds((1, 32), "int32"),
            inc_deg=hgverify.sds((16,), "int32"),
        ),
        hgverify.sds((32,), "int32"),
        hgverify.sds((), "int32"),
    )


@hgverify.entry(
    shapes=exemplar_shapes,
    statics={
        "geom": EXEMPLAR_GEOM,
        "kwp": 128, "max_hops": 2, "count_edges": True,
        "clear_dummy": True, "widths1": None, "widths2": None,
        "interpret": True,
    },
)
@partial(jax.jit, static_argnames=(
    "geom", "kwp", "max_hops", "count_edges", "clear_dummy",
    "widths1", "widths2", "interpret",
))
def _bfs_fused(
    plan: DeviceFusedPlan,
    seeds: jax.Array,          # (K,) int32 — K % 32 == 0, K <= kwp * 32
    n_atoms: jax.Array,        # scalar int32 — dummy row id
    geom: FusedGeom,
    kwp: int,
    max_hops: int,
    count_edges: bool,
    clear_dummy: bool,
    overlay: Optional[OverlayArrays] = None,
    widths1: Optional[tuple] = None,
    widths2: Optional[tuple] = None,
    interpret: bool = False,
) -> tuple[jax.Array, tuple, jax.Array]:
    """The whole fused BFS in ONE dispatch: seed bitmap → ``max_hops``
    fused hops (+ optional delta overlay per hop) → per-hop degree sums →
    reach counts. Returns ``(visited (n_rows, kwp) uint32, s_per_hop
    tuple, reach (kwp*32,) int32)``. Bit-identical to the unfused
    ``ellbfs`` chain on the same inputs."""
    visited = _seed_rows(seeds, geom.n_rows, kwp)
    if clear_dummy:
        visited = visited.at[n_atoms].set(jnp.uint32(0))
    deg_f = plan.inc_deg.astype(jnp.float32)
    rows = _bitdot_rows(kwp * WORD, geom.n_rows)
    s_ins = []
    for _ in range(max_hops):
        if count_edges:
            s_ins.append(_bitdot(visited, deg_f, rows))
        if overlay is not None:
            ov = _overlay_reach(visited, overlay, widths1, widths2)
        visited = _hop_fused(visited, plan, geom, interpret)
        if overlay is not None:
            visited = visited.at[overlay.rows].set(
                visited[overlay.rows] | ov
            )
    reach = _bitdot(visited, jnp.ones((geom.n_rows,), jnp.float32), rows)
    return visited, tuple(s_ins), reach


def bfs_pull_fused(
    snap: CSRSnapshot,
    seeds: np.ndarray,
    max_hops: int,
    count_edges: bool = True,
    interpret: bool = False,
):
    """Fused-path twin of one ``ellbfs._bfs_pull_device`` block: returns
    ``(visited_t (n_pad, Kw) uint32, s_ins list, reach (K,) int32)`` with
    the exact ``bfs_pull`` per-block contract (pad seeds = dummy row,
    dummy row cleared). ``Kw`` is the caller's K/32; lanes pad to
    :data:`KWP_MIN` internally and slice off on exit."""
    plan, geom = device_fused_plan(snap)
    seeds = jnp.asarray(np.asarray(seeds, dtype=np.int32))
    K = seeds.shape[0]
    kw = K // WORD
    kwp = max(_ceil_to(kw, KWP_MIN), KWP_MIN)
    visited, s_ins, reach = _bfs_fused(
        plan, seeds, jnp.int32(geom.n_atoms), geom, kwp, max_hops,
        count_edges, True, interpret=interpret,
    )
    n_pad = _ceil_to(geom.n_atoms + 1, 8)
    visited_t = visited[:n_pad, :kw]
    return visited_t, [s[:K] for s in s_ins], reach[:K]


def serve_fused_kwargs(base_snap: CSRSnapshot, delta,
                       k_bucket: int) -> Optional[dict]:
    """The ``ops/serving.bfs_serve_batch_fused`` argument bundle for one
    pinned (base, delta) pair, or None when the fused path must decline
    (budget overflow, or a delta whose overlay cannot be planned). Does
    NOT check tombstones or the backend probe — the executor owns those
    gates (it sees the pinned view's dead set and the runtime config)."""
    if plan_supported(base_snap, k_bucket) is not None:
        return None
    plan, geom = device_fused_plan(base_snap)
    kwp = max(_ceil_to(max(k_bucket, WORD) // WORD, KWP_MIN), KWP_MIN)
    out = {
        "fused": plan,
        "n_atoms": jnp.int32(geom.n_atoms),
        "geom": geom,
        "kwp": kwp,
        "overlay": None,
        "widths1": None,
        "widths2": None,
    }
    if delta is not None:
        ov = overlay_plan_for(delta, base_snap.num_atoms, geom)
        if ov is not None:
            out.update(overlay=ov.arrays, widths1=ov.widths1,
                       widths2=ov.widths2)
    return out


def first_r_from_bitmap(visited: jax.Array, n1: jax.Array,
                        top_r: int, K: int) -> jax.Array:
    """The serving compaction (``ops/serving.bfs_serve_batch`` contract)
    read straight off the transposed bitmap: per seed the ``top_r``
    smallest reached atom ids ascending, SENTINEL-padded — streamed in
    row blocks with a per-block top-k + merge so the (rows, K) unpack
    transient stays bounded instead of materializing whole."""
    from hypergraphdb_tpu.ops.setops import SENTINEL

    R, kwp = visited.shape
    rb = min(4096, R)
    n_blocks = -(-R // rb)
    cols = jnp.arange(K, dtype=jnp.int32)
    word = cols >> 5
    bit = (cols & 31).astype(jnp.uint32)
    init = jnp.full((K, top_r), SENTINEL, jnp.int32)
    # a block holds at most rb candidate rows — clamp the per-block top_k
    # so top_r > rb (the dense path serves it fine) cannot over-ask the
    # rb-wide lane at trace time; the merge below still yields top_r
    blk_r = min(top_r, rb)

    def body(i, cur):
        start = jnp.minimum(i * rb, R - rb)
        blk = jax.lax.dynamic_slice(visited, (start, 0), (rb, kwp))
        ids = start + jnp.arange(rb, dtype=jnp.int32)
        # the last block's clamped start overlaps the previous block; the
        # fresh mask zeroes already-counted rows (the _bitdot discipline)
        fresh = ids >= i * rb
        hit = ((blk[:, word] >> bit[None, :]) & 1).astype(bool)
        valid = fresh & (ids < n1)
        masked = jnp.where(hit & valid[:, None], ids[:, None], SENTINEL)
        blk_top = -jax.lax.top_k(-masked.T, blk_r)[0]
        merged = jnp.sort(
            jnp.concatenate([cur, blk_top], axis=1), axis=1
        )
        return merged[:, :top_r]

    return jax.lax.fori_loop(0, n_blocks, body, init)


# ----------------------------------------------------------------- gating


_PREFLIGHT: dict[str, bool] = {}


def pallas_bfs_ok() -> bool:
    """True when the fused hop kernel compiles and runs correctly on the
    default backend — probed once with a tiny instance, cached. Guarded
    by ``HG_PALLAS_BFS`` (default on)."""
    if os.environ.get("HG_PALLAS_BFS", "1") in ("0", "false", "no"):
        return False
    backend = jax.default_backend()
    hit = _PREFLIGHT.get(backend)
    if hit is not None:
        return hit
    if backend != "tpu":
        _PREFLIGHT[backend] = False
        return False
    try:
        ok = _probe()
    except Exception:  # noqa: BLE001 - any compile/runtime failure → fallback
        ok = False
    _PREFLIGHT[backend] = ok
    return ok


def _probe() -> bool:
    """A 2-block, 1-segment instance with a known OR pattern."""
    kwp = KWP_MIN
    n_rows = 2 * B
    visited = jnp.zeros((n_rows, kwp), jnp.uint32).at[0, 0].set(
        jnp.uint32(1)
    )
    # one chunk: row 1 pulls row 0 (w copies of it)
    blk_off = jnp.asarray([[0, 1, 1]], jnp.int32)
    chunk_rows = jnp.asarray([[1]], jnp.int32)
    idx = jnp.zeros((1, W), jnp.int32)
    out = _hop_call(blk_off[0], chunk_rows[0], idx[0], visited, visited,
                    nb=2, w=W, interpret=False)
    res = np.asarray(out)
    return bool(res[1, 0] == 1 and res[0, 0] == 1 and res[2:].sum() == 0)


def fused_ready(snap: CSRSnapshot, k_block: int) -> bool:
    """Should ``bfs_pull`` route this seed block through the fused path?
    Requires the backend probe, ``k_block`` a WORD multiple, and the
    snapshot's plan inside the SMEM/VMEM windows."""
    if k_block % WORD or not pallas_bfs_ok():
        return False
    return plan_supported(snap, k_block) is None


def plan_supported(snap: CSRSnapshot, k_block: int) -> Optional[str]:
    """None when the fused plan fits the budget model for this block
    width; otherwise the human-readable reason it must fall back."""
    kwp = max(_ceil_to(max(k_block, WORD) // WORD, KWP_MIN), KWP_MIN)
    if _vmem_bytes(kwp) > VMEM_BUDGET:
        # cheap decline before the O(E) plan build; snapshot plans are
        # always built at the default chunk width W
        return (f"VMEM working set {_vmem_bytes(kwp)} B exceeds the "
                f"{VMEM_BUDGET} B budget at kwp={kwp}")
    plan = fused_plans_for(snap)
    g = plan.geom
    if _vmem_bytes(kwp, g.w) > VMEM_BUDGET:
        return (f"VMEM working set {_vmem_bytes(kwp, g.w)} B exceeds the "
                f"{VMEM_BUDGET} B budget at kwp={kwp}, w={g.w}")
    if not plan.smem_ok:
        return (f"scalar-prefetch segment "
                f"{_smem_bytes(g.cap, g.nb, g.w)} B "
                f"exceeds half the {SMEM_BUDGET} B SMEM budget "
                f"(cap={g.cap}) — hub rows too wide to prefetch")
    return None


def fused_bytes_per_hop(geom: FusedGeom, K: int) -> int:
    """HBM traffic model of one fused hop, the honest-counting twin of
    ``bench.pull_bytes_per_run``: one Kwp-word row DMA per fused chunk
    entry, the scalar plan reads, and one read+write of the (n_rows, kwp)
    visited state; no stage buffers, no out_map re-gather."""
    kwp = max(_ceil_to(max(K, WORD) // WORD, KWP_MIN), KWP_MIN)
    row_bytes = kwp * 4
    n_chunks = -(-geom.total_entries // geom.w)
    per_hop = geom.total_entries * row_bytes        # gathered rows
    per_hop += n_chunks * (geom.w + 1) * 4          # idx + chunk_rows reads
    per_hop += geom.n_rows * row_bytes * 2          # visited read + write
    return per_hop
