"""Batched BFS frontier expansion as CSR hyperedge message passing.

The device-plane replacement for the reference's pointer-chasing traversal
hot loop (``HGBreadthFirstTraversal.java:49-66`` + ``DefaultALGenerator.java:
504-509``: per-atom incidence fetch, per-link target iteration). Here one
BFS hop over *all* seeds simultaneously is two fixed-shape scatter-max ops
over the flattened incidence/target relations:

    link_active[l]  = OR_{(a,l) ∈ incidence} frontier[a]      (atom → link)
    neighbor[t]     = OR_{(l,t) ∈ targets}   link_active[l]   (link → target)

Boolean semiring message passing (GraphBLAS-style "push" BFS) — no dynamic
shapes, no host sync per hop, every op maps onto the VPU's vector lanes, and
hops compose under ``lax.fori_loop`` inside a single ``jit``. Frontiers are
dense bitmaps over the id space; the dummy row ``N`` absorbs padded edges.

Semantics match ``SimpleALGenerator``: neighbors(a) = ∪ targets(l) for l in
incidence(a), minus already-visited atoms (the seed itself is visited at
hop 0, reproducing the "exclude self" rule).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot

def expand_frontier(dev: DeviceSnapshot, frontier: jax.Array) -> jax.Array:
    """One hop: frontier bitmap (..., N+1) → neighbor bitmap (..., N+1)."""

    def one(f):
        link_active = (
            jnp.zeros_like(f).at[dev.inc_links].max(f[dev.inc_src])
        )
        nbrs = (
            jnp.zeros_like(f).at[dev.tgt_flat].max(link_active[dev.tgt_src])
        )
        return nbrs.at[dev.num_atoms].set(False)  # clear the dummy slot

    if frontier.ndim == 1:
        return one(frontier)
    return jax.vmap(one)(frontier)


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((8,), "int32")),
    statics={"max_hops": 2},
)
@partial(jax.jit, static_argnames=("max_hops",))
def bfs_levels(
    dev: DeviceSnapshot, seeds: jax.Array, max_hops: int
) -> tuple[jax.Array, jax.Array]:
    """Batched K-seed BFS. Returns (levels, visited):

    - ``levels``: (K, N+1) int32, hop distance from each seed (-1 unreachable),
    - ``visited``: (K, N+1) bool reachable-within-max_hops mask.

    The whole multi-hop loop compiles to one XLA program (no host syncs) —
    the direct counter to the reference's per-hop cursor reads.
    """
    K = seeds.shape[0]
    n1 = dev.type_of.shape[0]
    frontier = jnp.zeros((K, n1), dtype=bool).at[jnp.arange(K), seeds].set(True)
    visited = frontier
    levels = jnp.where(frontier, 0, -1).astype(jnp.int32)

    def body(i, state):
        frontier, visited, levels = state
        nxt = expand_frontier(dev, frontier) & ~visited
        levels = jnp.where(nxt, i + 1, levels)
        return nxt, visited | nxt, levels

    frontier, visited, levels = jax.lax.fori_loop(
        0, max_hops, body, (frontier, visited, levels)
    )
    return levels, visited


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((), "int32")),
    statics={"max_hops": 2},
)
@partial(jax.jit, static_argnames=("max_hops",))
def reachable(dev: DeviceSnapshot, seed: jax.Array, max_hops: int) -> jax.Array:
    """Single-seed reachability bitmap (N+1,)."""
    _, visited = bfs_levels(dev, jnp.asarray([seed], dtype=jnp.int32), max_hops)
    return visited[0]


def bfs_reachable_host(
    snap: CSRSnapshot, seeds: np.ndarray, max_hops: int
) -> list[np.ndarray]:
    """Convenience wrapper: run the device BFS and return, per seed, the
    sorted array of reached atom ids (excluding the seed) — the same contract
    as draining ``HGBreadthFirstTraversal``."""
    dev = snap.device
    seeds = np.asarray(seeds, dtype=np.int32)
    levels, visited = bfs_levels(dev, jnp.asarray(seeds), max_hops)
    visited = np.asarray(visited)
    out = []
    for i, s in enumerate(seeds.tolist()):
        row = visited[i].copy()
        row[s] = False
        row[snap.num_atoms] = False
        out.append(np.nonzero(row)[0].astype(np.int64))
    return out


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.sds((8,), "int32")),
    statics={"max_hops": 2},
)
@partial(jax.jit, static_argnames=("max_hops",))
def frontier_edge_counts(
    dev: DeviceSnapshot, seeds: jax.Array, max_hops: int
) -> jax.Array:
    """Count incidence-relation edges touched by live frontiers, per seed —
    the workload measure used by the benchmark (edges/sec). Returned as
    (K,) int32 (each seed's count fits; callers sum in int64 on host).

    Edges from frontier atoms = Σ degree(a) over the frontier — an O(K·N)
    masked dot with the per-atom incidence degree instead of an O(K·E)
    per-edge gather (identical count, the degree vector IS the row-length
    table of the CSR)."""
    K = seeds.shape[0]
    n1 = dev.type_of.shape[0]
    inc_degree = (dev.inc_offsets[1:] - dev.inc_offsets[:-1]).astype(jnp.int32)
    frontier = jnp.zeros((K, n1), dtype=bool).at[jnp.arange(K), seeds].set(True)
    visited = frontier

    def body(i, state):
        frontier, visited, total = state
        per_seed = jnp.where(frontier, inc_degree[None, :], 0).sum(
            axis=1, dtype=jnp.int32
        )
        nxt = expand_frontier(dev, frontier) & ~visited
        return nxt, visited | nxt, total + per_seed

    _, _, total = jax.lax.fori_loop(
        0, max_hops, body, (frontier, visited, jnp.zeros(K, dtype=jnp.int32))
    )
    return total
