"""Incremental CSR re-pack: delta overlays over a base snapshot.

SURVEY §7 hard part 2 / BASELINE config 5: concurrent ingest must not stall
queries. A full CSR re-pack is O(graph); here mutation deltas accumulate in
fixed-shape **overlay buffers** that compose with the base snapshot inside
the kernels:

- the base is packed with id-space **headroom** (``capacity``), so new
  atoms keep fitting the existing frontier bitmap width — no recompiles;
- added edges collect into COO delta arrays, padded to power-of-two
  buckets (bounded recompile count as the delta grows);
- removals set a **tombstone mask**; base edges into dead atoms are
  neutralized by clearing dead bits after every hop;
- when the delta outgrows ``compact_ratio`` × base (or headroom runs out),
  ``refresh`` performs a full re-pack — the periodic compaction.

The reference's analogue is MVCC read snapshots over B-trees (readers never
stall, ``transaction/``); here a device snapshot is the long-lived read
transaction and the delta keeps it fresh between compactions.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.core import events as ev
from hypergraphdb_tpu.obs import global_tracer
from hypergraphdb_tpu.ops.frontier import expand_frontier
from hypergraphdb_tpu.ops.setops import _bucket
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot, _pad_to


@dataclass
class DeviceDelta:
    """Fixed-shape overlay: COO edge additions + tombstone mask. Padded
    entries point at the dummy row (base.num_atoms)."""

    inc_links: jax.Array  # (D_inc,)
    inc_src: jax.Array    # (D_inc,)
    tgt_flat: jax.Array   # (D_tgt,)
    tgt_src: jax.Array    # (D_tgt,)
    dead: jax.Array       # (N+1,) bool tombstones


def _register_pytree() -> None:
    jax.tree_util.register_pytree_node(
        DeviceDelta,
        lambda d: ((d.inc_links, d.inc_src, d.tgt_flat, d.tgt_src, d.dead),
                   None),
        lambda aux, ch: DeviceDelta(*ch),
    )


_register_pytree()


def expand_frontier_delta(
    dev: DeviceSnapshot, delta: DeviceDelta, frontier: jax.Array
) -> jax.Array:
    """One hop over base ∪ delta, minus tombstoned atoms."""

    def one(f):
        # base relation
        la = jnp.zeros_like(f).at[dev.inc_links].max(f[dev.inc_src])
        # delta atom→link edges
        la = la.at[delta.inc_links].max(f[delta.inc_src])
        la = la & ~delta.dead  # dead links emit nothing
        nb = jnp.zeros_like(f).at[dev.tgt_flat].max(la[dev.tgt_src])
        nb = nb.at[delta.tgt_flat].max(la[delta.tgt_src])
        nb = nb & ~delta.dead
        return nb.at[dev.num_atoms].set(False)

    if frontier.ndim == 1:
        return one(frontier)
    return jax.vmap(one)(frontier)


@hgverify.entry(
    shapes=lambda: (hgverify.dev_snapshot_exemplar(),
                    hgverify.device_delta_exemplar(),
                    hgverify.sds((8,), "int32")),
    statics={"max_hops": 2, "with_levels": True},
)
@partial(jax.jit, static_argnames=("max_hops", "with_levels"))
def bfs_levels_delta(
    dev: DeviceSnapshot, delta: DeviceDelta, seeds: jax.Array, max_hops: int,
    with_levels: bool = True,
) -> tuple[Optional[jax.Array], jax.Array]:
    """Batched BFS over base ∪ delta (same contract as ``bfs_levels``).

    ``with_levels=False`` skips the (K, N+1) int32 hop-distance matrix —
    at streaming-bench scale (K=256, N≈1.5M) that matrix alone is ~1.5 GB
    of HBM a reachability-only caller pays for nothing."""
    K = seeds.shape[0]
    n1 = dev.type_of.shape[0]
    frontier = (
        jnp.zeros((K, n1), dtype=bool).at[jnp.arange(K), seeds].set(True)
        & ~delta.dead[None, :]
    )
    visited = frontier
    levels = (
        jnp.where(frontier, 0, -1).astype(jnp.int32)
        if with_levels else jnp.zeros((), dtype=jnp.int32)
    )

    def body(i, state):
        frontier, visited, levels = state
        nxt = expand_frontier_delta(dev, delta, frontier) & ~visited
        if with_levels:
            levels = jnp.where(nxt, i + 1, levels)
        return nxt, visited | nxt, levels

    frontier, visited, levels = jax.lax.fori_loop(
        0, max_hops, body, (frontier, visited, levels)
    )
    return (levels if with_levels else None), visited


@partial(jax.jit, static_argnames=("n1",))
def _unpack_dead(words: jax.Array, n1: int) -> jax.Array:
    """(W,) uint32 packed tombstones → (n1,) bool on device — the host
    ships N/8 bytes instead of an N-byte bool array."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((words[:, None] >> shifts) & jnp.uint32(1)).astype(bool)
    return bits.reshape(-1)[:n1]


@jax.jit
def _splice(buf: jax.Array, tail: jax.Array, offset: jax.Array) -> jax.Array:
    """Write ``tail`` into ``buf`` at ``offset`` on device (append-only
    delta refresh: only the tail crosses the host→device link)."""
    return jax.lax.dynamic_update_slice(buf, tail, (offset,))


class PinnedView(NamedTuple):
    """One consistent read unit for a serving batch: the base snapshot, its
    device pair, AND the host memtable correction sets, all captured under a
    single manager lock. A batch built from one PinnedView can never
    straddle a compaction swap — the serve-layer twin of
    :meth:`SnapshotManager.read_view` (which returns host-only views).

    ``sharded_base``/``sharded_delta`` are the multi-chip twins of
    (``device``, ``delta``) — populated only when the manager has a mesh
    attached (:meth:`SnapshotManager.attach_mesh`) and the view was
    pinned with ``sharded=True``; the host correction sets apply to them
    unchanged (same epoch, same memtable)."""

    base: CSRSnapshot
    device: Optional[DeviceSnapshot]  # None for sharded views (never
    #   materialize the full single-chip upload beside the shards)
    delta: Optional[DeviceDelta]  # None when pinned with sync_delta=False
    epoch: int          # compaction counter the pair belongs to
    dead: set           # tombstoned handles not yet baked into the base
    new_atoms: list     # handles added since the base pack (commit order)
    revalued: set       # values replaced since the base pack
    sharded_base: object = None    # parallel.sharded.ShardedSnapshot
    sharded_delta: object = None   # parallel.sharded.ShardedDelta

    def factorized_join_rels(self):
        """The join engine's prefix-grouped (trie) relation encodings
        for this view's base epoch — ``ops/join.factorized_relations``'s
        build, cached on the base snapshot exactly like the device pair
        and the co-incidence CSR, so every view pinned within one epoch
        shares one build and a compaction swap invalidates them
        together. None until someone (the serve tier's plan step, or
        prewarm) builds them; readers treat None as "serve flat"."""
        return getattr(self.base, "_fact_rels", None)


class SnapshotManager:
    """Owns the (base, delta) pair for one graph: listens to mutation
    events, accumulates host-side delta buffers, re-uploads the (bucketed)
    device delta when asked, and compacts when the delta outgrows the base.

    This is the LSM read model (SURVEY §7 hard part 2, BASELINE config 5):
    an immutable device-resident base (the long-lived read transaction) + a
    small host memtable (delta), merged at read time. Readers NEVER stall
    on ingest: with ``background=True`` compaction extracts the store
    tables under the commit lock only (milliseconds) and assembles the new
    base in a worker thread while readers keep the old epoch's
    (base, delta) view — the analogue of the reference's BDB env serving
    reads during checkpoints (``BJEConfig.java:27-35``).

    Usage::

        mgr = SnapshotManager(graph, headroom=2.0)
        dev, delta = mgr.device()         # always-fresh pair for kernels
        levels, visited = bfs_levels_delta(dev, delta, seeds, 3)
    """

    def __init__(self, graph, headroom: float = 2.0,
                 compact_ratio: float = 0.5, background: bool = False,
                 delta_bucket_min: int = 128,
                 pack_pad_multiple: int = 128):
        import threading

        self.graph = graph
        self.headroom = headroom
        self.compact_ratio = compact_ratio
        self.background = background
        # floor for delta buffer padding: a large floor keeps ONE device
        # shape for a whole streaming run (no recompiles as the delta grows)
        self.delta_bucket_min = delta_bucket_min
        # id-space capacity AND edge arrays round up to this multiple: a
        # coarse multiple (e.g. 1<<21 for streaming benches) keeps the base
        # device shapes IDENTICAL across successive compactions, so a base
        # swap reuses the cached XLA executable instead of recompiling —
        # the freshness/latency lever of BASELINE config 5
        self.pack_pad_multiple = pack_pad_multiple
        #: per-compaction wall timing: [{extract_s, assemble_swap_s,
        #: total_s}]; entry 0 is the init pack
        self.compaction_stats: list[dict] = []
        self.base: Optional[CSRSnapshot] = None
        self._capacity = 0
        self._lock = threading.RLock()
        # signalled whenever a background compaction pass finishes —
        # wait_compacted() blocks on it instead of polling delta_edges
        self._compact_cv = threading.Condition(self._lock)
        self._compacting = False
        self._compact_thread = None
        # host delta buffers (the memtable)
        self._inc_links: list[int] = []
        self._inc_src: list[int] = []
        self._tgt_flat: list[int] = []
        self._tgt_src: list[int] = []
        self._dead: set[int] = set()
        self._new_atoms: list[int] = []   # handles added since base pack
        self._revalued: set[int] = set()  # values replaced since base pack
        self._delta_dirty = True
        self._device_delta: Optional[DeviceDelta] = None
        self.compactions = 0
        #: observability: how delta refreshes hit the wire (full re-upload
        #: vs append-only tail splice vs tombstone-only)
        self.full_uploads = 0
        self.tail_uploads = 0
        self._pack_highwater = 0
        self._needs_recompact = False
        self._uploaded_marker = (-1, -1, -1)
        self._uploaded_atoms = 0
        # multi-chip twins (attach_mesh): the sharded base is rebuilt per
        # epoch OUTSIDE the lock, the sharded delta re-partitioned from
        # the memtable under the same drift marker discipline as the
        # single-chip device delta
        self._mesh = None
        self._shard_edge_chunk = 1 << 16
        self._shard_delta_chunk = 4096
        self._sharded_base = None
        self._sharded_epoch = -1
        self._sharded_delta = None
        self._sharded_marker = (-1, -1, -1)
        # hgindex delta columns (storage/value_index): kind -> the cached
        # memtable column, refreshed under the same max_lag_edges drift
        # discipline as the device delta (see value_delta)
        self._value_delta: dict = {}
        graph.events.add_listener(ev.HGAtomAddedEvent, self._on_added)
        graph.events.add_listener(ev.HGAtomRemovedEvent, self._on_removed)
        graph.events.add_listener(ev.HGAtomReplacedEvent, self._on_replaced)
        self._compact_sync()

    def close(self) -> None:
        """Detach from the graph's event stream (managers are long-lived;
        an undetached manager would keep accumulating deltas forever)."""
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join()
        self.graph.events.remove_listener(ev.HGAtomAddedEvent, self._on_added)
        self.graph.events.remove_listener(
            ev.HGAtomRemovedEvent, self._on_removed
        )
        self.graph.events.remove_listener(
            ev.HGAtomReplacedEvent, self._on_replaced
        )

    # -- event intake ---------------------------------------------------------
    # Lock order everywhere: commit lock → manager lock. Event handlers run
    # with the commit lock potentially held by the committing thread, so
    # they may take ONLY the manager lock and must never start a compaction
    # (a sync compaction takes the commit lock — inversion → deadlock).
    def _on_added(self, g, event) -> None:
        with self._lock:
            h = int(event.handle)
            if h < self._pack_highwater:
                # already inside the base: a mid-batch compaction packed the
                # whole committed batch, the remaining events are echoes
                return
            self._new_atoms.append(h)
            if h >= self._capacity:
                # beyond the bitmap width: device kernels cannot see it
                # until the next compaction; host correction covers reads.
                # The flag (not a direct compact call) keeps lock order.
                self._needs_recompact = True
                return
            if self._buffer_edges_locked(g, h):
                self._dead.discard(h)
                self._delta_dirty = True

    def _buffer_edges_locked(self, g, h: int) -> bool:
        """Append atom h's incidence/target edge pairs to the memtable edge
        buffers (the ``_locked`` suffix documents the contract hglint
        enforces: the caller holds the mgr lock). Returns False — and
        flags a recompaction — when h or a target falls outside the
        bitmap."""
        rec = g.store.get_link(h)
        if rec is None:
            return False
        targets = rec[3:]
        if h >= self._capacity or any(t >= self._capacity for t in targets):
            self._needs_recompact = True
            return False
        for t in targets:
            # incidence edge (t ← h) + target edge (h → t)
            self._inc_links.append(h)
            self._inc_src.append(int(t))
            self._tgt_flat.append(int(t))
            self._tgt_src.append(h)
        return True

    def _on_removed(self, g, event) -> None:
        with self._lock:
            h = int(event.handle)
            if h < self._capacity:
                self._dead.add(h)
                self._delta_dirty = True

    def _on_replaced(self, g, event) -> None:
        # value changed in place: device value ranks for this atom are
        # stale; value-predicate reads re-check it host-side
        with self._lock:
            self._revalued.add(int(event.handle))

    # -- compaction -----------------------------------------------------------
    def _extract_locked(self) -> dict:
        """Consistent store extraction + epoch bookkeeping snapshot. Caller
        sequence: commit lock → mgr lock → this."""
        g = self.graph
        tables = CSRSnapshot.extract_tables(g)
        return {
            "tables": tables,
            "highwater": tables["peek"],
            "dead_at_extract": set(self._dead),
            "revalued_at_extract": set(self._revalued),
            "version": g._mutations,
        }

    def _assemble_and_swap(self, ext: dict) -> None:
        """CSR assembly (lock-free) + epoch swap (under mgr lock). The delta
        edge buffers are REBUILT from the memtable at swap, so atoms that
        committed while assembly ran — including ones beyond the old
        capacity whose edges could never be buffered — are re-derived from
        the store instead of lost."""
        g = self.graph
        hw = ext["highwater"]
        pm = self.pack_pad_multiple
        cap = max(int(hw * self.headroom), 1024)
        cap = -(-cap // pm) * pm  # shape-stable rounding (see __init__)
        base = CSRSnapshot.pack(
            g, version=ext["version"], capacity=cap, tables=ext["tables"],
            pad_multiple=pm,
        )
        with self._lock:
            self.base = base
            self._capacity = base.num_atoms
            self._pack_highwater = hw
            self._new_atoms = [h for h in self._new_atoms if h >= hw]
            self._inc_links = []
            self._inc_src = []
            self._tgt_flat = []
            self._tgt_src = []
            self._needs_recompact = False
            for h in self._new_atoms:
                self._buffer_edges_locked(g, h)
            # removals/replaces recorded BEFORE extraction are baked into
            # the new base; later ones must survive the swap
            self._dead -= ext["dead_at_extract"]
            self._revalued -= ext["revalued_at_extract"]
            self._delta_dirty = True
            self._uploaded_atoms = 0  # new epoch: nothing uploaded yet
            self._value_delta.clear()  # stale epoch: columns rebuild lazily
            self.compactions += 1

    def _compact_sync(self) -> None:
        import time as _time

        tracer = global_tracer()
        tr = tracer.start_trace("compact") if tracer.enabled else None
        root = None if tr is None else tr.start_span("compact")
        try:
            t0 = _time.perf_counter()
            drain = None if tr is None else tr.start_span("buffer_drain",
                                                          parent=root)
            with self.graph.txman._commit_lock:
                with self._lock:
                    ext = self._extract_locked()
            if drain is not None:
                drain.end()
            t1 = _time.perf_counter()
            swap = None if tr is None else tr.start_span("device_swap",
                                                         parent=root)
            self._assemble_and_swap(ext)
            if swap is not None:
                swap.set(highwater=int(ext["highwater"])).end()
            t2 = _time.perf_counter()
        except BaseException as e:
            # a failed pass is the telemetry worth keeping: export the
            # trace with an error terminal instead of dropping it
            if tr is not None:
                tr.finish_error(e, parent=root)
            self.graph.metrics.incr("compact.failures")
            raise
        finally:
            if tr is not None:
                tr.finish()
        self.compaction_stats.append({
            "extract_s": t1 - t0,       # commit lock held (writers stalled)
            "assemble_swap_s": t2 - t1,  # lock-free CSR assembly + swap
            "total_s": t2 - t0,
        })
        m = self.graph.metrics
        m.incr("compact.passes")
        m.observe("compact.extract_seconds", t1 - t0)
        m.observe("compact.assemble_swap_seconds", t2 - t1)
        from hypergraphdb_tpu.obs.flight import global_flight

        fl = global_flight()
        if fl.enabled:
            # the swap is the event serving consistency pivots on — one
            # ring append so an incident dump shows every recent epoch
            fl.record("compact.swap", highwater=int(ext["highwater"]),
                      total_s=t2 - t0)

    def _request_compact(self) -> None:
        if not self.background:
            self._compact_sync()
            return
        with self._lock:
            if self._compacting:
                return
            self._compacting = True
        import threading

        def work():
            # _compacting is owned by THIS function alone: cleared in the
            # finally after re-checking whether another pass is already due
            # (a request that arrived mid-assembly was coalesced into the
            # flag, so it must not be dropped)
            try:
                for _ in range(4):  # bounded catch-up, no livelock
                    self._compact_sync()
                    with self._lock:
                        if not self._needs_recompact:
                            break
            finally:
                with self._compact_cv:
                    self._compacting = False
                    self._compact_cv.notify_all()

        t = threading.Thread(target=work, name="hgdb-compact", daemon=True)
        with self._lock:
            # close() joins whatever thread handle it sees; publishing the
            # handle under the mgr lock keeps it from reading a stale None
            self._compact_thread = t
        t.start()

    def _maybe_compact(self) -> None:
        with self._lock:
            base_edges = max(self.base.n_edges_inc, 1)
            # memtable growth counts EVERY host-corrected set, not just
            # edges: a stream of node adds / replaces / removes would
            # otherwise grow new_atoms/revalued/dead forever and turn
            # value-query correction into a full host scan
            memtable = (
                len(self._new_atoms) + len(self._revalued) + len(self._dead)
            )
            need = (
                self._needs_recompact
                or len(self._inc_links) > (
                    self.compact_ratio * base_edges + 4096
                )
                or memtable > (
                    self.compact_ratio * max(self.base.num_atoms, 1) + 4096
                )
            )
        if need:
            self._request_compact()

    # -- read views ------------------------------------------------------------
    def device(self, max_lag_edges: int = 0) -> tuple[DeviceSnapshot, DeviceDelta]:
        """The current (base, delta) device pair; cheap when unchanged.

        ``max_lag_edges`` > 0 bounds staleness instead of forcing an upload
        per mutation: the device delta is re-uploaded only when the host
        memtable has drifted more than that many entries from what is
        already on device — the freshness/throughput dial of BASELINE
        config 5 (readers tolerate a bounded lag; a mutation-rate-paced
        uploader would otherwise serialize queries behind host→HBM
        transfers)."""
        self._maybe_compact()
        with self._lock:
            base = self.base
            self._sync_device_delta_locked(max_lag_edges)
            return base.device, self._device_delta

    def _sync_device_delta_locked(self, max_lag_edges: int) -> None:
        """Refresh the device delta if it drifted beyond ``max_lag_edges``
        (caller holds the mgr lock — the shared core of :meth:`device` and
        :meth:`pinned_view`)."""
        # epoch keyed on the monotonic compaction counter — id(base)
        # could be REUSED by CPython after the old base is collected,
        # silently pairing an old device delta with a new base
        marker = (self.compactions, len(self._inc_links), len(self._dead))
        stale = self._device_delta is None or marker[0] != self._uploaded_marker[0]
        if not stale and self._delta_dirty:
            drift = (
                marker[1] - self._uploaded_marker[1]
                + marker[2] - self._uploaded_marker[2]
            )
            stale = drift > max_lag_edges
        if stale:
            self._refresh_device_delta_locked(marker)

    # -- multi-chip twins -------------------------------------------------------
    def attach_mesh(self, mesh, edge_chunk: int = 1 << 16,
                    delta_edge_chunk: int = 4096) -> None:
        """Give this manager a device mesh: ``pinned_view(sharded=True)``
        then hands out the row-sharded (base, delta) twins alongside the
        host correction sets. Idempotent for the same mesh; attaching a
        DIFFERENT mesh drops the cached sharded state (next pin
        re-shards)."""
        with self._lock:
            if self._mesh is not mesh:
                self._sharded_base = None
                self._sharded_epoch = -1
                self._sharded_delta = None
                self._sharded_marker = (-1, -1, -1)
            self._mesh = mesh
            self._shard_edge_chunk = edge_chunk
            self._shard_delta_chunk = delta_edge_chunk

    def _sync_sharded_delta_locked(self, max_lag_edges: int):
        """Refresh the sharded delta when the memtable drifted past
        ``max_lag_edges`` (caller holds the mgr lock AND the cached
        sharded base matches the current epoch) — the multi-chip twin of
        :meth:`_sync_device_delta_locked`. Re-partitions the whole
        memtable per refresh (no append path yet: delta partitions
        interleave across devices, so there is no stable tail to
        splice)."""
        from hypergraphdb_tpu.parallel.sharded import shard_host_delta

        marker = (self.compactions, len(self._inc_links), len(self._dead))
        stale = (self._sharded_delta is None
                 or marker[0] != self._sharded_marker[0])
        if not stale:
            drift = (marker[1] - self._sharded_marker[1]
                     + marker[2] - self._sharded_marker[2])
            stale = drift > max_lag_edges
        if stale:
            self._sharded_delta = shard_host_delta(
                self._sharded_base, self._host_delta_locked(),
                edge_chunk=self._shard_delta_chunk,
            )
            self._sharded_marker = marker
        return self._sharded_delta

    def _ensure_sharded_base(self) -> None:
        """Make the cached sharded base current (called OUTSIDE the lock:
        sharding the base is an O(E) repartition + upload — holding the
        mgr lock across it would stall every committing writer). The
        epoch re-check loop mirrors how compaction publishes: shard,
        then swap in only if no compaction moved the epoch meanwhile."""
        from hypergraphdb_tpu.parallel.sharded import ShardedSnapshot

        while True:
            with self._lock:
                if self._mesh is None:
                    raise ValueError(
                        "pinned_view(sharded=True) needs attach_mesh first"
                    )
                if self._sharded_epoch == self.compactions:
                    return
                base, epoch = self.base, self.compactions
            sbase = ShardedSnapshot.from_host(
                base, self._mesh, edge_chunk=self._shard_edge_chunk
            )
            with self._lock:
                if self.compactions == epoch:
                    self._sharded_base = sbase
                    self._sharded_epoch = epoch
                    self._sharded_delta = None
                    self._sharded_marker = (-1, -1, -1)
                    return

    def pinned_view(self, max_lag_edges: int = 0,
                    sync_delta: bool = True,
                    sharded: bool = False) -> PinnedView:
        """The serving read unit: (base, device pair, memtable correction)
        captured under ONE lock. ``device()`` + a separate ``correction()``
        can straddle a background swap — a batch assembled from this view
        cannot: every request in it reads the same epoch, and the host
        correction sets compensate for exactly the delta this view's device
        overlay has (or has not, under ``max_lag_edges`` drift) seen.

        ``sync_delta=False`` skips the device-delta refresh entirely and
        returns ``delta=None`` — for readers (the pattern serving path)
        that consume only the base plus the HOST correction sets, paying a
        host→HBM delta upload per memtable change on their hot path would
        buy nothing.

        ``sharded=True`` (mesh attached via :meth:`attach_mesh`) fills
        ``sharded_base``/``sharded_delta`` with the row-sharded twins —
        the multi-chip serving read unit. The single-chip device pair is
        NOT synced for such views (a sharded reader pays no single-chip
        delta upload); the host correction sets are shared."""
        self._maybe_compact()
        while True:
            if sharded:
                self._ensure_sharded_base()
            with self._lock:
                if sharded and self._sharded_epoch != self.compactions:
                    continue  # a compaction swapped mid-shard: re-shard
                base = self.base
                sbase = sdelta = None
                if sharded:
                    sbase = self._sharded_base
                    sdelta = self._sync_sharded_delta_locked(max_lag_edges)
                elif sync_delta:
                    self._sync_device_delta_locked(max_lag_edges)
                return PinnedView(
                    base=base,
                    # a sharded view must NOT materialize the single-chip
                    # device snapshot: base.device is a cached_property
                    # whose first touch uploads the FULL CSR to device 0
                    # — exactly the copy sharding exists to avoid
                    device=None if sharded else base.device,
                    delta=(self._device_delta
                           if sync_delta and not sharded else None),
                    epoch=self.compactions,
                    dead=set(self._dead),
                    new_atoms=list(self._new_atoms),
                    revalued=set(self._revalued),
                    sharded_base=sbase,
                    sharded_delta=sdelta,
                )

    def value_delta(self, view: "PinnedView", kind: int,
                    max_lag_edges: int = 0):
        """The hgindex DELTA column for one pinned view and value kind
        (``storage/value_index.ValueIndexColumn``): memtable atoms of
        that kind, sorted and device-resident, covering a PREFIX of the
        view's ``new_atoms`` capture — never more (a column built from a
        later memtable would leak post-pin atoms into the batch), so a
        cached column is reused only while its coverage deficit against
        THIS view stays within ``max_lag_edges`` (the same bounded-drift
        dial as the BFS device delta). The residual
        ``view.new_atoms[col.covered:]`` plus ``view.revalued`` is the
        host correction the collect path owes.

        Built OUTSIDE the manager lock (value-key extraction walks the
        store, like ``_capture_candidates``); the cache swap re-checks
        coverage so concurrent builders keep the widest column."""
        from hypergraphdb_tpu.storage.value_index import build_delta_column

        kind = int(kind)
        n_view = len(view.new_atoms)
        with self._lock:
            cached = self._value_delta.get(kind)
        if (cached is not None and cached.epoch == view.epoch
                and cached.covered <= n_view
                and n_view - cached.covered <= max_lag_edges):
            return cached
        col = build_delta_column(self.graph, view.new_atoms, kind,
                                 epoch=view.epoch)
        with self._lock:
            prev = self._value_delta.get(kind)
            if (prev is None or prev.epoch != view.epoch
                    or prev.covered < col.covered):
                self._value_delta[kind] = col
        return col

    def wait_compacted(self, timeout: Optional[float] = None) -> bool:
        """Block until no compaction pass is in flight (bounded by
        ``timeout`` seconds; ``None`` waits forever). Returns True when
        quiesced, False on timeout — so serve-layer drains and tests await
        the swap directly instead of polling ``delta_edges``. A pass that
        re-queues itself (``_needs_recompact`` coalescing) is covered: the
        worker clears ``_compacting`` only after its bounded catch-up loop
        settles."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._compact_cv:
            while self._compacting:
                remaining = (
                    None if deadline is None
                    else deadline - _time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._compact_cv.wait(remaining)
            return True

    def _refresh_device_delta_locked(self, marker) -> None:
        """Re-materialize the device delta (the ``_locked`` suffix
        documents the contract hglint enforces: the caller holds the mgr
        lock).

        Uploads are INCREMENTAL when possible: the edge buffers are
        append-only between compactions, so while the pad bucket is
        unchanged only the new TAIL crosses the host→device link (a
        dynamic-update-slice into the resident buffers) — over a slow
        host↔HBM link the full 4-array re-upload was the streaming-bench
        query bottleneck. Tombstones always ship BIT-PACKED (N/8 bytes)
        and unpack on device. Falls back to a full upload when the bucket
        grows or the epoch moved."""
        base = self.base
        N = base.num_atoms
        cur_len = len(self._inc_links)
        bucket = _bucket(max(cur_len, 1), minimum=self.delta_bucket_min)

        # dead mask: pack on host, unpack on device (8× smaller transfer)
        n_pad = -(-(N + 1) // 32) * 32
        dead_bits = np.zeros(n_pad, dtype=bool)
        if self._dead:
            dd = np.fromiter(self._dead, dtype=np.int64)
            dead_bits[dd[dd <= N]] = True
        dead_words = np.packbits(
            dead_bits.reshape(-1, 32), axis=-1, bitorder="little"
        ).view("<u4").reshape(-1)
        dead_dev = _unpack_dead(jnp.asarray(dead_words), N + 1)

        prev = self._device_delta
        old_len = self._uploaded_marker[1]
        tail_n = max(cur_len - old_len, 0)
        # pad the tail to a coarse multiple so the update-slice executable
        # is reused across refreshes (pad value N is the buffer's own dummy
        # fill — overwriting pad with pad)
        t_pad = _bucket(max(tail_n, 1), minimum=256)
        can_append = (
            prev is not None
            and marker[0] == self._uploaded_marker[0]  # same epoch
            and prev.inc_links.shape[0] == bucket      # bucket unchanged
            and old_len <= cur_len
            # dynamic_update_slice CLAMPS the start index when the update
            # would overrun — a clamped write corrupts earlier entries, so
            # the padded tail must fit as-is
            and old_len + t_pad <= bucket
        )
        if can_append:
            if tail_n:

                def tail(xs, fill):
                    a = np.asarray(xs[old_len:cur_len], dtype=np.int32)
                    return jnp.asarray(_pad_to(a, t_pad, fill))  # noqa: B023

                off = jnp.int32(old_len)
                self._device_delta = DeviceDelta(
                    inc_links=_splice(prev.inc_links, tail(self._inc_links, N), off),
                    inc_src=_splice(prev.inc_src, tail(self._inc_src, N), off),
                    tgt_flat=_splice(prev.tgt_flat, tail(self._tgt_flat, N), off),
                    tgt_src=_splice(prev.tgt_src, tail(self._tgt_src, N), off),
                    dead=dead_dev,
                )
                self.tail_uploads += 1
                self.graph.metrics.incr("compact.tail_uploads")
            else:
                self._device_delta = DeviceDelta(
                    inc_links=prev.inc_links,
                    inc_src=prev.inc_src,
                    tgt_flat=prev.tgt_flat,
                    tgt_src=prev.tgt_src,
                    dead=dead_dev,
                )
        else:
            def up(xs, fill):
                a = np.asarray(xs, dtype=np.int32)
                return jnp.asarray(_pad_to(a, bucket, fill))

            self._device_delta = DeviceDelta(
                inc_links=up(self._inc_links, N),
                inc_src=up(self._inc_src, N),
                tgt_flat=up(self._tgt_flat, N),
                tgt_src=up(self._tgt_src, N),
                dead=dead_dev,
            )
            self.full_uploads += 1
            self.graph.metrics.incr("compact.full_uploads")
        self._delta_dirty = False
        self._uploaded_marker = marker
        self._uploaded_atoms = len(self._new_atoms)
        self.graph.metrics.gauge("compact.delta_edges", cur_len)

    def host_delta(self) -> dict:
        """Host-side copy of the delta memtable for OTHER planes to shard
        (``parallel.sharded.shard_host_delta``): COO edge buffers, the dead
        set, and the epoch (compaction counter) the buffers belong to.
        Captured under one lock so the arrays are mutually consistent; a
        multi-chip caller re-shards the base when ``epoch`` moves (the
        sharded twin of ``device()``'s epoch marker)."""
        with self._lock:
            return self._host_delta_locked()

    def _host_delta_locked(self) -> dict:
        """The ONE memtable capture shape (caller holds the mgr lock) —
        shared by :meth:`host_delta` and the sharded-delta refresh so
        the two can never drift on what a delta carries."""
        return {
            "epoch": self.compactions,
            "capacity": self._capacity,
            "inc_links": np.asarray(self._inc_links, dtype=np.int32),
            "inc_src": np.asarray(self._inc_src, dtype=np.int32),
            "tgt_flat": np.asarray(self._tgt_flat, dtype=np.int32),
            "tgt_src": np.asarray(self._tgt_src, dtype=np.int32),
            "dead": np.fromiter(self._dead, dtype=np.int64)
            if self._dead else np.empty(0, dtype=np.int64),
        }

    def device_visible_new_atoms(self) -> list[int]:
        """New atoms whose delta edges are ALREADY uploaded to the device
        (edge buffers append in commit order, so the first
        ``_uploaded_atoms`` entries of the memtable are on device) — what a
        bounded-lag reader is entitled to see (bench c5's probe set)."""
        with self._lock:
            cap = self._capacity
            return [
                h for h in self._new_atoms[: self._uploaded_atoms]
                if h < cap
            ]

    def correction(self) -> tuple[set, list, set]:
        """Host-side read correction for device results computed on the
        base: (dead, new_atoms, revalued). A reader drops dead ∪ revalued
        from the device result, then host-evaluates its condition over
        new_atoms ∪ revalued — the LSM memtable merge."""
        with self._lock:
            return set(self._dead), list(self._new_atoms), set(self._revalued)

    def read_view(self) -> tuple[CSRSnapshot, set, list, set]:
        """(base, dead, new_atoms, revalued) captured under ONE lock — the
        snapshot-isolation read unit. A reader that takes base and
        correction separately can straddle a background swap: the new
        epoch's trimmed memtable would no longer compensate for the OLD
        base it is about to query."""
        self._maybe_compact()
        with self._lock:
            return (
                self.base,
                set(self._dead),
                list(self._new_atoms),
                set(self._revalued),
            )

    @property
    def delta_edges(self) -> int:
        with self._lock:
            return len(self._inc_links)
