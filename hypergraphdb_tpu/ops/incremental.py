"""Incremental CSR re-pack: delta overlays over a base snapshot.

SURVEY §7 hard part 2 / BASELINE config 5: concurrent ingest must not stall
queries. A full CSR re-pack is O(graph); here mutation deltas accumulate in
fixed-shape **overlay buffers** that compose with the base snapshot inside
the kernels:

- the base is packed with id-space **headroom** (``capacity``), so new
  atoms keep fitting the existing frontier bitmap width — no recompiles;
- added edges collect into COO delta arrays, padded to power-of-two
  buckets (bounded recompile count as the delta grows);
- removals set a **tombstone mask**; base edges into dead atoms are
  neutralized by clearing dead bits after every hop;
- when the delta outgrows ``compact_ratio`` × base (or headroom runs out),
  ``refresh`` performs a full re-pack — the periodic compaction.

The reference's analogue is MVCC read snapshots over B-trees (readers never
stall, ``transaction/``); here a device snapshot is the long-lived read
transaction and the delta keeps it fresh between compactions.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu.core import events as ev
from hypergraphdb_tpu.ops.frontier import expand_frontier
from hypergraphdb_tpu.ops.setops import _bucket
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, DeviceSnapshot, _pad_to


@dataclass
class DeviceDelta:
    """Fixed-shape overlay: COO edge additions + tombstone mask. Padded
    entries point at the dummy row (base.num_atoms)."""

    inc_links: jax.Array  # (D_inc,)
    inc_src: jax.Array    # (D_inc,)
    tgt_flat: jax.Array   # (D_tgt,)
    tgt_src: jax.Array    # (D_tgt,)
    dead: jax.Array       # (N+1,) bool tombstones


def _register_pytree() -> None:
    jax.tree_util.register_pytree_node(
        DeviceDelta,
        lambda d: ((d.inc_links, d.inc_src, d.tgt_flat, d.tgt_src, d.dead),
                   None),
        lambda aux, ch: DeviceDelta(*ch),
    )


_register_pytree()


def expand_frontier_delta(
    dev: DeviceSnapshot, delta: DeviceDelta, frontier: jax.Array
) -> jax.Array:
    """One hop over base ∪ delta, minus tombstoned atoms."""

    def one(f):
        # base relation
        la = jnp.zeros_like(f).at[dev.inc_links].max(f[dev.inc_src])
        # delta atom→link edges
        la = la.at[delta.inc_links].max(f[delta.inc_src])
        la = la & ~delta.dead  # dead links emit nothing
        nb = jnp.zeros_like(f).at[dev.tgt_flat].max(la[dev.tgt_src])
        nb = nb.at[delta.tgt_flat].max(la[delta.tgt_src])
        nb = nb & ~delta.dead
        return nb.at[dev.num_atoms].set(False)

    if frontier.ndim == 1:
        return one(frontier)
    return jax.vmap(one)(frontier)


@partial(jax.jit, static_argnames=("max_hops",))
def bfs_levels_delta(
    dev: DeviceSnapshot, delta: DeviceDelta, seeds: jax.Array, max_hops: int
) -> tuple[jax.Array, jax.Array]:
    """Batched BFS over base ∪ delta (same contract as ``bfs_levels``)."""
    K = seeds.shape[0]
    n1 = dev.type_of.shape[0]
    frontier = (
        jnp.zeros((K, n1), dtype=bool).at[jnp.arange(K), seeds].set(True)
        & ~delta.dead[None, :]
    )
    visited = frontier
    levels = jnp.where(frontier, 0, -1).astype(jnp.int32)

    def body(i, state):
        frontier, visited, levels = state
        nxt = expand_frontier_delta(dev, delta, frontier) & ~visited
        levels = jnp.where(nxt, i + 1, levels)
        return nxt, visited | nxt, levels

    frontier, visited, levels = jax.lax.fori_loop(
        0, max_hops, body, (frontier, visited, levels)
    )
    return levels, visited


class SnapshotManager:
    """Owns the (base, delta) pair for one graph: listens to mutation
    events, accumulates host-side delta buffers, re-uploads the (bucketed)
    device delta when asked, and compacts when the delta outgrows the base.

    Usage::

        mgr = SnapshotManager(graph, headroom=2.0)
        dev, delta = mgr.device()         # always-fresh pair for kernels
        levels, visited = bfs_levels_delta(dev, delta, seeds, 3)
    """

    def __init__(self, graph, headroom: float = 2.0, compact_ratio: float = 0.5):
        self.graph = graph
        self.headroom = headroom
        self.compact_ratio = compact_ratio
        self.base: Optional[CSRSnapshot] = None
        self._capacity = 0
        # host delta buffers
        self._inc_links: list[int] = []
        self._inc_src: list[int] = []
        self._tgt_flat: list[int] = []
        self._tgt_src: list[int] = []
        self._dead: set[int] = set()
        self._delta_dirty = True
        self._device_delta: Optional[DeviceDelta] = None
        self.compactions = 0
        self._pack_highwater = 0
        graph.events.add_listener(ev.HGAtomAddedEvent, self._on_added)
        graph.events.add_listener(ev.HGAtomRemovedEvent, self._on_removed)
        self._compact()

    def close(self) -> None:
        """Detach from the graph's event stream (managers are long-lived;
        an undetached manager would keep accumulating deltas forever)."""
        self.graph.events.remove_listener(ev.HGAtomAddedEvent, self._on_added)
        self.graph.events.remove_listener(
            ev.HGAtomRemovedEvent, self._on_removed
        )

    # -- event intake ---------------------------------------------------------
    def _on_added(self, g, event) -> None:
        h = int(event.handle)
        if h < self._pack_highwater:
            # already inside the base: a mid-batch compaction packed the
            # whole committed batch, the remaining events are echoes
            return
        if h >= self._capacity:
            self._compact()
            return
        rec = g.store.get_link(h)
        if rec is None:
            return
        targets = rec[3:]
        for t in targets:
            if t >= self._capacity:
                self._compact()
                return
        for t in targets:
            # incidence edge (t ← h) + target edge (h → t)
            self._inc_links.append(h)
            self._inc_src.append(int(t))
            self._tgt_flat.append(int(t))
            self._tgt_src.append(h)
        self._dead.discard(h)
        self._delta_dirty = True

    def _on_removed(self, g, event) -> None:
        h = int(event.handle)
        if h < self._capacity:
            self._dead.add(h)
            self._delta_dirty = True
        else:
            self._compact()

    # -- compaction -----------------------------------------------------------
    def _compact(self) -> None:
        g = self.graph
        cap = max(int(g.handles.peek * self.headroom), 1024)
        self._pack_highwater = int(g.handles.peek)
        self.base = CSRSnapshot.pack(g, version=g._mutations, capacity=cap)
        self._capacity = self.base.num_atoms
        self._inc_links.clear()
        self._inc_src.clear()
        self._tgt_flat.clear()
        self._tgt_src.clear()
        self._dead.clear()
        self._delta_dirty = True
        self.compactions += 1

    def _maybe_compact(self) -> None:
        base_edges = max(self.base.n_edges_inc, 1)
        if len(self._inc_links) > self.compact_ratio * base_edges + 4096:
            self._compact()

    # -- device views ----------------------------------------------------------
    def device(self) -> tuple[DeviceSnapshot, DeviceDelta]:
        """The current (base, delta) device pair; cheap when unchanged."""
        self._maybe_compact()
        dev = self.base.device
        if self._delta_dirty or self._device_delta is None:
            N = self.base.num_atoms
            n1 = N + 1

            def up(xs, fill):
                a = np.asarray(xs, dtype=np.int32)
                return jnp.asarray(
                    _pad_to(a, _bucket(max(len(a), 1)), fill)
                )

            dead = np.zeros(n1, dtype=bool)
            if self._dead:
                dead[np.fromiter(self._dead, dtype=np.int64)] = True
            self._device_delta = DeviceDelta(
                inc_links=up(self._inc_links, N),
                inc_src=up(self._inc_src, N),
                tgt_flat=up(self._tgt_flat, N),
                tgt_src=up(self._tgt_src, N),
                dead=jnp.asarray(dead),
            )
            self._delta_dirty = False
        return dev, self._device_delta

    @property
    def delta_edges(self) -> int:
        return len(self._inc_links)
