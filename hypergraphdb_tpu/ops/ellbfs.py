"""Pull-mode, seed-transposed BFS — the fast path for config-4 scale.

Round 2's ``ops/bitfrontier.py`` made 10M-atom frontiers *fit* (bit-packed
``(K, W)`` bitmaps) but not *fast*: its push scan does a ``test_bits``
gather plus an ``.at[:, d].max`` scatter **per (seed, edge)** — K×E scalar
probes per hop. Measured on v5e, XLA lowers both to a latency-bound unit
running ~10⁸ indices/s, which is why BENCH_r02 saw 324 s/run and <1% of
HBM (VERDICT r2 Weak #2).

This module keeps the same BFS semantics (``SimpleALGenerator`` neighbor
rule: frontier atom → incident links → their targets, reference
``HGBreadthFirstTraversal.java:49-66``) but re-lays the computation so the
expensive primitive is **one row gather per edge**, not K probes per edge:

- the frontier is stored **transposed**: ``F[(N+1, Kw)] uint32`` — bit k of
  word ``F[v, k>>5]`` says "seed k has reached atom v". One 128-byte row
  per atom carries ALL 1024 seeds at once.
- a hop is two *pull* reductions with NO scatters:
  stage 1: ``link_live[l] = OR_{t ∈ targets(l)} F[t]``
  stage 2: ``reach[v]    = OR_{l ∈ incident(v)} link_live[l]``
  Each is a gather of edge-many rows followed by a fixed-width tree
  reduction over host-precomputed padded index plans (:class:`ReducePlan`):
  every CSR row is padded to a multiple of ``w`` and aligned, so the
  segment-OR is a plain ``reshape(-1, w, Kw) → OR(axis=1)`` — XLA's fused
  streaming path, no segment ids, no conflicts, hub rows handled by
  recursion (level ℓ reduces rows of up to ``w^(ℓ+1)`` entries).
- levels compose: stage 2's level-0 indices are pre-composed with stage
  1's output map on host, so link-space results are consumed directly
  without materializing a per-link destination array.
- per-seed edge counts (the benchmark numerator) are a bit-unpack +
  degree matmul per hop — MXU work, not gathers.

Geometry note: each gather row is ``Kw = K/32`` uint32 words (32 lanes for
K=1024). Gathers remain the dominant cost and are latency-bound, but the
total index count per hop drops from ``K × E`` to ``~1.3 × E × (1 + 1/w)``
— three orders of magnitude at K=1024.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

WORD = 32


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


# ------------------------------------------------------------------ host plans


@dataclass(frozen=True)
class ReducePlan:
    """Padded-gather tree reduction over one CSR relation.

    ``levels[0]`` indexes caller-provided value rows (with ``zero_row``
    pointing at a guaranteed-all-zero row) and covers every row; level
    ``ℓ>0`` covers ONLY rows still unfinished (more than one chunk) —
    single-chunk rows would otherwise pay a ``w_upper×`` pass-through pad
    per level, a 16× index blowup at hypergraph scale. Upper-level indices
    are local to the previous level's chunk array, with index
    ``len(prev_chunks)`` meaning the per-level appended zero row.

    A row's final chunk therefore lives in the chunk array of whichever
    level it finished at; ``out_map[r]`` addresses the **concatenation** of
    all level chunk arrays (in order) with one global zero row at the very
    end (``concat_size``). Empty rows map to the zero row. All index
    arrays are int32; every level's length is a multiple of its width.
    """

    levels: tuple[np.ndarray, ...]
    widths: tuple[int, ...]
    out_map: np.ndarray  # (R,) int32 into concat space; empty rows → zero row
    n_rows: int
    concat_size: int     # total chunks across levels; zero row lives here

    @property
    def total_indices(self) -> int:
        return int(sum(len(l) for l in self.levels))


def build_reduce_plan(
    offsets: np.ndarray,
    flat: np.ndarray,
    n_rows: int,
    zero_row: int,
    w: int = 8,
    w_upper: int = 8,
) -> ReducePlan:
    """Build the padded index pyramid for ``reduce_or`` over CSR rows.

    ``offsets``/``flat`` describe rows ``0..n_rows``; ``zero_row`` indexes
    an all-zero value row used for level-0 padding. Level 0 width is ``w``;
    upper levels use ``w_upper``.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    deg = offsets[1 : n_rows + 1] - offsets[:n_rows]
    nchunk = -(-deg // w)  # ceil; 0 for empty rows

    total = int(nchunk.sum()) * w
    idx0 = np.full(total, zero_row, dtype=np.int32)
    row_pad_starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(nchunk * w, out=row_pad_starts[1:])
    nz = np.nonzero(deg)[0]
    if len(nz):
        reps = deg[nz]
        dst = np.repeat(row_pad_starts[nz], reps) + _intra(reps)
        src = np.repeat(offsets[nz], reps) + _intra(reps)
        idx0[dst] = np.asarray(flat, dtype=np.int32)[src]
    levels = [idx0]
    widths = [w]

    # out_map in concat space; level offsets accumulate as levels are added
    out_map = np.full(n_rows, -1, dtype=np.int64)
    level_offset = 0
    n_prev = int(nchunk.sum())  # chunks in the previous (current last) level
    # rows' chunk spans start contiguously in the previous level's array
    cur_counts = nchunk
    cur_starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(cur_counts, out=cur_starts[1:])

    done = cur_counts == 1
    out_map[done] = level_offset + cur_starts[:n_rows][done]

    while int(cur_counts.max(initial=0)) > 1:
        wu = w_upper
        live = np.nonzero(cur_counts > 1)[0]
        live_counts = cur_counts[live]
        nxt_counts_live = -(-live_counts // wu)
        tot = int(nxt_counts_live.sum()) * wu
        idx = np.full(tot, n_prev, dtype=np.int32)  # pad → prev zero row
        pad_starts = np.zeros(len(live) + 1, dtype=np.int64)
        np.cumsum(nxt_counts_live * wu, out=pad_starts[1:])
        reps = live_counts
        dst = np.repeat(pad_starts[:-1], reps) + _intra(reps)
        src = np.repeat(cur_starts[live], reps) + _intra(reps)
        idx[dst] = src.astype(np.int32)
        levels.append(idx)
        widths.append(wu)

        level_offset += n_prev
        n_prev = int(nxt_counts_live.sum())
        cur_counts = np.zeros(n_rows, dtype=np.int64)
        cur_counts[live] = nxt_counts_live
        cur_starts = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(cur_counts, out=cur_starts[1:])
        done = cur_counts == 1
        out_map[done] = level_offset + cur_starts[:n_rows][done]

    concat_size = level_offset + n_prev
    out_map = np.where(out_map >= 0, out_map, concat_size)
    return ReducePlan(
        tuple(levels), tuple(widths), out_map.astype(np.int32),
        n_rows, concat_size,
    )


def _intra(reps: np.ndarray) -> np.ndarray:
    """Concatenated ``arange(r)`` for each r in reps (vectorized)."""
    total = int(reps.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    ends = np.cumsum(reps)
    return np.arange(total, dtype=np.int64) - np.repeat(ends - reps, reps)


# ------------------------------------------------------------------ device ops


def _reduce_level(
    values: jax.Array,  # (S, Kw) uint32 value rows; padding rows index S-1.. caller
    idx: jax.Array,     # (E,) int32, multiple of w
    w: int,
    chunk: int,
) -> jax.Array:
    """gather + fixed-width OR-reduce, streamed in ``chunk``-row slices to
    bound the gather transient: returns (E//w, Kw) uint32."""
    E = idx.shape[0]
    Kw = values.shape[1]
    n_out = E // w
    if E <= chunk * w:
        g = values[idx]
        return _or_fold(g.reshape(n_out, w, Kw))
    # pad out rows to a multiple of chunk for the scan
    n_blocks = -(-n_out // chunk)
    pad_rows = n_blocks * chunk - n_out
    if pad_rows:
        idx = jnp.concatenate(
            [idx, jnp.zeros((pad_rows * w,), dtype=idx.dtype)]
        )
    idx_b = idx.reshape(n_blocks, chunk * w)

    def body(_, ib):
        g = values[ib]
        return None, _or_fold(g.reshape(chunk, w, Kw))

    _, out = jax.lax.scan(body, None, idx_b)
    out = out.reshape(n_blocks * chunk, Kw)
    return out[:n_out] if pad_rows else out


def _or_fold(x: jax.Array) -> jax.Array:
    """(R, w, Kw) → (R, Kw) OR over axis 1 as a log-depth fold."""
    w = x.shape[1]
    while w > 1:
        if w % 2:
            x = jnp.concatenate(
                [x, jnp.zeros_like(x[:, :1])], axis=1
            )
            w += 1
        x = x[:, 0::2] | x[:, 1::2]
        w //= 2
    return x[:, 0]


def _apply_plan(
    values: jax.Array,            # (S, Kw) uint32 — level-0 value rows
    levels: Sequence[jax.Array],
    widths: Sequence[int],
    chunk: int,
) -> jax.Array:
    """Run the reduction pyramid; returns the CONCATENATION of every
    level's chunk array plus one global zero row at the end — the address
    space ``ReducePlan.out_map`` (and composed downstream level-0 indices)
    point into."""
    Kw = values.shape[1]
    parts = []
    cur = values
    for i, (idx, w) in enumerate(zip(levels, widths)):
        if i > 0:
            # upper-level padding references index len(prev) = its zero row
            cur = jnp.concatenate([cur, jnp.zeros((1, Kw), dtype=cur.dtype)])
        cur = _reduce_level(cur, idx, w, chunk)
        parts.append(cur)
    parts.append(jnp.zeros((1, Kw), dtype=values.dtype))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


class PullBFSResult(NamedTuple):
    visited_t: jax.Array      # (N_pad, Kw) uint32 — TRANSPOSED packed bitmaps
    edges_touched: np.ndarray  # (K,) int64 — summed over hops on host
    reach_counts: jax.Array   # (K,) int32 — |visited| per seed (incl. seed)


@dataclass
class PullBFSPlans:
    """Host-side precompute for :func:`bfs_pull` over one snapshot.

    Expensive to build (two padded index pyramids + a composed link map)
    but reusable across every BFS on the snapshot; cached on the snapshot
    object by :func:`plans_for`.
    """

    n_atoms: int
    n_pad: int
    stage1: ReducePlan  # tgt relation: link rows ← atom value rows
    stage2_levels: tuple[np.ndarray, ...]  # level0 composed into stage1 chunks
    stage2_widths: tuple[int, ...]
    out_map: np.ndarray
    inc_deg: np.ndarray  # (N_pad,) int32 — incidence degree (edge counting)

    @property
    def total_indices(self) -> int:
        return (
            self.stage1.total_indices
            + int(sum(len(l) for l in self.stage2_levels))
            + len(self.out_map)
        )


def build_pull_plans(
    snap: CSRSnapshot, w1: int = 8, w2: int = 8, w_upper: int = 8
) -> PullBFSPlans:
    N = snap.num_atoms
    n_pad = _ceil_to(N + 1, 8)
    e_tgt = snap.n_edges_tgt
    e_inc = snap.n_edges_inc
    # stage 1: link_live = OR of F over target rows (tgt CSR, rows=atoms)
    s1 = build_reduce_plan(
        snap.tgt_offsets[: N + 2], snap.tgt_flat[:e_tgt], N + 1,
        zero_row=N, w=w1, w_upper=w_upper,
    )
    # stage 2 runs over the incidence CSR; its level-0 entries are LINK ids.
    # Compose them through stage-1's concat-space out_map on host, so the
    # hop consumes stage-1 chunks directly — no per-link destination array
    # is ever materialized.
    s2 = build_reduce_plan(
        snap.inc_offsets[: N + 2], snap.inc_links[:e_inc], N + 1,
        zero_row=N, w=w2, w_upper=w_upper,
    )
    # level-0 padding used zero_row=N (an atom id); atom N has no targets →
    # its out_map entry is stage-1's zero row. Non-link atoms likewise.
    lvl0 = s1.out_map[s2.levels[0]]
    s2_levels = (lvl0,) + s2.levels[1:]

    out_map = np.full(n_pad, s2.concat_size, dtype=np.int32)
    out_map[: N + 1] = s2.out_map
    out_map[N] = s2.concat_size  # dummy row must stay empty
    inc_deg = np.zeros(n_pad, dtype=np.int32)
    inc_deg[: N + 1] = (
        snap.inc_offsets[1 : N + 2].astype(np.int64)
        - snap.inc_offsets[: N + 1]
    ).astype(np.int32)
    inc_deg[N] = 0
    return PullBFSPlans(
        n_atoms=N,
        n_pad=n_pad,
        stage1=s1,
        stage2_levels=s2_levels,
        stage2_widths=s2.widths,
        out_map=out_map,
        inc_deg=inc_deg,
    )


def plans_for(snap: CSRSnapshot) -> PullBFSPlans:
    plans = getattr(snap, "_pull_plans", None)
    if plans is None:
        plans = build_pull_plans(snap)
        object.__setattr__(snap, "_pull_plans", plans)
    return plans


# ------------------------------------------------------------------ kernel


def _bitdot(packed_t: jax.Array, vec: jax.Array, block_rows: int) -> jax.Array:
    """Σ_v vec[v] · bit(v, k) for every seed column k.

    ``packed_t (R, Kw) uint32``, ``vec (R,) float32`` → ``(K,) int32``.
    Bit-unpack + matvec in row blocks so the unpack transient stays
    ~``block_rows × K`` floats (MXU work, not gathers). Values are exact
    while each block's partial sum stays below 2^24 (always true in the
    test-scale graphs; at benchmark scale the relative error is ≤1e-7 of a
    throughput counter).
    """
    R, Kw = packed_t.shape
    K = Kw * WORD
    n_blocks = -(-R // block_rows)
    pad = n_blocks * block_rows - R
    if pad:
        packed_t = jnp.concatenate(
            [packed_t, jnp.zeros((pad, Kw), jnp.uint32)]
        )
        vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
    pb = packed_t.reshape(n_blocks, block_rows, Kw)
    vb = vec.reshape(n_blocks, block_rows)
    shifts = jnp.arange(WORD, dtype=jnp.uint32)

    def body(carry, sv):
        sl, dg = sv
        bits = ((sl[:, :, None] >> shifts) & 1).astype(jnp.float32)
        part = jnp.einsum(
            "rk,r->k", bits.reshape(block_rows, K), dg,
            preferred_element_type=jnp.float32,
        )
        return carry + part.astype(jnp.int32), None

    total, _ = jax.lax.scan(body, jnp.zeros((K,), jnp.int32), (pb, vb))
    return total


@partial(
    jax.jit,
    static_argnames=("max_hops", "widths1", "widths2", "chunk", "count_edges"),
)
def _bfs_pull_device(
    levels1: tuple[jax.Array, ...],
    widths1: tuple[int, ...],
    levels2: tuple[jax.Array, ...],
    widths2: tuple[int, ...],
    out_map: jax.Array,      # (N_pad,) int32
    inc_deg: jax.Array,      # (N_pad,) int32
    seeds: jax.Array,        # (K,) int32 — K % 32 == 0
    n_atoms: jax.Array,      # scalar int32 — dummy row id
    max_hops: int,
    chunk: int = 1 << 19,
    count_edges: bool = True,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    K = seeds.shape[0]
    Kw = K // WORD
    n_pad = out_map.shape[0]
    block_rows = max(1024, min(131072, _ceil_to(n_pad, 8) // 8))

    # transposed seed bitmap: bit k of F[seeds[k]] — per-k bits are distinct,
    # so scatter-add over (possibly duplicate) seed rows equals bitwise OR
    k = jnp.arange(K, dtype=jnp.int32)
    bit = jnp.left_shift(jnp.uint32(1), (k & 31).astype(jnp.uint32))
    onehot = jnp.zeros((K, Kw), dtype=jnp.uint32).at[k, k >> 5].set(bit)
    F = jnp.zeros((n_pad, Kw), dtype=jnp.uint32).at[seeds].add(onehot)
    F = F.at[n_atoms].set(jnp.uint32(0))  # dummy row stays all-zero
    visited = F

    deg_f = inc_deg.astype(jnp.float32)

    def hop(state, _):
        F, visited = state
        # a single hop's per-seed count is bounded by E_inc < 2^31, so the
        # int32 carrier cannot wrap within a hop (bit-exactness is still
        # subject to _bitdot's float32 accumulation, see its docstring);
        # totals over MANY hops can exceed int32, so hops are summed on
        # host in int64
        if count_edges:
            hop_counts = _bitdot(F, deg_f, block_rows)
        else:
            hop_counts = jnp.zeros((K,), dtype=jnp.int32)
        live = _apply_plan(F, levels1, widths1, chunk)
        reach_chunks = _apply_plan(live, levels2, widths2, chunk)
        raw = reach_chunks[out_map]
        nxt = raw & ~visited
        nxt = nxt.at[n_atoms].set(jnp.uint32(0))
        return (nxt, visited | nxt), hop_counts

    init = (F, visited)
    (F, visited), hop_counts = jax.lax.scan(hop, init, None, length=max_hops)

    reach = _bitdot(visited, jnp.ones((n_pad,), jnp.float32), block_rows)
    return visited, hop_counts, reach


# ------------------------------------------------------------------ host API


def block_layout(K: int, k_block: int) -> list[int]:
    """The real seed-block widths :func:`bfs_pull` runs for (K, k_block):
    K is padded to a multiple of WORD (floor WORD), then split into
    k_block-wide blocks with a possibly-ragged tail. Exposed so traffic
    models (bench.py) stay tied to the kernel's actual layout."""
    K_pad = _ceil_to(max(K, WORD), WORD)
    return [min(k_block, K_pad - s) for s in range(0, K_pad, k_block)]


def bfs_pull(
    snap: CSRSnapshot,
    seeds: np.ndarray,
    max_hops: int,
    chunk: int = 1 << 19,
    k_block: int = 1024,
    count_edges: bool = True,
) -> PullBFSResult:
    """Pull-mode multi-hop BFS over all seeds at once (blocked past
    ``k_block`` so the (N_pad, K/32) state stays ~1.3 GB at 10M atoms).

    Returns ``PullBFSResult(visited_t, edges_touched, reach_counts)``:
    ``visited_t`` is a device (N_pad, K/32) uint32 transposed bitmap,
    ``edges_touched`` a HOST (K,) int64 ndarray (per-hop int32 device
    partials summed on host so deep traversals cannot wrap), and
    ``reach_counts`` a device (K,) int32. Use :func:`visited_rows` to
    extract per-seed reachable sets on host.
    """
    if k_block <= 0 or k_block % WORD:
        raise ValueError(
            f"k_block must be a positive multiple of {WORD} (device words "
            f"pack {WORD} seeds); got {k_block}"
        )
    plans = plans_for(snap)
    seeds = np.asarray(seeds, dtype=np.int32)
    K = len(seeds)
    K_pad = _ceil_to(max(K, WORD), WORD)
    if K_pad != K:
        seeds = np.concatenate(
            [seeds, np.full(K_pad - K, snap.num_atoms, dtype=np.int32)]
        )
    dev = _device_plans(snap, plans)
    n_atoms = jnp.int32(plans.n_atoms)
    blocks = []
    for s in range(0, K_pad, k_block):
        block = seeds[s : s + k_block]
        blocks.append(
            _bfs_pull_device(
                dev["levels1"], plans.stage1.widths,
                dev["levels2"], plans.stage2_widths,
                dev["out_map"], dev["inc_deg"],
                jnp.asarray(block), n_atoms, max_hops,
                chunk=chunk, count_edges=count_edges,
            )
        )
    # host int64 hop-sum AFTER all blocks are dispatched, so multi-block
    # calls keep JAX's async-dispatch overlap
    if len(blocks) == 1:
        visited_t, hop_counts, reach = blocks[0]
        res = PullBFSResult(
            visited_t,
            np.asarray(hop_counts).astype(np.int64).sum(axis=0),
            reach,
        )
    else:
        res = PullBFSResult(
            jnp.concatenate([b[0] for b in blocks], axis=1),
            np.concatenate(
                [np.asarray(b[1]).astype(np.int64).sum(axis=0) for b in blocks]
            ),
            jnp.concatenate([b[2] for b in blocks]),
        )
    if K_pad != K:
        res = PullBFSResult(
            res.visited_t, res.edges_touched[:K], res.reach_counts[:K]
        )
    return res


def _device_plans(snap: CSRSnapshot, plans: PullBFSPlans) -> dict:
    cache = getattr(snap, "_pull_device", None)
    if cache is None:
        cache = {
            "levels1": tuple(jnp.asarray(l) for l in plans.stage1.levels),
            "levels2": tuple(jnp.asarray(l) for l in plans.stage2_levels),
            "out_map": jnp.asarray(plans.out_map),
            "inc_deg": jnp.asarray(plans.inc_deg),
        }
        object.__setattr__(snap, "_pull_device", cache)
    return cache


def visited_rows(res: PullBFSResult, n_atoms: int) -> list[np.ndarray]:
    """Per-seed sorted reachable-atom arrays from the transposed bitmap."""
    vt = np.asarray(res.visited_t)[: n_atoms]  # drop dummy+pad rows
    K = vt.shape[1] * WORD
    out = []
    for k in range(K):
        word = vt[:, k >> 5]
        hit = (word >> np.uint32(k & 31)) & np.uint32(1)
        out.append(np.nonzero(hit)[0].astype(np.int64))
    return out
