"""Pull-mode, seed-transposed BFS — the fast path for config-4 scale.

Round 2's ``ops/bitfrontier.py`` made 10M-atom frontiers *fit* (bit-packed
``(K, W)`` bitmaps) but not *fast*: its push scan does a ``test_bits``
gather plus an ``.at[:, d].max`` scatter **per (seed, edge)** — K×E scalar
probes per hop. Measured on v5e, XLA lowers both to a latency-bound unit
running ~10⁸ indices/s, which is why BENCH_r02 saw 324 s/run and <1% of
HBM (VERDICT r2 Weak #2).

This module keeps the same BFS semantics (``SimpleALGenerator`` neighbor
rule: frontier atom → incident links → their targets, reference
``HGBreadthFirstTraversal.java:49-66``) but re-lays the computation so the
expensive primitive is **one row gather per edge**, not K probes per edge:

- the reached set is stored **transposed**: ``V[(N+1, Kw)] uint32`` — bit
  k of word ``V[v, k>>5]`` says "seed k has reached atom v". One row per
  atom carries ALL seeds of the block at once (128 bytes at K=1024; 512
  bytes at K=4096 — the wide mode that feeds the Pallas gather, see
  ``ops/pallas_gather.py``).
- a hop is two *pull* reductions with NO scatters, pulling from VISITED
  (monotone closure — no separate frontier array, half the state):
  stage 1: ``link_live[l] = OR_{t ∈ targets(l)} V[t]``
  stage 2: ``reach[v]    = OR_{l ∈ incident(v)} link_live[l]``
  Each is a gather of edge-many rows followed by a fixed-width tree
  reduction over host-precomputed padded index plans (:class:`ReducePlan`):
  every CSR row is padded to a multiple of ``w`` and aligned, so the
  segment-OR is a plain ``reshape(-1, w, Kw) → OR(axis=1)`` — XLA's fused
  streaming path, no segment ids, no conflicts, hub rows handled by
  recursion (level ℓ reduces rows of up to ``w^(ℓ+1)`` entries).
- levels compose: stage 2's level-0 indices are pre-composed with stage
  1's output map on host, so link-space results are consumed directly
  without materializing a per-link destination array.
- per-seed edge counts (the benchmark numerator) are a bit-unpack +
  degree matmul per hop — MXU work, not gathers.

Geometry note: each gather row is ``Kw = K/32`` uint32 words (32 lanes for
K=1024). Gathers remain the dominant cost and are latency-bound, but the
total index count per hop drops from ``K × E`` to ``~1.3 × E × (1 + 1/w)``
— three orders of magnitude at K=1024.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops import pallas_gather as _pg
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

WORD = 32


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


# ------------------------------------------------------------------ host plans


@dataclass(frozen=True)
class ReducePlan:
    """Padded-gather tree reduction over one CSR relation.

    ``levels[0]`` indexes caller-provided value rows (with ``zero_row``
    pointing at a guaranteed-all-zero row) and covers every row; level
    ``ℓ>0`` covers ONLY rows still unfinished (more than one chunk) —
    single-chunk rows would otherwise pay a ``w_upper×`` pass-through pad
    per level, a 16× index blowup at hypergraph scale. Upper-level indices
    are local to the previous level's chunk array, with index
    ``len(prev_chunks)`` meaning the per-level appended zero row.

    A row's final chunk therefore lives in the chunk array of whichever
    level it finished at; ``out_map[r]`` addresses the **concatenation** of
    all level chunk arrays (in order) with one global zero row at the very
    end (``concat_size``). Empty rows map to the zero row. All index
    arrays are int32; every level's length is a multiple of its width.
    """

    levels: tuple[np.ndarray, ...]
    widths: tuple[int, ...]
    out_map: np.ndarray  # (R,) int32 into concat space; empty rows → zero row
    n_rows: int
    concat_size: int     # total chunks across levels; zero row lives here

    @property
    def total_indices(self) -> int:
        return int(sum(len(l) for l in self.levels))


def build_reduce_plan(
    offsets: np.ndarray,
    flat: np.ndarray,
    n_rows: int,
    zero_row: int,
    w: int = 8,
    w_upper: int = 8,
) -> ReducePlan:
    """Build the padded index pyramid for ``reduce_or`` over CSR rows.

    ``offsets``/``flat`` describe rows ``0..n_rows``; ``zero_row`` indexes
    an all-zero value row used for level-0 padding. Level 0 width is ``w``;
    upper levels use ``w_upper``.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    deg = offsets[1 : n_rows + 1] - offsets[:n_rows]
    nchunk = -(-deg // w)  # ceil; 0 for empty rows

    total = int(nchunk.sum()) * w
    idx0 = np.full(total, zero_row, dtype=np.int32)
    row_pad_starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(nchunk * w, out=row_pad_starts[1:])
    nz = np.nonzero(deg)[0]
    if len(nz):
        reps = deg[nz]
        dst = _segmented_ranges(row_pad_starts[nz], reps)
        src = _segmented_ranges(offsets[nz], reps)
        idx0[dst] = np.asarray(flat, dtype=np.int32)[src]
    levels = [idx0]
    widths = [w]

    # out_map in concat space; level offsets accumulate as levels are added
    out_map = np.full(n_rows, -1, dtype=np.int64)
    level_offset = 0
    n_prev = int(nchunk.sum())  # chunks in the previous (current last) level
    # rows' chunk spans start contiguously in the previous level's array
    cur_counts = nchunk
    cur_starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(cur_counts, out=cur_starts[1:])

    done = cur_counts == 1
    out_map[done] = level_offset + cur_starts[:n_rows][done]

    while int(cur_counts.max(initial=0)) > 1:
        wu = w_upper
        live = np.nonzero(cur_counts > 1)[0]
        live_counts = cur_counts[live]
        nxt_counts_live = -(-live_counts // wu)
        tot = int(nxt_counts_live.sum()) * wu
        idx = np.full(tot, n_prev, dtype=np.int32)  # pad → prev zero row
        pad_starts = np.zeros(len(live) + 1, dtype=np.int64)
        np.cumsum(nxt_counts_live * wu, out=pad_starts[1:])
        reps = live_counts
        dst = _segmented_ranges(pad_starts[:-1], reps)
        src = _segmented_ranges(cur_starts[live], reps)
        idx[dst] = src.astype(np.int32)
        levels.append(idx)
        widths.append(wu)

        level_offset += n_prev
        n_prev = int(nxt_counts_live.sum())
        cur_counts = np.zeros(n_rows, dtype=np.int64)
        cur_counts[live] = nxt_counts_live
        cur_starts = np.zeros(n_rows + 1, dtype=np.int64)
        np.cumsum(cur_counts, out=cur_starts[1:])
        done = cur_counts == 1
        out_map[done] = level_offset + cur_starts[:n_rows][done]

    concat_size = level_offset + n_prev
    out_map = np.where(out_map >= 0, out_map, concat_size)
    return ReducePlan(
        tuple(levels), tuple(widths), out_map.astype(np.int32),
        n_rows, concat_size,
    )


def _segmented_ranges(starts: np.ndarray, reps: np.ndarray) -> np.ndarray:
    """``concat([arange(s, s + r) for s, r in zip(starts, reps)])`` as two
    cumsums — no ``np.repeat``, which dominated plan-build time at 10M
    scale (VERDICT r4 weak #2). Requires every rep ≥ 1 (both call sites
    filter zero-degree rows first)."""
    total = int(reps.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    starts = np.asarray(starts, dtype=np.int64)
    delta = np.ones(total, dtype=np.int64)
    ends = np.cumsum(reps)
    delta[0] = starts[0]
    if len(starts) > 1:
        delta[ends[:-1]] = starts[1:] - (starts[:-1] + reps[:-1] - 1)
    return np.cumsum(delta)


# ------------------------------------------------------------------ device ops


def _reduce_level(
    values: jax.Array,  # (S, Kw) uint32 value rows; padding rows index S-1.. caller
    idx: jax.Array,     # (E,) int32, multiple of w
    w: int,
    chunk: int,
    use_pallas: bool = False,
) -> jax.Array:
    """gather + fixed-width OR-reduce, streamed in ``chunk``-row slices to
    bound the gather transient: returns (E//w, Kw) uint32."""
    E = idx.shape[0]
    Kw = values.shape[1]
    n_out = E // w
    if (use_pallas and Kw % 128 == 0 and E >= _pg.MIN_INDICES
            and _pg.SEG % (_pg.G * w) == 0
            and _pg._vmem_bytes(w, Kw) <= _pg.VMEM_BUDGET):
        return _pg.gather_or(values, idx, w)
    if E <= chunk * w:
        g = values[idx]
        return _or_fold(g.reshape(n_out, w, Kw))
    # pad out rows to a multiple of chunk for the scan
    n_blocks = -(-n_out // chunk)
    pad_rows = n_blocks * chunk - n_out
    if pad_rows:
        idx = jnp.concatenate(
            [idx, jnp.zeros((pad_rows * w,), dtype=idx.dtype)]
        )
    idx_b = idx.reshape(n_blocks, chunk * w)

    def body(_, ib):
        g = values[ib]
        return None, _or_fold(g.reshape(chunk, w, Kw))

    _, out = jax.lax.scan(body, None, idx_b)
    out = out.reshape(n_blocks * chunk, Kw)
    return out[:n_out] if pad_rows else out


def _or_fold(x: jax.Array) -> jax.Array:
    """(R, w, Kw) → (R, Kw) OR over axis 1 as a log-depth fold."""
    w = x.shape[1]
    while w > 1:
        if w % 2:
            x = jnp.concatenate(
                [x, jnp.zeros_like(x[:, :1])], axis=1
            )
            w += 1
        x = x[:, 0::2] | x[:, 1::2]
        w //= 2
    return x[:, 0]


def _apply_plan(
    values: jax.Array,            # (S, Kw) uint32 — level-0 value rows
    levels: Sequence[jax.Array],
    widths: Sequence[int],
    chunk: int,
    use_pallas: bool = False,
) -> jax.Array:
    """Run the reduction pyramid; returns the CONCATENATION of every
    level's chunk array plus one global zero row at the end — the address
    space ``ReducePlan.out_map`` (and composed downstream level-0 indices)
    point into.

    The concat buffer is allocated ONCE and level outputs are written into
    their sections by dynamic-update-slice — level 0 in ``chunk``-row
    blocks through a scan whose carry IS the buffer (XLA aliases scan
    carries in place). The old parts-then-concatenate shape held the
    dominant level-0 output alive twice, which at 10M atoms × 4096 seeds
    (512-byte rows) was the difference between ~13 GB peak and
    ResourceExhausted. Upper levels gather FROM the buffer itself with
    host-local indices rebased on device (pad marker ``n_prev`` → the
    global zero row); their outputs are small enough to materialize."""
    Kw = values.shape[1]
    sizes = [lvl.shape[0] // w for lvl, w in zip(levels, widths)]
    total = sum(sizes) + 1  # + global zero row at index `sum(sizes)`
    buf = jnp.zeros((total, Kw), dtype=values.dtype)
    buf = _reduce_into(buf, 0, values, levels[0], widths[0], chunk,
                       use_pallas)
    return _upper_levels(buf, levels[1:], widths[1:], sizes, sizes[0],
                         chunk)


def _upper_levels(
    buf: jax.Array,
    levels: Sequence[jax.Array],
    widths: Sequence[int],
    sizes: Sequence[int],
    off: int,
    chunk: int,
) -> jax.Array:
    """Run the upper levels of a pyramid over a concat buffer whose level-0
    section is already in place. ``sizes`` lists EVERY level's chunk count
    (level 0 first); ``off`` is the first upper section's offset; the
    global zero row sits at ``buf.shape[0] - 1``. Prev-level-local indices
    are rebased into buffer space on device (pad marker ``len(prev)`` →
    the global zero row). Upper levels stay on the XLA gather: they are
    small, and the Pallas wrapper's pad/slice copies would cost more than
    they save."""
    total = buf.shape[0]
    for i, (idx, w) in enumerate(zip(levels, widths)):
        n_prev = sizes[i]
        prev_off = off - n_prev
        idx_g = jnp.where(
            idx == n_prev, total - 1, idx + prev_off
        ).astype(idx.dtype)
        out = _reduce_level(buf, idx_g, w, chunk, False)
        buf = jax.lax.dynamic_update_slice(buf, out, (off, 0))
        off += sizes[i + 1]
    return buf


def _reduce_into(
    buf: jax.Array,
    off: int,
    values: jax.Array,
    idx: jax.Array,
    w: int,
    chunk: int,
    use_pallas: bool,
) -> jax.Array:
    """OR-reduce ``values`` rows over ``idx`` groups of ``w``, writing the
    ``len(idx)//w`` output rows into ``buf[off:]`` in place: full blocks of
    ``chunk`` outputs stream through a scan (carry = buf, aliased by XLA),
    the ragged tail lands with one final update."""
    E = idx.shape[0]
    n_out = E // w
    n_full = n_out // chunk
    if n_full:
        xs = idx[: n_full * chunk * w].reshape(n_full, chunk * w)

        def body(b, ib_i):
            ib, i = ib_i
            out = _reduce_level(values, ib, w, chunk, use_pallas)
            return jax.lax.dynamic_update_slice(
                b, out, (off + i * chunk, 0)
            ), None

        buf, _ = jax.lax.scan(
            body, buf, (xs, jnp.arange(n_full, dtype=jnp.int32))
        )
    tail = n_out - n_full * chunk
    if tail:
        out = _reduce_level(
            values, idx[n_full * chunk * w :], w, chunk, use_pallas
        )
        buf = jax.lax.dynamic_update_slice(
            buf, out, (off + n_full * chunk, 0)
        )
    return buf


class PullBFSResult(NamedTuple):
    visited_t: jax.Array      # (N_pad, Kw) uint32 — TRANSPOSED packed bitmaps
    edges_touched: np.ndarray  # (K,) int64 — summed over hops on host
    reach_counts: jax.Array   # (K,) int32 — |visited| per seed (incl. seed)


@dataclass
class PullBFSPlans:
    """Host-side precompute for :func:`bfs_pull` over one snapshot.

    Expensive to build (two padded index pyramids + a composed link map)
    but reusable across every BFS on the snapshot; cached on the snapshot
    object by :func:`plans_for`.
    """

    n_atoms: int
    n_pad: int
    stage1: ReducePlan  # tgt relation: link rows ← atom value rows
    stage2_levels: tuple[np.ndarray, ...]  # level0 composed into stage1 chunks
    stage2_widths: tuple[int, ...]
    out_map: np.ndarray
    inc_deg: np.ndarray  # (N_pad,) int32 — incidence degree (edge counting)

    @property
    def total_indices(self) -> int:
        return (
            self.stage1.total_indices
            + int(sum(len(l) for l in self.stage2_levels))
            + len(self.out_map)
        )


def build_pull_plans(
    snap: CSRSnapshot, w1: int = 8, w2: int = 8, w_upper: int = 8
) -> PullBFSPlans:
    N = snap.num_atoms
    n_pad = _ceil_to(N + 1, 8)
    e_tgt = snap.n_edges_tgt
    e_inc = snap.n_edges_inc
    # stage 1: link_live = OR of F over target rows (tgt CSR, rows=atoms)
    s1 = build_reduce_plan(
        snap.tgt_offsets[: N + 2], snap.tgt_flat[:e_tgt], N + 1,
        zero_row=N, w=w1, w_upper=w_upper,
    )
    # stage 2 runs over the incidence CSR; its level-0 entries are LINK ids.
    # Compose them through stage-1's concat-space out_map on host, so the
    # hop consumes stage-1 chunks directly — no per-link destination array
    # is ever materialized.
    s2 = build_reduce_plan(
        snap.inc_offsets[: N + 2], snap.inc_links[:e_inc], N + 1,
        zero_row=N, w=w2, w_upper=w_upper,
    )
    # level-0 padding used zero_row=N (an atom id); atom N has no targets →
    # its out_map entry is stage-1's zero row. Non-link atoms likewise.
    lvl0 = s1.out_map[s2.levels[0]]
    s2_levels = (lvl0,) + s2.levels[1:]

    out_map = np.full(n_pad, s2.concat_size, dtype=np.int32)
    out_map[: N + 1] = s2.out_map
    out_map[N] = s2.concat_size  # dummy row must stay empty
    inc_deg = np.zeros(n_pad, dtype=np.int32)
    inc_deg[: N + 1] = (
        snap.inc_offsets[1 : N + 2].astype(np.int64)
        - snap.inc_offsets[: N + 1]
    ).astype(np.int32)
    inc_deg[N] = 0
    return PullBFSPlans(
        n_atoms=N,
        n_pad=n_pad,
        stage1=s1,
        stage2_levels=s2_levels,
        stage2_widths=s2.widths,
        out_map=out_map,
        inc_deg=inc_deg,
    )


PLAN_FORMAT = 1


class StalePlans(ValueError):
    """The sidecar is WELL-FORMED but belongs to a different snapshot or
    plan format — the quiet-rebuild case loaders treat as "no sidecar",
    deliberately distinct from a corrupt/unreadable file (which
    ``load_snapshot`` logs and counts as ``fault.sidecar_corrupt``)."""


def save_plans(plans: PullBFSPlans, path, fingerprint: str = "") -> None:
    """Persist a plan pyramid as an .npz (uncompressed — load speed is the
    point: rebuilding at 10M scale costs ~15 s of host cumsums, loading
    costs one sequential read). ``path`` may be an open binary file
    object (the crash-atomic checkpoint writer hands in its tmp file).
    ``fingerprint`` (see :func:`snapshot_fingerprint`) travels with the
    file so loaders can reject a sidecar that no longer matches its
    snapshot."""
    arrs: dict = {
        "fingerprint": np.frombuffer(
            fingerprint.encode("ascii"), dtype=np.uint8
        ),
        "format": np.int64(PLAN_FORMAT),
        "n_atoms": np.int64(plans.n_atoms),
        "n_pad": np.int64(plans.n_pad),
        "s1_widths": np.asarray(plans.stage1.widths, np.int64),
        "s1_out_map": plans.stage1.out_map,
        "s1_n_rows": np.int64(plans.stage1.n_rows),
        "s1_concat": np.int64(plans.stage1.concat_size),
        "s2_widths": np.asarray(plans.stage2_widths, np.int64),
        "out_map": plans.out_map,
        "inc_deg": plans.inc_deg,
    }
    for i, lvl in enumerate(plans.stage1.levels):
        arrs[f"s1_l{i}"] = lvl
    for i, lvl in enumerate(plans.stage2_levels):
        arrs[f"s2_l{i}"] = lvl
    np.savez(path, **arrs)


def load_plans(path: str,
               expect_fingerprint: Optional[str] = None) -> PullBFSPlans:
    with np.load(path) as z:
        if int(z["format"]) != PLAN_FORMAT:
            raise StalePlans(
                f"plan file {path}: format {int(z['format'])} != "
                f"{PLAN_FORMAT}"
            )
        if expect_fingerprint is not None:
            got = bytes(z["fingerprint"]).decode("ascii") \
                if "fingerprint" in z else ""
            if got != expect_fingerprint:
                raise StalePlans(
                    f"plan file {path}: fingerprint {got!r} does not match "
                    f"the snapshot ({expect_fingerprint!r}) — stale sidecar"
                )
        s1_levels = tuple(
            z[k] for k in sorted(
                (k for k in z.files if k.startswith("s1_l")),
                key=lambda k: int(k[4:]),
            )
        )
        s2_levels = tuple(
            z[k] for k in sorted(
                (k for k in z.files if k.startswith("s2_l")),
                key=lambda k: int(k[4:]),
            )
        )
        s1 = ReducePlan(
            s1_levels, tuple(int(w) for w in z["s1_widths"]),
            z["s1_out_map"], int(z["s1_n_rows"]), int(z["s1_concat"]),
        )
        return PullBFSPlans(
            n_atoms=int(z["n_atoms"]),
            n_pad=int(z["n_pad"]),
            stage1=s1,
            stage2_levels=s2_levels,
            stage2_widths=tuple(int(w) for w in z["s2_widths"]),
            out_map=z["out_map"],
            inc_deg=z["inc_deg"],
        )


def snapshot_fingerprint(snap: CSRSnapshot) -> str:
    """Content key over the structural CSR arrays — two snapshots with the
    same fingerprint have identical plans."""
    import zlib

    h = 0
    for a in (
        snap.tgt_offsets, snap.tgt_flat[: snap.n_edges_tgt],
        snap.inc_offsets, snap.inc_links[: snap.n_edges_inc],
    ):
        h = zlib.crc32(np.ascontiguousarray(a).view(np.uint8), h)
    return (f"{snap.num_atoms}_{snap.n_edges_tgt}_"
            f"{snap.n_edges_inc}_{h:08x}")


def plans_for(snap: CSRSnapshot) -> PullBFSPlans:
    """Plans for a snapshot: memoized on the snapshot object, and — when
    ``HG_PLAN_CACHE`` names a directory — persisted there keyed by the
    snapshot's content fingerprint, so repeated sessions over the same
    graph (the benchmark's warm runs, a reopened store) skip the ~15 s
    10M-scale rebuild entirely."""
    plans = getattr(snap, "_pull_plans", None)
    if plans is None:
        cache_dir = os.environ.get("HG_PLAN_CACHE")
        cache_path = None
        fp = None
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            fp = snapshot_fingerprint(snap)
            cache_path = os.path.join(cache_dir, f"pullplans_{fp}.npz")
            if os.path.exists(cache_path):
                try:
                    plans = load_plans(cache_path, expect_fingerprint=fp)
                except Exception:
                    plans = None  # stale/corrupt cache entry → rebuild
        if plans is None:
            plans = build_pull_plans(snap)
            if cache_path is not None:
                # .npz suffix keeps np.savez from appending another one;
                # write-then-rename = no torn cache entries
                tmp = cache_path[:-4] + ".tmp.npz"
                save_plans(plans, tmp, fingerprint=fp)
                os.replace(tmp, cache_path)
        object.__setattr__(snap, "_pull_plans", plans)
    return plans


# ------------------------------------------------------------------ kernel


def _bitdot(packed_t: jax.Array, vec: jax.Array, block_rows: int) -> jax.Array:
    """Σ_v vec[v] · bit(v, k) for every seed column k.

    ``packed_t (R, Kw) uint32``, ``vec (R,) float32`` → ``(K,) int32``.
    Bit-unpack + matvec in row blocks so the unpack transient stays
    ~``block_rows × K`` floats (MXU work, not gathers). Values are exact
    while each block's partial sum stays below 2^24 (always true in the
    test-scale graphs; at benchmark scale the relative error is ≤1e-7 of a
    throughput counter).
    """
    R, Kw = packed_t.shape
    K = Kw * WORD
    if R < block_rows:  # tiny inputs: pad up to one whole block
        pad = _ceil_to(R, 8) - R
        if pad:
            packed_t = jnp.concatenate(
                [packed_t, jnp.zeros((pad, Kw), jnp.uint32)]
            )
            vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
        block_rows = R + pad
    n_blocks = -(-R // block_rows)
    shifts = jnp.arange(WORD, dtype=jnp.uint32)

    # fori + clamped dynamic slices instead of pad-and-reshape: the pad
    # path CONCATENATED (= copied) the whole packed array, a second
    # visited-bitmap's worth of HBM at 10M atoms × 4096 seeds. The last
    # block's clamped start overlaps the previous block; the row mask
    # zeroes the already-counted rows.
    def body(i, acc):
        start = jnp.minimum(i * block_rows, packed_t.shape[0] - block_rows)
        sl = jax.lax.dynamic_slice(packed_t, (start, 0), (block_rows, Kw))
        dg = jax.lax.dynamic_slice(vec, (start,), (block_rows,))
        fresh = (start + jnp.arange(block_rows)) >= i * block_rows
        dg = jnp.where(fresh, dg, 0.0)
        bits = ((sl[:, :, None] >> shifts) & 1).astype(jnp.float32)
        part = jnp.einsum(
            "rk,r->k", bits.reshape(block_rows, K), dg,
            preferred_element_type=jnp.float32,
        )
        return acc + part.astype(jnp.int32)

    return jax.lax.fori_loop(0, n_blocks, body, jnp.zeros((K,), jnp.int32))


# The hop runs as FOUR host-sequenced jits instead of one scan. At 10M
# atoms × 4096 seeds the hop's working set (visited 5.1 GB + stage-1
# buffer 5.9 GB + stage-2 buffer 4.6 GB) only fits the 16 GiB HBM when
# buffers are freed/reused the moment they are dead — a lax.scan keeps the
# carry double-buffered and every intermediate alive for the compiler's
# conservative lifetime, which measured 21 GB of temps (ResourceExhausted).
# Host sequencing + donate_argnums makes each free explicit; dispatch cost
# is a few RTTs per hop, noise against multi-second hops.
#
# Hops pull from VISITED, not from a separate frontier array: the closure
# is monotone (visited_h ∪ N(visited_h) = visited_h ∪ N(frontier_h) =
# visited_{h+1}), so pulling the superset reaches the identical per-hop
# visited sets while carrying HALF the state. Per-hop frontier edge counts
# fall out as differences of S_h = Σ_v visited_h[v]·deg(v): frontiers
# partition visited, so Σdeg(frontier_h) = S_h − S_{h-1}.


@hgverify.entry(
    shapes=lambda: (hgverify.sds((32,), "int32"),
                    hgverify.sds((), "int32")),
    statics={"n_pad": 64},
)
@partial(jax.jit, static_argnames=("n_pad",))
def _seed_bitmap(seeds: jax.Array, n_atoms: jax.Array, n_pad: int):
    K = seeds.shape[0]
    Kw = K // WORD
    # bit k of V[seeds[k]] — per-k bits are distinct, so scatter-add over
    # (possibly duplicate) seed rows equals bitwise OR
    k = jnp.arange(K, dtype=jnp.int32)
    bit = jnp.left_shift(jnp.uint32(1), (k & 31).astype(jnp.uint32))
    onehot = jnp.zeros((K, Kw), dtype=jnp.uint32).at[k, k >> 5].set(bit)
    visited = jnp.zeros((n_pad, Kw), dtype=jnp.uint32).at[seeds].add(onehot)
    return visited.at[n_atoms].set(jnp.uint32(0))  # dummy row stays zero


def _bitdot_rows(K: int, n_pad: int) -> int:
    # bitdot unpacks a (block_rows, K) f32 transient — cap it at ~0.5 GB
    # so wide seed blocks leave HBM for the state
    return max(1024, min((1 << 27) // max(K, 1), 131072,
                         _ceil_to(n_pad, 8) // 8))


@hgverify.entry(
    shapes=lambda: (hgverify.sds((64, 1), "uint32"),
                    hgverify.sds((64,), "float32")),
)
@jax.jit
def _deg_sum(visited: jax.Array, deg_f: jax.Array) -> jax.Array:
    """S = Σ_v visited[v]·deg(v) per seed. Bounded by E_inc < 2^31 so
    int32 cannot wrap (bit-exactness subject to _bitdot's f32
    accumulation, see its docstring)."""
    return _bitdot(visited, deg_f,
                   _bitdot_rows(visited.shape[1] * WORD, visited.shape[0]))


@hgverify.entry(
    shapes=lambda: (hgverify.sds((64, 1), "uint32"),
                    (hgverify.sds((64,), "int32"),)),
    statics={"widths": (8,), "chunk": 1 << 19, "use_pallas": False},
)
@partial(jax.jit, static_argnames=("widths", "chunk", "use_pallas"))
def _stage(values, levels, widths, chunk, use_pallas):
    return _apply_plan(values, levels, widths, chunk, use_pallas)


@partial(jax.jit, static_argnames=("w", "chunk", "use_pallas"))
def _stage_lvl0_consume(values, idx, w, chunk, use_pallas):
    """Level-0 chunks only, into an exact-size buffer. ``values`` (the
    previous stage's buffer, ~5.9 GB at benchmark scale) is genuinely dead
    once this jit returns; the caller drops its ref and syncs — splitting
    stage 2 here is what lets that buffer free before the full concat
    buffer allocates. (No donate: the shapes can never alias, donation
    would only warn.)"""
    n0 = idx.shape[0] // w
    buf = jnp.zeros((n0, values.shape[1]), dtype=values.dtype)
    return _reduce_into(buf, 0, values, idx, w, chunk, use_pallas)


@partial(jax.jit, static_argnames=("widths", "chunk"))
def _stage_upper(lvl0, levels, widths, chunk):
    """Assemble the stage's concat buffer from the level-0 chunks, then
    run the (small) upper levels on the XLA gather path. ``widths``
    includes the level-0 width at [0]; ``levels`` holds only the upper
    index arrays."""
    n0, Kw = lvl0.shape
    sizes = [n0] + [lvl.shape[0] // w
                    for lvl, w in zip(levels, widths[1:])]
    total = sum(sizes) + 1
    buf = jnp.zeros((total, Kw), dtype=lvl0.dtype)
    buf = jax.lax.dynamic_update_slice(buf, lvl0, (0, 0))
    return _upper_levels(buf, levels, widths[1:], sizes, n0, chunk)


@hgverify.entry(
    shapes=lambda: (hgverify.sds((64, 1), "uint32"),
                    hgverify.sds((9, 1), "uint32"),
                    hgverify.sds((64,), "int32"),
                    hgverify.sds((), "int32")),
    donate=True,
)
@partial(jax.jit, donate_argnums=(0,))  # visited aliases the output
def _visited_update(visited, reach_chunks, out_map, n_atoms):
    """visited | reach_chunks[out_map], folded in row blocks so no second
    (n_pad, Kw) array materializes while the stage buffer is alive;
    fori_loop carries alias in place."""
    n_pad, Kw = visited.shape
    ub = 1 << 18
    n_full = n_pad // ub

    def upd(i, vis):
        sl = jax.lax.dynamic_slice(out_map, (i * ub,), (ub,))
        cur = jax.lax.dynamic_slice(vis, (i * ub, 0), (ub, Kw))
        return jax.lax.dynamic_update_slice(
            vis, cur | reach_chunks[sl], (i * ub, 0)
        )

    nxt = (jax.lax.fori_loop(0, n_full, upd, visited)
           if n_full else visited)
    tail = n_pad - n_full * ub
    if tail:
        sl = out_map[n_full * ub:]
        cur = jax.lax.dynamic_slice(nxt, (n_full * ub, 0), (tail, Kw))
        nxt = jax.lax.dynamic_update_slice(
            nxt, cur | reach_chunks[sl], (n_full * ub, 0)
        )
    return nxt.at[n_atoms].set(jnp.uint32(0))


@hgverify.entry(shapes=lambda: (hgverify.sds((64, 1), "uint32"),))
@jax.jit
def _reach_counts(visited: jax.Array) -> jax.Array:
    n_pad = visited.shape[0]
    return _bitdot(visited, jnp.ones((n_pad,), jnp.float32),
                   _bitdot_rows(visited.shape[1] * WORD, n_pad))


def _bfs_pull_device(
    levels1: tuple[jax.Array, ...],
    widths1: tuple[int, ...],
    levels2: tuple[jax.Array, ...],
    widths2: tuple[int, ...],
    out_map: jax.Array,      # (N_pad,) int32
    inc_deg: jax.Array,      # (N_pad,) int32
    seeds: jax.Array,        # (K,) int32 — K % 32 == 0
    n_atoms: jax.Array,      # scalar int32 — dummy row id
    max_hops: int,
    chunk: int = 1 << 19,
    count_edges: bool = True,
    use_pallas: bool = False,
) -> tuple[jax.Array, list[jax.Array], jax.Array]:
    n_pad = out_map.shape[0]
    visited = _seed_bitmap(seeds, n_atoms, n_pad)
    deg_f = inc_deg.astype(jnp.float32)
    s_ins: list[jax.Array] = []
    for _ in range(max_hops):
        if count_edges:
            s_ins.append(_deg_sum(visited, deg_f))
            jax.block_until_ready(s_ins[-1])
        live = _stage(visited, levels1, widths1, chunk, use_pallas)
        jax.block_until_ready(live)
        lvl0b = _stage_lvl0_consume(live, levels2[0], widths2[0], chunk,
                                    use_pallas)
        # the donations can't alias (shapes differ), so the host ref is
        # what keeps each dead buffer resident — drop it AND sync before
        # the next dispatch: async dispatch would let the allocator grab
        # stage-upper's buffers while the consume step (and therefore
        # `live`'s 5.9 GB) is still in flight. The sync costs one RTT per
        # hop against multi-second hops.
        del live
        jax.block_until_ready(lvl0b)
        reach_chunks = _stage_upper(lvl0b, levels2[1:], widths2, chunk)
        del lvl0b
        visited = _visited_update(visited, reach_chunks, out_map, n_atoms)
        del reach_chunks
        jax.block_until_ready(visited)
    reach = _reach_counts(visited)
    return visited, s_ins, reach


# ------------------------------------------------------------------ host API


def block_layout(K: int, k_block: int) -> list[int]:
    """The real seed-block widths :func:`bfs_pull` runs for (K, k_block):
    K is padded to a multiple of WORD (floor WORD), then split into
    k_block-wide blocks with a possibly-ragged tail. Exposed so traffic
    models (bench.py) stay tied to the kernel's actual layout."""
    K_pad = _ceil_to(max(K, WORD), WORD)
    return [min(k_block, K_pad - s) for s in range(0, K_pad, k_block)]


def bfs_pull(
    snap: CSRSnapshot,
    seeds: np.ndarray,
    max_hops: int,
    chunk: int = 1 << 19,
    k_block: int = 1024,
    count_edges: bool = True,
) -> PullBFSResult:
    """Pull-mode multi-hop BFS over all seeds at once (blocked past
    ``k_block``; at 10M atoms a 4096-wide block's working set fills most
    of a v5e's HBM, so callers should drop previous results before
    re-running at that width).

    Returns ``PullBFSResult(visited_t, edges_touched, reach_counts)``:
    ``visited_t`` is a device (N_pad, K/32) uint32 transposed bitmap,
    ``edges_touched`` a HOST (K,) int64 ndarray (the telescoped
    Σdeg(visited) of the last hop — frontiers partition visited, so it
    equals the per-hop frontier-degree total; a single int32-bounded
    quantity ≤ E_inc), and ``reach_counts`` a device (K,) int32. Use
    :func:`visited_rows` to extract per-seed reachable sets on host.
    Blocks run sequentially: each hop synchronizes internally so stage
    buffers free before the next allocates (HBM headroom, see
    ``_bfs_pull_device``).
    """
    if k_block <= 0 or k_block % WORD:
        raise ValueError(
            f"k_block must be a positive multiple of {WORD} (device words "
            f"pack {WORD} seeds); got {k_block}"
        )
    plans = plans_for(snap)
    seeds = np.asarray(seeds, dtype=np.int32)
    K = len(seeds)
    K_pad = _ceil_to(max(K, WORD), WORD)
    if K_pad != K:
        seeds = np.concatenate(
            [seeds, np.full(K_pad - K, snap.num_atoms, dtype=np.int32)]
        )
    dev = _device_plans(snap, plans)
    n_atoms = jnp.int32(plans.n_atoms)
    blocks = []
    for s in range(0, K_pad, k_block):
        block = seeds[s : s + k_block]
        # fused megakernel first: ONE dispatch runs every hop with no
        # stage buffers and no host sequencing (ops/pallas_bfs); declines
        # (CPU backend, window budgets) fall through to the staged chain
        from hypergraphdb_tpu.ops import pallas_bfs as _pbfs

        if _pbfs.fused_ready(snap, len(block)):
            blocks.append(
                _pbfs.bfs_pull_fused(snap, block, max_hops,
                                     count_edges=count_edges)
            )
            continue
        # wide blocks (k_block % 4096 == 0 → 128-lane rows) run the Pallas
        # gather when it preflights on this backend; everything else keeps
        # the XLA gather (same measured descriptor rate, no width limits)
        use_pallas = len(block) % 4096 == 0 and _pg.pallas_ok()
        blocks.append(
            _bfs_pull_device(
                dev["levels1"], plans.stage1.widths,
                dev["levels2"], plans.stage2_widths,
                dev["out_map"], dev["inc_deg"],
                jnp.asarray(block), n_atoms, max_hops,
                chunk=chunk, count_edges=count_edges,
                use_pallas=use_pallas,
            )
        )
    # The device emits S_h (Σ deg over visited entering each hop);
    # frontiers partition visited, so the total over all hops telescopes
    # to the LAST emitted S — one (K,) download per block.
    def total_edges(b) -> np.ndarray:
        s_ins = b[1]
        if not len(s_ins):  # zero hops / counting off
            return np.zeros(b[2].shape[0], np.int64)
        return np.asarray(s_ins[-1]).astype(np.int64)

    if len(blocks) == 1:
        visited_t, _, reach = blocks[0]
        res = PullBFSResult(visited_t, total_edges(blocks[0]), reach)
    else:
        res = PullBFSResult(
            jnp.concatenate([b[0] for b in blocks], axis=1),
            np.concatenate([total_edges(b) for b in blocks]),
            jnp.concatenate([b[2] for b in blocks]),
        )
    if K_pad != K:
        res = PullBFSResult(
            res.visited_t, res.edges_touched[:K], res.reach_counts[:K]
        )
    return res


def _device_plans(snap: CSRSnapshot, plans: PullBFSPlans) -> dict:
    cache = getattr(snap, "_pull_device", None)
    if cache is None:
        cache = {
            "levels1": tuple(jnp.asarray(l) for l in plans.stage1.levels),
            "levels2": tuple(jnp.asarray(l) for l in plans.stage2_levels),
            "out_map": jnp.asarray(plans.out_map),
            "inc_deg": jnp.asarray(plans.inc_deg),
        }
        object.__setattr__(snap, "_pull_device", cache)
    return cache


def visited_rows(res: PullBFSResult, n_atoms: int) -> list[np.ndarray]:
    """Per-seed sorted reachable-atom arrays from the transposed bitmap."""
    vt = np.asarray(res.visited_t)[: n_atoms]  # drop dummy+pad rows
    K = vt.shape[1] * WORD
    out = []
    for k in range(K):
        word = vt[:, k >> 5]
        hit = (word >> np.uint32(k & 31)) & np.uint32(1)
        out.append(np.nonzero(hit)[0].astype(np.int64))
    return out
