"""hgindex storage layer: device-resident secondary value indexes.

The reference promises ordered/range lookups over atom values through
``HGSortIndex`` (B-tree cursors repositioned per probe); the TPU-native
twin is a **sorted device column pair per indexed dimension**: for each
value KIND byte (int / float / bool / time — the order-preserving key
families of ``utils/ordered_bytes``), the base snapshot's live atoms
sorted ascending by ``(value_rank, gid)``. Range and ordered queries then
run as batched ``searchsorted`` over the rank words plus bounded gathers
(``ops/value_index.py``) — the role-free indexing move (PAPERS.md,
arXiv:0811.1083): one sorted column serves every predicate shape over
that dimension, no per-predicate index.

Consistency follows the pinned-view LSM discipline everywhere else in
the serve tier: the base column is immutable per compaction epoch
(cached on the snapshot, rebuilt when compaction swaps the base), a
small **delta column** covers memtable atoms under the same
``max_lag_edges`` drift-marker refresh as the BFS device delta
(``ops/incremental.SnapshotManager.value_delta``), and the host
correction sets (dead / revalued / the uncovered memtable residual)
compensate at collect time — exact at any lag.

Rank semantics: ``value_rank`` is the order-preserving 64-bit payload
rank of ``ops/snapshot.py``. For fixed-width kinds the rank order IS the
value order (tie-free). Variable-width kinds (str/bytes) carry a SECOND
rank word (key payload bytes 8..16, ``utils/ordered_bytes.rank128``) so
rank-tied windows stay exact on device up to 16 payload bytes; columns
holding any AMBIGUOUS key (payload >16 bytes, or NUL among the first 16
— zero-padding stops being order/identity-faithful there) clear
``device_exact`` and the serve lane routes those requests to the exact
host path instead of shipping maybe-wrong windows.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: value kinds whose 64-bit payload rank is the exact value order (the
#: compiler's ``_FIXED_WIDTH_KINDS``, re-exported at the storage layer so
#: serve/bridge need not import the query compiler for it)
FIXED_WIDTH_KINDS = frozenset(b"ifbt")

#: gid padding for column tails (int32 max — sorts last, never a real id)
GID_PAD = np.int32(np.iinfo(np.int32).max)

#: rank-word padding (uint32 max pair — sorts after every real rank)
RANK_PAD = np.uint32(0xFFFFFFFF)


@dataclass
class ValueIndexColumn:
    """One indexed dimension's sorted column pair, device-resident.

    ``rank_hi``/``rank_lo`` are the first 64-bit rank word split into
    uint32 words (compare lexicographically hi-then-lo — the
    ``ops/snapshot.DeviceSnapshot`` convention; jnp would truncate
    uint64), ``rank2_hi``/``rank2_lo`` the SECOND rank word (payload
    bytes 8..16) split the same way, ``gids`` the owning atom ids; all
    five sorted ascending by ``(rank, rank2, gid)`` and padded to a
    power-of-two bucket with ``RANK_PAD``/``GID_PAD``. ``n`` is the real
    (unpadded) entry count; kernels bound their binary searches by it,
    so pad entries are never probed. ``covered`` is meaningful for DELTA
    columns only: how many leading entries of the memtable's
    ``new_atoms`` list the column accounts for (the residual past it is
    host-corrected at collect). ``device_exact`` asserts the 128-bit
    rank pair totally orders AND identifies every entry — always True
    for fixed-width kinds, True for variable-width only when no entry's
    key is ambiguous; the serve lane may ship device windows for a
    variable-width request only when every consulted column says so."""

    kind: int             # value kind byte this column indexes
    n: int                # real entries
    rank_hi: object       # (M,) uint32 jax array
    rank_lo: object       # (M,) uint32
    gids: object          # (M,) int32
    rank2_hi: object = None  # (M,) uint32 — second rank word, high half
    rank2_lo: object = None  # (M,) uint32 — second rank word, low half
    epoch: int = -1       # compaction epoch (delta columns)
    covered: int = 0      # new_atoms prefix length scanned (delta columns)
    device_exact: bool = False  # 128-bit rank pair is order+identity-exact


def _sorted_device_column(kind: int, ranks: np.ndarray, gids: np.ndarray,
                          epoch: int = -1, covered: int = 0,
                          minimum: int = 128,
                          ranks2: np.ndarray = None,
                          exact: bool = None) -> ValueIndexColumn:
    """Sort host ``(rank uint64, rank2 uint64, gid)`` triples, split rank
    words, pad to a bucket, and upload. The ONE constructor both the base
    and delta builders go through, so the two can never disagree on
    layout. ``ranks2`` defaults to zeros (fixed-width kinds carry no
    second word); ``exact`` defaults to the kind's fixed-width verdict.
    The bucket rule is ``ops/setops._bucket`` — the same rule that sizes
    the kernels' gather pads (deferred import, like jnp: every caller is
    already on a device path)."""
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.setops import _bucket

    if ranks2 is None:
        ranks2 = np.zeros(len(ranks), dtype=np.uint64)
    if exact is None:
        exact = int(kind) in FIXED_WIDTH_KINDS
    order = np.lexsort((gids, ranks2, ranks))
    ranks = ranks[order]
    ranks2 = ranks2[order]
    gids = gids[order].astype(np.int32)
    n = len(gids)
    m = _bucket(max(n, 1), minimum=minimum)
    hi = np.full(m, RANK_PAD, dtype=np.uint32)
    lo = np.full(m, RANK_PAD, dtype=np.uint32)
    hi2 = np.full(m, RANK_PAD, dtype=np.uint32)
    lo2 = np.full(m, RANK_PAD, dtype=np.uint32)
    gp = np.full(m, GID_PAD, dtype=np.int32)
    hi[:n] = (ranks >> np.uint64(32)).astype(np.uint32)
    lo[:n] = (ranks & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi2[:n] = (ranks2 >> np.uint64(32)).astype(np.uint32)
    lo2[:n] = (ranks2 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    gp[:n] = gids
    return ValueIndexColumn(
        kind=int(kind), n=n,
        rank_hi=jnp.asarray(hi), rank_lo=jnp.asarray(lo),
        gids=jnp.asarray(gp),
        rank2_hi=jnp.asarray(hi2), rank2_lo=jnp.asarray(lo2),
        epoch=epoch, covered=covered, device_exact=bool(exact),
    )


def value_index_column(snap, kind: int) -> ValueIndexColumn:
    """The BASE column of one kind for a packed snapshot — built from the
    snapshot's ``value_rank``/``value_kind`` columns (live atoms only)
    and cached on the snapshot like ``ell_targets``: one build + upload
    per (compaction epoch, kind), shared by every batch that epoch."""
    cache = getattr(snap, "_value_index_cols", None)
    if cache is None:
        cache = {}
        object.__setattr__(snap, "_value_index_cols", cache)
    kind = int(kind)
    col = cache.get(kind)
    if col is not None:
        return col
    N = snap.num_atoms
    sel = np.flatnonzero(
        (snap.value_kind[:N] == np.uint8(kind)) & (snap.type_of[:N] >= 0)
    )
    rank2 = getattr(snap, "value_rank2", None)
    ambig = getattr(snap, "value_ambig", None)
    if rank2 is not None and len(rank2) >= N:
        ranks2 = rank2[sel].astype(np.uint64)
        exact = (kind in FIXED_WIDTH_KINDS
                 or (ambig is not None and len(ambig) >= N
                     and not bool(np.any(ambig[sel]))))
    else:
        # pre-tie-break snapshot (no second rank word): variable-width
        # kinds cannot certify device exactness
        ranks2 = None
        exact = kind in FIXED_WIDTH_KINDS
    col = _sorted_device_column(
        kind, snap.value_rank[sel].astype(np.uint64), sel,
        ranks2=ranks2, exact=exact,
    )
    cache[kind] = col
    return col


def type_of_device(snap):
    """The snapshot's ``type_of`` column alone on device, cached — the
    range lane's per-candidate type filter must not force the FULL
    ``DeviceSnapshot`` upload under executors (the sharded one) that
    deliberately never materialize it."""
    cached = getattr(snap, "_type_of_dev", None)
    if cached is not None:
        return cached
    import jax.numpy as jnp

    dev = snap.__dict__.get("device")
    out = dev.type_of if dev is not None else jnp.asarray(snap.type_of)
    object.__setattr__(snap, "_type_of_dev", out)
    return out


def inc_csr_device(snap):
    """The incidence CSR (offsets, links) on device, cached under the
    same rule as :func:`type_of_device` — the anchored range lane's
    membership filter reads just these two arrays."""
    cached = getattr(snap, "_inc_csr_dev", None)
    if cached is not None:
        return cached
    import jax.numpy as jnp

    dev = snap.__dict__.get("device")
    out = ((dev.inc_offsets, dev.inc_links) if dev is not None
           else (jnp.asarray(snap.inc_offsets), jnp.asarray(snap.inc_links)))
    object.__setattr__(snap, "_inc_csr_dev", out)
    return out


def value_key_of(graph, h: int):
    """One atom's order-preserving value key bytes, or None when the
    atom is gone / its value has no key encoding. The shared probe of
    the delta-column builder and the host correction path."""
    from hypergraphdb_tpu.core.graph import HGLink

    try:
        v = graph.get(h)
        if isinstance(v, HGLink):
            v = v.value
        at = graph.typesystem.get_type(graph.get_type_handle_of(h))
        return at.to_key(v)
    except Exception:  # noqa: BLE001 - racing delete / keyless value
        return None


def build_delta_column(graph, new_atoms, kind: int,
                       epoch: int) -> ValueIndexColumn:
    """Delta column: memtable atoms (a captured ``new_atoms`` prefix) of
    one kind, sorted and uploaded. ``covered`` records the FULL scanned
    length — atoms of other kinds, dead atoms, and keyless values are
    accounted as scanned (they can contribute nothing), so the collect
    residual is exactly ``new_atoms[covered:]``."""
    from hypergraphdb_tpu.utils.ordered_bytes import rank128, rank_ambiguous

    ranks: list[int] = []
    ranks2: list[int] = []
    gids: list[int] = []
    kb = bytes([int(kind)])
    fixed = int(kind) in FIXED_WIDTH_KINDS
    exact = True
    for h in new_atoms:
        key = value_key_of(graph, int(h))
        if key is not None and key[:1] == kb:
            payload = key[1:]
            r1, r2 = rank128(payload)
            ranks.append(r1)
            ranks2.append(r2)
            gids.append(int(h))
            if not fixed and rank_ambiguous(payload):
                exact = False
    return _sorted_device_column(
        int(kind),
        np.asarray(ranks, dtype=np.uint64),
        np.asarray(gids, dtype=np.int64),
        epoch=epoch, covered=len(new_atoms), minimum=32,
        ranks2=np.asarray(ranks2, dtype=np.uint64),
        exact=fixed or exact,
    )
