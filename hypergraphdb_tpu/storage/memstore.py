"""In-memory columnar storage backend — the default.

The host-plane analogue of the reference's default bdb-je backend
(``storage/bdb-je/.../BJEStorageImplementation.java:46-48`` with its three
B-tree DBs: datadb / primitivedb / incidencedb). Here the same three stores
are plain dicts + sorted containers, because (a) the hot read paths are
served from immutable CSR device snapshots, not from this store, and (b)
durability comes from the write-ahead log in the native backend
(``storage/native.py``), not from this one.

Incidence sets and index value-sets keep a *sorted numpy snapshot* cache so
repeated reads (CSR packing, zig-zag joins) are O(1) after first touch.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

try:  # soft dependency: slim images run the pure-Python shim
    from sortedcontainers import SortedDict, SortedList
except ImportError:  # pragma: no cover - exercised on images without it
    from hypergraphdb_tpu.utils.sortedshim import SortedDict, SortedList

from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.storage.api import (
    HGBidirectionalIndex,
    HGSortedResultSet,
    StorageBackend,
)


class _SortedHandleSet:
    """Mutable sorted set of int64 handles with a cached numpy snapshot."""

    __slots__ = ("_sl", "_snap")

    def __init__(self) -> None:
        self._sl = SortedList()
        self._snap: Optional[np.ndarray] = None

    def add(self, h: int) -> None:
        if h not in self._sl:
            self._sl.add(h)
            self._snap = None

    def discard(self, h: int) -> None:
        try:
            self._sl.remove(h)
            self._snap = None
        except ValueError:
            pass

    def snapshot(self) -> np.ndarray:
        if self._snap is None:
            self._snap = np.fromiter(self._sl, dtype=np.int64, count=len(self._sl))
        return self._snap

    def __len__(self) -> int:
        return len(self._sl)

    def __contains__(self, h: int) -> bool:
        return h in self._sl


class MemIndex(HGBidirectionalIndex):
    """Sorted-dict index: bytes key → sorted handle set, plus inverse map."""

    def __init__(self, name: str):
        self.name = name
        self._kv: SortedDict = SortedDict()          # bytes -> _SortedHandleSet
        self._vk: dict[int, set[bytes]] = {}         # handle -> keys

    def add_entry(self, key: bytes, value: HGHandle) -> None:
        s = self._kv.get(key)
        if s is None:
            s = self._kv[key] = _SortedHandleSet()
        s.add(value)
        self._vk.setdefault(value, set()).add(key)

    def remove_entry(self, key: bytes, value: HGHandle) -> None:
        s = self._kv.get(key)
        if s is not None:
            s.discard(value)
            if not len(s):
                del self._kv[key]
        ks = self._vk.get(value)
        if ks is not None:
            ks.discard(key)
            if not ks:
                del self._vk[value]

    def remove_all_entries(self, key: bytes) -> None:
        s = self._kv.pop(key, None)
        if s is not None:
            for v in s.snapshot().tolist():
                ks = self._vk.get(v)
                if ks is not None:
                    ks.discard(key)
                    if not ks:
                        del self._vk[v]

    def find(self, key: bytes) -> HGSortedResultSet:
        s = self._kv.get(key)
        if s is None:
            return HGSortedResultSet.EMPTY
        return HGSortedResultSet(s.snapshot())

    def key_count(self) -> int:
        return len(self._kv)

    def scan_keys(self) -> Iterator[bytes]:
        return iter(self._kv.keys())

    def find_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
    ) -> HGSortedResultSet:
        keys = self._kv.irange(lo, hi, (lo_inclusive, hi_inclusive))
        parts = [self._kv[k].snapshot() for k in keys]
        if not parts:
            return HGSortedResultSet.EMPTY
        merged = np.unique(np.concatenate(parts))
        return HGSortedResultSet(merged)

    def count_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
        cap: Optional[int] = None,
    ) -> int:
        n = 0
        for k in self._kv.irange(lo, hi, (lo_inclusive, hi_inclusive)):
            n += len(self._kv[k])
            if cap is not None and n >= cap:
                return cap
        return n

    def find_by_value(self, value: HGHandle) -> list[bytes]:
        return sorted(self._vk.get(value, ()))

    def bulk_items(self, lo=None):
        # direct container access: no result-set wrappers on the pack path
        keys = self._kv.irange(minimum=lo) if lo is not None else self._kv
        for k in keys:
            yield k, self._kv[k].snapshot()


class MemStorage(StorageBackend):
    def __init__(self) -> None:
        self._links: dict[int, tuple[int, ...]] = {}
        self._data: dict[int, bytes] = {}
        self._incidence: dict[int, _SortedHandleSet] = {}
        self._indices: dict[str, MemIndex] = {}

    # -- lifecycle ----------------------------------------------------------
    def startup(self) -> None:
        pass

    def shutdown(self) -> None:
        pass

    # -- links ---------------------------------------------------------------
    def store_link(self, h: HGHandle, targets: Sequence[HGHandle]) -> None:
        self._links[h] = tuple(int(t) for t in targets)

    def get_link(self, h: HGHandle) -> Optional[tuple[HGHandle, ...]]:
        return self._links.get(h)

    def remove_link(self, h: HGHandle) -> None:
        self._links.pop(h, None)

    # -- data ------------------------------------------------------------------
    def store_data(self, h: HGHandle, data: bytes) -> None:
        self._data[h] = bytes(data)

    def get_data(self, h: HGHandle) -> Optional[bytes]:
        return self._data.get(h)

    def remove_data(self, h: HGHandle) -> None:
        self._data.pop(h, None)

    # -- incidence -------------------------------------------------------------
    def add_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        s = self._incidence.get(atom)
        if s is None:
            s = self._incidence[atom] = _SortedHandleSet()
        s.add(link)

    def remove_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        s = self._incidence.get(atom)
        if s is not None:
            s.discard(link)
            if not len(s):
                del self._incidence[atom]

    def remove_incidence_set(self, atom: HGHandle) -> None:
        self._incidence.pop(atom, None)

    def get_incidence_set(self, atom: HGHandle) -> HGSortedResultSet:
        s = self._incidence.get(atom)
        if s is None:
            return HGSortedResultSet.EMPTY
        return HGSortedResultSet(s.snapshot())

    # -- indices -----------------------------------------------------------------
    def get_index(self, name: str, create: bool = True) -> Optional[MemIndex]:
        idx = self._indices.get(name)
        if idx is None and create:
            idx = self._indices[name] = MemIndex(name)
        return idx

    def remove_index(self, name: str) -> None:
        self._indices.pop(name, None)

    def index_names(self) -> list[str]:
        return sorted(self._indices)

    # -- bulk --------------------------------------------------------------------
    def bulk_links(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        ids = np.fromiter(sorted(self._links), dtype=np.int64, count=len(self._links))
        lengths = np.fromiter(
            (len(self._links[int(i)]) for i in ids), dtype=np.int64, count=len(ids)
        )
        offsets = np.zeros(len(ids) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        flat = np.empty(int(offsets[-1]), dtype=np.int64)
        for j, i in enumerate(ids.tolist()):
            flat[offsets[j] : offsets[j + 1]] = self._links[i]
        return ids, offsets, flat

    def iter_record_handles(self) -> set:
        """Every handle this backend holds ANY record for (link, payload,
        or incidence set) — the enumeration a partition-map migration
        (``partitioned.PartitionedStorage.repartition``) walks to decide
        which records changed owners."""
        return set(self._links) | set(self._data) | set(self._incidence)

    def max_handle(self) -> int:
        m = -1
        if self._links:
            m = max(m, max(self._links))
        if self._data:
            m = max(m, max(self._data))
        if self._incidence:
            m = max(m, max(self._incidence))
        return m + 1
