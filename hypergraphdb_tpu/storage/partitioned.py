"""Partitioned storage backend — the hazelstore role in the rebuild.

The reference proves its storage SPI tolerates non-local, sharded backends
with a Hazelcast data-grid implementation (``storage/hazelstore/
Hazelstore.scala``, ``HazelIndex.scala`` — SURVEY §2.2). This module plays
the same role TPU-natively: one :class:`PartitionedStorage` front routes
every SPI operation across N child backends —

- **records route by handle** (modulo partitioning of link/data/incidence
  rows: the owner of atom ``h`` holds its record, payload and incidence
  set),
- **index entries route by key** (stable key hash), with range scans and
  key enumeration served by k-way merges across all partitions (Hazelcast
  orders within partitions the same way),
- commit-batch barriers fan out to every partition. NB: the fan-out is
  sequential and uncoordinated — each partition replays atomically to
  ITS OWN last barrier, but a crash between two partitions' barrier
  writes can leave one partition a commit ahead of another (the same
  eventual-consistency stance as a real storage grid; a cross-partition
  commit marker would be the upgrade path to atomic multi-partition
  recovery).

Children are any ``StorageBackend`` (memory partitions for tests, native
C++ WAL stores for durable sharding — the closest single-process analogue
of a storage grid, and the shape a multi-host DCN storage service would
take: swap the child list for RPC stubs without touching the SPI).
"""

from __future__ import annotations

import heapq
import zlib
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.storage.api import (
    HGBidirectionalIndex,
    HGSortedResultSet,
    StorageBackend,
)


def _key_part(key: bytes, n: int) -> int:
    """Stable partition of an index key (content hash, not Python hash —
    must agree across processes)."""
    return zlib.crc32(bytes(key)) % n


@dataclass(frozen=True)
class PartitionMap:
    """Contiguous gid-range ownership: the ONE partition map the storage
    grid, the device mesh, and the serving tier all read.

    The id space ``[0, capacity)`` splits into ``n_parts`` ranges of
    ``part_size`` ids each (``part_size`` is ``align``-rounded so a
    device shard's packed frontier words stay 128-lane aligned — the
    same rounding :class:`parallel.sharded.ShardedSnapshot` applies to
    its per-device row ranges, by construction: the sharded snapshot
    derives its layout FROM this map). Ids at or beyond ``capacity``
    (atoms minted after the map was cut) clamp into the LAST range, so
    ownership is total at any moment; a :meth:`repartition` to a larger
    capacity is how those ids move to their steady-state owner.

    Frozen + hashable: the map rides jit static args and pytree aux data
    unchanged."""

    n_parts: int
    part_size: int       # ids per range, align-rounded
    capacity: int        # id space the map was cut for

    #: alignment the device mesh needs (packed words: 128-lane rows)
    ALIGN = 128

    @staticmethod
    def for_mesh(capacity: int, n_parts: int,
                 align: int = ALIGN) -> "PartitionMap":
        """The map for an ``n_parts``-way split of ``[0, capacity)``:
        ranges sized ``ceil(capacity / (n_parts·align)) · align`` — the
        exact per-device row-range formula of
        ``ShardedSnapshot.from_host``, now owned here."""
        if n_parts < 1:
            raise ValueError("n_parts must be >= 1")
        capacity = max(int(capacity), 1)
        part_size = -(-capacity // (n_parts * align)) * align
        return PartitionMap(n_parts=int(n_parts), part_size=part_size,
                            capacity=capacity)

    def owner_of(self, gid: int) -> int:
        """The range owner of one gid (ids beyond the map's capacity
        clamp into the last range — ownership is total)."""
        if gid < 0:
            raise ValueError(f"negative gid {gid}")
        return min(int(gid) // self.part_size, self.n_parts - 1)

    def owner_np(self, gids: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`owner_of` (the snapshot partitioner's hot
        path: one integer divide + clip over the whole COO relation)."""
        return np.minimum(
            np.asarray(gids, dtype=np.int64) // self.part_size,
            self.n_parts - 1,
        )

    def range_of(self, part: int) -> tuple[int, int]:
        """[lo, hi) id range of one partition; the LAST range is
        unbounded above (it owns every clamped overflow id)."""
        lo = part * self.part_size
        hi = (lo + self.part_size if part < self.n_parts - 1
              else max(lo + self.part_size, self.capacity))
        return lo, hi

    def ranges(self) -> list:
        return [self.range_of(p) for p in range(self.n_parts)]

    def to_dict(self) -> dict:
        """The wire shape ``/healthz`` advertises (mesh topology +
        per-shard gid ranges — what shard-aware routing reads)."""
        return {
            "n_parts": self.n_parts,
            "part_size": self.part_size,
            "capacity": self.capacity,
            "ranges": [[lo, hi] for lo, hi in self.ranges()],
        }

    def repartitioned(self, capacity: int) -> "PartitionMap":
        """The same ``n_parts`` split re-cut for a grown id space —
        ranges move; :meth:`PartitionedStorage.repartition` migrates the
        records whose owner changed."""
        return PartitionMap.for_mesh(capacity, self.n_parts)


class PartitionedIndex(HGBidirectionalIndex):
    """Key-routed view over the per-partition indices of one logical name."""

    def __init__(self, children: list[HGBidirectionalIndex]):
        self._children = children

    def _owner(self, key: bytes) -> HGBidirectionalIndex:
        return self._children[_key_part(key, len(self._children))]

    # -- single-key ops route to the owner ------------------------------------
    def add_entry(self, key: bytes, value: HGHandle) -> None:
        self._owner(key).add_entry(key, value)

    def remove_entry(self, key: bytes, value: HGHandle) -> None:
        self._owner(key).remove_entry(key, value)

    def remove_all_entries(self, key: bytes) -> None:
        self._owner(key).remove_all_entries(key)

    def find(self, key: bytes) -> HGSortedResultSet:
        return self._owner(key).find(key)

    def find_first(self, key: bytes) -> Optional[HGHandle]:
        return self._owner(key).find_first(key)

    def count(self, key: bytes) -> int:
        return self._owner(key).count(key)

    # -- whole-index ops merge across partitions -------------------------------
    def key_count(self) -> int:
        return sum(c.key_count() for c in self._children)

    def scan_keys(self) -> Iterator[bytes]:
        # each child scans in sorted order; k-way merge keeps the global
        # sorted-key contract range scans rely on
        yield from heapq.merge(*(c.scan_keys() for c in self._children))

    def scan_values(self) -> Iterator[HGHandle]:
        for c in self._children:
            yield from c.scan_values()

    def bulk_items(self, lo=None):
        yield from heapq.merge(
            *(c.bulk_items(lo) for c in self._children), key=lambda kv: kv[0]
        )

    def find_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
    ) -> HGSortedResultSet:
        parts = [
            c.find_range(lo, hi, lo_inclusive, hi_inclusive).array()
            for c in self._children
        ]
        parts = [p for p in parts if len(p)]
        if not parts:
            return HGSortedResultSet(np.empty(0, dtype=np.int64))
        return HGSortedResultSet(np.unique(np.concatenate(parts)))

    def find_lt(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(hi=key, hi_inclusive=False)

    def find_lte(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(hi=key, hi_inclusive=True)

    def find_gt(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(lo=key, lo_inclusive=False)

    def find_gte(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(lo=key, lo_inclusive=True)

    def find_by_value(self, value: HGHandle) -> list[bytes]:
        keys: list[bytes] = []
        for c in self._children:
            keys.extend(c.find_by_value(value))
        return sorted(set(keys))

    def count_keys(self, value: HGHandle) -> int:
        return len(self.find_by_value(value))


class PartitionedStorage(StorageBackend):
    """Handle-routed record storage + key-routed indices over N children."""

    def __init__(
        self,
        partitions: Sequence[StorageBackend] = (),
        n_partitions: int = 4,
        factory: Optional[Callable[[int], StorageBackend]] = None,
        partition_map: Optional[PartitionMap] = None,
    ):
        if partition_map is not None:
            n_partitions = partition_map.n_parts
        if partitions:
            self._parts = list(partitions)
        else:
            if factory is None:
                from hypergraphdb_tpu.storage.memstore import MemStorage

                factory = lambda i: MemStorage()  # noqa: E731
            self._parts = [factory(i) for i in range(n_partitions)]
        if not self._parts:
            raise ValueError("need at least one partition")
        if (partition_map is not None
                and partition_map.n_parts != len(self._parts)):
            raise ValueError(
                f"partition map covers {partition_map.n_parts} owners but "
                f"{len(self._parts)} partitions were given"
            )
        #: gid-range routing (the device-mesh-aligned owner map). None
        #: keeps the legacy modulo routing — the two never mix: a store
        #: opened with a map routes by range for its whole life.
        self.partition_map = partition_map

    # -- lifecycle --------------------------------------------------------------
    def startup(self) -> None:
        for p in self._parts:
            p.startup()

    def shutdown(self) -> None:
        for p in self._parts:
            p.shutdown()

    def checkpoint(self) -> None:
        for p in self._parts:
            p.checkpoint()

    def commit_batch_begin(self) -> None:
        for p in self._parts:
            p.commit_batch_begin()

    def commit_batch_end(self) -> None:
        for p in self._parts:
            p.commit_batch_end()

    def commit_batch_abort(self) -> None:
        for p in self._parts:
            p.commit_batch_abort()

    # -- record routing ---------------------------------------------------------
    def _own(self, h: HGHandle) -> StorageBackend:
        if self.partition_map is not None:
            return self._parts[self.partition_map.owner_of(int(h))]
        return self._parts[int(h) % len(self._parts)]

    def repartition(self, new_map: PartitionMap) -> int:
        """Adopt a re-cut partition map (gid ranges MOVE), migrating
        every record whose owner changed: link records, data payloads,
        and incidence sets each move to the handle's new range owner.
        Index entries are key-hash routed and untouched — ``find`` /
        ``count`` answers are identical before, during (per SPI op), and
        after the move. Returns the number of handles migrated.

        Same consistency stance as the commit-batch fan-out above: the
        walk is sequential per partition, so a crash mid-migration can
        leave a handle moved and its sibling not — re-running the same
        repartition is idempotent and completes the move."""
        if new_map.n_parts != len(self._parts):
            raise ValueError(
                "repartition cannot change the partition count "
                f"({new_map.n_parts} != {len(self._parts)}): owners are "
                "the fixed children, only their gid ranges move"
            )
        if self.partition_map is None:
            raise ValueError(
                "repartition needs gid-range routing; this store uses "
                "legacy modulo routing"
            )
        moved = 0
        for src_part, child in enumerate(self._parts):
            enum = getattr(child, "iter_record_handles", None)
            if enum is None:
                raise TypeError(
                    f"partition {src_part} ({type(child).__name__}) does "
                    "not enumerate record handles; repartition needs "
                    "iter_record_handles()"
                )
            for h in sorted(enum()):
                dst_part = new_map.owner_of(int(h))
                if dst_part == src_part:
                    continue
                dst = self._parts[dst_part]
                rec = child.get_link(h)
                if rec is not None:
                    dst.store_link(h, rec)
                    child.remove_link(h)
                payload = child.get_data(h)
                if payload is not None:
                    dst.store_data(h, payload)
                    child.remove_data(h)
                inc = child.get_incidence_set(h)
                if len(inc):
                    for link in inc:
                        dst.add_incidence_link(h, int(link))
                    child.remove_incidence_set(h)
                moved += 1
        self.partition_map = new_map
        return moved

    def store_link(self, h: HGHandle, targets: Sequence[HGHandle]) -> None:
        self._own(h).store_link(h, targets)

    def get_link(self, h: HGHandle):
        return self._own(h).get_link(h)

    def remove_link(self, h: HGHandle) -> None:
        self._own(h).remove_link(h)

    def contains_link(self, h: HGHandle) -> bool:
        return self._own(h).contains_link(h)

    def store_data(self, h: HGHandle, data: bytes) -> None:
        self._own(h).store_data(h, data)

    def get_data(self, h: HGHandle) -> Optional[bytes]:
        return self._own(h).get_data(h)

    def remove_data(self, h: HGHandle) -> None:
        self._own(h).remove_data(h)

    def contains_data(self, h: HGHandle) -> bool:
        return self._own(h).contains_data(h)

    def add_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        self._own(atom).add_incidence_link(atom, link)

    def remove_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        self._own(atom).remove_incidence_link(atom, link)

    def remove_incidence_set(self, atom: HGHandle) -> None:
        self._own(atom).remove_incidence_set(atom)

    def get_incidence_set(self, atom: HGHandle) -> HGSortedResultSet:
        return self._own(atom).get_incidence_set(atom)

    def incidence_count(self, atom: HGHandle) -> int:
        return self._own(atom).incidence_count(atom)

    # -- indices ----------------------------------------------------------------
    def get_index(self, name: str, create: bool = True):
        children = []
        for p in self._parts:
            idx = p.get_index(name, create=create)
            if idx is None:
                return None
            children.append(idx)
        return PartitionedIndex(children)

    def remove_index(self, name: str) -> None:
        for p in self._parts:
            p.remove_index(name)

    def index_names(self) -> list[str]:
        names: set[str] = set()
        for p in self._parts:
            names.update(p.index_names())
        return sorted(names)

    # -- bulk export ------------------------------------------------------------
    def bulk_links(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Concatenate the partitions' record tables, re-sorted to the
        id-ascending order the snapshot packer expects."""
        ids_l, offs_l, flats_l = [], [], []
        for p in self._parts:
            ids, offsets, flat = p.bulk_links()
            ids_l.append(np.asarray(ids, dtype=np.int64))
            offs_l.append(np.asarray(offsets, dtype=np.int64))
            flats_l.append(np.asarray(flat, dtype=np.int64))
        total_ids = np.concatenate(ids_l) if ids_l else np.empty(0, np.int64)
        if not len(total_ids):
            return total_ids, np.zeros(1, np.int64), np.empty(0, np.int64)
        # vectorized global re-sort (this is the snapshot-pack hot path):
        # permute record lengths by id order, then gather each record's
        # flat slice via repeat/offset arithmetic — no per-record python
        all_lens = np.concatenate(
            [o[1:] - o[:-1] for o in offs_l]
        ).astype(np.int64)
        # rebase starts into the concatenated flat array
        flat_cat = (
            np.concatenate(flats_l) if flats_l else np.empty(0, np.int64)
        )
        base = 0
        rebased = []
        for o, f in zip(offs_l, flats_l):
            rebased.append(o[:-1].astype(np.int64) + base)
            base += len(f)
        all_starts = np.concatenate(rebased)
        order = np.argsort(total_ids, kind="stable")
        out_ids = total_ids[order]
        lens = all_lens[order]
        starts = all_starts[order]
        out_offsets = np.zeros(len(out_ids) + 1, dtype=np.int64)
        np.cumsum(lens, out=out_offsets[1:])
        total = int(lens.sum())
        if total:
            idx = np.repeat(
                starts - np.concatenate([[0], np.cumsum(lens)[:-1]]), lens
            ) + np.arange(total, dtype=np.int64)
            out_flat = flat_cat[idx]
        else:
            out_flat = np.empty(0, np.int64)
        return out_ids, out_offsets, out_flat

    def max_handle(self) -> int:
        return max(p.max_handle() for p in self._parts)
