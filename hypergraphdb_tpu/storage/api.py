"""Storage SPI — the contract every backend implements.

Re-expression of the reference's ``HGStoreImplementation``
(``core/src/java/org/hypergraphdb/storage/HGStoreImplementation.java:27-188``)
and its index family ``HGIndex``/``HGBidirectionalIndex``/``HGSortIndex``
(``storage/HGIndex.java:26``), redesigned for the TPU build:

- handles are dense ``int`` ids (see ``core/handles.py``),
- index keys are **order-preserving bytes** (see ``utils/ordered_bytes.py``)
  so memcmp is the single comparator,
- backends hold *committed state only* — transaction buffering, validation
  and commit application live above in ``tx/`` (the reference instead
  delegates transactions to each backend, ``HGStoreImplementation.java:40``;
  lifting them out keeps native backends dumb and fast),
- every read that feeds the device plane can be produced in bulk as numpy
  arrays (``bulk_*`` methods) — that is the CSR-pack fast path.

A ``StorageBackend`` is single-writer: the transaction manager serializes
commit application. Readers may run concurrently with a writer only through
the façade's versioning (see ``tx/manager.py``).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

from hypergraphdb_tpu.core.handles import HGHandle


class HGSortedResultSet:
    """A sorted, random-access result over int64 handles.

    Host analogue of ``HGRandomAccessResult`` (``storage/HGRandomAccessResult.java:22``):
    ``go_to`` is the primitive the zig-zag/leapfrog join relies on. Backed by
    a sorted numpy array; device kernels consume ``array()`` directly.
    """

    __slots__ = ("_a",)

    def __init__(self, sorted_array: np.ndarray):
        self._a = np.asarray(sorted_array, dtype=np.int64)

    def array(self) -> np.ndarray:
        return self._a

    def __len__(self) -> int:
        return len(self._a)

    def __iter__(self) -> Iterator[int]:
        return iter(self._a.tolist())

    def __contains__(self, h: int) -> bool:
        i = np.searchsorted(self._a, h)
        return i < len(self._a) and self._a[i] == h

    def go_to(self, h: int, exact: bool = True) -> int:
        """Position at first element >= h; returns index or -1 (exact miss)."""
        i = int(np.searchsorted(self._a, h))
        if exact:
            if i < len(self._a) and self._a[i] == h:
                return i
            return -1
        return i if i < len(self._a) else -1

    EMPTY: "HGSortedResultSet"


HGSortedResultSet.EMPTY = HGSortedResultSet(np.empty(0, dtype=np.int64))


class HGIndex:
    """Named sorted index: bytes key → sorted set of int64 values.

    Contract of ``HGIndex.java:26`` (addEntry/removeEntry/findFirst/find/
    count/scanKeys/scanValues) plus ``HGSortIndex`` range operations
    (findLT/findGT/findLTE/findGTE) — ranges work because keys are
    order-preserving bytes.
    """

    name: str

    def add_entry(self, key: bytes, value: HGHandle) -> None:
        raise NotImplementedError

    def remove_entry(self, key: bytes, value: HGHandle) -> None:
        raise NotImplementedError

    def remove_all_entries(self, key: bytes) -> None:
        raise NotImplementedError

    def find(self, key: bytes) -> HGSortedResultSet:
        raise NotImplementedError

    def find_first(self, key: bytes) -> Optional[HGHandle]:
        rs = self.find(key)
        return int(rs.array()[0]) if len(rs) else None

    def count(self, key: bytes) -> int:
        return len(self.find(key))

    def key_count(self) -> int:
        raise NotImplementedError

    def scan_keys(self) -> Iterator[bytes]:
        raise NotImplementedError

    def scan_values(self) -> Iterator[HGHandle]:
        for k in self.scan_keys():
            yield from self.find(k)

    def bulk_items(self, lo: Optional[bytes] = None):
        """Iterate (key, sorted int64 ndarray) pairs in key order — the
        CSR-pack fast path and the op-log cursor. ``lo`` starts the scan at
        the first key ≥ lo. Backends override with direct container
        access."""
        for k in self.scan_keys():
            if lo is not None and k < lo:
                continue
            yield k, self.find(k).array()

    def count_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
        cap: Optional[int] = None,
    ) -> int:
        """Entries (not keys) in the key range — EXACT up to ``cap``, then
        clamped to ``cap``. This is the planner's cardinality source for
        range scans (the reference's cost-capped index statistics,
        ``storage/HGIndexStats.java:37`` feeding ``ResultSizeEstimation``):
        a bounded cursor walk gives exact small counts (where ordering
        decisions matter) and a cheap "at least cap" for large ranges
        (which all land on the same side of every planner threshold).
        Backends with direct container access override."""
        n = 0
        for k, hs in self.bulk_items(lo=lo):
            if lo is not None and not lo_inclusive and k == lo:
                continue
            if hi is not None and (k > hi or (k == hi and not hi_inclusive)):
                break
            n += len(hs)
            if cap is not None and n >= cap:
                return cap
        return n

    # range queries (HGSortIndex semantics)
    def find_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
    ) -> HGSortedResultSet:
        raise NotImplementedError

    def find_lt(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(hi=key, hi_inclusive=False)

    def find_lte(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(hi=key, hi_inclusive=True)

    def find_gt(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(lo=key, lo_inclusive=False)

    def find_gte(self, key: bytes) -> HGSortedResultSet:
        return self.find_range(lo=key, lo_inclusive=True)


class HGBidirectionalIndex(HGIndex):
    """Adds value → keys lookup (``storage/HGBidirectionalIndex.java``)."""

    def find_by_value(self, value: HGHandle) -> list[bytes]:
        raise NotImplementedError

    def count_keys(self, value: HGHandle) -> int:
        return len(self.find_by_value(value))


class StorageBackend:
    """Committed-state store: links, data payloads, incidence, named indices.

    Mirrors ``HGStoreImplementation.java:27-188`` minus transaction factory
    (lifted into ``tx/``). All mutation methods are called only by the
    transaction manager during commit application.
    """

    # -- lifecycle ----------------------------------------------------------
    def startup(self) -> None: ...
    def shutdown(self) -> None: ...

    def checkpoint(self) -> None:
        """Flush to durable media (no-op for memory)."""

    def commit_batch_begin(self) -> None:
        """Mark the start of one transaction's worth of mutations. Durable
        backends make everything until ``commit_batch_end`` replay
        atomically (all-or-nothing) after a crash. No-op for memory."""

    def commit_batch_end(self) -> None:
        """Seal the commit batch (see ``commit_batch_begin``)."""

    def commit_batch_abort(self) -> None:
        """Mark the open commit batch failed: durable backends must discard
        its records on replay instead of applying them. No-op for memory."""

    # -- link store: handle → ordered tuple of target handles ---------------
    def store_link(self, h: HGHandle, targets: Sequence[HGHandle]) -> None:
        raise NotImplementedError

    def get_link(self, h: HGHandle) -> Optional[tuple[HGHandle, ...]]:
        raise NotImplementedError

    def remove_link(self, h: HGHandle) -> None:
        raise NotImplementedError

    def contains_link(self, h: HGHandle) -> bool:
        return self.get_link(h) is not None

    # -- data store: handle → bytes -----------------------------------------
    def store_data(self, h: HGHandle, data: bytes) -> None:
        raise NotImplementedError

    def get_data(self, h: HGHandle) -> Optional[bytes]:
        raise NotImplementedError

    def remove_data(self, h: HGHandle) -> None:
        raise NotImplementedError

    def contains_data(self, h: HGHandle) -> bool:
        return self.get_data(h) is not None

    # -- incidence: atom → sorted set of link handles -------------------------
    def add_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        raise NotImplementedError

    def remove_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        raise NotImplementedError

    def remove_incidence_set(self, atom: HGHandle) -> None:
        raise NotImplementedError

    def get_incidence_set(self, atom: HGHandle) -> HGSortedResultSet:
        raise NotImplementedError

    def incidence_count(self, atom: HGHandle) -> int:
        return len(self.get_incidence_set(atom))

    # -- named indices --------------------------------------------------------
    def get_index(self, name: str, create: bool = True) -> Optional[HGBidirectionalIndex]:
        raise NotImplementedError

    def remove_index(self, name: str) -> None:
        raise NotImplementedError

    def index_names(self) -> list[str]:
        raise NotImplementedError

    # -- bulk access for CSR packing (TPU fast path; no reference analogue) --
    def bulk_links(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Return (link_ids, target_offsets, flat_targets) over ALL links,
        link_ids ascending. ``flat_targets[target_offsets[i]:target_offsets[i+1]]``
        are the ordered targets of ``link_ids[i]``."""
        raise NotImplementedError

    def max_handle(self) -> int:
        """Upper bound (exclusive) on any handle present in the store."""
        raise NotImplementedError
