"""NativeStorage — the durable C++ storage backend (ctypes binding).

The rebuild's counterpart to the reference's native storage module
(``storage/bdb-native/``: the same SPI over the BerkeleyDB C library via
JNI). The engine (``native/hgstore.cpp``) is a log-structured columnar
store: RAM-resident committed state + write-ahead log + compacted
checkpoints, with a bulk flat-array export feeding CSR snapshot packing.

Implements the exact same ``StorageBackend`` contract as ``MemStorage`` and
passes the same conformance suite (``tests/test_storage.py``).
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Sequence

import numpy as np

from hypergraphdb_tpu.core.errors import HGException
from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.native import lib
from hypergraphdb_tpu.storage.api import (
    HGBidirectionalIndex,
    HGSortedResultSet,
    StorageBackend,
)

_i64p = ctypes.POINTER(ctypes.c_int64)


def _take_i64_array(L, out_p, n: int) -> np.ndarray:
    """Copy a malloc'd i64 buffer into numpy and free it."""
    try:
        if n == 0:
            return np.empty(0, dtype=np.int64)
        return np.ctypeslib.as_array(out_p, shape=(n,)).astype(
            np.int64, copy=True
        )
    finally:
        L.hgs_free(out_p)


def _take_key_list(L, out_c, total: int, count: int) -> list[bytes]:
    """Decode the [u32 len][bytes]... key framing and free the buffer."""
    try:
        if count == 0:
            return []
        raw = ctypes.string_at(out_c, total)
        keys = []
        pos = 0
        for _ in range(count):
            ln = int.from_bytes(raw[pos : pos + 4], "little")
            pos += 4
            keys.append(raw[pos : pos + ln])
            pos += ln
        return keys
    finally:
        L.hgs_free(out_c)


class NativeIndex(HGBidirectionalIndex):
    def __init__(self, store: "NativeStorage", name: str):
        self._s = store
        self.name = name
        self._nm = name.encode()

    def add_entry(self, key: bytes, value: HGHandle) -> None:
        self._s._L.hgs_idx_add(self._s._h, self._nm, key, len(key), int(value))
        self._s._check_wal()

    def remove_entry(self, key: bytes, value: HGHandle) -> None:
        self._s._L.hgs_idx_remove(self._s._h, self._nm, key, len(key), int(value))
        self._s._check_wal()

    def remove_all_entries(self, key: bytes) -> None:
        self._s._L.hgs_idx_remove_all(self._s._h, self._nm, key, len(key))
        self._s._check_wal()

    def find(self, key: bytes) -> HGSortedResultSet:
        L = self._s._L
        out = _i64p()
        n = ctypes.c_uint32()
        L.hgs_idx_find(
            self._s._h, self._nm, key, len(key),
            ctypes.byref(out), ctypes.byref(n),
        )
        return HGSortedResultSet(_take_i64_array(L, out, n.value))

    def key_count(self) -> int:
        return int(self._s._L.hgs_idx_key_count(self._s._h, self._nm))

    def scan_keys(self) -> Iterator[bytes]:
        L = self._s._L
        out = ctypes.c_char_p()
        total = ctypes.c_uint32()
        count = ctypes.c_uint32()
        L.hgs_idx_scan_keys(
            self._s._h, self._nm,
            ctypes.byref(out), ctypes.byref(total), ctypes.byref(count),
        )
        return iter(_take_key_list(L, out, total.value, count.value))

    def bulk_items(self, lo: Optional[bytes] = None):
        # one native call for the (sorted) key list, bisect to the cursor
        # start, then per-key value fetches — O(result + one key scan in
        # C), not a Python skip-loop over every key (op-log cursor path)
        keys = list(self.scan_keys())
        if lo is not None:
            import bisect

            keys = keys[bisect.bisect_left(keys, lo):]
        for k in keys:
            yield k, self.find(k).array()

    def find_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
    ) -> HGSortedResultSet:
        L = self._s._L
        out = _i64p()
        n = ctypes.c_uint32()
        L.hgs_idx_range(
            self._s._h, self._nm,
            lo if lo is not None else b"", len(lo or b""),
            1 if lo is not None else 0, 1 if lo_inclusive else 0,
            hi if hi is not None else b"", len(hi or b""),
            1 if hi is not None else 0, 1 if hi_inclusive else 0,
            ctypes.byref(out), ctypes.byref(n),
        )
        return HGSortedResultSet(_take_i64_array(L, out, n.value))

    def find_by_value(self, value: HGHandle) -> list[bytes]:
        L = self._s._L
        out = ctypes.c_char_p()
        total = ctypes.c_uint32()
        count = ctypes.c_uint32()
        L.hgs_idx_find_by_value(
            self._s._h, self._nm, int(value),
            ctypes.byref(out), ctypes.byref(total), ctypes.byref(count),
        )
        return _take_key_list(L, out, total.value, count.value)


class NativeStorage(StorageBackend):
    """Durable storage backend over the C++ engine. Single-writer, as the
    SPI requires; see ``storage/api.py``."""

    def __init__(self, location: str):
        self.location = location
        self._L = lib()
        self._h = None

    # -- lifecycle ----------------------------------------------------------
    def startup(self) -> None:
        if self._h is not None:
            return
        os.makedirs(self.location, exist_ok=True)
        h = self._L.hgs_open(self.location.encode())
        if not h:
            raise HGException(
                f"native store failed to open (corrupt log?): {self.location}"
            )
        self._h = h

    def shutdown(self) -> None:
        if self._h is not None:
            self._L.hgs_close(self._h)
            self._h = None

    def checkpoint(self) -> None:
        if self._h is not None and self._L.hgs_checkpoint(self._h) != 0:
            raise HGException(f"checkpoint failed: {self.location}")
        self._check_wal()

    def commit_batch_begin(self) -> None:
        self._L.hgs_batch_begin(self._h)

    def commit_batch_end(self) -> None:
        self._L.hgs_batch_commit(self._h)
        self._check_wal()

    def commit_batch_abort(self) -> None:
        self._L.hgs_batch_abort(self._h)

    def _check_wal(self) -> None:
        """Surface any latched WAL write failure (disk full, IO error) —
        silent durability loss is worse than a failing commit."""
        if self._h is not None and not self._L.hgs_wal_ok(self._h):
            raise HGException(
                f"write-ahead log write failed (disk full?): {self.location}; "
                "mutations since the failure are NOT durable"
            )

    # -- links --------------------------------------------------------------
    def store_link(self, h: HGHandle, targets: Sequence[HGHandle]) -> None:
        arr = (ctypes.c_int64 * len(targets))(*[int(t) for t in targets])
        self._L.hgs_store_link(self._h, int(h), arr, len(targets))
        self._check_wal()

    def get_link(self, h: HGHandle) -> Optional[tuple[HGHandle, ...]]:
        out = _i64p()
        n = ctypes.c_uint32()
        if not self._L.hgs_get_link(
            self._h, int(h), ctypes.byref(out), ctypes.byref(n)
        ):
            return None
        return tuple(_take_i64_array(self._L, out, n.value).tolist())

    def remove_link(self, h: HGHandle) -> None:
        self._L.hgs_remove_link(self._h, int(h))
        self._check_wal()

    def contains_link(self, h: HGHandle) -> bool:
        return bool(self._L.hgs_contains_link(self._h, int(h)))

    # -- data ---------------------------------------------------------------
    def store_data(self, h: HGHandle, data: bytes) -> None:
        self._L.hgs_store_data(self._h, int(h), data, len(data))
        self._check_wal()

    def get_data(self, h: HGHandle) -> Optional[bytes]:
        out = ctypes.c_char_p()
        n = ctypes.c_uint32()
        if not self._L.hgs_get_data(
            self._h, int(h), ctypes.byref(out), ctypes.byref(n)
        ):
            return None
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._L.hgs_free(out)

    def remove_data(self, h: HGHandle) -> None:
        self._L.hgs_remove_data(self._h, int(h))
        self._check_wal()

    # -- incidence ----------------------------------------------------------
    def add_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        self._L.hgs_inc_add(self._h, int(atom), int(link))
        self._check_wal()

    def remove_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        self._L.hgs_inc_remove(self._h, int(atom), int(link))
        self._check_wal()

    def remove_incidence_set(self, atom: HGHandle) -> None:
        self._L.hgs_inc_clear(self._h, int(atom))
        self._check_wal()

    def get_incidence_set(self, atom: HGHandle) -> HGSortedResultSet:
        out = _i64p()
        n = ctypes.c_uint32()
        self._L.hgs_inc_get(self._h, int(atom), ctypes.byref(out), ctypes.byref(n))
        return HGSortedResultSet(_take_i64_array(self._L, out, n.value))

    def incidence_count(self, atom: HGHandle) -> int:
        return int(self._L.hgs_inc_count(self._h, int(atom)))

    # -- indices ------------------------------------------------------------
    def get_index(self, name: str, create: bool = True) -> Optional[NativeIndex]:
        exists = bool(self._L.hgs_idx_exists(self._h, name.encode()))
        if not exists:
            if not create:
                return None
            self._L.hgs_idx_touch(self._h, name.encode())
        return NativeIndex(self, name)

    def remove_index(self, name: str) -> None:
        self._L.hgs_idx_drop(self._h, name.encode())
        self._check_wal()

    def index_names(self) -> list[str]:
        out = ctypes.c_char_p()
        total = ctypes.c_uint32()
        count = ctypes.c_uint32()
        self._L.hgs_idx_names(
            self._h, ctypes.byref(out), ctypes.byref(total), ctypes.byref(count)
        )
        return [
            k.decode() for k in _take_key_list(self._L, out, total.value, count.value)
        ]

    # -- bulk ---------------------------------------------------------------
    def bulk_links(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        ids_p, off_p, flat_p = _i64p(), _i64p(), _i64p()
        n_links = ctypes.c_uint32()
        n_flat = ctypes.c_uint32()
        self._L.hgs_bulk_links(
            self._h,
            ctypes.byref(ids_p), ctypes.byref(off_p), ctypes.byref(flat_p),
            ctypes.byref(n_links), ctypes.byref(n_flat),
        )
        nl = n_links.value
        ids = _take_i64_array(self._L, ids_p, nl)
        offsets = _take_i64_array(self._L, off_p, nl + 1)
        flat = _take_i64_array(self._L, flat_p, n_flat.value)
        return ids, offsets, flat

    def max_handle(self) -> int:
        return int(self._L.hgs_max_handle(self._h))
