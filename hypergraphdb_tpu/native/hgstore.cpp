// hgstore — native storage engine for hypergraphdb_tpu.
//
// The TPU-native counterpart of the reference's native storage module
// (storage/bdb-native/: the HGStoreImplementation SPI over the BerkeleyDB C
// library via JNI — see /root/reference/storage/bdb-native/pom.xml:36-37).
// Deliberately NOT a B-tree database: the rebuild's device plane wants the
// whole graph as flat arrays, so the native engine is a **log-structured
// columnar store**:
//
//   - all committed state lives in RAM in gather-friendly containers
//     (links: handle -> target vector; incidence: handle -> sorted vector;
//     indices: ordered key map -> sorted value vectors),
//   - durability = a write-ahead log (wal.log) of every mutation, replayed
//     on open (the analogue of BDB log replay in impl.startup, see
//     HyperGraph.java:50-54) + periodic compacted checkpoints
//     (checkpoint.bin) that truncate the log,
//   - bulk_links() exports the link table as three flat arrays in one call
//     — the zero-copy feed for CSR snapshot packing.
//
// Exposed as a C API (extern "C") consumed through ctypes from
// hypergraphdb_tpu/storage/native.py. Single-writer, as the SPI specifies
// (storage/api.py): the Python transaction manager serializes commits.
//
// WAL record framing (v2, file starts with magic "HGW2"):
//   [u32 len][u32 crc32][u32 seq][u8 op][payload]
// len = 1 + payload bytes; crc32 covers (seq, op, payload); seq is a
// per-log monotonically increasing record number (reset when a checkpoint
// truncates the log). Replay verifies BOTH: a failed crc or a sequence
// discontinuity marks the end of the valid prefix and the tail is
// truncated — torn tails, bit rot, and interleaved/partial flushes are all
// caught, not just short reads (the reference's BDB log is checksummed the
// same way). Logs without the magic use the legacy length-only framing.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#if defined(_WIN32)
#error "POSIX only"
#endif
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

typedef int64_t i64;
typedef uint32_t u32;
typedef uint8_t u8;

// WAL v2 file magic + CRC32 (IEEE 802.3 polynomial, table-driven)
const char kWalMagic[4] = {'H', 'G', 'W', '2'};

u32 crc32_update(u32 crc, const void* data, size_t n) {
  static u32 table[256];
  static bool init = false;
  if (!init) {
    for (u32 i = 0; i < 256; ++i) {
      u32 c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  const u8* p = static_cast<const u8*>(data);
  crc = ~crc;
  for (size_t i = 0; i < n; ++i) crc = table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

u32 wal_record_crc(u32 seq, u8 op, const char* payload, size_t n) {
  u32 c = crc32_update(0, &seq, 4);
  c = crc32_update(c, &op, 1);
  return crc32_update(c, payload, n);
}

enum Op : u8 {
  OP_STORE_LINK = 1,
  OP_REMOVE_LINK = 2,
  OP_STORE_DATA = 3,
  OP_REMOVE_DATA = 4,
  OP_INC_ADD = 5,
  OP_INC_REMOVE = 6,
  OP_INC_CLEAR = 7,
  OP_IDX_ADD = 8,
  OP_IDX_REMOVE = 9,
  OP_IDX_REMOVE_ALL = 10,
  OP_IDX_DROP = 11,
  OP_IDX_TOUCH = 12,
  OP_BATCH_BEGIN = 13,
  OP_BATCH_COMMIT = 14,
  OP_BATCH_ABORT = 15,
};

struct Index {
  // ordered key -> ascending-sorted values; memcmp order == key order
  // (keys are the order-preserving byte encodings from utils/ordered_bytes)
  std::map<std::string, std::vector<i64>> entries;
  // value -> keys holding it (HGBidirectionalIndex contract)
  std::unordered_map<i64, std::set<std::string>> by_value;

  void add(const std::string& key, i64 v) {
    std::vector<i64>& vec = entries[key];
    std::vector<i64>::iterator it =
        std::lower_bound(vec.begin(), vec.end(), v);
    if (it == vec.end() || *it != v) vec.insert(it, v);
    by_value[v].insert(key);
  }
  void remove(const std::string& key, i64 v) {
    std::map<std::string, std::vector<i64>>::iterator e = entries.find(key);
    if (e != entries.end()) {
      std::vector<i64>& vec = e->second;
      std::vector<i64>::iterator it =
          std::lower_bound(vec.begin(), vec.end(), v);
      if (it != vec.end() && *it == v) vec.erase(it);
      if (vec.empty()) entries.erase(e);
    }
    std::unordered_map<i64, std::set<std::string>>::iterator b =
        by_value.find(v);
    if (b != by_value.end()) {
      b->second.erase(key);
      if (b->second.empty()) by_value.erase(b);
    }
  }
  void remove_all(const std::string& key) {
    std::map<std::string, std::vector<i64>>::iterator e = entries.find(key);
    if (e == entries.end()) return;
    for (size_t i = 0; i < e->second.size(); ++i) {
      i64 v = e->second[i];
      std::unordered_map<i64, std::set<std::string>>::iterator b =
          by_value.find(v);
      if (b != by_value.end()) {
        b->second.erase(key);
        if (b->second.empty()) by_value.erase(b);
      }
    }
    entries.erase(e);
  }
};

struct Store {
  std::string dir;
  FILE* wal = nullptr;
  bool replaying = false;
  bool wal_ok = true;    // sticky: any WAL write failure latches false
  bool in_batch = false; // commit batch open: defer flush to batch commit
  u32 wal_seq = 0;       // next record sequence number (v2 framing)

  std::unordered_map<i64, std::vector<i64>> links;
  std::unordered_map<i64, std::string> data;
  std::unordered_map<i64, std::vector<i64>> incidence;  // sorted
  std::map<std::string, Index> indices;
  i64 max_handle = 0;

  std::string wal_path() const { return dir + "/wal.log"; }
  std::string ckpt_path() const { return dir + "/checkpoint.bin"; }

  void note_handle(i64 h) {
    if (h + 1 > max_handle) max_handle = h + 1;
  }
};

// ---------------------------------------------------------------- WAL I/O

void w_bytes(std::string& buf, const void* p, size_t n) {
  buf.append(reinterpret_cast<const char*>(p), n);
}
void w_i64(std::string& buf, i64 v) { w_bytes(buf, &v, 8); }
void w_u32(std::string& buf, u32 v) { w_bytes(buf, &v, 4); }
void w_blob(std::string& buf, const char* p, u32 n) {
  w_u32(buf, n);
  w_bytes(buf, p, n);
}

struct Reader {
  const char* p;
  const char* end;
  bool ok = true;
  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      ok = false;
      return false;
    }
    return true;
  }
  i64 r_i64() {
    if (!need(8)) return 0;
    i64 v;
    memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  u32 r_u32() {
    if (!need(4)) return 0;
    u32 v;
    memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  std::string r_blob() {
    u32 n = r_u32();
    if (!need(n)) return std::string();
    std::string s(p, n);
    p += n;
    return s;
  }
};

void wal_append(Store* s, u8 op, const std::string& payload) {
  if (s->replaying) return;
  if (!s->wal) {  // e.g. checkpoint failed to reopen the log
    s->wal_ok = false;
    return;
  }
  u32 len = static_cast<u32>(payload.size()) + 1;
  u32 seq = s->wal_seq++;
  u32 crc = wal_record_crc(seq, op, payload.data(), payload.size());
  bool ok = fwrite(&len, 4, 1, s->wal) == 1 &&
            fwrite(&crc, 4, 1, s->wal) == 1 &&
            fwrite(&seq, 4, 1, s->wal) == 1 &&
            fwrite(&op, 1, 1, s->wal) == 1 &&
            fwrite(payload.data(), 1, payload.size(), s->wal) ==
                payload.size();
  // fflush pushes into the kernel page cache: survives process death (the
  // AbruptExit contract); fsync-on-checkpoint covers OS crash. Inside a
  // commit batch the flush is deferred to the OP_BATCH_COMMIT barrier.
  if (ok && !s->in_batch) ok = fflush(s->wal) == 0;
  if (!ok) s->wal_ok = false;  // sticky; surfaced via hgs_wal_ok
}

// ---------------------------------------------------------------- mutations

void do_store_link(Store* s, i64 h, const i64* targets, u32 n) {
  std::vector<i64>& vec = s->links[h];
  vec.assign(targets, targets + n);
  s->note_handle(h);
  for (u32 i = 0; i < n; ++i) s->note_handle(targets[i]);
}

void do_remove_link(Store* s, i64 h) { s->links.erase(h); }

void do_store_data(Store* s, i64 h, const char* bytes, u32 n) {
  s->data[h].assign(bytes, n);
  s->note_handle(h);
}

void do_remove_data(Store* s, i64 h) { s->data.erase(h); }

void do_inc_add(Store* s, i64 atom, i64 link) {
  std::vector<i64>& vec = s->incidence[atom];
  std::vector<i64>::iterator it =
      std::lower_bound(vec.begin(), vec.end(), link);
  if (it == vec.end() || *it != link) vec.insert(it, link);
  s->note_handle(atom);
  s->note_handle(link);
}

void do_inc_remove(Store* s, i64 atom, i64 link) {
  std::unordered_map<i64, std::vector<i64>>::iterator e =
      s->incidence.find(atom);
  if (e == s->incidence.end()) return;
  std::vector<i64>& vec = e->second;
  std::vector<i64>::iterator it =
      std::lower_bound(vec.begin(), vec.end(), link);
  if (it != vec.end() && *it == link) vec.erase(it);
  if (vec.empty()) s->incidence.erase(e);
}

void do_inc_clear(Store* s, i64 atom) { s->incidence.erase(atom); }

void apply_record(Store* s, u8 op, Reader& r) {
  switch (op) {
    case OP_STORE_LINK: {
      i64 h = r.r_i64();
      u32 n = r.r_u32();
      if (!r.need(8ull * n)) return;
      std::vector<i64> ts(n);
      if (n) memcpy(ts.data(), r.p, 8ull * n);
      r.p += 8ull * n;
      do_store_link(s, h, ts.data(), n);
      break;
    }
    case OP_REMOVE_LINK:
      do_remove_link(s, r.r_i64());
      break;
    case OP_STORE_DATA: {
      i64 h = r.r_i64();
      std::string b = r.r_blob();
      if (r.ok) do_store_data(s, h, b.data(), static_cast<u32>(b.size()));
      break;
    }
    case OP_REMOVE_DATA:
      do_remove_data(s, r.r_i64());
      break;
    case OP_INC_ADD: {
      i64 a = r.r_i64(), l = r.r_i64();
      if (r.ok) do_inc_add(s, a, l);
      break;
    }
    case OP_INC_REMOVE: {
      i64 a = r.r_i64(), l = r.r_i64();
      if (r.ok) do_inc_remove(s, a, l);
      break;
    }
    case OP_INC_CLEAR:
      do_inc_clear(s, r.r_i64());
      break;
    case OP_IDX_ADD: {
      std::string name = r.r_blob(), key = r.r_blob();
      i64 v = r.r_i64();
      if (r.ok) s->indices[name].add(key, v);
      break;
    }
    case OP_IDX_REMOVE: {
      std::string name = r.r_blob(), key = r.r_blob();
      i64 v = r.r_i64();
      if (r.ok) {
        std::map<std::string, Index>::iterator it = s->indices.find(name);
        if (it != s->indices.end()) it->second.remove(key, v);
      }
      break;
    }
    case OP_IDX_REMOVE_ALL: {
      std::string name = r.r_blob(), key = r.r_blob();
      if (r.ok) {
        std::map<std::string, Index>::iterator it = s->indices.find(name);
        if (it != s->indices.end()) it->second.remove_all(key);
      }
      break;
    }
    case OP_IDX_DROP: {
      std::string name = r.r_blob();
      if (r.ok) s->indices.erase(name);
      break;
    }
    case OP_IDX_TOUCH: {
      std::string name = r.r_blob();
      if (r.ok) s->indices[name];
      break;
    }
    default:
      break;
  }
}

// ---------------------------------------------------------------- checkpoint

const u32 CKPT_MAGIC = 0x48475354;  // "HGST"
const u32 CKPT_VERSION = 1;

bool write_all(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

bool save_checkpoint(Store* s) {
  std::string tmp = s->ckpt_path() + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return false;
  std::string buf;
  w_u32(buf, CKPT_MAGIC);
  w_u32(buf, CKPT_VERSION);
  w_i64(buf, s->max_handle);
  w_u32(buf, static_cast<u32>(s->links.size()));
  for (std::unordered_map<i64, std::vector<i64>>::const_iterator it =
           s->links.begin();
       it != s->links.end(); ++it) {
    w_i64(buf, it->first);
    w_u32(buf, static_cast<u32>(it->second.size()));
    w_bytes(buf, it->second.data(), it->second.size() * 8);
  }
  w_u32(buf, static_cast<u32>(s->data.size()));
  for (std::unordered_map<i64, std::string>::const_iterator it =
           s->data.begin();
       it != s->data.end(); ++it) {
    w_i64(buf, it->first);
    w_blob(buf, it->second.data(), static_cast<u32>(it->second.size()));
  }
  w_u32(buf, static_cast<u32>(s->incidence.size()));
  for (std::unordered_map<i64, std::vector<i64>>::const_iterator it =
           s->incidence.begin();
       it != s->incidence.end(); ++it) {
    w_i64(buf, it->first);
    w_u32(buf, static_cast<u32>(it->second.size()));
    w_bytes(buf, it->second.data(), it->second.size() * 8);
  }
  w_u32(buf, static_cast<u32>(s->indices.size()));
  for (std::map<std::string, Index>::const_iterator it = s->indices.begin();
       it != s->indices.end(); ++it) {
    w_blob(buf, it->first.data(), static_cast<u32>(it->first.size()));
    w_u32(buf, static_cast<u32>(it->second.entries.size()));
    for (std::map<std::string, std::vector<i64>>::const_iterator e =
             it->second.entries.begin();
         e != it->second.entries.end(); ++e) {
      w_blob(buf, e->first.data(), static_cast<u32>(e->first.size()));
      w_u32(buf, static_cast<u32>(e->second.size()));
      w_bytes(buf, e->second.data(), e->second.size() * 8);
    }
  }
  bool ok = write_all(f, buf.data(), buf.size());
  ok = ok && fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok) {
    remove(tmp.c_str());
    return false;
  }
  if (rename(tmp.c_str(), s->ckpt_path().c_str()) != 0) return false;
  // make the rename durable before the caller truncates the WAL: without a
  // directory fsync, POSIX gives no ordering between the rename and the
  // truncation reaching disk, and a power cut could surface the truncated
  // WAL with the OLD checkpoint
  int dfd = open(s->dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd < 0) return false;
  bool synced = fsync(dfd) == 0;
  close(dfd);
  return synced;
}

bool load_checkpoint(Store* s) {
  FILE* f = fopen(s->ckpt_path().c_str(), "rb");
  if (!f) return true;  // no checkpoint yet
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(sz), '\0');
  size_t got = fread(&buf[0], 1, static_cast<size_t>(sz), f);
  fclose(f);
  if (got != static_cast<size_t>(sz)) return false;
  Reader r{buf.data(), buf.data() + buf.size()};
  if (r.r_u32() != CKPT_MAGIC || r.r_u32() != CKPT_VERSION) return false;
  s->max_handle = r.r_i64();
  u32 nl = r.r_u32();
  for (u32 i = 0; i < nl && r.ok; ++i) {
    i64 h = r.r_i64();
    u32 n = r.r_u32();
    if (!r.need(8ull * n)) break;
    std::vector<i64>& vec = s->links[h];
    vec.resize(n);
    if (n) memcpy(vec.data(), r.p, 8ull * n);
    r.p += 8ull * n;
  }
  u32 nd = r.r_u32();
  for (u32 i = 0; i < nd && r.ok; ++i) {
    i64 h = r.r_i64();
    s->data[h] = r.r_blob();
  }
  u32 ni = r.r_u32();
  for (u32 i = 0; i < ni && r.ok; ++i) {
    i64 h = r.r_i64();
    u32 n = r.r_u32();
    if (!r.need(8ull * n)) break;
    std::vector<i64>& vec = s->incidence[h];
    vec.resize(n);
    if (n) memcpy(vec.data(), r.p, 8ull * n);
    r.p += 8ull * n;
  }
  u32 nx = r.r_u32();
  for (u32 i = 0; i < nx && r.ok; ++i) {
    std::string name = r.r_blob();
    Index& idx = s->indices[name];
    u32 nk = r.r_u32();
    for (u32 k = 0; k < nk && r.ok; ++k) {
      std::string key = r.r_blob();
      u32 nv = r.r_u32();
      if (!r.need(8ull * nv)) break;
      std::vector<i64>& vec = idx.entries[key];
      vec.resize(nv);
      if (nv) memcpy(vec.data(), r.p, 8ull * nv);
      r.p += 8ull * nv;
      for (u32 v = 0; v < nv; ++v) idx.by_value[vec[v]].insert(key);
    }
  }
  return r.ok;
}

bool replay_wal(Store* s) {
  FILE* f = fopen(s->wal_path().c_str(), "rb");
  if (!f) return true;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(sz), '\0');
  size_t got = fread(&buf[0], 1, static_cast<size_t>(sz), f);
  fclose(f);
  if (got != static_cast<size_t>(sz)) return false;
  s->replaying = true;
  const char* p = buf.data();
  const char* end = p + buf.size();
  long good = 0;
  bool v2 = buf.size() >= 4 && memcmp(p, kWalMagic, 4) == 0;
  if (v2) {
    p += 4;
    good = 4;
  }
  const size_t head = v2 ? 13 : 5;  // len+crc+seq+op | len+op
  u32 expect_seq = 0;
  u32 good_seq = 0;
  // Commit-batch replay: records between OP_BATCH_BEGIN and OP_BATCH_COMMIT
  // are buffered and applied atomically at the commit barrier; a crash
  // mid-commit leaves an unterminated batch, which is discarded — no
  // half-applied transactions survive. Records outside a batch (standalone
  // ops, e.g. non-transactional mode) apply immediately.
  std::vector<std::pair<u8, std::pair<const char*, const char*>>> pending;
  bool batch = false;
  while (static_cast<size_t>(end - p) >= head) {
    u32 len;
    memcpy(&len, p, 4);
    const char* rec = p + (head - 1);  // points at the op byte
    if (static_cast<size_t>(end - rec) < len || len == 0) break;  // torn tail
    u8 op = static_cast<u8>(rec[0]);
    const char* body = rec + 1;
    const char* body_end = rec + len;
    if (v2) {
      u32 crc, seq;
      memcpy(&crc, p + 4, 4);
      memcpy(&seq, p + 8, 4);
      if (seq != expect_seq ||
          crc != wal_record_crc(seq, op, body, body_end - body))
        break;  // corruption: valid prefix ends here
      ++expect_seq;
    }
    const long rec_total = static_cast<long>(head - 1) + len;
    if (op == OP_BATCH_BEGIN) {
      pending.clear();
      batch = true;
    } else if (op == OP_BATCH_COMMIT) {
      for (size_t i = 0; i < pending.size(); ++i) {
        Reader r{pending[i].second.first, pending[i].second.second};
        apply_record(s, pending[i].first, r);
      }
      pending.clear();
      batch = false;
      good = (p + rec_total) - buf.data();
      good_seq = expect_seq;
    } else if (op == OP_BATCH_ABORT) {
      pending.clear();
      batch = false;
      good = (p + rec_total) - buf.data();
      good_seq = expect_seq;
    } else if (batch) {
      pending.push_back(std::make_pair(
          op, std::make_pair(body, body_end)));
    } else {
      Reader r{body, body_end};
      apply_record(s, op, r);
      good = (p + rec_total) - buf.data();
      good_seq = expect_seq;
    }
    p += rec_total;
  }
  s->replaying = false;
  // appends continue the sequence of the last KEPT record: everything past
  // `good` (e.g. a verified-but-unterminated batch) is truncated below, so
  // its sequence numbers are legitimately reused
  if (v2) s->wal_seq = good_seq;
  if (good < sz) {
    // truncate the torn tail so the next append starts at a clean boundary
    if (truncate(s->wal_path().c_str(), good) != 0) return false;
  }
  return true;
}

// ---------------------------------------------------------------- out buffers

char* out_alloc(size_t n) { return static_cast<char*>(malloc(n ? n : 1)); }

}  // namespace

// ================================================================ C API

extern "C" {

Store* hgs_open(const char* path) {
  Store* s = new Store();
  s->dir = path;
  mkdir(path, 0755);  // ok if exists
  if (!load_checkpoint(s) || !replay_wal(s)) {
    delete s;
    return nullptr;
  }
  s->wal = fopen(s->wal_path().c_str(), "ab");
  if (!s->wal) {
    delete s;
    return nullptr;
  }
  fseek(s->wal, 0, SEEK_END);
  long wal_size = ftell(s->wal);
  if (wal_size == 0) {
    // fresh log: start with the v2 magic so every record is checksummed
    if (fwrite(kWalMagic, 1, 4, s->wal) != 4 || fflush(s->wal) != 0)
      s->wal_ok = false;
    s->wal_seq = 0;
  } else if (s->wal_seq == 0 && wal_size > 4) {
    // non-empty log that replayed WITHOUT v2 sequencing = legacy framing.
    // Its state is fully loaded, so convert once: checkpoint + truncate
    // rewrites the log as v2 (appending unchecksummed frames forever
    // would defeat the point of the CRC).
    FILE* probe = fopen(s->wal_path().c_str(), "rb");
    char m[4] = {0, 0, 0, 0};
    bool is_v2 = probe && fread(m, 1, 4, probe) == 4 &&
                 memcmp(m, kWalMagic, 4) == 0;
    if (probe) fclose(probe);
    if (!is_v2) {
      fclose(s->wal);
      s->wal = nullptr;
      if (!save_checkpoint(s)) {
        delete s;
        return nullptr;
      }
      s->wal = fopen(s->wal_path().c_str(), "wb");
      if (!s->wal) {
        delete s;
        return nullptr;
      }
      if (fwrite(kWalMagic, 1, 4, s->wal) != 4 || fflush(s->wal) != 0)
        s->wal_ok = false;
      s->wal_seq = 0;
    }
  }
  return s;
}

void hgs_close(Store* s) {
  if (!s) return;
  if (s->wal) fclose(s->wal);
  delete s;
}

// checkpoint: compact state to disk, truncate the WAL
int hgs_checkpoint(Store* s) {
  if (!save_checkpoint(s)) return -1;
  if (s->wal) fclose(s->wal);
  s->wal = fopen(s->wal_path().c_str(), "wb");  // truncate
  if (!s->wal) {
    s->wal_ok = false;  // nothing can be logged until reopen
    return -1;
  }
  if (fwrite(kWalMagic, 1, 4, s->wal) != 4 || fflush(s->wal) != 0)
    s->wal_ok = false;
  s->wal_seq = 0;  // a fresh log restarts the record sequence
  return 0;
}

void hgs_free(void* p) { free(p); }

// 1 while every WAL write so far has fully reached the OS; latches 0 on the
// first failure (disk full, IO error) so callers can surface lost durability
int hgs_wal_ok(Store* s) { return s->wal_ok ? 1 : 0; }

// commit-batch barriers: mutations between begin and commit replay
// atomically (all or nothing) after a crash
void hgs_batch_begin(Store* s) {
  wal_append(s, OP_BATCH_BEGIN, std::string());
  s->in_batch = true;
}

void hgs_batch_commit(Store* s) {
  s->in_batch = false;
  wal_append(s, OP_BATCH_COMMIT, std::string());
}

// abort: the batch's records stay in the log but replay discards them at
// this barrier — the durable image never shows a half-applied commit
void hgs_batch_abort(Store* s) {
  s->in_batch = false;
  wal_append(s, OP_BATCH_ABORT, std::string());
}

i64 hgs_max_handle(Store* s) { return s->max_handle; }

// -- links ------------------------------------------------------------

void hgs_store_link(Store* s, i64 h, const i64* targets, u32 n) {
  std::string pl;
  w_i64(pl, h);
  w_u32(pl, n);
  w_bytes(pl, targets, 8ull * n);
  wal_append(s, OP_STORE_LINK, pl);
  do_store_link(s, h, targets, n);
}

// returns 1 if present; *out (malloc'd) holds n targets
int hgs_get_link(Store* s, i64 h, i64** out, u32* n) {
  std::unordered_map<i64, std::vector<i64>>::const_iterator it =
      s->links.find(h);
  if (it == s->links.end()) return 0;
  *n = static_cast<u32>(it->second.size());
  *out = reinterpret_cast<i64*>(out_alloc(8ull * *n));
  if (*n) memcpy(*out, it->second.data(), 8ull * *n);
  return 1;
}

void hgs_remove_link(Store* s, i64 h) {
  std::string pl;
  w_i64(pl, h);
  wal_append(s, OP_REMOVE_LINK, pl);
  do_remove_link(s, h);
}

int hgs_contains_link(Store* s, i64 h) {
  return s->links.count(h) ? 1 : 0;
}

u32 hgs_link_count(Store* s) { return static_cast<u32>(s->links.size()); }

// bulk export: ids ascending + CSR offsets + flat targets (CSR-pack feed)
void hgs_bulk_links(Store* s, i64** ids, i64** offsets, i64** flat,
                    u32* n_links, u32* n_flat) {
  std::vector<i64> sorted_ids;
  sorted_ids.reserve(s->links.size());
  size_t total = 0;
  for (std::unordered_map<i64, std::vector<i64>>::const_iterator it =
           s->links.begin();
       it != s->links.end(); ++it) {
    sorted_ids.push_back(it->first);
    total += it->second.size();
  }
  std::sort(sorted_ids.begin(), sorted_ids.end());
  *n_links = static_cast<u32>(sorted_ids.size());
  *n_flat = static_cast<u32>(total);
  *ids = reinterpret_cast<i64*>(out_alloc(8ull * sorted_ids.size()));
  *offsets = reinterpret_cast<i64*>(out_alloc(8ull * (sorted_ids.size() + 1)));
  *flat = reinterpret_cast<i64*>(out_alloc(8ull * total));
  i64 off = 0;
  for (size_t i = 0; i < sorted_ids.size(); ++i) {
    (*ids)[i] = sorted_ids[i];
    (*offsets)[i] = off;
    const std::vector<i64>& ts = s->links[sorted_ids[i]];
    if (!ts.empty()) memcpy(*flat + off, ts.data(), 8ull * ts.size());
    off += static_cast<i64>(ts.size());
  }
  (*offsets)[sorted_ids.size()] = off;
}

// -- data -------------------------------------------------------------

void hgs_store_data(Store* s, i64 h, const char* bytes, u32 n) {
  std::string pl;
  w_i64(pl, h);
  w_blob(pl, bytes, n);
  wal_append(s, OP_STORE_DATA, pl);
  do_store_data(s, h, bytes, n);
}

int hgs_get_data(Store* s, i64 h, char** out, u32* n) {
  std::unordered_map<i64, std::string>::const_iterator it = s->data.find(h);
  if (it == s->data.end()) return 0;
  *n = static_cast<u32>(it->second.size());
  *out = out_alloc(*n);
  if (*n) memcpy(*out, it->second.data(), *n);
  return 1;
}

void hgs_remove_data(Store* s, i64 h) {
  std::string pl;
  w_i64(pl, h);
  wal_append(s, OP_REMOVE_DATA, pl);
  do_remove_data(s, h);
}

// -- incidence ---------------------------------------------------------

void hgs_inc_add(Store* s, i64 atom, i64 link) {
  std::string pl;
  w_i64(pl, atom);
  w_i64(pl, link);
  wal_append(s, OP_INC_ADD, pl);
  do_inc_add(s, atom, link);
}

void hgs_inc_remove(Store* s, i64 atom, i64 link) {
  std::string pl;
  w_i64(pl, atom);
  w_i64(pl, link);
  wal_append(s, OP_INC_REMOVE, pl);
  do_inc_remove(s, atom, link);
}

void hgs_inc_clear(Store* s, i64 atom) {
  std::string pl;
  w_i64(pl, atom);
  wal_append(s, OP_INC_CLEAR, pl);
  do_inc_clear(s, atom);
}

void hgs_inc_get(Store* s, i64 atom, i64** out, u32* n) {
  std::unordered_map<i64, std::vector<i64>>::const_iterator it =
      s->incidence.find(atom);
  if (it == s->incidence.end()) {
    *n = 0;
    *out = reinterpret_cast<i64*>(out_alloc(0));
    return;
  }
  *n = static_cast<u32>(it->second.size());
  *out = reinterpret_cast<i64*>(out_alloc(8ull * *n));
  if (*n) memcpy(*out, it->second.data(), 8ull * *n);
}

u32 hgs_inc_count(Store* s, i64 atom) {
  std::unordered_map<i64, std::vector<i64>>::const_iterator it =
      s->incidence.find(atom);
  return it == s->incidence.end() ? 0 : static_cast<u32>(it->second.size());
}

// -- indices -----------------------------------------------------------

void hgs_idx_add(Store* s, const char* name, const char* key, u32 klen,
                 i64 v) {
  std::string nm(name), k(key, klen), pl;
  w_blob(pl, nm.data(), static_cast<u32>(nm.size()));
  w_blob(pl, k.data(), klen);
  w_i64(pl, v);
  wal_append(s, OP_IDX_ADD, pl);
  s->indices[nm].add(k, v);
}

void hgs_idx_remove(Store* s, const char* name, const char* key, u32 klen,
                    i64 v) {
  std::string nm(name), k(key, klen), pl;
  w_blob(pl, nm.data(), static_cast<u32>(nm.size()));
  w_blob(pl, k.data(), klen);
  w_i64(pl, v);
  wal_append(s, OP_IDX_REMOVE, pl);
  std::map<std::string, Index>::iterator it = s->indices.find(nm);
  if (it != s->indices.end()) it->second.remove(k, v);
}

void hgs_idx_remove_all(Store* s, const char* name, const char* key,
                        u32 klen) {
  std::string nm(name), k(key, klen), pl;
  w_blob(pl, nm.data(), static_cast<u32>(nm.size()));
  w_blob(pl, k.data(), klen);
  wal_append(s, OP_IDX_REMOVE_ALL, pl);
  std::map<std::string, Index>::iterator it = s->indices.find(nm);
  if (it != s->indices.end()) it->second.remove_all(k);
}

void hgs_idx_drop(Store* s, const char* name) {
  std::string nm(name), pl;
  w_blob(pl, nm.data(), static_cast<u32>(nm.size()));
  wal_append(s, OP_IDX_DROP, pl);
  s->indices.erase(nm);
}

// ensure the index exists (get_index(create=True) semantics); WAL'd so an
// index created empty survives reopen like it does on the memory backend
void hgs_idx_touch(Store* s, const char* name) {
  std::string nm(name);
  if (!s->indices.count(nm)) {
    std::string pl;
    w_blob(pl, nm.data(), static_cast<u32>(nm.size()));
    wal_append(s, OP_IDX_TOUCH, pl);
  }
  s->indices[nm];
}

int hgs_idx_exists(Store* s, const char* name) {
  return s->indices.count(name) ? 1 : 0;
}

void hgs_idx_find(Store* s, const char* name, const char* key, u32 klen,
                  i64** out, u32* n) {
  *n = 0;
  *out = nullptr;
  std::map<std::string, Index>::const_iterator it = s->indices.find(name);
  if (it == s->indices.end()) {
    *out = reinterpret_cast<i64*>(out_alloc(0));
    return;
  }
  std::map<std::string, std::vector<i64>>::const_iterator e =
      it->second.entries.find(std::string(key, klen));
  if (e == it->second.entries.end()) {
    *out = reinterpret_cast<i64*>(out_alloc(0));
    return;
  }
  *n = static_cast<u32>(e->second.size());
  *out = reinterpret_cast<i64*>(out_alloc(8ull * *n));
  if (*n) memcpy(*out, e->second.data(), 8ull * *n);
}

// range scan over [lo, hi] with inclusivity flags; null bound = open end.
// Returns the UNION of value sets over keys in range, ascending & deduped.
void hgs_idx_range(Store* s, const char* name, const char* lo, u32 lo_len,
                   int has_lo, int lo_incl, const char* hi, u32 hi_len,
                   int has_hi, int hi_incl, i64** out, u32* n) {
  *n = 0;
  std::map<std::string, Index>::const_iterator it = s->indices.find(name);
  if (it == s->indices.end()) {
    *out = reinterpret_cast<i64*>(out_alloc(0));
    return;
  }
  const std::map<std::string, std::vector<i64>>& m = it->second.entries;
  std::map<std::string, std::vector<i64>>::const_iterator b, e;
  if (has_lo) {
    std::string k(lo, lo_len);
    b = lo_incl ? m.lower_bound(k) : m.upper_bound(k);
  } else {
    b = m.begin();
  }
  if (has_hi) {
    std::string k(hi, hi_len);
    e = hi_incl ? m.upper_bound(k) : m.lower_bound(k);
  } else {
    e = m.end();
  }
  std::vector<i64> acc;
  for (; b != e; ++b) acc.insert(acc.end(), b->second.begin(), b->second.end());
  std::sort(acc.begin(), acc.end());
  acc.erase(std::unique(acc.begin(), acc.end()), acc.end());
  *n = static_cast<u32>(acc.size());
  *out = reinterpret_cast<i64*>(out_alloc(8ull * acc.size()));
  if (*n) memcpy(*out, acc.data(), 8ull * acc.size());
}

u32 hgs_idx_key_count(Store* s, const char* name) {
  std::map<std::string, Index>::const_iterator it = s->indices.find(name);
  return it == s->indices.end() ? 0
                                : static_cast<u32>(it->second.entries.size());
}

// all keys, concatenated as [u32 len][bytes]...; caller frees
void hgs_idx_scan_keys(Store* s, const char* name, char** out, u32* total,
                       u32* count) {
  *total = 0;
  *count = 0;
  std::map<std::string, Index>::const_iterator it = s->indices.find(name);
  std::string buf;
  if (it != s->indices.end()) {
    for (std::map<std::string, std::vector<i64>>::const_iterator e =
             it->second.entries.begin();
         e != it->second.entries.end(); ++e) {
      w_blob(buf, e->first.data(), static_cast<u32>(e->first.size()));
      ++*count;
    }
  }
  *total = static_cast<u32>(buf.size());
  *out = out_alloc(buf.size());
  if (!buf.empty()) memcpy(*out, buf.data(), buf.size());
}

// keys holding a value, same framing as scan_keys
void hgs_idx_find_by_value(Store* s, const char* name, i64 v, char** out,
                           u32* total, u32* count) {
  *total = 0;
  *count = 0;
  std::string buf;
  std::map<std::string, Index>::const_iterator it = s->indices.find(name);
  if (it != s->indices.end()) {
    std::unordered_map<i64, std::set<std::string>>::const_iterator b =
        it->second.by_value.find(v);
    if (b != it->second.by_value.end()) {
      for (std::set<std::string>::const_iterator k = b->second.begin();
           k != b->second.end(); ++k) {
        w_blob(buf, k->data(), static_cast<u32>(k->size()));
        ++*count;
      }
    }
  }
  *total = static_cast<u32>(buf.size());
  *out = out_alloc(buf.size());
  if (!buf.empty()) memcpy(*out, buf.data(), buf.size());
}

// index names, same framing
void hgs_idx_names(Store* s, char** out, u32* total, u32* count) {
  std::string buf;
  *count = 0;
  for (std::map<std::string, Index>::const_iterator it = s->indices.begin();
       it != s->indices.end(); ++it) {
    w_blob(buf, it->first.data(), static_cast<u32>(it->first.size()));
    ++*count;
  }
  *total = static_cast<u32>(buf.size());
  *out = out_alloc(buf.size());
  if (!buf.empty()) memcpy(*out, buf.data(), buf.size());
}

}  // extern "C"
