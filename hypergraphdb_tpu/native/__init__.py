"""Native (C++) storage engine build + load.

``lib()`` returns the loaded ``libhgstore.so``, compiling it from
``hgstore.cpp`` with g++ on first use (and whenever the source is newer than
the binary). The reference ships native code as a separate Maven module
linked against BerkeleyDB C (``storage/bdb-native/pom.xml:100-120``); here
the native engine is self-contained and built on demand.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_HERE, "hgstore.cpp")
SO = os.path.join(_HERE, "libhgstore.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeBuildError(RuntimeError):
    pass


def build(force: bool = False) -> str:
    """Compile hgstore.cpp → libhgstore.so if missing or stale."""
    with _lock:
        if (
            not force
            and os.path.exists(SO)
            and os.path.getmtime(SO) >= os.path.getmtime(SRC)
        ):
            return SO
        tmp = SO + f".tmp.{os.getpid()}"  # concurrent builders must not share
        cmd = [
            "g++", "-std=c++17", "-O2", "-fPIC", "-shared",
            "-o", tmp, SRC,
        ]
        try:
            # deliberate blocking-under-lock: the lock EXISTS to make
            # concurrent callers wait for one compile instead of racing
            # N g++ processes at the same output
            proc = subprocess.run(  # hglint: disable=HG701
                cmd, capture_output=True, text=True, timeout=300
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise NativeBuildError(f"g++ invocation failed: {e}") from e
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build failed:\n{proc.stderr[-4000:]}"
            )
        # publish-under-the-same-hold: a waiter must observe the fresh
        # .so the moment it acquires
        os.replace(tmp, SO)  # hglint: disable=HG701
        return SO


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    path = build()
    with _lock:
        if _lib is None:
            _lib = _bind(ctypes.CDLL(path))
    return _lib


def _bind(L: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    u32 = ctypes.c_uint32
    p = ctypes.POINTER
    vp = ctypes.c_void_p
    cp = ctypes.c_char_p

    L.hgs_open.argtypes = [cp]
    L.hgs_open.restype = vp
    L.hgs_close.argtypes = [vp]
    L.hgs_checkpoint.argtypes = [vp]
    L.hgs_checkpoint.restype = ctypes.c_int
    L.hgs_wal_ok.argtypes = [vp]
    L.hgs_wal_ok.restype = ctypes.c_int
    L.hgs_batch_begin.argtypes = [vp]
    L.hgs_batch_commit.argtypes = [vp]
    L.hgs_batch_abort.argtypes = [vp]
    L.hgs_free.argtypes = [vp]
    L.hgs_max_handle.argtypes = [vp]
    L.hgs_max_handle.restype = i64

    L.hgs_store_link.argtypes = [vp, i64, p(i64), u32]
    L.hgs_get_link.argtypes = [vp, i64, p(p(i64)), p(u32)]
    L.hgs_get_link.restype = ctypes.c_int
    L.hgs_remove_link.argtypes = [vp, i64]
    L.hgs_contains_link.argtypes = [vp, i64]
    L.hgs_contains_link.restype = ctypes.c_int
    L.hgs_link_count.argtypes = [vp]
    L.hgs_link_count.restype = u32
    L.hgs_bulk_links.argtypes = [vp, p(p(i64)), p(p(i64)), p(p(i64)), p(u32), p(u32)]

    L.hgs_store_data.argtypes = [vp, i64, cp, u32]
    L.hgs_get_data.argtypes = [vp, i64, p(cp), p(u32)]
    L.hgs_get_data.restype = ctypes.c_int
    L.hgs_remove_data.argtypes = [vp, i64]

    L.hgs_inc_add.argtypes = [vp, i64, i64]
    L.hgs_inc_remove.argtypes = [vp, i64, i64]
    L.hgs_inc_clear.argtypes = [vp, i64]
    L.hgs_inc_get.argtypes = [vp, i64, p(p(i64)), p(u32)]
    L.hgs_inc_count.argtypes = [vp, i64]
    L.hgs_inc_count.restype = u32

    L.hgs_idx_add.argtypes = [vp, cp, cp, u32, i64]
    L.hgs_idx_remove.argtypes = [vp, cp, cp, u32, i64]
    L.hgs_idx_remove_all.argtypes = [vp, cp, cp, u32]
    L.hgs_idx_drop.argtypes = [vp, cp]
    L.hgs_idx_touch.argtypes = [vp, cp]
    L.hgs_idx_exists.argtypes = [vp, cp]
    L.hgs_idx_exists.restype = ctypes.c_int
    L.hgs_idx_find.argtypes = [vp, cp, cp, u32, p(p(i64)), p(u32)]
    L.hgs_idx_range.argtypes = [
        vp, cp, cp, u32, ctypes.c_int, ctypes.c_int,
        cp, u32, ctypes.c_int, ctypes.c_int, p(p(i64)), p(u32),
    ]
    L.hgs_idx_key_count.argtypes = [vp, cp]
    L.hgs_idx_key_count.restype = u32
    L.hgs_idx_scan_keys.argtypes = [vp, cp, p(cp), p(u32), p(u32)]
    L.hgs_idx_find_by_value.argtypes = [vp, cp, i64, p(cp), p(u32), p(u32)]
    L.hgs_idx_names.argtypes = [vp, p(cp), p(u32), p(u32)]
    return L
