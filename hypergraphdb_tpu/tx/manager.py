"""MVCC/optimistic transactions over a storage backend.

Re-expression of the reference's JVSTM-derived STM layer
(``core/src/java/org/hypergraphdb/transaction/``): versioned cells with
commit-time read-set validation under a global commit lock
(``HGTransaction.java:96-108`` validation, commit ``:202``,
``HGTransactionManager.java:35-38`` COMMIT_LOCK) and the retry-on-conflict
``transact()`` loop (``HGTransactionManager.java:356-418``).

Design differences, deliberate:

- The reference pushes transactions *down* into each backend (BDB txns +
  ``VBox`` STM cells above them). Here the backend holds committed state
  only and ALL buffering/validation happens in this layer, so backends —
  including the C++ native store — stay dumb data structures.
- Cells are logical, coarse: ``("link", h)``, ``("data", h)``,
  ``("inc", atom)``, ``("idx", name, key)``. A transaction records the
  version of every cell it reads; commit validates those versions under the
  lock (optimistic concurrency = the reference's conflict semantics), then
  applies buffered writes and bumps written cells.
- Long-lived *consistent* reads are served by the device plane: an immutable
  CSR snapshot IS a long-lived read transaction (SURVEY §7 design stance).
  Host-side reads inside a transaction see committed-state + own writes.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Optional, Sequence, TypeVar

import numpy as np

from hypergraphdb_tpu.core.errors import TransactionAborted, TransactionConflict
from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.fault import global_faults
from hypergraphdb_tpu.storage.api import HGSortedResultSet, StorageBackend

T = TypeVar("T")

#: process fault registry (singleton contract): the ingest crash drill
#: arms ``tx.commit.pre`` / ``tx.commit.apply`` with InjectedCrash and
#: kills the process at the k-th write commit — one attribute read per
#: commit while disabled
_FAULTS = global_faults()

_TOMBSTONE = object()


class _IncDelta:
    __slots__ = ("added", "removed", "cleared")

    def __init__(self) -> None:
        self.added: set[int] = set()
        self.removed: set[int] = set()
        self.cleared = False

    def add(self, link: int) -> None:
        self.removed.discard(link)
        self.added.add(link)

    def remove(self, link: int) -> None:
        self.added.discard(link)
        self.removed.add(link)

    def clear(self) -> None:
        self.added.clear()
        self.removed.clear()
        self.cleared = True


class _IdxDelta:
    __slots__ = ("added", "removed", "removed_all")

    def __init__(self) -> None:
        self.added: set[int] = set()
        self.removed: set[int] = set()
        self.removed_all = False

    def add(self, v: int) -> None:
        self.removed.discard(v)
        self.added.add(v)

    def remove(self, v: int) -> None:
        self.added.discard(v)
        self.removed.add(v)


class HGTransaction:
    """A single (possibly nested) transaction's buffered state."""

    def __init__(self, mgr: "HGTransactionManager", parent: Optional["HGTransaction"],
                 readonly: bool = False):
        self.mgr = mgr
        self.parent = parent
        self.readonly = readonly
        self.active = True
        # begin-time read snapshot (VBox semantics, transaction/VBox.java:28):
        # every read inside this tx sees the committed state AS OF this
        # version; nested txs share the top-level snapshot
        self.start_version = (
            parent.start_version if parent is not None else mgr._clock
        )
        # cell -> observed version
        self.read_set: dict[tuple, int] = {}
        # write buffers
        self.links: dict[int, Any] = {}            # h -> tuple | _TOMBSTONE
        self.data: dict[int, Any] = {}             # h -> bytes | _TOMBSTONE
        self.inc: dict[int, _IncDelta] = {}        # atom -> delta
        self.idx: dict[tuple[str, bytes], _IdxDelta] = {}
        # actions deferred until (and discarded unless) top-level commit —
        # post-commit event dispatch, mutation counters (the reference fires
        # events synchronously inside the tx; deferring keeps listeners from
        # observing atoms that never commit)
        self.on_commit: list[Callable[[], None]] = []

    # -- read-set tracking ---------------------------------------------------
    def note_read(self, cell: tuple) -> None:
        if self.readonly:
            return
        v = self.mgr.cell_version(cell)
        # snapshot reads return the BEGIN-time value: if the cell already
        # moved past our snapshot, this tx read stale data by design and
        # must fail commit validation — record a version that can never
        # match (the reference's doomed-transaction outcome)
        self.read_set.setdefault(
            cell, v if v <= self.start_version else -1
        )

    def is_empty(self) -> bool:
        return not (self.links or self.data or self.inc or self.idx)

    # -- merge into parent (nested commit) ------------------------------------
    def merge_into(self, p: "HGTransaction") -> None:
        for c, v in self.read_set.items():
            p.read_set.setdefault(c, v)
        p.links.update(self.links)
        p.data.update(self.data)
        for atom, d in self.inc.items():
            pd = p.inc.setdefault(atom, _IncDelta())
            if d.cleared:
                pd.clear()
            for l in d.added:
                pd.add(l)
            for l in d.removed:
                pd.remove(l)
        for key, d in self.idx.items():
            pd = p.idx.setdefault(key, _IdxDelta())
            if d.removed_all:
                pd.added.clear()
                pd.removed.clear()
                pd.removed_all = True
            for v in d.added:
                pd.add(v)
            for v in d.removed:
                pd.remove(v)
        p.on_commit.extend(self.on_commit)


class HGTransactionManager:
    """Owns the commit lock, version clock and per-thread transaction stacks."""

    def __init__(self, backend: StorageBackend, enabled: bool = True):
        self.backend = backend
        self.enabled = enabled
        self._commit_lock = threading.Lock()
        self._versions: dict[tuple, int] = {}
        self._clock = 0
        self._tls = threading.local()
        # MVCC version chains (transaction/VBox.java:28): per cell, an
        # ascending list of (version, pre-image) — "immediately before
        # commit `version`, the committed value was `pre-image`". Captured
        # only while OTHER transactions are active (their snapshots may
        # need the old values) and GC'd up to the oldest active snapshot
        # (ActiveTransactionsRecord.java:33 semantics).
        self._history: dict[tuple, list[tuple[int, Any]]] = {}
        #: id(tx) -> start_version for every live top-level transaction
        self._active: dict[int, int] = {}
        # stats (reference: TxMonitor.java:14 + conflicted/successful counters
        # at HGTransactionManager.java:40-41); mirrored into the owning
        # graph's hgobs registry (tx.* namespace) when `metrics` is set
        self.committed = 0
        self.conflicted = 0
        self.aborted = 0
        self.metrics = None  # utils.metrics.Metrics, attached by the graph

    # -- context ---------------------------------------------------------------
    def _stack(self) -> list[HGTransaction]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[HGTransaction]:
        st = self._stack()
        return st[-1] if st else None

    @contextmanager
    def scoped(self, tx: Optional[HGTransaction]):
        """Join an existing transaction from ANOTHER thread for the dynamic
        extent of the block (parallel query-union workers run child plans
        under the caller's tx). Safe for concurrent *reads*: ``note_read``
        records via a single ``dict.setdefault`` call, atomic under the GIL;
        workers must not write through a shared tx."""
        if tx is None:
            yield
            return
        st = self._stack()
        st.append(tx)
        try:
            yield
        finally:
            st.pop()

    # -- lifecycle --------------------------------------------------------------
    def begin(self, readonly: bool = False) -> HGTransaction:
        tx = HGTransaction(self, self.current(), readonly=readonly)
        if tx.parent is None:
            # snapshot choice + registration must be atomic with commits:
            # outside the lock, a committer could bump the clock and skip
            # pre-image capture between our clock read and registration,
            # silently letting this tx read past its snapshot. The lock
            # also guarantees the chosen version's state is fully applied.
            with self._commit_lock:
                tx.start_version = self._clock
                self._active[id(tx)] = tx.start_version
        self._stack().append(tx)
        return tx

    def abort(self, tx: HGTransaction) -> None:
        st = self._stack()
        if not st or st[-1] is not tx:
            raise TransactionAborted("abort of non-innermost transaction")
        st.pop()
        tx.active = False
        if tx.parent is None:
            self._active.pop(id(tx), None)
        with self._commit_lock:
            # += on a shared counter is load/add/store — concurrent aborts
            # lose counts without the lock (hglint HG402)
            self.aborted += 1
        m = self.metrics
        if m is not None:
            m.incr("tx.aborts")

    def commit(self, tx: HGTransaction) -> None:
        st = self._stack()
        if not st or st[-1] is not tx:
            raise TransactionAborted("commit of non-innermost transaction")
        st.pop()
        tx.active = False
        if tx.parent is not None:
            tx.merge_into(tx.parent)
            return
        try:
            if tx.readonly or tx.is_empty():
                with self._commit_lock:
                    # same torn-increment hazard as `aborted` (hglint HG402);
                    # the write path below already counts under the lock
                    self.committed += 1
                m = self.metrics
                if m is not None:
                    m.incr("tx.commits")
                self._run_commit_hooks(tx)
                return
            if _FAULTS.enabled:
                # registered crash point: dying HERE loses this commit
                # entirely (nothing staged) — replay must be a no-op
                _FAULTS.check("tx.commit.pre")
            with self._commit_lock:
                for cell, observed in tx.read_set.items():
                    if self._versions.get(cell, 0) != observed:
                        self.conflicted += 1
                        m = self.metrics
                        if m is not None:
                            m.incr("tx.conflicts")
                        raise TransactionConflict(f"cell {cell!r} changed")
                self._clock += 1
                v = self._clock
                self._capture_history(tx, v)
                if _FAULTS.enabled:
                    # registered crash point: dying mid-commit before the
                    # write-through — the WAL sees no (or a torn) batch
                    # and must discard it on replay
                    _FAULTS.check("tx.commit.apply")
                self._apply(tx)
                for h in tx.links:
                    self._versions[("link", h)] = v
                for h in tx.data:
                    self._versions[("data", h)] = v
                for atom in tx.inc:
                    self._versions[("inc", atom)] = v
                for key in tx.idx:
                    self._versions[("idx",) + key] = v
                self.committed += 1
                # mirror bumped ADJACENT to the legacy counter: an
                # exception later in the commit (e.g. _gc_history) must
                # not leave the two surfaces permanently disagreeing
                m = self.metrics
                if m is not None:
                    m.incr("tx.commits")
                self._gc_history()
        finally:
            self._active.pop(id(tx), None)
        self._run_commit_hooks(tx)

    # -- MVCC history -----------------------------------------------------------
    def _capture_history(self, tx: HGTransaction, v: int) -> None:
        """Record pre-images of every cell this commit overwrites, IF any
        other live transaction's snapshot might still need them. Called
        under the commit lock, before ``_apply``."""
        # fast path: no OTHER active transaction → nobody can read the old
        # values, skip all capture work (the single-threaded common case).
        # NB: begin()/abort() mutate _active without the commit lock, so
        # iterate over a point-in-time copy.
        if not any(tid != id(tx) for tid in list(self._active)):
            return
        b = self.backend
        H = self._history
        for h in tx.links:
            H.setdefault(("link", h), []).append((v, b.get_link(h)))
        for h in tx.data:
            H.setdefault(("data", h), []).append((v, b.get_data(h)))
        for atom, d in tx.inc.items():
            if d.cleared:
                old = ("full", b.get_incidence_set(atom).array().copy())
            else:
                old = ("delta", set(d.added), set(d.removed))
            H.setdefault(("inc", atom), []).append((v, old))
        for (name, key), d in tx.idx.items():
            index = b.get_index(name, create=True)
            if d.removed_all:
                old = ("full", index.find(key).array().copy())
            else:
                old = ("delta", set(d.added), set(d.removed))
            H.setdefault(("idx", name, key), []).append((v, old))

    def _gc_history(self) -> None:
        """Drop pre-images no live snapshot can reach (called under the
        commit lock)."""
        if not self._history:
            return
        floor = min(list(self._active.values()) or [self._clock])
        dead = []
        for cell, entries in self._history.items():
            keep = [e for e in entries if e[0] > floor]
            if keep:
                self._history[cell] = keep
            else:
                dead.append(cell)
        for cell in dead:
            del self._history[cell]

    def _value_at(self, cell: tuple, sv: int, current: Any) -> Any:
        """Reconstruct a link/data cell's value at snapshot ``sv``: the
        pre-image of the FIRST commit after sv (chains are ascending).

        Callers MUST read ``current`` from the backend BEFORE consulting
        the history: capture happens before apply under the commit lock,
        so a backend read that raced a commit is always correctable by the
        (already-visible) pre-image — the reverse order has a window where
        the history looks empty but the backend already moved."""
        for ver, old in self._history.get(cell, ()):
            if ver > sv:
                return old
        return current

    def link_at(self, h: int, sv: int):
        current = self.backend.get_link(h)
        return self._value_at(("link", h), sv, current)

    def data_at(self, h: int, sv: int):
        current = self.backend.get_data(h)
        return self._value_at(("data", h), sv, current)

    def _set_at(self, cell: tuple, sv: int, current: set) -> set:
        """Reconstruct a set cell (incidence/index members) at ``sv`` by
        undoing newer commits newest-first."""
        entries = self._history.get(cell)
        if not entries:
            return current
        vals = current
        for ver, old in reversed(entries):
            if ver <= sv:
                break
            if old[0] == "full":
                vals = set(old[1].tolist())
            else:
                _, added, removed = old
                vals = (vals - added) | removed
        return vals

    def inc_at(self, atom: int, sv: int) -> np.ndarray:
        arr = self.backend.get_incidence_set(atom).array()
        # fast path: no history for this cell → `arr` already is the value
        # at `sv`. The membership check runs AFTER the backend read:
        # capture-before-apply means a commit that raced the read has
        # already published its pre-image, so an empty chain here proves
        # the read didn't straddle an apply. NB the array may be SHARED
        # with the backend (memstore memoizes its snapshot and rebuilds on
        # mutation; native copies out) — immutable once returned, so
        # callers may cache/freeze it but must never write through it.
        if ("inc", atom) not in self._history:
            return np.asarray(arr, dtype=np.int64)
        vals = self._set_at(("inc", atom), sv, set(arr.tolist()))
        return np.asarray(sorted(vals), dtype=np.int64)

    def idx_at(self, name: str, key: bytes, sv: int) -> np.ndarray:
        idx = self.backend.get_index(name, create=True)
        cur = set(idx.find(key).array().tolist())
        vals = self._set_at(("idx", name, key), sv, cur)
        return np.asarray(sorted(vals), dtype=np.int64)

    def idx_keys_changed_since(self, name: str, sv: int) -> list[bytes]:
        """Index keys whose membership moved after ``sv`` (range/scan reads
        under a snapshot patch exactly these)."""
        out = []
        # point-in-time copy: committers mutate _history under the commit
        # lock, but this runs on reader threads without it
        for cell, entries in list(self._history.items()):
            if cell[0] == "idx" and cell[1] == name and entries and entries[-1][0] > sv:
                out.append(cell[2])
        return out

    def cell_changed_since(self, cell: tuple, sv: int) -> bool:
        return self._versions.get(cell, 0) > sv

    @staticmethod
    def _run_commit_hooks(tx: HGTransaction) -> None:
        for hook in tx.on_commit:
            hook()

    def cell_version(self, cell: tuple) -> int:
        return self._versions.get(cell, 0)

    @property
    def version(self) -> int:
        return self._clock

    def _apply(self, tx: HGTransaction) -> None:
        b = self.backend
        # bracket the whole application in a backend commit batch so durable
        # backends replay it atomically after a crash (no half-applied
        # commits — the WAL analogue of the reference's BDB txn commit)
        b.commit_batch_begin()
        try:
            self._apply_ops(tx, b)
        except BaseException:
            # an error mid-apply must NOT seal the batch: sealing would make
            # the half-applied commit replay as atomic. Abort discards it.
            b.commit_batch_abort()
            raise
        else:
            b.commit_batch_end()

    @staticmethod
    def _apply_ops(tx: HGTransaction, b: StorageBackend) -> None:
        for h, v in tx.links.items():
            if v is _TOMBSTONE:
                b.remove_link(h)
            else:
                b.store_link(h, v)
        for h, v in tx.data.items():
            if v is _TOMBSTONE:
                b.remove_data(h)
            else:
                b.store_data(h, v)
        for atom, d in tx.inc.items():
            if d.cleared:
                b.remove_incidence_set(atom)
            for l in sorted(d.removed):
                b.remove_incidence_link(atom, l)
            for l in sorted(d.added):
                b.add_incidence_link(atom, l)
        for (name, key), d in tx.idx.items():
            index = b.get_index(name, create=True)
            if d.removed_all:
                index.remove_all_entries(key)
            for v in sorted(d.removed):
                index.remove_entry(key, v)
            for v in sorted(d.added):
                index.add_entry(key, v)

    # -- the retry loop (HGTransactionManager.transact :356) --------------------
    def transact(self, fn: Callable[[], T], retries: int = 16,
                 readonly: bool = False) -> T:
        if not self.enabled:
            return fn()
        last: Optional[Exception] = None
        for _ in range(retries):
            tx = self.begin(readonly=readonly)
            try:
                result = fn()
            except BaseException:
                if tx.active:
                    self.abort(tx)
                raise
            try:
                self.commit(tx)
                return result
            except TransactionConflict as e:
                last = e
                continue
        raise TransactionConflict(f"giving up after {retries} retries") from last

    def ensure_transaction(self, fn: Callable[[], T], readonly: bool = False) -> T:
        """Run fn inside the current transaction if one exists, else a new one
        (``HGTransactionManager.ensureTransaction`` ``:296``)."""
        if not self.enabled or self.current() is not None:
            return fn()
        return self.transact(fn, readonly=readonly)
