"""Pure-Python fallback for the ``sortedcontainers`` API surface we use.

``storage/memstore.py`` keeps its sorted key/handle structures in
``SortedList`` / ``SortedDict``. The real package is a soft dependency: when
it is absent (slim CI images, the growth container), these bisect-backed
drop-ins provide the exact subset of the API the storage layer touches —
``SortedList.add/remove/__contains__/__iter__/__len__`` and
``SortedDict.get/__getitem__/__setitem__/__delitem__/pop/irange/keys/
__iter__/__len__``.

Asymptotics differ (``list.insert`` is O(n) vs sortedcontainers' O(√n))
but the hot read paths are served from cached numpy snapshots and immutable
CSR device snapshots, so insert cost on the host write path is acceptable
for the fallback.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Any, Iterator, Optional


class SortedList:
    """Sorted sequence with O(log n) membership and O(n) insert/remove."""

    __slots__ = ("_items",)

    def __init__(self, iterable=()):
        self._items = sorted(iterable)

    def add(self, value) -> None:
        insort(self._items, value)

    def remove(self, value) -> None:
        i = bisect_left(self._items, value)
        if i == len(self._items) or self._items[i] != value:
            raise ValueError(f"{value!r} not in list")
        del self._items[i]

    def discard(self, value) -> None:
        try:
            self.remove(value)
        except ValueError:
            pass

    def __contains__(self, value) -> bool:
        i = bisect_left(self._items, value)
        return i < len(self._items) and self._items[i] == value

    def __iter__(self) -> Iterator:
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __getitem__(self, index):
        return self._items[index]

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SortedList({self._items!r})"


class SortedDict:
    """Dict iterated in key order, with ``irange`` range scans.

    Keys are kept in a parallel sorted list; the list is rebuilt lazily
    after deletions (tombstone-free, amortized via a dirty flag) and
    maintained incrementally on inserts.
    """

    __slots__ = ("_data", "_keys", "_dirty")

    def __init__(self, *args, **kwargs):
        self._data = dict(*args, **kwargs)
        self._keys = sorted(self._data)
        self._dirty = False

    # -- key list maintenance -------------------------------------------------
    def _klist(self) -> list:
        if self._dirty:
            self._keys = sorted(self._data)
            self._dirty = False
        return self._keys

    # -- mapping protocol -----------------------------------------------------
    def __getitem__(self, key):
        return self._data[key]

    def __setitem__(self, key, value) -> None:
        if key not in self._data:
            if self._dirty:
                self._data[key] = value
                return  # key list rebuilds on next read
            insort(self._keys, key)
        self._data[key] = value

    def __delitem__(self, key) -> None:
        del self._data[key]
        self._dirty = True

    def __contains__(self, key) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self) -> Iterator:
        return iter(self._klist())

    def get(self, key, default=None):
        return self._data.get(key, default)

    def pop(self, key, *default):
        if key in self._data:
            self._dirty = True
        return self._data.pop(key, *default)

    def setdefault(self, key, default=None):
        if key not in self._data:
            self[key] = default
        return self._data[key]

    def keys(self):
        return list(self._klist())

    def items(self):
        return [(k, self._data[k]) for k in self._klist()]

    def values(self):
        return [self._data[k] for k in self._klist()]

    # -- range scans ----------------------------------------------------------
    def irange(
        self,
        minimum: Optional[Any] = None,
        maximum: Optional[Any] = None,
        inclusive=(True, True),
        reverse: bool = False,
    ) -> Iterator:
        """Iterate keys in ``[minimum, maximum]`` honoring the per-bound
        inclusivity pair — the sortedcontainers signature."""
        keys = self._klist()
        lo_inc, hi_inc = inclusive
        start = 0
        if minimum is not None:
            start = (
                bisect_left(keys, minimum)
                if lo_inc
                else bisect_right(keys, minimum)
            )
        end = len(keys)
        if maximum is not None:
            end = (
                bisect_right(keys, maximum)
                if hi_inc
                else bisect_left(keys, maximum)
            )
        sel = keys[start:end]
        return iter(reversed(sel)) if reverse else iter(sel)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SortedDict({self._data!r})"
