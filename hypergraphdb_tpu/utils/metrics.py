"""Structured metrics — counters, gauges, timers per graph instance.

The reference's observability is a handful of ad-hoc counters (``HGStats``
atom access counts ``atom/HGStats.java:20``, ``TxMonitor`` tx bookkeeping,
``HGIndexStats`` planner estimates) with no unified surface. SURVEY §5
asks for structured metrics from day one: ingest rate, frontier sizes,
kernel timings, query latencies — one registry, one ``snapshot()`` dump.

Thread-safe; cheap enough to stay on in production (a dict update and a
perf_counter per event)."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        # name -> (count, total_seconds, max_seconds)
        self.timings: dict[str, tuple[int, float, float]] = {}

    # -- primitives ----------------------------------------------------------
    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            cnt, tot, mx = self.timings.get(name, (0, 0.0, 0.0))
            self.timings[name] = (cnt + 1, tot + seconds, max(mx, seconds))

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # -- reporting -----------------------------------------------------------
    def snapshot(self) -> dict:
        """One structured dump: counters, gauges, and per-timer
        count/total/mean/max (seconds)."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timings": {
                    k: {
                        "count": c,
                        "total_s": t,
                        "mean_s": (t / c if c else 0.0),
                        "max_s": m,
                    }
                    for k, (c, t, m) in self.timings.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.timings.clear()


#: process-wide registry for code without a graph in reach (kernel wrappers)
global_metrics = Metrics()
