"""Structured metrics — a thin façade over the hgobs registry.

The reference's observability is a handful of ad-hoc counters (``HGStats``
atom access counts ``atom/HGStats.java:20``, ``TxMonitor`` tx bookkeeping,
``HGIndexStats`` planner estimates) with no unified surface — and until
hgobs, this repro had TWO disjoint surfaces of its own (this module's
timing triples vs ``serve.stats``'s latency ring). ``Metrics`` keeps its
day-one API (``incr``/``gauge``/``observe``/``timer``/``snapshot``) but
every instrument now lives in an :class:`hypergraphdb_tpu.obs.Registry`:
timers are shared log-bucketed histograms, and the whole surface renders
to Prometheus via ``obs.export.prometheus_text(metrics.registry)``.

Thread-safe; cheap enough to stay on in production (a locked int bump
or one histogram insert per event)."""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Optional

from hypergraphdb_tpu.obs.registry import Registry, default_registry


class Metrics:
    """Counters / gauges / timers for one graph instance (or the process,
    via :data:`global_metrics`), all backed by ``self.registry``."""

    def __init__(self, registry: Optional[Registry] = None) -> None:
        self.registry = registry if registry is not None else Registry()
        # hot-path memo: name -> instrument, so repeat events touch only
        # the instrument's own lock, not the registry's get-or-create
        # (plain dict ops are GIL-atomic; a racing miss just resolves the
        # same instrument twice)
        self._memo: dict = {}

    # -- primitives ----------------------------------------------------------
    # memo keys carry the kind so a kind-mismatched name still surfaces
    # the registry's ValueError instead of hitting a cached wrong type
    def incr(self, name: str, n: int = 1) -> None:
        m = self._memo.get(("c", name))
        if m is None:
            m = self._memo[("c", name)] = self.registry.counter(name)
        m.inc(n)

    def gauge(self, name: str, value: float) -> None:
        m = self._memo.get(("g", name))
        if m is None:
            m = self._memo[("g", name)] = self.registry.gauge(name)
        m.set(value)

    def observe(self, name: str, seconds: float) -> None:
        m = self._memo.get(("h", name))
        if m is None:
            m = self._memo[("h", name)] = self.registry.histogram(name)
        m.observe(seconds)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # -- compat views (the pre-hgobs public attributes) ----------------------
    @property
    def counters(self) -> dict:
        return {m.name: m.value for m in self.registry.instruments()
                if m.kind == "counter"}

    @property
    def gauges(self) -> dict:
        return {m.name: m.value for m in self.registry.instruments()
                if m.kind == "gauge"}

    @property
    def timings(self) -> dict:
        """name -> (count, total_seconds, max_seconds) — the legacy triple
        view over the shared histograms (each triple read under one
        lock, so it never tears against a concurrent observe)."""
        out = {}
        for m in self.registry.instruments():
            if m.kind == "histogram":
                s = m.summary()
                out[m.name] = (s["count"], s["total"], s["max"])
        return out

    # -- reporting -----------------------------------------------------------
    def snapshot(self) -> dict:
        """One structured dump: counters, gauges, and per-timer
        count/total/mean/max (seconds) — shape unchanged from day one."""
        counters, gauges, timings = {}, {}, {}
        for m in self.registry.instruments():
            if m.kind == "counter":
                counters[m.name] = m.value
            elif m.kind == "gauge":
                gauges[m.name] = m.value
            else:
                s = m.summary()  # one lock: the triple can't tear
                timings[m.name] = {
                    "count": s["count"],
                    "total_s": s["total"],
                    "mean_s": s["mean"],
                    "max_s": s["max"],
                }
        return {"counters": counters, "gauges": gauges, "timings": timings}

    def reset(self) -> None:
        """Zero every instrument THIS façade created (every event routes
        through the memo, so that is all of them) — on a shared registry,
        instruments other façades registered are left alone. Iterates a
        snapshot: a concurrent first-time recording inserting into the
        memo must not blow up the reset loop."""
        for m in list(self._memo.values()):
            m.reset()


#: process-wide registry for code without a graph in reach (kernel wrappers)
global_metrics = Metrics(registry=default_registry())
