"""Hot host-side atom→type column — the typed-incidence annotation.

The reference's bdb-native extension annotates incidence-index entries with
(type, position) so ``And(Incident, AtomType)`` is answered from the
incidence index alone, never loading candidate links
(``storage/bdb-native/.../incidence/TypeAndPositionIncidenceAnnotator.java``).
The TPU-native equivalent is columnar instead of per-entry: a dense int32
handle→type array kept hot on the HOST, so an incidence row filters by one
vectorized gather + compare (``query/compiler.TypedIncidencePlan``) instead
of one store record read per candidate link.

Maintenance is post-commit event driven, so the column only ever reflects
COMMITTED state; ``-1`` means "not observed yet" and falls back to a store
read — staleness can cost time, never correctness.
"""

from __future__ import annotations

import threading

import numpy as np

from hypergraphdb_tpu.core import events as ev


class TypeColumn:
    """Dense committed handle→type-handle column with store fallback."""

    def __init__(self, graph) -> None:
        self.graph = graph
        self._lock = threading.Lock()
        self._col = np.full(1024, -1, dtype=np.int32)
        graph.events.add_listener(ev.HGAtomAddedEvent, self._on_changed)
        graph.events.add_listener(ev.HGAtomReplacedEvent, self._on_changed)
        graph.events.add_listener(ev.HGAtomRemovedEvent, self._on_removed)
        self._build()

    def close(self) -> None:
        g = self.graph
        g.events.remove_listener(ev.HGAtomAddedEvent, self._on_changed)
        g.events.remove_listener(ev.HGAtomReplacedEvent, self._on_changed)
        g.events.remove_listener(ev.HGAtomRemovedEvent, self._on_removed)

    # -- build + maintenance ---------------------------------------------------
    def _build(self) -> None:
        """One vectorized committed-store scan (the same bulk_links fast
        path CSR packing uses; record layout = (type, value, flags,
        *targets), see core/graph.py)."""
        g = self.graph
        with g.txman._commit_lock:  # consistent extraction, same as packing
            ids, offsets, flat = g.backend.bulk_links()
            peek = max(
                int(getattr(g.handles, "peek", 0)), int(g.backend.max_handle())
            )
        ids = np.asarray(ids, dtype=np.int64)
        offsets = np.asarray(offsets, dtype=np.int64)
        flat = np.asarray(flat, dtype=np.int64)
        with self._lock:
            self._grow_locked(peek)
            if len(ids):
                # fill ONLY still-unknown slots: the listeners registered
                # before this scan, so a commit landing between the locked
                # extraction and this write may already have recorded a
                # NEWER type — overwriting it with the scanned (older)
                # value would leave a permanently stale non-(-1) entry
                # (review r5 finding 2)
                vals = flat[offsets[:-1]].astype(np.int32)
                unknown = self._col[ids] == -1
                self._col[ids[unknown]] = vals[unknown]

    def _grow_locked(self, n: int) -> None:
        # the `_locked` suffix documents the contract hglint enforces:
        # every caller already holds self._lock
        if n < len(self._col):
            return
        new = np.full(max(n + 1024, len(self._col) * 2), -1, dtype=np.int32)
        new[: len(self._col)] = self._col
        self._col = new

    def _on_changed(self, g, event) -> None:
        h = int(event.handle)
        rec = g.store.get_link(h)
        with self._lock:
            self._grow_locked(h)
            self._col[h] = int(rec[0]) if rec is not None else -1

    def _on_removed(self, g, event) -> None:
        h = int(event.handle)
        with self._lock:
            self._grow_locked(h)
            self._col[h] = -1

    # -- reads -----------------------------------------------------------------
    def types_of(self, handles: np.ndarray) -> np.ndarray:
        """Vectorized handle→type gather; unknown entries (-1) re-check the
        store (and backfill), so results match committed state exactly."""
        handles = np.asarray(handles, dtype=np.int64)
        with self._lock:
            col = self._col  # snapshot reference; writers replace, not mutate len
        out = np.full(len(handles), -1, dtype=np.int32)
        in_range = handles < len(col)
        out[in_range] = col[handles[in_range]]
        unknown = np.nonzero(out == -1)[0]
        if len(unknown):
            g = self.graph
            for i in unknown.tolist():
                rec = g.store.get_link(int(handles[i]))
                if rec is not None:
                    out[i] = int(rec[0])
                    self._on_changed(g, ev.HGAtomAddedEvent(int(handles[i]), None))
        return out
