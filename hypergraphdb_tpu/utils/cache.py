"""Small host-side caches.

The reference's caching layer (``cache/WeakRefAtomCache.java:58``,
``cache/LRUCache.java:34``) manages JVM weak/phantom references and
GC-pressure eviction. CPython's refcounting removes most of that machinery;
what remains useful is a bounded LRU for deserialized atoms and incidence
snapshots.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Generic, Hashable, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

_MISSING = object()


class LRUCache(Generic[K, V]):
    __slots__ = ("_d", "capacity", "hits", "misses")

    def __init__(self, capacity: int = 1 << 16):
        self._d: OrderedDict[K, V] = OrderedDict()
        self.capacity = capacity
        self.hits = 0
        self.misses = 0

    def get(self, key: K, default: Any = None) -> Optional[V]:
        v = self._d.get(key, _MISSING)
        if v is _MISSING:
            self.misses += 1
            return default
        self._d.move_to_end(key)
        self.hits += 1
        return v

    def put(self, key: K, value: V) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def invalidate(self, key: K) -> None:
        self._d.pop(key, None)

    def clear(self) -> None:
        self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key: K) -> bool:
        return key in self._d
