"""Small host-side caches.

The reference's caching layer (``cache/WeakRefAtomCache.java:58``,
``cache/LRUCache.java:34``) manages JVM weak/phantom references and
GC-pressure eviction. CPython's refcounting removes most of that machinery;
what remains useful is a bounded LRU for deserialized atoms and incidence
snapshots.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Generic, Hashable, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

_MISSING = object()


class LRUCache(Generic[K, V]):
    """Bounded LRU, thread-safe: shared across reader threads and the
    memory-watcher daemon (an unguarded ``move_to_end`` would KeyError if
    another thread evicted/cleared the key mid-``get``)."""

    __slots__ = ("_d", "_lock", "capacity", "hits", "misses")

    def __init__(self, capacity: int = 1 << 16):
        import threading

        self._d: OrderedDict[K, V] = OrderedDict()
        self._lock = threading.Lock()
        self.capacity = capacity
        self.hits = 0
        self.misses = 0

    def get(self, key: K, default: Any = None) -> Optional[V]:
        with self._lock:
            v = self._d.get(key, _MISSING)
            if v is _MISSING:
                self.misses += 1
                return default
            self._d.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key: K, value: V) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def invalidate(self, key: K) -> None:
        with self._lock:
            self._d.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key: K) -> bool:
        return key in self._d


class MemoryWarningSystem:
    """RSS-threshold cache eviction — the ``util/MemoryWarningSystem``
    analogue (the reference listens to JVM memory-pool thresholds and
    shrinks caches, ``cache/ColdAtoms.java:32-52``, ``LRUCache.java:227``).

    Listeners are shrink callbacks; ``check_now()`` reads the process RSS
    from ``/proc/self/statm`` and fires them when over the threshold. A
    daemon thread polls on an interval; tests call ``check_now`` directly.
    """

    def __init__(self, threshold_bytes: int, interval_s: float = 5.0):
        import threading

        self.threshold_bytes = int(threshold_bytes)
        self.interval_s = interval_s
        self._listeners: list = []
        self._stop = threading.Event()
        self._thread = None
        self.triggered = 0

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    @staticmethod
    def rss_bytes() -> int:
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            import os

            return pages * os.sysconf("SC_PAGE_SIZE")
        except Exception:  # pragma: no cover - non-linux
            # no reliable CURRENT-rss source without psutil (ru_maxrss is a
            # lifetime peak — and platform-dependent units — which would
            # latch the watcher permanently on once tripped): stay inert
            return 0

    def check_now(self) -> bool:
        if self.threshold_bytes <= 0:
            return False
        if self.rss_bytes() <= self.threshold_bytes:
            return False
        self.triggered += 1
        for fn in list(self._listeners):
            try:
                fn()
            except Exception:  # pragma: no cover - listener bug
                import logging

                logging.getLogger("hypergraphdb_tpu.cache").warning(
                    "memory-warning listener failed", exc_info=True
                )
        return True

    def start(self) -> None:
        import threading

        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.check_now()
                except Exception:  # the watch must outlive a bad sweep
                    import logging

                    logging.getLogger("hypergraphdb_tpu.cache").warning(
                        "memory watch sweep failed", exc_info=True
                    )

        self._thread = threading.Thread(
            target=loop, name="hgdb-memwatch", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
