"""Order-preserving byte encodings for index keys.

The reference's index sort order comes from each primitive type being a
``ByteArrayConverter`` + comparator (``type/HGPrimitiveType.java:28``); every
index compares raw bytes with a type-supplied comparator. The TPU-native
design strengthens that contract: every primitive type encodes to bytes whose
**plain lexicographic (memcmp) order equals the value order**. That one
invariant buys three things:

- host indices need no per-type comparators (memcmp everywhere),
- the C++ native store can sort/search without calling back into Python,
- device-side sort keys are derivable (the first 8 bytes of a key form an
  order-preserving ``uint64`` rank usable in jnp sorts).
"""

from __future__ import annotations

import struct

# --- int64: flip sign bit, big-endian --------------------------------------


def encode_int(v: int) -> bytes:
    return struct.pack(">Q", (v + (1 << 63)) & ((1 << 64) - 1))


def decode_int(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - (1 << 63)


# --- float64: IEEE 754 total-order trick ------------------------------------
# For non-negative floats, flipping the sign bit gives ascending order; for
# negative floats, flipping all bits does. Standard order-preserving encoding.


def encode_float(v: float) -> bytes:
    bits = struct.unpack(">Q", struct.pack(">d", v))[0]
    if bits & (1 << 63):
        bits = ~bits & ((1 << 64) - 1)
    else:
        bits |= 1 << 63
    return struct.pack(">Q", bits)


def decode_float(b: bytes) -> float:
    bits = struct.unpack(">Q", b)[0]
    if bits & (1 << 63):
        bits &= ~(1 << 63) & ((1 << 64) - 1)
    else:
        bits = ~bits & ((1 << 64) - 1)
    return struct.unpack(">d", struct.pack(">Q", bits))[0]


# --- strings: UTF-8 (lexicographic byte order == codepoint order) -----------


def encode_str(v: str) -> bytes:
    return v.encode("utf-8")


def decode_str(b: bytes) -> str:
    return b.decode("utf-8")


# --- bool -------------------------------------------------------------------


def encode_bool(v: bool) -> bytes:
    return b"\x01" if v else b"\x00"


def decode_bool(b: bytes) -> bool:
    return b != b"\x00"


def rank64(key: bytes) -> int:
    """First 8 bytes of a key as a big-endian unsigned rank.

    Order-preserving coarse rank for device-side sort keys: if
    ``rank64(a) < rank64(b)`` then ``a < b``; ties need host fallback.
    """
    b = key[:8].ljust(8, b"\x00")
    return struct.unpack(">Q", b)[0]


def rank128(key: bytes) -> tuple[int, int]:
    """First 16 bytes of a key as TWO big-endian unsigned rank words —
    ``(rank64(key[:8]), rank64(key[8:16]))``, compared lexicographically.

    The hgindex tie-break pair: zero-padding is order-preserving over
    NUL-free payloads, and INJECTIVE for payloads that fit the 16 bytes
    entirely — for those columns rank order IS key order and the device
    window needs no host tie service (``storage/value_index``'s
    ``device_exact`` contract). Keys sharing their first 16 bytes still
    tie; ``rank_ambiguous`` names exactly when.
    """
    return rank64(key), rank64(key[8:16])


def rank_ambiguous(payload: bytes) -> bool:
    """True when ``payload``'s 128-bit rank pair is NOT a faithful stand-
    in for the full key: longer than 16 bytes (the pair is a proper
    prefix) or containing NUL among the first 16 (zero-padding collides
    with a real ``\\x00`` byte, breaking injectivity AND strict order
    against bounds that are its prefix). Fixed-width encodings (int /
    float / bool / timestamp payloads, exactly 8 NUL-admitting bytes)
    never consult this — their single rank word is already exact by
    construction."""
    return len(payload) > 16 or b"\x00" in payload[:16]
