"""Multi-host initialization: the DCN control-plane glue.

SURVEY §2.5/§5: scaling beyond one host uses ``jax.distributed`` for the
device plane (XLA collectives ride ICI within a slice and DCN across
hosts) and the ``peer`` package's TCP transport for the host-side service
plane (replication, remote query). This module owns the boilerplate:

    from hypergraphdb_tpu.parallel import multihost
    multihost.initialize(coordinator="10.0.0.1:8476",
                         num_processes=4, process_id=int(os.environ["RANK"]))
    mesh = multihost.global_mesh()          # all devices across hosts
    sdev = ShardedSnapshot.from_host(snap, mesh)

Single-host (or test) environments skip ``initialize`` and
``global_mesh()`` degrades to the local-device mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_initialized = False


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host JAX cluster (``jax.distributed.initialize``).

    With no arguments, defers to environment auto-detection (TPU pods
    populate the coordinator variables). Safe to call once per process,
    BEFORE any device access."""
    global _initialized
    if _initialized:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def global_mesh(axis: str = "shard"):
    """One-axis mesh over every device in the cluster (local ones when not
    distributed) — the CSR shard axis used by ``parallel.sharded``."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis,))


def local_process_info() -> dict:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
