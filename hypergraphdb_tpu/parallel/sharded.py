"""Multi-chip execution: CSR snapshot + frontier state sharded over a Mesh.

The reference scales out with Hazelcast-partitioned storage and XMPP peers
(`storage/hazelstore/`, `p2p/` — SURVEY §2.5); computation never leaves one
JVM thread pool. The TPU-native replacement is SPMD over a device mesh.

Round-2 design (fixing VERDICT r1 Weak #2 — the round-1 plane replicated all
per-atom state and moved (K, N) int8 allreduces per hop):

- **Row partitioning**: the id space [0, N] is split into ``n_dev``
  contiguous ranges. Each device owns its range's slice of every per-atom
  column AND of the frontier/visited/levels state — per-device BFS state is
  O(K·N/n_dev) instead of O(K·N).
- **Edges live with their destination**: each COO relation is partitioned by
  the owner of its *destination* id, destinations rewritten to local
  coordinates at pack time. A hop's scatter is therefore purely local.
- **Only packed bitmaps cross ICI**: per hop, each device all-gathers the
  bit-packed (K, N/32/n_dev) frontier words (atom→link), scatters its local
  edge slice, packs, all-gathers link activations (link→target), scatters
  again. Total ICI bytes per hop = 2·K·N/8 — at config-4 scale (K=256
  blocks, N=10M) that is ~160 MB/hop, vs ~20 GB/hop for the round-1 design.
- **Candidate parallelism** for conjunctive pattern match is unchanged: the
  by-type candidate array shards across devices, each device probes the
  (replicated, small) anchor rows via vectorized zig-zag membership.

Everything is ``jax.shard_map`` over an explicit ``Mesh`` so XLA inserts the
collectives; no NCCL/MPI translation (SURVEY §2.5 mapping).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from types import MappingProxyType

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # newer jax exports it at top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental namespace only
    from jax.experimental.shard_map import shard_map

# Replication checking needs lax.pcast so bitfrontier._scatter_relation can
# cast its scan-carry init to axis-varying; on jax builds without pcast the
# checker would reject that carry, so disable it (the workaround jax itself
# suggests). Keyed on the SAME probe as the pcast call site — gating it on
# where shard_map lives would leave a version window with checking on but
# no cast available.
_SHARD_MAP_KW = (
    MappingProxyType({}) if hasattr(jax.lax, "pcast")
    else MappingProxyType({"check_rep": False})
)

from hypergraphdb_tpu import verify as hgverify
from hypergraphdb_tpu.ops.bitfrontier import (
    WORD,
    _scatter_relation,
    pack_bits,
    unpack_bits,
)
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
from hypergraphdb_tpu.ops.setops import SENTINEL, _bucket, member_mask, pad_sorted
from hypergraphdb_tpu.storage.partitioned import PartitionMap

#: name of the device-mesh axis rows/edges/candidates are sharded over
AXIS = "shard"


def make_mesh(devices=None, axis: str = AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def _partition_by_owner(
    src: np.ndarray, dst: np.ndarray, n_dev: int, n_loc: int,
    n_dummy: int, chunk: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Partition a COO relation by ``owner(dst) = dst // n_loc``; rewrite dst
    to local ids; pad every partition to one common chunk-aligned length.

    Pad entries use ``src = n_dummy`` (a bit that is never set — the dummy
    row) and ``dst_local = 0`` (scatter-max of False: no-op)."""
    owner = dst // n_loc
    order = np.argsort(owner, kind="stable")
    src_s, dst_s = src[order], dst[order]
    counts = np.bincount(owner[order], minlength=n_dev)
    e_loc = max(int(counts.max()), 1)
    e_loc = -(-e_loc // chunk) * chunk
    src_out = np.full((n_dev, e_loc), n_dummy, dtype=np.int32)
    dst_out = np.zeros((n_dev, e_loc), dtype=np.int32)
    pos = 0
    for d in range(n_dev):
        c = int(counts[d])
        src_out[d, :c] = src_s[pos : pos + c]
        dst_out[d, :c] = dst_s[pos : pos + c] - d * n_loc
        pos += c
    return src_out.reshape(-1), dst_out.reshape(-1)


@dataclass
class ShardedSnapshot:
    """Row + edge sharded twin of :class:`CSRSnapshot`.

    Per-atom columns are sharded over padded row ranges of size ``n_loc``
    (a multiple of 128 so packed words align); COO edges are co-located with
    their destination row's owner, destinations in local coordinates.
    """

    mesh: Mesh
    num_atoms: int         # N: real id space (dummy row is N)
    n_loc: int             # per-device row-range size (multiple of 128)
    edge_chunk: int        # static scan slice for the scatter loop
    inc_src: jax.Array     # (n_dev*E_inc_loc,) sharded — global source atom
    inc_dst: jax.Array     # (n_dev*E_inc_loc,) sharded — LOCAL dest link
    tgt_src: jax.Array     # (n_dev*E_tgt_loc,) sharded — global source link
    tgt_dst: jax.Array     # (n_dev*E_tgt_loc,) sharded — LOCAL dest atom
    type_of: jax.Array        # (n_dev*n_loc,) sharded
    is_link: jax.Array        # (n_dev*n_loc,) sharded
    arity: jax.Array          # (n_dev*n_loc,) sharded
    value_rank_hi: jax.Array  # (n_dev*n_loc,) sharded uint32
    value_rank_lo: jax.Array  # (n_dev*n_loc,) sharded uint32

    @property
    def n_dev(self) -> int:
        return self.mesh.devices.size

    @property
    def partition_map(self) -> PartitionMap:
        """The gid-range owner map this snapshot's rows follow — derived,
        not stored (the storage layer owns the map type; the layout here
        is ``for_mesh``'s by construction)."""
        return PartitionMap(n_parts=int(self.mesh.devices.size),
                            part_size=self.n_loc,
                            capacity=self.num_atoms + 1)

    @staticmethod
    def from_host(
        snap: CSRSnapshot, mesh: Mesh, edge_chunk: int = 1 << 16
    ) -> "ShardedSnapshot":
        n_dev = int(mesh.devices.size)
        N = snap.num_atoms
        # the row layout IS the storage partition map: one owner per
        # contiguous gid range, 128-aligned (PartitionMap.for_mesh is the
        # single source of the split arithmetic)
        n_loc = PartitionMap.for_mesh(N + 1, n_dev).part_size
        n_pad = n_dev * n_loc
        shard = NamedSharding(mesh, P(AXIS))

        def put(a):
            return jax.device_put(jnp.asarray(a), shard)

        def pad_rows(a, fill):
            out = np.full(n_pad, fill, dtype=a.dtype)
            out[: len(a)] = a
            return out

        e_inc, e_tgt = snap.n_edges_inc, snap.n_edges_tgt
        inc_src, inc_dst = _partition_by_owner(
            snap.inc_src[:e_inc], snap.inc_links[:e_inc],
            n_dev, n_loc, N, edge_chunk,
        )
        tgt_src, tgt_dst = _partition_by_owner(
            snap.tgt_src[:e_tgt], snap.tgt_flat[:e_tgt],
            n_dev, n_loc, N, edge_chunk,
        )
        return ShardedSnapshot(
            mesh=mesh,
            num_atoms=N,
            n_loc=n_loc,
            edge_chunk=edge_chunk,
            inc_src=put(inc_src),
            inc_dst=put(inc_dst),
            tgt_src=put(tgt_src),
            tgt_dst=put(tgt_dst),
            type_of=put(pad_rows(snap.type_of, -1)),
            is_link=put(pad_rows(snap.is_link, False)),
            arity=put(pad_rows(snap.arity, 0)),
            value_rank_hi=put(pad_rows(
                (snap.value_rank >> np.uint64(32)).astype(np.uint32), 0
            )),
            value_rank_lo=put(pad_rows(
                (snap.value_rank & np.uint64(0xFFFFFFFF)).astype(np.uint32), 0
            )),
        )


def _register_pytree() -> None:
    jax.tree_util.register_pytree_node(
        ShardedSnapshot,
        lambda s: (
            (s.inc_src, s.inc_dst, s.tgt_src, s.tgt_dst,
             s.type_of, s.is_link, s.arity, s.value_rank_hi, s.value_rank_lo),
            (s.mesh, s.num_atoms, s.n_loc, s.edge_chunk),
        ),
        lambda aux, ch: ShardedSnapshot(*aux[:1], aux[1], aux[2], aux[3], *ch),
    )


_register_pytree()


# --------------------------------------------------------------------------
# sharded BFS: row-sharded packed state, packed-bitmap exchange over ICI
# --------------------------------------------------------------------------


def _scatter_local(src, dst, f_full_packed, n_loc, edge_chunk, count):
    """Scan the local edge slice: gather source bits from the all-gathered
    packed frontier, OR into a local dense bool destination, re-pack.
    Shares the scatter kernel with the single-device path; the carry is
    device-varying, so the init is cast to varying over the mesh axis."""
    return _scatter_relation(
        src.reshape(-1, edge_chunk),
        dst.reshape(-1, edge_chunk),
        f_full_packed,
        n_loc,
        count,
        varying_axis=AXIS,
    )


@hgverify.entry(
    shapes=lambda: (hgverify.sharded_snapshot_exemplar(),
                    hgverify.sds((32,), "int32")),
    statics={"max_hops": 2},
    mesh=(AXIS,),
)
@partial(jax.jit, static_argnames=("max_hops", "with_levels"))
def bfs_packed_sharded(
    sdev: ShardedSnapshot,
    seeds: jax.Array,   # (K,) int32
    max_hops: int,
    with_levels: bool = False,
):
    """Batched K-seed BFS over the mesh with row-sharded packed state.

    Returns (visited_packed (K, n_pad/32) uint32 [row-sharded layout],
    edges_touched (K,) int32, levels (K, n_pad) int8 or None).

    Per hop, exactly two all-gathers of packed (K, W) words cross ICI —
    2·K·N/8 bytes — and two local edge scans do the compute. The full
    multi-hop loop is one XLA program per device. ``max_hops`` is capped at
    127 so levels fit int8.
    """
    if max_hops > 127:
        raise ValueError(
            "bfs_packed_sharded: max_hops > 127 would overflow int8 levels"
        )
    mesh = sdev.mesh
    N = sdev.num_atoms
    n_loc = sdev.n_loc
    w_loc = n_loc // WORD
    chunk = sdev.edge_chunk
    K = seeds.shape[0]

    def stepper(inc_src, inc_dst, tgt_src, tgt_dst, seeds):
        d = jax.lax.axis_index(AXIS)
        row_start = d * n_loc
        # local validity: global id in [row_start, row_start + n_loc) ∩ [0, N)
        local_ids = row_start + jnp.arange(n_loc, dtype=jnp.int32)
        valid_loc = pack_bits((local_ids < N)[None, :])[0]

        # seed bits owned by this device
        mine = (seeds >= row_start) & (seeds < row_start + n_loc)
        sl = jnp.where(mine, seeds - row_start, 0)
        bitv = jnp.where(
            mine,
            jnp.left_shift(jnp.uint32(1), (sl & 31).astype(jnp.uint32)),
            jnp.uint32(0),
        )
        frontier = (
            jnp.zeros((K, w_loc), dtype=jnp.uint32)
            .at[jnp.arange(K), sl >> 5].max(bitv)
        )
        visited = frontier
        if with_levels:
            levels = jnp.where(
                unpack_bits(frontier), 0, -1
            ).astype(jnp.int8)
        else:
            levels = jnp.zeros((), dtype=jnp.int8)

        def body(i, state):
            frontier, visited, counts, levels = state
            f_full = jax.lax.all_gather(frontier, AXIS, axis=1, tiled=True)
            link_loc, c = _scatter_local(
                inc_src, inc_dst, f_full, n_loc, chunk, count=True
            )
            l_full = jax.lax.all_gather(link_loc, AXIS, axis=1, tiled=True)
            nbr_loc, _ = _scatter_local(
                tgt_src, tgt_dst, l_full, n_loc, chunk, count=False
            )
            nxt = nbr_loc & valid_loc & ~visited
            if with_levels:
                levels = jnp.where(
                    unpack_bits(nxt), (i + 1).astype(jnp.int8), levels
                )
            counts = counts + jax.lax.psum(c, AXIS)
            return nxt, visited | nxt, counts, levels

        frontier, visited, counts, levels = jax.lax.fori_loop(
            0, max_hops, body,
            (frontier, visited, jnp.zeros((K,), dtype=jnp.int32), levels),
        )
        return visited, counts, levels

    out_levels_spec = P(None, AXIS) if with_levels else P()
    fn = shard_map(
        stepper,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
        out_specs=(P(None, AXIS), P(), out_levels_spec),
        **_SHARD_MAP_KW,
    )
    visited, counts, levels = fn(
        sdev.inc_src, sdev.inc_dst, sdev.tgt_src, sdev.tgt_dst,
        jnp.asarray(seeds, dtype=jnp.int32),
    )
    return visited, counts, (levels if with_levels else None)


# --------------------------------------------------------------------------
# sharded (base, delta) overlay: the multi-chip face of ops.incremental
# --------------------------------------------------------------------------


@dataclass
class ShardedDelta:
    """Row/edge-sharded twin of :class:`ops.incremental.DeviceDelta`.

    Delta COO edges are partitioned by the owner of their *destination*
    row — the SAME row partition as the :class:`ShardedSnapshot` they
    overlay — with destinations rewritten to local ids, so a hop's delta
    scatter is purely local and OR-merges with the base scatter before the
    packed bitmaps cross ICI. Tombstones ship as per-device packed words.

    The reference serves concurrent reads during checkpoints from MVCC
    B-tree snapshots (``storage/bdb-je/.../BJEConfig.java:27-35``); here
    the immutable sharded base + this small sharded overlay is that read
    snapshot, kept fresh between compactions.
    """

    epoch: int            # SnapshotManager.compactions the buffers belong to
    edge_chunk: int       # static scan slice for the delta scatter loop
    inc_src: jax.Array    # (n_dev*D_inc_loc,) sharded — global source atom
    inc_dst: jax.Array    # (n_dev*D_inc_loc,) sharded — LOCAL dest link
    tgt_src: jax.Array    # (n_dev*D_tgt_loc,) sharded — global source link
    tgt_dst: jax.Array    # (n_dev*D_tgt_loc,) sharded — LOCAL dest atom
    dead: jax.Array       # (n_dev*w_loc,) sharded uint32 — packed tombstones


def _register_delta_pytree() -> None:
    jax.tree_util.register_pytree_node(
        ShardedDelta,
        lambda d: ((d.inc_src, d.inc_dst, d.tgt_src, d.tgt_dst, d.dead),
                   (d.epoch, d.edge_chunk)),
        lambda aux, ch: ShardedDelta(aux[0], aux[1], *ch),
    )


_register_delta_pytree()


def shard_host_delta(
    sdev: ShardedSnapshot, hd: dict, edge_chunk: int = 4096
) -> ShardedDelta:
    """Shard a ``SnapshotManager.host_delta()`` capture over ``sdev``'s mesh.

    ``hd['capacity']`` must equal ``sdev.num_atoms`` (same epoch: the delta's
    id space is the base's padded capacity); a mismatch means the manager
    compacted after ``sdev`` was built and the caller must re-shard the base.
    """
    if hd["capacity"] != sdev.num_atoms:
        raise ValueError(
            f"delta capacity {hd['capacity']} != sharded base "
            f"{sdev.num_atoms}: epochs diverged, re-shard the base"
        )
    n_dev, n_loc, N = sdev.n_dev, sdev.n_loc, sdev.num_atoms
    shard = NamedSharding(sdev.mesh, P(AXIS))

    def part(src, dst):
        if len(src) == 0:
            src = np.empty(0, dtype=np.int32)
            dst = np.empty(0, dtype=np.int32)
        s, d = _partition_by_owner(
            np.asarray(src, dtype=np.int32), np.asarray(dst, dtype=np.int32),
            n_dev, n_loc, N, edge_chunk,
        )
        return (
            jax.device_put(jnp.asarray(s), shard),
            jax.device_put(jnp.asarray(d), shard),
        )

    # direction mirrors DeviceDelta's scatters: atom→link lands on the
    # link's owner; link→target lands on the target atom's owner
    inc_src, inc_dst = part(hd["inc_src"], hd["inc_links"])
    tgt_src, tgt_dst = part(hd["tgt_src"], hd["tgt_flat"])

    dead_bits = np.zeros(n_dev * n_loc, dtype=bool)
    dd = hd["dead"]
    if len(dd):
        dead_bits[dd[dd < n_dev * n_loc]] = True
    dead_words = np.packbits(
        dead_bits.reshape(-1, WORD), axis=-1, bitorder="little"
    ).view("<u4").reshape(-1)
    return ShardedDelta(
        epoch=int(hd["epoch"]),
        edge_chunk=edge_chunk,
        inc_src=inc_src,
        inc_dst=inc_dst,
        tgt_src=tgt_src,
        tgt_dst=tgt_dst,
        dead=jax.device_put(jnp.asarray(dead_words), shard),
    )


@partial(jax.jit, static_argnames=("max_hops", "with_levels"))
def bfs_packed_sharded_delta(
    sdev: ShardedSnapshot,
    sdelta: ShardedDelta,
    seeds: jax.Array,   # (K,) int32
    max_hops: int,
    with_levels: bool = False,
):
    """Batched K-seed BFS over base ∪ delta minus tombstones, on the mesh.

    Same contract and ICI profile as :func:`bfs_packed_sharded` — two
    all-gathers of packed words per hop — plus two LOCAL delta scatters
    OR-merged in before each exchange; tombstoned rows are cleared with a
    per-device packed mask. Sharded twin of
    :func:`ops.incremental.bfs_levels_delta`.
    """
    if max_hops > 127:
        raise ValueError(
            "bfs_packed_sharded_delta: max_hops > 127 would overflow int8"
        )
    mesh = sdev.mesh
    N = sdev.num_atoms
    n_loc = sdev.n_loc
    w_loc = n_loc // WORD
    chunk = sdev.edge_chunk
    d_chunk = sdelta.edge_chunk
    K = seeds.shape[0]

    def stepper(inc_src, inc_dst, tgt_src, tgt_dst,
                d_inc_src, d_inc_dst, d_tgt_src, d_tgt_dst,
                dead_w, seeds):
        d = jax.lax.axis_index(AXIS)
        row_start = d * n_loc
        local_ids = row_start + jnp.arange(n_loc, dtype=jnp.int32)
        live_loc = pack_bits((local_ids < N)[None, :])[0] & ~dead_w

        mine = (seeds >= row_start) & (seeds < row_start + n_loc)
        sl = jnp.where(mine, seeds - row_start, 0)
        bitv = jnp.where(
            mine,
            jnp.left_shift(jnp.uint32(1), (sl & 31).astype(jnp.uint32)),
            jnp.uint32(0),
        )
        frontier = (
            jnp.zeros((K, w_loc), dtype=jnp.uint32)
            .at[jnp.arange(K), sl >> 5].max(bitv)
        ) & live_loc  # dead seeds emit nothing (bfs_levels_delta semantics)
        visited = frontier
        if with_levels:
            levels = jnp.where(unpack_bits(frontier), 0, -1).astype(jnp.int8)
        else:
            levels = jnp.zeros((), dtype=jnp.int8)

        def body(i, state):
            frontier, visited, counts, levels = state
            f_full = jax.lax.all_gather(frontier, AXIS, axis=1, tiled=True)
            link_loc, c = _scatter_local(
                inc_src, inc_dst, f_full, n_loc, chunk, count=True
            )
            dlink_loc, dc = _scatter_local(
                d_inc_src, d_inc_dst, f_full, n_loc, d_chunk, count=True
            )
            link_loc = (link_loc | dlink_loc) & live_loc
            l_full = jax.lax.all_gather(link_loc, AXIS, axis=1, tiled=True)
            nbr_loc, _ = _scatter_local(
                tgt_src, tgt_dst, l_full, n_loc, chunk, count=False
            )
            dnbr_loc, _ = _scatter_local(
                d_tgt_src, d_tgt_dst, l_full, n_loc, d_chunk, count=False
            )
            nxt = (nbr_loc | dnbr_loc) & live_loc & ~visited
            if with_levels:
                levels = jnp.where(
                    unpack_bits(nxt), (i + 1).astype(jnp.int8), levels
                )
            counts = counts + jax.lax.psum(c + dc, AXIS)
            return nxt, visited | nxt, counts, levels

        frontier, visited, counts, levels = jax.lax.fori_loop(
            0, max_hops, body,
            (frontier, visited, jnp.zeros((K,), dtype=jnp.int32), levels),
        )
        return visited, counts, levels

    out_levels_spec = P(None, AXIS) if with_levels else P()
    fn = shard_map(
        stepper,
        mesh=mesh,
        in_specs=(P(AXIS),) * 9 + (P(),),
        out_specs=(P(None, AXIS), P(), out_levels_spec),
        **_SHARD_MAP_KW,
    )
    visited, counts, levels = fn(
        sdev.inc_src, sdev.inc_dst, sdev.tgt_src, sdev.tgt_dst,
        sdelta.inc_src, sdelta.inc_dst, sdelta.tgt_src, sdelta.tgt_dst,
        sdelta.dead,
        jnp.asarray(seeds, dtype=jnp.int32),
    )
    return visited, counts, (levels if with_levels else None)


def bfs_levels_sharded_delta(
    sdev: ShardedSnapshot, sdelta: ShardedDelta, seeds, max_hops: int
) -> tuple[jax.Array, jax.Array]:
    """Dense (levels, visited) compat contract of
    :func:`ops.incremental.bfs_levels_delta` on the mesh — for graphs small
    enough to materialize (K, N+1); large callers use
    :func:`bfs_packed_sharded_delta` directly."""
    visited_p, _, levels = bfs_packed_sharded_delta(
        sdev, sdelta, jnp.asarray(seeds, dtype=jnp.int32), max_hops,
        with_levels=True,
    )
    n1 = sdev.num_atoms + 1
    visited = unpack_bits(visited_p)[:, :n1]
    return levels.astype(jnp.int32)[:, :n1], visited


def device_memory_stats() -> dict:
    """MEASURED per-device allocator stats via ``memory_stats()``:
    ``bytes_in_use`` now and the PROCESS-LIFETIME ``peak_bytes_in_use``
    (allocators expose no per-run peak reset — callers wanting a per-run
    bound snapshot ``bytes_in_use`` before/after, as
    :func:`bfs_packed_sharded_blocked` does). Backends without stats
    (CPU) return an empty dict."""
    out = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(d.id)] = {
                "process_peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", 0)
                ),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            }
    return out


def bfs_packed_sharded_blocked(
    sdev: ShardedSnapshot,
    seeds,
    max_hops: int,
    k_block: int = 256,
):
    """Seed-blocked driver for :func:`bfs_packed_sharded` (VERDICT r2 item
    8: the docstring's 160 MB/hop ICI figure assumes K=256 blocks, but no
    blocked driver existed — K=1024 all at once made the per-hop
    all-gather and the dense local scatter 4× larger). Runs ceil(K/k_block)
    sequential mesh programs and concatenates along the seed axis.

    Returns (visited_packed (K, n_pad/32), edges_touched (K,) int64 host,
    measured memory report: per-device bytes_in_use before/after and the
    process-lifetime peak — the before/after delta is what blocking
    bounds; the lifetime peak is reported for context only)."""
    if k_block <= 0 or k_block % WORD:
        raise ValueError(
            f"k_block must be a positive multiple of {WORD}; got {k_block}"
        )
    seeds = np.asarray(seeds, dtype=np.int32)
    K = len(seeds)
    if K == 0:
        w = (sdev.n_loc * len(sdev.mesh.devices.flat)) // WORD
        empty_report = {
            did: {
                "bytes_in_use_before": stats["bytes_in_use"],
                "bytes_in_use_after": stats["bytes_in_use"],
                "process_peak_bytes_in_use": stats["process_peak_bytes_in_use"],
            }
            for did, stats in device_memory_stats().items()
        }
        return (
            jnp.zeros((0, w), dtype=jnp.uint32),
            np.zeros(0, dtype=np.int64),
            empty_report,
        )
    pads = (-K) % WORD
    if pads:
        seeds = np.concatenate(
            [seeds, np.full(pads, sdev.num_atoms, dtype=np.int32)]
        )
    before = device_memory_stats()
    vis_blocks = []
    cnt_blocks = []
    for s in range(0, len(seeds), k_block):
        block = seeds[s : s + k_block]
        visited, counts, _ = bfs_packed_sharded(
            sdev, jnp.asarray(block), max_hops
        )
        vis_blocks.append(visited)
        cnt_blocks.append(np.asarray(counts).astype(np.int64))
    after = device_memory_stats()
    report = {
        did: {
            "bytes_in_use_before": before.get(did, {}).get("bytes_in_use", 0),
            "bytes_in_use_after": stats["bytes_in_use"],
            "process_peak_bytes_in_use": stats["process_peak_bytes_in_use"],
        }
        for did, stats in after.items()
    }
    visited = (
        vis_blocks[0] if len(vis_blocks) == 1
        else jnp.concatenate(vis_blocks, axis=0)
    )
    counts = np.concatenate(cnt_blocks)[:K]
    return visited[:K] if pads else visited, counts, report


@partial(jax.jit, static_argnames=("max_hops",))
def bfs_levels_sharded(
    sdev: ShardedSnapshot, seeds: jax.Array, max_hops: int
) -> tuple[jax.Array, jax.Array]:
    """Compatibility contract of ``ops.frontier.bfs_levels`` on the mesh:
    (levels (K, N+1) int32, visited (K, N+1) bool) — dense outputs, for
    graphs small enough to materialize them (tests / small deployments).
    Large-scale callers use :func:`bfs_packed_sharded` directly."""
    visited_p, _, levels = bfs_packed_sharded(
        sdev, seeds, max_hops, with_levels=True
    )
    n1 = sdev.num_atoms + 1
    visited = unpack_bits(visited_p)[:, :n1]
    return levels.astype(jnp.int32)[:, :n1], visited


# --------------------------------------------------------------------------
# sharded conjunctive pattern match: candidate-parallel membership filter
# --------------------------------------------------------------------------

@hgverify.entry(
    shapes=lambda: (hgverify.sharded_snapshot_exemplar(),
                    hgverify.sds((64,), "int32"),
                    hgverify.sds((2, 16), "int32")),
    mesh=(AXIS,),
)
@jax.jit
def match_candidates_sharded(
    sdev: ShardedSnapshot,
    candidates: jax.Array,     # (C,) atom ids, replicated input
    anchor_rows: jax.Array,    # (A, L) SENTINEL-padded sorted rows, replicated
) -> jax.Array:
    """``And(type, incident(a1), ..., incident(ak))`` on the mesh.

    Candidates (the by-type sorted id array) are split across devices; each
    device checks membership of its slice in every anchor's (replicated,
    sorted) incidence row via ``setops.member_mask`` — the vectorized
    zig-zag join (``ZigZagIntersectionResult.java:37-75``); shard_map
    assembles the per-device mask shards into the full mask.
    """
    mesh = sdev.mesh
    n_dev = mesh.devices.size
    C = candidates.shape[0]
    pad = (-C) % n_dev
    cand = jnp.concatenate(
        [candidates, jnp.full((pad,), SENTINEL, dtype=candidates.dtype)]
    ) if pad else candidates

    def local(cand_slice, rows):
        # (A, C_local) membership of every local candidate in every anchor
        # row, AND-ed over anchors; local shard returned, shard_map assembles
        hits = jax.vmap(lambda row: member_mask(row, cand_slice))(rows)
        return jnp.all(hits, axis=0)

    fn = shard_map(
        local, mesh=mesh, in_specs=(P(AXIS), P()), out_specs=P(AXIS),
        **_SHARD_MAP_KW,
    )
    full = fn(cand, anchor_rows)
    return full[:C]


def and_incident_pattern_sharded(
    snap: CSRSnapshot, sdev: ShardedSnapshot, type_handle: int,
    anchors: list[int],
) -> np.ndarray:
    """Host wrapper: ids of atoms of ``type_handle`` incident to every anchor."""
    cands = snap.type_set(type_handle)
    if len(cands) == 0 or not anchors:
        return np.empty(0, dtype=np.int32)
    rows = [snap.incidence_row(a) for a in anchors]
    L = _bucket(max((len(r) for r in rows), default=1))
    padded = np.stack([pad_sorted(r, L) for r in rows])
    mask = match_candidates_sharded(
        sdev, jnp.asarray(cands), jnp.asarray(padded)
    )
    return np.asarray(cands)[np.asarray(mask)]


def and_incident_pattern_sharded_delta(
    mgr, sdev: ShardedSnapshot, type_handle: int, anchors: list[int],
) -> np.ndarray:
    """(base, delta)-aware sharded conjunctive pattern: the mesh answers
    the BASE (candidate-sharded membership over the immutable sharded
    snapshot) and the host merges the LSM memtable — tombstoned candidates
    drop, post-base atoms are evaluated against the live graph. The
    pattern twin of :func:`bfs_packed_sharded_delta` (VERDICT r4 item 3's
    'BFS/pattern path'); read semantics match
    ``query/compiler.DeviceValueConjPlan``'s single-device merge.

    ``mgr`` is the graph's :class:`ops.incremental.SnapshotManager`; its
    base must be the snapshot ``sdev`` was sharded from (same epoch).
    """
    if not anchors:
        # an anchorless conjunction degenerates to a plain by-type query —
        # silently answering with only the post-base memtable subset would
        # be a wrong hybrid; make callers say what they mean
        raise ValueError(
            "and_incident_pattern_sharded_delta needs ≥1 anchor; use a "
            "type query for the anchorless form"
        )
    base, dead, new_atoms, revalued = mgr.read_view()
    if base.num_atoms != sdev.num_atoms:
        raise ValueError(
            "sharded base and manager epoch diverged: re-shard the base"
        )
    out = and_incident_pattern_sharded(base, sdev, type_handle, anchors)
    # LSM merge, same semantics as DeviceValueConjPlan: drop dead AND
    # revalued from the device result (a replace may have changed the
    # type), then host-evaluate new ∪ revalued against the live graph
    drop = dead | revalued
    if drop and len(out):
        out = out[~np.isin(out, np.fromiter(drop, dtype=np.int64))]
    g = mgr.graph
    fresh = []
    for h in (set(new_atoms) | revalued) - dead:
        try:
            if int(g.get_type_handle_of(h)) != int(type_handle):
                continue
            ts = {int(t) for t in g.get_targets(h)}
        except Exception:
            continue
        if all(int(a) in ts for a in anchors):
            fresh.append(h)
    if fresh:
        out = np.union1d(
            out.astype(np.int64), np.asarray(fresh, dtype=np.int64)
        ).astype(out.dtype if len(out) else np.int64)
    return out
