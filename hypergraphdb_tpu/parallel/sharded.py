"""Multi-chip execution: CSR snapshot sharded over a ``jax.sharding.Mesh``.

The reference scales out with Hazelcast-partitioned storage and XMPP peers
(`storage/hazelstore/`, `p2p/` — SURVEY §2.5); computation never leaves one
JVM thread pool. The TPU-native replacement is SPMD over a device mesh:

- **Edge parallelism** (the "model parallel" axis): the flattened COO
  incidence/target relations are split contiguously across devices along the
  edge dimension. Each device owns ``E/n_dev`` edges of each relation.
- **Frontier exchange over ICI**: one BFS hop is two local scatter-OR ops
  followed by a ``psum``-style OR-allreduce of the partial bitmaps — the
  frontier-partition exchange SURVEY §5 calls the "ring-attention analogue".
  A bitmap over 10M atoms is ~10 MB of bool — one allreduce per relation per
  hop rides ICI comfortably.
- **Candidate parallelism** (the "data parallel" axis): conjunctive pattern
  match shards the by-type candidate array across devices; each device
  filters its slice against (replicated) incidence rows and shard_map
  assembles the sharded result mask.

Everything is expressed with ``jax.shard_map`` over an explicit ``Mesh`` so
XLA inserts the collectives; no NCCL/MPI translation (SURVEY §2.5 mapping).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hypergraphdb_tpu.ops.snapshot import CSRSnapshot, _pad_to
from hypergraphdb_tpu.ops.setops import SENTINEL, _bucket, member_mask, pad_sorted

#: name of the device-mesh axis edges/candidates are sharded over
AXIS = "shard"


def make_mesh(devices=None, axis: str = AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


@dataclass
class ShardedSnapshot:
    """Device-sharded twin of :class:`CSRSnapshot`.

    Edge (COO) arrays are sharded along their only axis; per-atom arrays are
    replicated (they are O(N) int32 — cheap relative to edges; row-sharding
    them is the next scaling step and changes only ``from_host``).
    """

    mesh: Mesh
    num_atoms: int
    inc_links: jax.Array   # (E_inc,) sharded
    inc_src: jax.Array     # (E_inc,) sharded
    tgt_flat: jax.Array    # (E_tgt,) sharded
    tgt_src: jax.Array     # (E_tgt,) sharded
    type_of: jax.Array        # (N+1,) replicated
    is_link: jax.Array        # (N+1,) replicated
    arity: jax.Array          # (N+1,) replicated
    value_rank_hi: jax.Array  # (N+1,) replicated uint32 (see DeviceSnapshot)
    value_rank_lo: jax.Array  # (N+1,) replicated uint32

    @staticmethod
    def from_host(snap: CSRSnapshot, mesh: Mesh) -> "ShardedSnapshot":
        n_dev = mesh.devices.size
        N = snap.num_atoms
        shard = NamedSharding(mesh, P(AXIS))
        repl = NamedSharding(mesh, P())

        def put_edges(a):
            return jax.device_put(jnp.asarray(_pad_to(a, n_dev, N)), shard)

        def put_repl(a):
            return jax.device_put(jnp.asarray(a), repl)

        return ShardedSnapshot(
            mesh=mesh,
            num_atoms=N,
            inc_links=put_edges(snap.inc_links),
            inc_src=put_edges(snap.inc_src),
            tgt_flat=put_edges(snap.tgt_flat),
            tgt_src=put_edges(snap.tgt_src),
            type_of=put_repl(snap.type_of),
            is_link=put_repl(snap.is_link),
            arity=put_repl(snap.arity),
            value_rank_hi=put_repl(
                (snap.value_rank >> np.uint64(32)).astype(np.uint32)
            ),
            value_rank_lo=put_repl(
                (snap.value_rank & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            ),
        )


def _register_pytree() -> None:
    jax.tree_util.register_pytree_node(
        ShardedSnapshot,
        lambda s: (
            (s.inc_links, s.inc_src, s.tgt_flat, s.tgt_src,
             s.type_of, s.is_link, s.arity, s.value_rank_hi, s.value_rank_lo),
            (s.mesh, s.num_atoms),
        ),
        lambda aux, ch: ShardedSnapshot(aux[0], aux[1], *ch),
    )


_register_pytree()


# --------------------------------------------------------------------------
# sharded BFS: edge-parallel scatter + OR-allreduce frontier exchange
# --------------------------------------------------------------------------

def _expand_local(inc_links, inc_src, tgt_flat, tgt_src, frontier):
    """Per-device partial hop over the local edge slice.

    frontier: (K, N+1) replicated bool → partial neighbor bitmap (K, N+1).
    Collectives (OR via psum of bool→int max) happen outside, once per
    relation, so atom→link and link→target each cross ICI exactly once.
    """
    K = frontier.shape[0]
    n1 = frontier.shape[1]

    def one(f):
        la = jnp.zeros(n1, dtype=bool).at[inc_links].max(f[inc_src])
        return la

    link_partial = jax.vmap(one)(frontier)
    link_active = jax.lax.pmax(link_partial.astype(jnp.int8), AXIS) > 0

    def two(la):
        nb = jnp.zeros(n1, dtype=bool).at[tgt_flat].max(la[tgt_src])
        return nb

    nbr_partial = jax.vmap(two)(link_active)
    nbrs = jax.lax.pmax(nbr_partial.astype(jnp.int8), AXIS) > 0
    return nbrs


@partial(jax.jit, static_argnames=("max_hops",))
def bfs_levels_sharded(
    sdev: ShardedSnapshot, seeds: jax.Array, max_hops: int
) -> tuple[jax.Array, jax.Array]:
    """Batched K-seed BFS over the mesh. Same contract as
    ``ops.frontier.bfs_levels`` — (levels, visited), each (K, N+1).

    The full multi-hop loop is one XLA program per device; per hop there are
    exactly two OR-allreduces over ICI (link activation + neighbor bitmap).
    """
    mesh = sdev.mesh
    K = seeds.shape[0]
    n1 = sdev.type_of.shape[0]

    def stepper(inc_links, inc_src, tgt_flat, tgt_src, seeds):
        frontier = (
            jnp.zeros((K, n1), dtype=bool).at[jnp.arange(K), seeds].set(True)
        )
        visited = frontier
        levels = jnp.where(frontier, 0, -1).astype(jnp.int32)

        def body(i, state):
            frontier, visited, levels = state
            nxt = _expand_local(inc_links, inc_src, tgt_flat, tgt_src, frontier)
            nxt = nxt.at[:, n1 - 1].set(False) & ~visited
            levels = jnp.where(nxt, i + 1, levels)
            return nxt, visited | nxt, levels

        return jax.lax.fori_loop(0, max_hops, body, (frontier, visited, levels))

    fn = jax.shard_map(
        stepper,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
        out_specs=(P(), P(), P()),
    )
    frontier, visited, levels = fn(
        sdev.inc_links, sdev.inc_src, sdev.tgt_flat, sdev.tgt_src,
        jnp.asarray(seeds, dtype=jnp.int32),
    )
    return levels, visited


# --------------------------------------------------------------------------
# sharded conjunctive pattern match: candidate-parallel membership filter
# --------------------------------------------------------------------------

@jax.jit
def match_candidates_sharded(
    sdev: ShardedSnapshot,
    candidates: jax.Array,     # (C,) atom ids, replicated input
    anchor_rows: jax.Array,    # (A, L) SENTINEL-padded sorted rows, replicated
) -> jax.Array:
    """``And(type, incident(a1), ..., incident(ak))`` on the mesh.

    Candidates (the by-type sorted id array) are split across devices; each
    device checks membership of its slice in every anchor's (replicated,
    sorted) incidence row via ``setops.member_mask`` — the vectorized
    zig-zag join (``ZigZagIntersectionResult.java:37-75``); shard_map
    assembles the per-device mask shards into the full mask.
    """
    mesh = sdev.mesh
    n_dev = mesh.devices.size
    C = candidates.shape[0]
    pad = (-C) % n_dev
    cand = jnp.concatenate(
        [candidates, jnp.full((pad,), SENTINEL, dtype=candidates.dtype)]
    ) if pad else candidates

    def local(cand_slice, rows):
        # (A, C_local) membership of every local candidate in every anchor
        # row, AND-ed over anchors; local shard returned, shard_map assembles
        hits = jax.vmap(lambda row: member_mask(row, cand_slice))(rows)
        return jnp.all(hits, axis=0)

    fn = jax.shard_map(
        local, mesh=mesh, in_specs=(P(AXIS), P()), out_specs=P(AXIS),
    )
    full = fn(cand, anchor_rows)
    return full[:C]


def and_incident_pattern_sharded(
    snap: CSRSnapshot, sdev: ShardedSnapshot, type_handle: int,
    anchors: list[int],
) -> np.ndarray:
    """Host wrapper: ids of atoms of ``type_handle`` incident to every anchor."""
    cands = snap.type_set(type_handle)
    if len(cands) == 0 or not anchors:
        return np.empty(0, dtype=np.int32)
    rows = [snap.incidence_row(a) for a in anchors]
    L = _bucket(max((len(r) for r in rows), default=1))
    padded = np.stack([pad_sorted(r, L) for r in rows])
    mask = match_candidates_sharded(
        sdev, jnp.asarray(cands), jnp.asarray(padded)
    )
    return np.asarray(cands)[np.asarray(mask)]
