"""Multi-chip SPMD execution over a ``jax.sharding.Mesh`` (SURVEY §2.5/§5).

The data plane: edge-sharded CSR snapshots, OR-allreduce frontier exchange
over ICI, candidate-sharded pattern matching. The host-side control plane
(peer identity, replication, remote query) lives in ``hypergraphdb_tpu.peer``.
"""

from hypergraphdb_tpu.parallel.sharded import (
    AXIS,
    ShardedDelta,
    ShardedSnapshot,
    and_incident_pattern_sharded,
    and_incident_pattern_sharded_delta,
    bfs_levels_sharded,
    bfs_levels_sharded_delta,
    bfs_packed_sharded,
    bfs_packed_sharded_delta,
    make_mesh,
    match_candidates_sharded,
    shard_host_delta,
)

__all__ = [
    "AXIS",
    "ShardedDelta",
    "ShardedSnapshot",
    "and_incident_pattern_sharded",
    "and_incident_pattern_sharded_delta",
    "bfs_levels_sharded",
    "bfs_levels_sharded_delta",
    "bfs_packed_sharded",
    "bfs_packed_sharded_delta",
    "make_mesh",
    "match_candidates_sharded",
    "shard_host_delta",
]
