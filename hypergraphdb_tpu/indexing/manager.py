"""Index manager: user-registered indexers maintained on every mutation.

Re-expression of the reference's ``HGIndexManager``
(``core/.../indexing/HGIndexManager.java:62-215`` — register/unregister +
``maybeIndex`` called from the add path at ``HyperGraph.java:1618``) and the
``HGIndexer`` family (``ByPartIndexer``, ``ByTargetIndexer``,
``DirectValueIndexer``, ``CompositeIndexer``, ``LinkIndexer``,
``TargetToTargetIndexer`` — SURVEY §2.1 Indexing framework).

An indexer projects an (atom, type, value, targets) tuple to zero or more
(key, value) entries in a named storage index. Registration is per type
handle; ``maybe_index`` fires only for atoms of that type (or its subtypes).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.utils.ordered_bytes import encode_int


class HGIndexer:
    """SPI: project an atom into index entries (``HGKeyIndexer`` analogue)."""

    #: storage index name; must be unique
    name: str
    #: type handle this indexer applies to
    type_handle: HGHandle

    def keys(
        self, graph, h: HGHandle, value: Any, targets: Optional[Sequence[HGHandle]]
    ) -> list[bytes]:
        raise NotImplementedError

    def values(
        self, graph, h: HGHandle, value: Any, targets: Optional[Sequence[HGHandle]]
    ) -> list[HGHandle]:
        """Indexed values; default: the atom handle itself."""
        return [h]


class ByPartIndexer(HGIndexer):
    """Index atoms of a record type by a projection path
    (``indexing/ByPartIndexer.java``)."""

    def __init__(self, name: str, type_handle: HGHandle, dimension: str):
        self.name = name
        self.type_handle = int(type_handle)
        self.dimension = dimension

    def keys(self, graph, h, value, targets):
        atype = graph.typesystem.get_type(self.type_handle)
        part = atype.project(value, self.dimension)
        if part is None:
            return []
        pt = graph.typesystem.infer(part)
        if pt is None:
            return []
        return [pt.to_key(part)]


class ByTargetIndexer(HGIndexer):
    """Index links by the target at a fixed position
    (``indexing/ByTargetIndexer.java``)."""

    def __init__(self, name: str, type_handle: HGHandle, position: int):
        self.name = name
        self.type_handle = int(type_handle)
        self.position = position

    def keys(self, graph, h, value, targets):
        if targets is None or self.position >= len(targets):
            return []
        return [encode_int(int(targets[self.position]))]


class DirectValueIndexer(HGIndexer):
    """Index atoms by their full value key (``DirectValueIndexer.java``)."""

    def __init__(self, name: str, type_handle: HGHandle):
        self.name = name
        self.type_handle = int(type_handle)

    def keys(self, graph, h, value, targets):
        atype = graph.typesystem.get_type(self.type_handle)
        return [atype.to_key(value)]


class CompositeIndexer(HGIndexer):
    """Concatenation of several indexers' keys (``CompositeIndexer.java``)."""

    def __init__(self, name: str, type_handle: HGHandle, parts: Sequence[HGIndexer]):
        self.name = name
        self.type_handle = int(type_handle)
        self.parts = list(parts)

    def keys(self, graph, h, value, targets):
        parts = []
        for p in self.parts:
            ks = p.keys(graph, h, value, targets)
            if not ks:
                return []
            parts.append(ks[0])
        return [b"\x00".join(parts)]


class LinkIndexer(HGIndexer):
    """Index links of a type by their FULL ordered target tuple
    (``indexing/LinkIndexer.java``): one key per link, the concatenation
    of its targets' order-preserving encodings — an exact-tuple lookup
    ("find the link (a, b, c)") without intersecting incidence sets."""

    def __init__(self, name: str, type_handle: HGHandle):
        self.name = name
        self.type_handle = int(type_handle)

    def keys(self, graph, h, value, targets):
        if not targets:
            return []
        return [b"".join(encode_int(int(t)) for t in targets)]

    @staticmethod
    def tuple_key(targets: Sequence[HGHandle]) -> bytes:
        """The lookup key for an ordered target tuple."""
        return b"".join(encode_int(int(t)) for t in targets)


class TargetToTargetIndexer(HGIndexer):
    """Bidirectional target→target index over links of a type
    (``TargetToTargetIndexer.java``): key = target at ``key_pos``, value =
    target at ``value_pos``."""

    def __init__(self, name: str, type_handle: HGHandle, key_pos: int, value_pos: int):
        self.name = name
        self.type_handle = int(type_handle)
        self.key_pos = key_pos
        self.value_pos = value_pos

    def keys(self, graph, h, value, targets):
        if targets is None or max(self.key_pos, self.value_pos) >= len(targets):
            return []
        return [encode_int(int(targets[self.key_pos]))]

    def values(self, graph, h, value, targets):
        if targets is None or max(self.key_pos, self.value_pos) >= len(targets):
            return []
        return [int(targets[self.value_pos])]


# -- persistence ---------------------------------------------------------------

#: storage index holding one JSON descriptor per registered indexer — the
#: analogue of the reference persisting indexer atoms so registrations
#: survive reopen (``HGIndexManager.java:62-215`` ``loadIndexers``)
_REG_INDEX = "hg.sys.indexers"


def _to_config(ix: HGIndexer) -> Optional[dict]:
    """JSON-able descriptor for the built-in indexer kinds; custom
    subclasses may implement ``to_config()`` themselves (returning a dict
    with a ``cls`` naming an importable class with ``from_config``)."""
    own = getattr(ix, "to_config", None)
    if own is not None:
        return own()
    if isinstance(ix, ByPartIndexer):
        return {"cls": "ByPartIndexer", "name": ix.name,
                "type_handle": ix.type_handle, "dimension": ix.dimension}
    if isinstance(ix, ByTargetIndexer):
        return {"cls": "ByTargetIndexer", "name": ix.name,
                "type_handle": ix.type_handle, "position": ix.position}
    if isinstance(ix, LinkIndexer):
        return {"cls": "LinkIndexer", "name": ix.name,
                "type_handle": ix.type_handle}
    if isinstance(ix, DirectValueIndexer):
        return {"cls": "DirectValueIndexer", "name": ix.name,
                "type_handle": ix.type_handle}
    if isinstance(ix, TargetToTargetIndexer):
        return {"cls": "TargetToTargetIndexer", "name": ix.name,
                "type_handle": ix.type_handle,
                "key_pos": ix.key_pos, "value_pos": ix.value_pos}
    if isinstance(ix, CompositeIndexer):
        parts = [_to_config(p) for p in ix.parts]
        if any(p is None for p in parts):
            return None
        return {"cls": "CompositeIndexer", "name": ix.name,
                "type_handle": ix.type_handle, "parts": parts}
    return None


def _from_config(cfg: dict) -> HGIndexer:
    cls = cfg["cls"]
    if cls == "ByPartIndexer":
        return ByPartIndexer(cfg["name"], cfg["type_handle"], cfg["dimension"])
    if cls == "ByTargetIndexer":
        return ByTargetIndexer(cfg["name"], cfg["type_handle"], cfg["position"])
    if cls == "LinkIndexer":
        return LinkIndexer(cfg["name"], cfg["type_handle"])
    if cls == "DirectValueIndexer":
        return DirectValueIndexer(cfg["name"], cfg["type_handle"])
    if cls == "TargetToTargetIndexer":
        return TargetToTargetIndexer(cfg["name"], cfg["type_handle"],
                                     cfg["key_pos"], cfg["value_pos"])
    if cls == "CompositeIndexer":
        return CompositeIndexer(cfg["name"], cfg["type_handle"],
                                [_from_config(p) for p in cfg["parts"]])
    # dotted path to a user class exposing from_config
    import importlib

    mod, _, attr = cls.rpartition(".")
    klass = getattr(importlib.import_module(mod), attr)
    return klass.from_config(cfg)


def load_indexers(graph) -> int:
    """Open path: restore persisted registrations into the in-process
    registry WITHOUT rebuilding (the index data itself is already in the
    store). Returns how many were loaded."""
    import json

    idx = graph.store.get_index(_REG_INDEX, create=False)
    if idx is None:
        return 0
    n = 0
    reg = _registry(graph)
    for key, _hs in idx.bulk_items():
        try:
            ix = _from_config(json.loads(key.decode("utf-8")))
        except Exception:
            import logging

            logging.getLogger("hypergraphdb_tpu.indexing").warning(
                "could not restore indexer registration %r", key, exc_info=True
            )
            continue
        if any(x.name == ix.name for xs in reg.values() for x in xs):
            continue
        reg.setdefault(int(ix.type_handle), []).append(ix)
        n += 1
    if n:
        _bump_registry_version(graph)
    return n


# -- registration + hooks ------------------------------------------------------

def _bump_registry_version(graph) -> None:
    graph._indexer_reg_version = getattr(graph, "_indexer_reg_version", 0) + 1


def register(graph, indexer: HGIndexer, populate: bool = True) -> None:
    """Register and (optionally) build the index over existing atoms — the
    online equivalent of the reference's offline ``ApplyNewIndexer``
    maintenance op (``maintenance/ApplyNewIndexer.java:36``). The
    registration descriptor is persisted so it survives reopen."""
    import json

    reg = _registry(graph)
    reg.setdefault(int(indexer.type_handle), []).append(indexer)
    _bump_registry_version(graph)
    cfg = _to_config(indexer)
    if cfg is not None:
        key = json.dumps(cfg, sort_keys=True).encode("utf-8")
        graph.txman.ensure_transaction(
            lambda: graph.store.get_index(_REG_INDEX).add_entry(key, 0)
        )
    if populate:
        rebuild(graph, indexer)


def unregister(graph, indexer_name: str) -> None:
    import json

    reg = _registry(graph)
    dropped: list[HGIndexer] = []
    for th, idxs in list(reg.items()):
        dropped += [ix for ix in idxs if ix.name == indexer_name]
        reg[th] = [ix for ix in idxs if ix.name != indexer_name]
        if not reg[th]:
            del reg[th]
    _bump_registry_version(graph)
    for ix in dropped:
        cfg = _to_config(ix)
        if cfg is not None:
            key = json.dumps(cfg, sort_keys=True).encode("utf-8")
            graph.txman.ensure_transaction(
                lambda k=key: graph.store.get_index(_REG_INDEX)
                .remove_entry(k, 0)
            )
    graph.store.remove_index(_storage_name(indexer_name))


def indexers_of(graph, type_handle: HGHandle) -> list[HGIndexer]:
    """All indexers applying to a type, including via supertype registration.

    Called from the per-atom write path, so the empty-registry case (the
    common one) exits before any supertype walk, and non-empty lookups are
    memoized until the registry or the type hierarchy changes."""
    reg = _registry(graph)
    if not reg:
        return []
    version = (getattr(graph, "_indexer_reg_version", 0),
               getattr(graph.typesystem, "hierarchy_version", 0))
    cache = getattr(graph, "_indexers_of_cache", None)
    if cache is None or cache[0] != version:
        cache = (version, {})
        graph._indexers_of_cache = cache
    memo = cache[1]
    th = int(type_handle)
    hit = memo.get(th)
    if hit is not None:
        return hit
    out = list(reg.get(th, ()))
    try:
        name = graph.typesystem.name_of(type_handle)
    except KeyError:
        memo[th] = out
        return out
    for sup in graph.typesystem.supertypes_of(name):
        try:
            sh = graph.typesystem.handle_of(sup)
        except Exception:
            continue
        out.extend(reg.get(int(sh), ()))
    memo[th] = out
    return out


def get_index(graph, indexer_name: str):
    """The queryable storage index for a registered indexer."""
    return graph.store.get_index(_storage_name(indexer_name), create=True)


# -- index statistics ----------------------------------------------------------

#: persisted per-index cardinality: name → data record holding
#: {keys, entries, capped, version}; the HGIndexStats analogue
#: (``storage/HGIndexStats.java:37`` feeding ``ResultSizeEstimation``)
_STATS_INDEX = "hg.sys.indexstats"

#: scan-cost ceiling when (re)counting an index (entries touched)
STATS_COST_CAP = 1 << 20


def index_stats(graph, indexer_name: str, refresh: bool = False) -> dict:
    """Per-index cardinality for the planner and for observability:
    ``{"keys": int, "entries": int, "capped": bool, "version": int}``.

    Computed by a cost-capped scan, PERSISTED next to the registrations
    (VERDICT r4 missing #3), and reused across calls — and across reopens —
    mirroring the reference's cached cost-capped ``IndexStats``. Validity
    is double-checked: the session mutation counter must not have drifted
    more than 25% past the recorded version, AND the live key count (O(1))
    must sit within 25% of the recorded one — the key check is the
    cross-session authority, since the mutation counter resets at reopen
    (a negative counter drift says nothing about how much the index
    changed in between — review r5 finding 3). ``refresh=True`` forces a
    recount."""
    import json

    current = int(getattr(graph, "_mutations", 0))
    key = indexer_name.encode("utf-8")
    idx = graph.store.get_index(_storage_name(indexer_name), create=False)
    if idx is None:
        idx = graph.store.get_index(indexer_name, create=False)  # system ix
    sidx = graph.store.get_index(_STATS_INDEX, create=False)
    if sidx is not None and not refresh:
        try:
            live_keys = idx.key_count() if idx is not None else 0
        except Exception:
            live_keys = None
        for dh in sidx.find(key).array().tolist():
            raw = graph.store.get_data(int(dh))
            if raw is None:
                continue
            rec = json.loads(raw.decode("utf-8"))
            drift = current - int(rec.get("version", 0))
            rec_keys = int(rec.get("keys", 0))
            keys_ok = live_keys is not None and abs(
                live_keys - rec_keys
            ) <= max(rec_keys // 4, 1024)
            mut_ok = drift < 0 or drift <= max(
                int(rec.get("entries", 0)) // 4, 1024
            )
            if keys_ok and mut_ok:
                return rec
    if idx is None:
        return {"keys": 0, "entries": 0, "capped": False, "version": current}
    keys = 0
    entries = 0
    capped = False
    for _k, hs in idx.bulk_items():
        keys += 1
        entries += len(hs)
        if entries >= STATS_COST_CAP:
            capped = True
            break
    rec = {
        "keys": keys, "entries": entries, "capped": capped,
        "version": current,
    }

    def persist() -> None:
        sidx = graph.store.get_index(_STATS_INDEX)
        for old in sidx.find(key).array().tolist():
            sidx.remove_entry(key, int(old))
            graph.store.remove_data(int(old))
        dh = graph.handles.make()
        graph.store.store_data(
            dh, json.dumps(rec, sort_keys=True).encode("utf-8")
        )
        sidx.add_entry(key, dh)

    try:
        graph.txman.ensure_transaction(persist)
    except Exception:
        import logging

        logging.getLogger("hypergraphdb_tpu.indexing").warning(
            "could not persist index stats for %s", indexer_name,
            exc_info=True,
        )
    return rec


def rebuild(graph, indexer: HGIndexer, batch: int = 1024) -> int:
    """(Re)build an index from scratch in batches (resumable maintenance —
    ``ApplyNewIndexer`` used batch=100 with a lastProcessed cursor)."""
    idx = get_index(graph, indexer.name)
    n = 0
    applicable = {int(indexer.type_handle)}
    try:
        tname = graph.typesystem.name_of(indexer.type_handle)
        for sub in graph.typesystem.subtypes_closure(tname):
            applicable.add(int(graph.typesystem.handle_of(sub)))
    except KeyError:
        pass
    for h in graph.atoms():
        rec = graph.store.get_link(h)
        if rec is None or int(rec[0]) not in applicable:
            continue
        value = graph.get(h)
        targets = None
        from hypergraphdb_tpu.core.graph import HGLink

        if isinstance(value, HGLink):
            targets = value.targets
            value = value.value
        for key in indexer.keys(graph, h, value, targets):
            for v in indexer.values(graph, h, value, targets):
                idx.add_entry(key, v)
        n += 1
    return n


def maybe_index(
    graph,
    h: HGHandle,
    type_handle: HGHandle,
    value: Any,
    targets: Optional[Sequence[HGHandle]],
    touched: Optional[set] = None,
    before_write: Optional[Callable] = None,
) -> None:
    """Called from the kernel's add path (``HyperGraph.java:1618``).
    ``touched`` (if given) collects the ``(index_name, key)`` cells written
    — bulk loaders bump their transaction versions so open readers fail
    validation instead of committing on stale index reads.
    ``before_write(storage_name, key, idx)`` (if given) runs before the
    first entry lands on a key — bulk loaders capture MVCC pre-images
    there so snapshot readers keep their begin-time view."""
    for indexer in indexers_of(graph, type_handle):
        idx = get_index(graph, indexer.name)
        for key in indexer.keys(graph, h, value, targets):
            if before_write is not None:
                before_write(_storage_name(indexer.name), key, idx)
            for v in indexer.values(graph, h, value, targets):
                idx.add_entry(key, v)
            if touched is not None:
                # the STORAGE name — readers note ("idx", storage_name, key)
                # (core/store.py), so bumps must use the same cell id
                touched.add((_storage_name(indexer.name), key))


def maybe_unindex(
    graph,
    h: HGHandle,
    type_handle: HGHandle,
    value: Any,
    targets: Optional[Sequence[HGHandle]],
) -> None:
    for indexer in indexers_of(graph, type_handle):
        idx = get_index(graph, indexer.name)
        for key in indexer.keys(graph, h, value, targets):
            for v in indexer.values(graph, h, value, targets):
                idx.remove_entry(key, v)


def _registry(graph) -> dict[int, list[HGIndexer]]:
    reg = getattr(graph, "_indexer_registry", None)
    if reg is None:
        reg = graph._indexer_registry = {}
    return reg


def _storage_name(indexer_name: str) -> str:
    return f"hg.user.{indexer_name}"
