"""Benchmark / example graph model families.

The reference validates against lexical (WordNet-style) and encyclopedic
(DBpedia-style) hypergraphs (BASELINE configs 1-5). These generators build
synthetic graphs with the same shape characteristics — zipf-skewed hub
degrees, mostly-binary relations with a higher-arity tail — entirely
through the public ingest API, so they double as ingest benchmarks.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Synset:
    """WordNet-style node payload."""

    lemma: str = ""
    pos: str = "n"


@dataclass(frozen=True)
class Entity:
    """DBpedia-style node payload."""

    uri: str = ""


def zipf_hypergraph(graph, n_nodes: int = 10_000, n_links: int = 5_000,
                    max_arity: int = 5, zipf_a: float = 1.3, seed: int = 7,
                    values: bool = True):
    """Skewed-degree hypergraph (the shape of lexical graphs): returns
    (node_handles, link_handles)."""
    r = np.random.default_rng(seed)
    nodes = graph.bulk_import(values=np.arange(n_nodes).tolist())
    node0 = int(nodes[0])
    popularity = r.zipf(zipf_a, size=n_links * (max_arity + 1)) % n_nodes
    arities = r.integers(2, max_arity + 1, size=n_links)
    target_lists = []
    k = 0
    for a in arities:
        ts = popularity[k : k + a]
        k += a
        target_lists.append([node0 + int(t) for t in ts])
    links = graph.bulk_import(
        values=list(range(n_links)) if values else [None] * n_links,
        target_lists=target_lists,
    )
    return nodes, links


#: WordNet relation inventory (name, approximate share of links)
WORDNET_RELS = (
    ("hypernym", 0.40),
    ("hyponym", 0.25),
    ("meronym", 0.12),
    ("holonym", 0.08),
    ("antonym", 0.05),
    ("entailment", 0.05),
    ("similar-to", 0.05),
)


def wordnet_like(graph, n_synsets: int = 20_000, n_relations: int = 40_000,
                 seed: int = 11):
    """WordNet-shaped typed graph: ``Synset`` nodes + binary relation links
    whose VALUE is the relation name (so typed-incident queries exercise
    the by-value/by-type paths). Returns (synset_handles, rel_handles)."""
    r = np.random.default_rng(seed)
    poses = np.array(["n", "v", "a", "r"])
    synsets = graph.add_nodes_bulk([
        Synset(f"lemma{i}", str(poses[i % 4])) for i in range(n_synsets)
    ])
    s0 = int(synsets[0])
    names = [n for n, _ in WORDNET_RELS]
    probs = np.array([p for _, p in WORDNET_RELS])
    probs = probs / probs.sum()
    rel_names = r.choice(names, size=n_relations, p=probs)
    # hypernym chains give depth; the rest are zipf-skewed
    src = r.zipf(1.2, size=n_relations) % n_synsets
    dst = (src + r.integers(1, max(2, n_synsets // 10),
                            size=n_relations)) % n_synsets
    targets = [[s0 + int(a), s0 + int(b)] for a, b in zip(src, dst)]
    rels = graph.add_links_bulk(targets, values=[str(n) for n in rel_names])
    return synsets, rels


def dbpedia_snapshot(
    n_entities: int = 2_000_000,
    n_links: int = 8_000_000,
    max_arity: int = 10,
    n_properties: int = 64,
    zipf_a: float = 1.1,
    seed: int = 13,
):
    """Columnar DBpedia-shaped build at benchmark scale (BASELINE configs
    3-4: 10M atoms / ~50M arity): assembles a :class:`CSRSnapshot` directly
    via ``CSRSnapshot.from_tables`` — the bulk-stream load path — in
    seconds instead of minutes of per-atom store writes.

    Id layout: [0] entity-type atom, [1..P] property-type atoms,
    [T..T+n_entities) entity nodes, then links. Each link's first target is
    zipf-skewed (hubs), the rest uniform. Link value ranks carry the
    property id so value-predicate pushdown benches against real skew.

    Returns (snapshot, info) where info has the id ranges and type handles.
    """
    import numpy as np

    from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

    r = np.random.default_rng(seed)
    T = 1 + n_properties
    N = T + n_entities + n_links
    e0 = T
    l0 = T + n_entities

    type_of = np.zeros(N, dtype=np.int32)
    type_of[1:T] = 0                      # property-type atoms are plain atoms
    type_of[e0:l0] = 0                    # entities: type = entity-type atom 0
    props = r.integers(0, n_properties, size=n_links).astype(np.int32)
    type_of[l0:] = 1 + props              # links: type = their property atom

    is_link = np.zeros(N, dtype=bool)
    is_link[l0:] = True

    arities = r.integers(2, max_arity + 1, size=n_links).astype(np.int64)
    total = int(arities.sum())
    tgt_offsets = np.zeros(N + 1, dtype=np.int64)
    np.cumsum(arities, out=tgt_offsets[l0 + 1 :])
    tgt_offsets[: l0 + 1] = 0

    tgt_flat = e0 + r.integers(0, n_entities, size=total).astype(np.int64)
    subj = e0 + (r.zipf(zipf_a, size=n_links) % n_entities)
    tgt_flat[tgt_offsets[l0:-1][: n_links]] = subj  # first slot of each link

    value_rank = np.zeros(N, dtype=np.uint64)
    value_rank[l0:] = props.astype(np.uint64)
    value_rank[e0:l0] = np.arange(n_entities, dtype=np.uint64)

    snap = CSRSnapshot.from_tables(
        type_of, is_link, tgt_offsets, tgt_flat.astype(np.int32),
        value_rank=value_rank,
    )
    info = {
        "entity_type": 0,
        "property_types": list(range(1, T)),
        "entities": (e0, l0),
        "links": (l0, N),
        "n_atoms": N,
        "total_arity": total,
    }
    return snap, info


def dbpedia_like(graph, n_entities: int = 100_000, n_triples: int = 500_000,
                 n_properties: int = 64, seed: int = 13, batch: int = 100_000):
    """DBpedia-shaped graph at configurable scale: ``Entity`` nodes and
    property links (value = property id). Ingests in batches so 10M-atom
    builds stream. Returns (entity_handles, first_link_handle)."""
    r = np.random.default_rng(seed)
    entities = graph.bulk_import(
        values=[Entity(f"e/{i}") for i in range(n_entities)]
    )
    e0 = int(entities[0])
    first_link = None
    remaining = n_triples
    while remaining > 0:
        m = min(batch, remaining)
        remaining -= m
        subj = r.zipf(1.1, size=m) % n_entities
        obj = r.integers(0, n_entities, size=m)
        props = r.integers(0, n_properties, size=m)
        links = graph.bulk_import(
            values=[int(p) for p in props],
            target_lists=[[e0 + int(a), e0 + int(b)]
                          for a, b in zip(subj, obj)],
        )
        if first_link is None:
            first_link = int(links[0])
    return entities, first_link
