"""Graph model families: generators for the workload shapes the framework
is benchmarked on (lexical/WordNet-like, encyclopedic/DBpedia-like,
zipf-skewed synthetic hypergraphs) — BASELINE configs 1-5."""

from hypergraphdb_tpu.models.generators import (
    Entity,
    Synset,
    dbpedia_like,
    dbpedia_snapshot,
    wordnet_like,
    zipf_hypergraph,
)

__all__ = [
    "Entity",
    "Synset",
    "dbpedia_like",
    "dbpedia_snapshot",
    "wordnet_like",
    "zipf_hypergraph",
]
