"""Request tracing: lightweight span trees with explicit parenting.

A :class:`Trace` is a bounded tree of :class:`Span` records, all stamped
by ONE injectable clock (fake clocks in tests make every duration exact).
Spans carry typed attributes; parenting is EXPLICIT (``parent=``) because
the span chains this repo cares about cross threads — a served request's
``submit`` span is opened on the caller's thread and its ``collect`` span
on the dispatch thread, so an implicit thread-local "current span" could
never link them. Every in-tree producer (the serve chain, the query
compiler's ``compile → plan → execute``, the compaction pass's
``compact → buffer_drain → device_swap``) uses the explicit API; a
thread-local convenience layer (``Tracer.trace_ctx`` / ``Tracer.span``)
is offered for ad-hoc single-thread instrumentation.

Safety properties that make tracing reasonable to leave on:

- **off-gate**: ``Tracer.enabled`` is a plain attribute; every
  instrumentation site reads it (or a ``Ticket.trace is None`` it
  derives from) ONCE and allocates nothing when tracing is off;
- **span budget**: each trace records at most ``max_spans`` spans —
  overflow spans are counted in ``Trace.dropped`` and discarded, never
  accumulated (a pathological per-row instrumentation bug degrades to a
  counter, not an OOM);
- **bounded retention**: finished traces land in a ``maxlen`` deque on
  the tracer (``drain()`` hands them to the exporter); a server nobody
  scrapes stays O(max_finished), not O(requests);
- **head-based sampling** (production qps): per-root-kind sample rates
  (``set_sample_rate``) decide AT START whether a trace will be
  retained. Unsampled traces still record spans (bounded as above) but
  are discarded at finish — unless something upgrades them: the
  ``error``/``shed`` terminals and explicit :meth:`Trace.force_sample`
  calls (breaker trips) always retain, so incidents are captured at
  100% no matter how low the rate. An optional adaptive controller
  (:meth:`Tracer.enable_adaptive`) scales every rate down when the
  finished-trace buffer fills faster than it is drained, and back up
  when pressure clears — always-on tracing degrades to a lower rate,
  never to buffer overflow.

**Cross-process propagation**: :meth:`Trace.context` emits a compact
wire context ``{"tid", "sid", "s"}`` (trace id, parent span id, sampling
decision); :meth:`Tracer.start_remote_trace` opens the receiving side's
trace UNDER that context — same trace id, root spans parented on the
propagated remote span id, the sender's sampling decision honored — so a
replication push or snapshot transfer renders as ONE span tree spanning
sender and receiver (join the two tracers' drains on ``trace_id``).
Trace ids carry a per-process random high-bits base, so trees from two
real processes cannot collide.

No jax imports — the deterministic tier-1 tests drive everything with a
fake clock and zero device work.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Optional

from hypergraphdb_tpu.obs.flight import global_flight as _global_flight

#: injectable time source (seconds, monotonic) — tests pass a fake
Clock = Callable[[], float]

#: attribute value types the JSONL exporter commits to (schema v1)
ATTR_TYPES = (bool, int, float, str, type(None))

#: terminal span names that force-sample their trace (the always-capture
#: set: a failed or shed request is exactly the trace worth keeping)
ALWAYS_SAMPLE_TERMINALS = frozenset({"error", "shed"})

_ids = itertools.count(1)

#: per-process random high bits for trace AND span ids: a joined
#: cross-process tree is reconstructed by (trace id, parent span id), so
#: BOTH key spaces must be collision-free across processes — a server
#: span whose local id equals the client's propagated parent id would
#: misattach the remote subtree. FULL 128-BIT ids (86 random high bits
#: over a 42-bit per-process counter): a multi-chip pod puts many
#: processes behind ONE collector, and the former 62-bit space made
#: cross-process collisions merely improbable instead of negligible —
#: the id-width change is the trace-record schema v2 bump
#: (``obs.export.TRACE_SCHEMA_VERSION``)
_TRACE_ID_BASE = random.SystemRandom().getrandbits(86) << 42

_FLIGHT = _global_flight()


class Span:
    """One timed node of a trace tree. ``t1 is None`` while open."""

    __slots__ = ("span_id", "parent_id", "name", "t0", "t1", "attrs",
                 "_trace")

    def __init__(self, trace: "Trace", name: str,
                 parent_id: Optional[int], t0: float, attrs: dict):
        self.span_id = _TRACE_ID_BASE + next(_ids)
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.attrs = attrs
        self._trace = trace

    def set(self, **attrs) -> "Span":
        """Attach typed attributes (scalars only — the exporter's schema)."""
        for k, v in attrs.items():
            if not isinstance(v, ATTR_TYPES):
                raise TypeError(
                    f"span attr {k}={v!r}: only scalars are exportable"
                )
            self.attrs[k] = v
        return self

    def end(self, t1: Optional[float] = None) -> "Span":
        """Close the span (idempotent — the first end wins). Taken under
        the trace lock so the cross-thread race the serve path relies on
        (submitter ends ``submit`` while the dispatch thread's ``finish``
        closes everything) really is first-end-wins, not check-then-act."""
        tr = self._trace
        with tr._lock:
            if self.t1 is None:
                self.t1 = tr.clock() if t1 is None else t1
        return self

    @property
    def duration(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, t0={self.t0}, t1={self.t1})")


class Trace:
    """A bounded span tree plus free-form ``marks`` (caller-owned refs to
    spans left open across threads, e.g. the serve path's ``queue_wait``).

    Thread-safe: one lock guards the span list and the budget counter —
    a served request's spans are appended from both the submitting and
    the dispatching thread."""

    def __init__(self, name: str, clock: Clock, max_spans: int,
                 attrs: Optional[dict] = None,
                 owner: Optional["Tracer"] = None,
                 trace_id: Optional[int] = None,
                 remote_parent: Optional[int] = None,
                 sampled: bool = True):
        self.name = name
        self.clock = clock
        self.max_spans = max_spans
        self._owner = owner
        self.attrs = dict(attrs or {})
        self.trace_id = (_TRACE_ID_BASE + next(_ids)
                         if trace_id is None else int(trace_id))
        #: propagated remote span id: parentless spans of this trace
        #: attach under it, so the receiver's subtree hangs off the
        #: sender's span in the joined tree (None for local roots)
        self.remote_parent = remote_parent
        #: head-based sampling decision — set at start, upgradable by
        #: force_sample(); unsampled traces are discarded at retain time
        self.sampled = sampled
        self.t0 = clock()
        self.t1: Optional[float] = None
        self.dropped = 0
        self.marks: dict = {}     # caller-owned cross-thread span refs
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._finished = False

    # -- recording -----------------------------------------------------------
    def start_span(self, name: str, parent: Optional[Span] = None,
                   t0: Optional[float] = None, **attrs) -> Span:
        """Open a child span. Over-budget spans are counted and DISCARDED,
        and spans started after ``finish()`` (a cross-thread race: e.g. a
        submitter instrumenting a ticket the dispatch thread already
        resolved) are silently detached — the returned span is real but
        unrecorded in both cases, so call sites never branch."""
        span = Span(self, name,
                    self.remote_parent if parent is None else parent.span_id,
                    self.clock() if t0 is None else t0, {})
        if attrs:
            span.set(**attrs)
        with self._lock:
            if self._finished:
                pass  # already exported: never mutate a retained trace
            elif len(self._spans) < self.max_spans:
                self._spans.append(span)
            else:
                self.dropped += 1
        return span

    def add_span(self, name: str, t0: float, t1: float,
                 parent: Optional[Span] = None, **attrs) -> Span:
        """Record an already-timed interval (device timing hooks measure
        first, attribute after)."""
        return self.start_span(name, parent=parent, t0=t0, **attrs).end(t1)

    @contextmanager
    def span(self, name: str, parent: Optional[Span] = None, **attrs):
        sp = self.start_span(name, parent=parent, **attrs)
        try:
            yield sp
        finally:
            sp.end()

    def finish_terminal(self, name: str, parent: Optional[Span] = None,
                        **attrs) -> None:
        """Record a terminal span (``resolve`` / ``shed`` / ``error`` …)
        under ``parent`` (default: the ``root`` mark) and finish the
        trace — the ONE place the terminal-span schema lives, shared by
        the serve, query, compaction, and peer producers. The
        always-sample terminals (``error``/``shed``) upgrade an
        unsampled trace so incidents survive any sampling rate, and
        every terminal lands one event in the flight recorder. No-op on
        an already-finished trace."""
        if self.finished:
            return
        if name in ALWAYS_SAMPLE_TERMINALS:
            self.force_sample()
        if _FLIGHT.enabled:
            _FLIGHT.record("trace.terminal", trace=self.name,
                           terminal=name)
        self.start_span(
            name,
            parent=parent if parent is not None else self.marks.get("root"),
            **attrs,
        ).end()
        self.finish()

    def finish_error(self, exc: BaseException,
                     parent: Optional[Span] = None, **attrs) -> None:
        """The error terminal: span ``error`` with the exception's type
        name, then finish."""
        self.finish_terminal("error", parent=parent,
                             error=type(exc).__name__, **attrs)

    # -- lifecycle -----------------------------------------------------------
    def finish(self) -> bool:
        """Close the trace (idempotent) and hand it to the owning tracer's
        finished buffer. Returns True on the first call."""
        with self._lock:
            if self._finished:
                return False
            self._finished = True
            self.t1 = self.clock()
            for sp in self._spans:
                if sp.t1 is None:  # inline: Span.end takes THIS lock
                    sp.t1 = self.t1
        if self._owner is not None:
            self._owner._retain(self)
        return True

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._finished

    def force_sample(self) -> None:
        """Upgrade the head-based sampling decision: retain this trace
        regardless of the rate it was started under (errors, sheds,
        breaker trips — the traces an operator is actually hunting)."""
        with self._lock:
            self.sampled = True

    # -- cross-process propagation -------------------------------------------
    def context(self, span: Optional[Span] = None) -> dict:
        """The compact wire context carried on peer messages:
        ``{"tid": trace id, "sid": parent span id, "s": sampled}``.
        ``span`` names the local span remote children should hang under
        (default: the ``root`` mark, else the propagated parent)."""
        if span is None:
            span = self.marks.get("root")
        sid = span.span_id if span is not None else (self.remote_parent or 0)
        with self._lock:
            s = 1 if self.sampled else 0
        return {"tid": self.trace_id, "sid": sid, "s": s}

    # -- reading -------------------------------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> Optional[Span]:
        with self._lock:
            for sp in self._spans:
                if sp.name == name:
                    return sp
        return None

    def children_of(self, span: Optional[Span]) -> list[Span]:
        want = None if span is None else span.span_id
        with self._lock:
            return [s for s in self._spans if s.parent_id == want]


class Tracer:
    """The trace factory + finished-trace buffer. One per process by
    default (``hypergraphdb_tpu.obs.tracer()``), instantiable for tests.

    ``enabled`` is the zero-cost gate: every ``start_trace`` caller checks
    it first (one attribute read); while False nothing is allocated and
    ``start_trace`` returns None.

    Sampling: ``default_sample_rate`` (1.0 = everything) with per-root-kind
    overrides (``set_sample_rate("serve.request", 0.01)``). The decision is
    made at ``start_trace`` (head-based) from a seeded RNG; unsampled
    traces still run (bounded) but are counted into ``traces_dropped``
    instead of retained — unless an always-sample terminal or
    ``force_sample()`` upgrades them. ``enable_adaptive()`` adds the rate
    controller: when the finished buffer fills past ``target_fill`` the
    effective rate scales down (never below ``floor``); a drain that finds
    the pressure gone scales it back up toward 1.0."""

    def __init__(self, clock: Optional[Clock] = None, max_spans: int = 64,
                 max_finished: int = 1024, seed: Optional[int] = None):
        self.clock: Clock = clock or time.perf_counter
        self.max_spans = max_spans
        self.enabled = False
        self.traces_started = 0
        #: unsampled traces discarded at finish (never buffered)
        self.traces_dropped = 0
        #: sampled traces that pushed the FULL buffer (oldest evicted) —
        #: nonzero means the scraper/drain cadence lost data
        self.traces_evicted = 0
        self.default_sample_rate = 1.0
        self._rates: dict[str, float] = {}
        self._rng = random.Random(seed)
        # adaptive controller state (None target = controller off).
        # PER-ROOT-KIND first: pressure halves the scale of the kind
        # holding the largest share of the finished buffer (the hot
        # kind), so replication-qps `peer.push` traces cannot starve
        # `serve.request`'s budget; the GLOBAL scale is the outer clamp,
        # halved only once the hot kind is already at its floor. The
        # effective scale never drops below the floor.
        self._adapt_target: Optional[float] = None
        self._adapt_floor = 0.01
        self._adapt_scale = 1.0
        self._adapt_kind_scales: dict[str, float] = {}
        #: finished-buffer composition by root kind (who is filling it)
        self._kind_fill: dict[str, int] = {}
        self._lock = threading.Lock()
        self._finished: deque[Trace] = deque(maxlen=max_finished)
        self._tls = threading.local()

    # -- lifecycle -----------------------------------------------------------
    def enable(self, clock: Optional[Clock] = None) -> "Tracer":
        with self._lock:
            if clock is not None:
                self.clock = clock
            self.enabled = True
        return self

    def disable(self) -> "Tracer":
        with self._lock:
            self.enabled = False
        return self

    # -- sampling knobs ------------------------------------------------------
    def set_sample_rate(self, name: str, rate: float) -> "Tracer":
        """Per-root-kind head sample rate (exact trace-name match, e.g.
        ``"serve.request"``); rates outside [0, 1] are clamped."""
        with self._lock:
            self._rates[name] = min(1.0, max(0.0, float(rate)))
        return self

    def sample_rate_of(self, name: str) -> float:
        """The EFFECTIVE rate for ``name``: configured × adaptive scale
        (per-kind × global, floored)."""
        with self._lock:
            return (self._rates.get(name, self.default_sample_rate)
                    * self._scale_locked(name))

    def _scale_locked(self, name: str) -> float:
        scale = self._adapt_scale * self._adapt_kind_scales.get(name, 1.0)
        if self._adapt_target is not None:
            scale = max(self._adapt_floor, scale)
        return scale

    def enable_adaptive(self, target_fill: float = 0.5,
                        floor: float = 0.01) -> "Tracer":
        """Turn the rate controller on: when a retain finds the finished
        buffer past ``target_fill`` of its capacity, halve the global
        rate scale (never below ``floor``); a drain that finds the buffer
        under half the target doubles it back toward 1.0. Bounded-buffer
        fill is the controlled variable, so always-on tracing sheds RATE
        under pressure instead of overflowing."""
        with self._lock:
            self._adapt_target = min(1.0, max(0.0, float(target_fill)))
            self._adapt_floor = float(floor)
        return self

    def sampling_snapshot(self) -> dict:
        """The sampling/buffer counters one dict deep — what
        ``bench.py --telemetry`` records per config."""
        with self._lock:
            return {
                "default_rate": self.default_sample_rate,
                "rates": dict(self._rates),
                "adaptive_scale": self._adapt_scale,
                "adaptive_kind_scales": dict(self._adapt_kind_scales),
                "traces_started": self.traces_started,
                "traces_dropped_unsampled": self.traces_dropped,
                "traces_evicted": self.traces_evicted,
                "finished_fill": len(self._finished),
                "finished_capacity": self._finished.maxlen,
            }

    # -- explicit API (cross-thread chains) ----------------------------------
    def start_trace(self, name: str, **attrs) -> Optional[Trace]:
        """A new trace, or None when tracing is off — callers thread the
        returned handle (e.g. on a serve Ticket) and call ``finish_trace``
        when the request resolves. The head-based sampling decision is
        drawn HERE; an unsampled trace still records (bounded) so a later
        error/shed terminal can upgrade it."""
        if not self.enabled:
            return None
        with self._lock:
            self.traces_started += 1
            rate = (self._rates.get(name, self.default_sample_rate)
                    * self._scale_locked(name))
            sampled = rate >= 1.0 or self._rng.random() < rate
        return Trace(name, self.clock, self.max_spans, attrs, owner=self,
                     sampled=sampled)

    def start_remote_trace(self, name: str, ctx: Optional[dict],
                           **attrs) -> Optional[Trace]:
        """The receiving half of cross-process propagation: a trace that
        JOINS the context's tree — same trace id, parentless spans hang
        under the propagated span id, and the SENDER's head sampling
        decision is honored (no local draw, so both halves of a tree are
        kept or dropped together). None when tracing is off or no context
        arrived (then callers fall back to ``start_trace`` or nothing)."""
        if not self.enabled or not ctx:
            return None
        try:
            tid = int(ctx["tid"])
            sid = int(ctx["sid"]) or None
            sampled = bool(ctx.get("s", 1))
        except (KeyError, TypeError, ValueError):
            return None  # malformed context from a foreign/older peer
        with self._lock:
            self.traces_started += 1
        return Trace(name, self.clock, self.max_spans, attrs, owner=self,
                     trace_id=tid, remote_parent=sid, sampled=sampled)

    def finish_trace(self, trace: Optional[Trace]) -> None:
        """Close + retain a trace (idempotent, None-tolerant)."""
        if trace is not None:
            trace.finish()

    def _retain(self, trace: Trace) -> None:
        with self._lock:
            if not trace.sampled:
                self.traces_dropped += 1
                return
            if len(self._finished) == self._finished.maxlen:
                self.traces_evicted += 1  # deque evicts the oldest
                old = self._finished[0]
                n = self._kind_fill.get(old.name, 0)
                if n > 1:
                    self._kind_fill[old.name] = n - 1
                else:
                    self._kind_fill.pop(old.name, None)
            self._finished.append(trace)
            self._kind_fill[trace.name] = (
                self._kind_fill.get(trace.name, 0) + 1
            )
            if (self._adapt_target is not None
                    and self._finished.maxlen
                    and len(self._finished)
                    >= self._adapt_target * self._finished.maxlen):
                # per-kind controller first: throttle whoever owns the
                # largest share of the buffer, not every kind at once
                hot = max(self._kind_fill, key=self._kind_fill.get)
                cur = self._adapt_kind_scales.get(hot, 1.0)
                if cur > self._adapt_floor:
                    self._adapt_kind_scales[hot] = max(
                        self._adapt_floor, cur * 0.5
                    )
                else:
                    # the hot kind is floored and pressure persists:
                    # the global scale is the outer clamp
                    self._adapt_scale = max(self._adapt_floor,
                                            self._adapt_scale * 0.5)

    # -- implicit API (single-thread chains) ---------------------------------
    @contextmanager
    def trace_ctx(self, name: str, **attrs):
        """Open a trace AND make it the thread's current one, so nested
        ``tracer.span(...)`` calls attach without handle-threading. Yields
        None when tracing is off (callers never branch — ``span`` no-ops
        with no current trace)."""
        tr = self.start_trace(name, **attrs)
        if tr is None:
            yield None
            return
        stack = self._stack()
        root = tr.start_span(name)
        stack.append((tr, root))
        try:
            yield tr
        finally:
            stack.pop()
            root.end()
            self.finish_trace(tr)

    @contextmanager
    def span(self, name: str, **attrs):
        """A span under the thread's current trace (no-op without one)."""
        stack = self._stack()
        if not stack:
            yield None
            return
        tr, parent = stack[-1]
        sp = tr.start_span(name, parent=parent, **attrs)
        stack.append((tr, sp))
        try:
            yield sp
        finally:
            stack.pop()
            sp.end()

    def current_trace(self) -> Optional[Trace]:
        stack = self._stack()
        return stack[-1][0] if stack else None

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- reading -------------------------------------------------------------
    def drain(self) -> list[Trace]:
        """Pop every finished trace (export consumes the buffer). With
        the adaptive controller on, a drain that finds the pressure gone
        grows the rate scale back toward 1.0."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            self._kind_fill.clear()
            if (self._adapt_target is not None and self._finished.maxlen
                    and len(out)
                    < 0.5 * self._adapt_target * self._finished.maxlen):
                self._adapt_scale = min(1.0, self._adapt_scale * 2.0)
                for k, v in list(self._adapt_kind_scales.items()):
                    grown = min(1.0, v * 2.0)
                    if grown >= 1.0:
                        del self._adapt_kind_scales[k]
                    else:
                        self._adapt_kind_scales[k] = grown
            return out

    def peek(self, n: Optional[int] = None) -> list[Trace]:
        """The most recent finished traces WITHOUT consuming them — the
        ``/debug/traces`` read (drain() stays the exporter's)."""
        with self._lock:
            out = list(self._finished)
        return out if n is None else out[-int(n):]

    def finished_count(self) -> int:
        with self._lock:
            return len(self._finished)


#: the process-wide tracer — disabled until obs.enable()
_GLOBAL = Tracer()


def global_tracer() -> Tracer:
    return _GLOBAL
