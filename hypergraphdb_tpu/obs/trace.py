"""Request tracing: lightweight span trees with explicit parenting.

A :class:`Trace` is a bounded tree of :class:`Span` records, all stamped
by ONE injectable clock (fake clocks in tests make every duration exact).
Spans carry typed attributes; parenting is EXPLICIT (``parent=``) because
the span chains this repo cares about cross threads — a served request's
``submit`` span is opened on the caller's thread and its ``collect`` span
on the dispatch thread, so an implicit thread-local "current span" could
never link them. Every in-tree producer (the serve chain, the query
compiler's ``compile → plan → execute``, the compaction pass's
``compact → buffer_drain → device_swap``) uses the explicit API; a
thread-local convenience layer (``Tracer.trace_ctx`` / ``Tracer.span``)
is offered for ad-hoc single-thread instrumentation.

Safety properties that make tracing reasonable to leave on:

- **off-gate**: ``Tracer.enabled`` is a plain attribute; every
  instrumentation site reads it (or a ``Ticket.trace is None`` it
  derives from) ONCE and allocates nothing when tracing is off;
- **span budget**: each trace records at most ``max_spans`` spans —
  overflow spans are counted in ``Trace.dropped`` and discarded, never
  accumulated (a pathological per-row instrumentation bug degrades to a
  counter, not an OOM);
- **bounded retention**: finished traces land in a ``maxlen`` deque on
  the tracer (``drain()`` hands them to the exporter); a server nobody
  scrapes stays O(max_finished), not O(requests).

No jax imports — the deterministic tier-1 tests drive everything with a
fake clock and zero device work.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Optional

#: injectable time source (seconds, monotonic) — tests pass a fake
Clock = Callable[[], float]

#: attribute value types the JSONL exporter commits to (schema v1)
ATTR_TYPES = (bool, int, float, str, type(None))

_ids = itertools.count(1)


class Span:
    """One timed node of a trace tree. ``t1 is None`` while open."""

    __slots__ = ("span_id", "parent_id", "name", "t0", "t1", "attrs",
                 "_trace")

    def __init__(self, trace: "Trace", name: str,
                 parent_id: Optional[int], t0: float, attrs: dict):
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.attrs = attrs
        self._trace = trace

    def set(self, **attrs) -> "Span":
        """Attach typed attributes (scalars only — the exporter's schema)."""
        for k, v in attrs.items():
            if not isinstance(v, ATTR_TYPES):
                raise TypeError(
                    f"span attr {k}={v!r}: only scalars are exportable"
                )
            self.attrs[k] = v
        return self

    def end(self, t1: Optional[float] = None) -> "Span":
        """Close the span (idempotent — the first end wins). Taken under
        the trace lock so the cross-thread race the serve path relies on
        (submitter ends ``submit`` while the dispatch thread's ``finish``
        closes everything) really is first-end-wins, not check-then-act."""
        tr = self._trace
        with tr._lock:
            if self.t1 is None:
                self.t1 = tr.clock() if t1 is None else t1
        return self

    @property
    def duration(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, t0={self.t0}, t1={self.t1})")


class Trace:
    """A bounded span tree plus free-form ``marks`` (caller-owned refs to
    spans left open across threads, e.g. the serve path's ``queue_wait``).

    Thread-safe: one lock guards the span list and the budget counter —
    a served request's spans are appended from both the submitting and
    the dispatching thread."""

    def __init__(self, name: str, clock: Clock, max_spans: int,
                 attrs: Optional[dict] = None,
                 owner: Optional["Tracer"] = None):
        self.name = name
        self.clock = clock
        self.max_spans = max_spans
        self._owner = owner
        self.attrs = dict(attrs or {})
        self.trace_id = next(_ids)
        self.t0 = clock()
        self.t1: Optional[float] = None
        self.dropped = 0
        self.marks: dict = {}     # caller-owned cross-thread span refs
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._finished = False

    # -- recording -----------------------------------------------------------
    def start_span(self, name: str, parent: Optional[Span] = None,
                   t0: Optional[float] = None, **attrs) -> Span:
        """Open a child span. Over-budget spans are counted and DISCARDED,
        and spans started after ``finish()`` (a cross-thread race: e.g. a
        submitter instrumenting a ticket the dispatch thread already
        resolved) are silently detached — the returned span is real but
        unrecorded in both cases, so call sites never branch."""
        span = Span(self, name,
                    None if parent is None else parent.span_id,
                    self.clock() if t0 is None else t0, {})
        if attrs:
            span.set(**attrs)
        with self._lock:
            if self._finished:
                pass  # already exported: never mutate a retained trace
            elif len(self._spans) < self.max_spans:
                self._spans.append(span)
            else:
                self.dropped += 1
        return span

    def add_span(self, name: str, t0: float, t1: float,
                 parent: Optional[Span] = None, **attrs) -> Span:
        """Record an already-timed interval (device timing hooks measure
        first, attribute after)."""
        return self.start_span(name, parent=parent, t0=t0, **attrs).end(t1)

    @contextmanager
    def span(self, name: str, parent: Optional[Span] = None, **attrs):
        sp = self.start_span(name, parent=parent, **attrs)
        try:
            yield sp
        finally:
            sp.end()

    def finish_terminal(self, name: str, parent: Optional[Span] = None,
                        **attrs) -> None:
        """Record a terminal span (``resolve`` / ``shed`` / ``error`` …)
        under ``parent`` (default: the ``root`` mark) and finish the
        trace — the ONE place the terminal-span schema lives, shared by
        the serve, query, and compaction producers. No-op on an
        already-finished trace."""
        if self.finished:
            return
        self.start_span(
            name,
            parent=parent if parent is not None else self.marks.get("root"),
            **attrs,
        ).end()
        self.finish()

    def finish_error(self, exc: BaseException,
                     parent: Optional[Span] = None, **attrs) -> None:
        """The error terminal: span ``error`` with the exception's type
        name, then finish."""
        self.finish_terminal("error", parent=parent,
                             error=type(exc).__name__, **attrs)

    # -- lifecycle -----------------------------------------------------------
    def finish(self) -> bool:
        """Close the trace (idempotent) and hand it to the owning tracer's
        finished buffer. Returns True on the first call."""
        with self._lock:
            if self._finished:
                return False
            self._finished = True
            self.t1 = self.clock()
            for sp in self._spans:
                if sp.t1 is None:  # inline: Span.end takes THIS lock
                    sp.t1 = self.t1
        if self._owner is not None:
            self._owner._retain(self)
        return True

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._finished

    # -- reading -------------------------------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> Optional[Span]:
        with self._lock:
            for sp in self._spans:
                if sp.name == name:
                    return sp
        return None

    def children_of(self, span: Optional[Span]) -> list[Span]:
        want = None if span is None else span.span_id
        with self._lock:
            return [s for s in self._spans if s.parent_id == want]


class Tracer:
    """The trace factory + finished-trace buffer. One per process by
    default (``hypergraphdb_tpu.obs.tracer()``), instantiable for tests.

    ``enabled`` is the zero-cost gate: every ``start_trace`` caller checks
    it first (one attribute read); while False nothing is allocated and
    ``start_trace`` returns None."""

    def __init__(self, clock: Optional[Clock] = None, max_spans: int = 64,
                 max_finished: int = 1024):
        self.clock: Clock = clock or time.perf_counter
        self.max_spans = max_spans
        self.enabled = False
        self.traces_started = 0
        self._lock = threading.Lock()
        self._finished: deque[Trace] = deque(maxlen=max_finished)
        self._tls = threading.local()

    # -- lifecycle -----------------------------------------------------------
    def enable(self, clock: Optional[Clock] = None) -> "Tracer":
        with self._lock:
            if clock is not None:
                self.clock = clock
            self.enabled = True
        return self

    def disable(self) -> "Tracer":
        with self._lock:
            self.enabled = False
        return self

    # -- explicit API (cross-thread chains) ----------------------------------
    def start_trace(self, name: str, **attrs) -> Optional[Trace]:
        """A new trace, or None when tracing is off — callers thread the
        returned handle (e.g. on a serve Ticket) and call ``finish_trace``
        when the request resolves."""
        if not self.enabled:
            return None
        tr = Trace(name, self.clock, self.max_spans, attrs, owner=self)
        with self._lock:
            self.traces_started += 1
        return tr

    def finish_trace(self, trace: Optional[Trace]) -> None:
        """Close + retain a trace (idempotent, None-tolerant)."""
        if trace is not None:
            trace.finish()

    def _retain(self, trace: Trace) -> None:
        with self._lock:
            self._finished.append(trace)

    # -- implicit API (single-thread chains) ---------------------------------
    @contextmanager
    def trace_ctx(self, name: str, **attrs):
        """Open a trace AND make it the thread's current one, so nested
        ``tracer.span(...)`` calls attach without handle-threading. Yields
        None when tracing is off (callers never branch — ``span`` no-ops
        with no current trace)."""
        tr = self.start_trace(name, **attrs)
        if tr is None:
            yield None
            return
        stack = self._stack()
        root = tr.start_span(name)
        stack.append((tr, root))
        try:
            yield tr
        finally:
            stack.pop()
            root.end()
            self.finish_trace(tr)

    @contextmanager
    def span(self, name: str, **attrs):
        """A span under the thread's current trace (no-op without one)."""
        stack = self._stack()
        if not stack:
            yield None
            return
        tr, parent = stack[-1]
        sp = tr.start_span(name, parent=parent, **attrs)
        stack.append((tr, sp))
        try:
            yield sp
        finally:
            stack.pop()
            sp.end()

    def current_trace(self) -> Optional[Trace]:
        stack = self._stack()
        return stack[-1][0] if stack else None

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- reading -------------------------------------------------------------
    def drain(self) -> list[Trace]:
        """Pop every finished trace (export consumes the buffer)."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            return out

    def finished_count(self) -> int:
        with self._lock:
            return len(self._finished)


#: the process-wide tracer — disabled until obs.enable()
_GLOBAL = Tracer()


def global_tracer() -> Tracer:
    return _GLOBAL
