"""One metrics registry: counters, gauges, log-bucketed histograms.

This replaces the repo's two disjoint metric surfaces — the
``utils.metrics.Metrics`` ``(count, total, max)`` timing triples and the
``serve.stats.ServeStats`` latency ring — with ONE instrument vocabulary:

- :class:`Counter` — monotonic int (``inc``);
- :class:`Gauge`   — last-write float (``set``);
- :class:`Histogram` — log-bucketed distribution. Buckets grow
  geometrically (default ×2 from 1 µs): 31 bounds + the +Inf tail span
  1 µs → ~10³ s with bounded relative error; ``count/total/max`` ride along so the old
  timing-triple reports cost nothing extra. An optional bounded
  ``window`` keeps the most recent raw samples for EXACT percentiles
  (the ServeStats latency ring, now inside the shared instrument);
  without a window, percentiles come from the buckets (error ≤ one
  bucket ratio).

A :class:`Registry` is a flat dotted-name → instrument map. There is one
process-wide default (``default_registry()``); everything is instantiable
so tests and per-graph/per-runtime surfaces stay isolated. Names are
namespaced by convention (``serve.*``, ``graph.*``, ``compact.*``,
``query.*``, ``tx.*`` — see README "Observability"); registering the same
name as two different kinds is an error, which is what keeps the
namespace drift-free.

Lock discipline (hglint HG402): the registry lock guards the name map;
each instrument owns its own lock for its counters — recording never
takes the registry lock, and no path holds two instrument locks at once.
"""

from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Optional, Sequence

#: default log-bucket boundaries: ×2 from 1 µs to ~1100 s (seconds-scaled
#: instruments; pass explicit ``bounds`` for anything else)
DEFAULT_BOUNDS = tuple(1e-6 * 2.0 ** k for k in range(31))


class Counter:
    """Monotonic counter (``.value`` reads, ``inc`` writes)."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._n = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n

    def reset(self) -> None:
        with self._lock:
            self._n = 0


class Gauge:
    """Last-write-wins float."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._v = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def reset(self) -> None:
        with self._lock:
            self._v = 0.0


class Histogram:
    """Log-bucketed distribution with count/total/max and optional exact
    percentile window.

    ``bounds`` are the bucket UPPER edges (ascending); an implicit +Inf
    bucket catches the tail, so ``observe`` never fails on range."""

    kind = "histogram"

    def __init__(self, name: str, bounds: Sequence[float] = DEFAULT_BOUNDS,
                 window: int = 0):
        if list(bounds) != sorted(set(bounds)):
            raise ValueError("histogram bounds must be ascending, unique")
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)  # +Inf tail bucket
        self._count = 0
        self._total = 0.0
        self._max = 0.0
        self._window: Optional[deque] = (
            deque(maxlen=window) if window else None
        )

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)  # first bound >= v
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._total += v
            if v > self._max:
                self._max = v
            if self._window is not None:
                self._window.append(v)

    # -- reading -------------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def total(self) -> float:
        with self._lock:
            return self._total

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    @property
    def mean(self) -> float:
        with self._lock:
            return self._total / self._count if self._count else 0.0

    def summary(self) -> dict:
        """count/total/mean/max under ONE lock acquisition — reading the
        properties separately can tear against a concurrent observe
        (mean × count ≠ total)."""
        with self._lock:
            return {
                "count": self._count,
                "total": self._total,
                "mean": self._total / self._count if self._count else 0.0,
                "max": self._max,
            }

    def percentile(self, p: float) -> Optional[float]:
        """p ∈ [0, 1]. EXACT over the raw-sample window when one is
        configured (and non-empty); otherwise the bucket upper edge at the
        cumulative rank — error bounded by one bucket ratio. None before
        any observation."""
        return self.percentiles((p,))[0]

    def percentiles(self, ps: Sequence[float]) -> list[Optional[float]]:
        """Several percentiles from ONE locked read (one window sort) —
        separate :meth:`percentile` calls each see a different live state,
        so a concurrently-updated window could report p50 > p99."""
        for p in ps:
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"percentile {p} outside [0, 1]")
        # one locked SNAPSHOT (the consistency contract), but the window
        # sort itself runs outside the lock — a wide window must not
        # stall concurrent observe() calls
        with self._lock:
            window = list(self._window) if self._window else None
            if window is None:
                if not self._count:
                    return [None] * len(ps)
                return [self._bucket_percentile_locked(p) for p in ps]
        lat = sorted(window)
        return [
            lat[min(len(lat) - 1, int(round(p * (len(lat) - 1))))]
            for p in ps
        ]

    def _bucket_percentile_locked(self, p: float) -> float:
        rank = p * self._count
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank and c:
                if i < len(self.bounds):
                    return self.bounds[i]
                return self._max  # +Inf tail: best bound we have
        return self._max

    def bucket_counts(self) -> list[tuple[float, int]]:
        """Cumulative (upper_edge, count) pairs, Prometheus-style, ending
        with (+Inf, count)."""
        return self.export_state()[0]

    def export_state(self) -> tuple[list[tuple[float, int]], float, int]:
        """(cumulative buckets, sum, count) under ONE lock — the scrape
        read. Separate reads could emit an exposition whose ``_sum``
        disagrees with its own ``_bucket``/``_count`` lines."""
        with self._lock:
            out, cum = [], 0
            for edge, c in zip(self.bounds, self._counts):
                cum += c
                out.append((edge, cum))
            out.append((math.inf, cum + self._counts[-1]))
            return out, self._total, self._count

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._total = 0.0
            self._max = 0.0
            if self._window is not None:
                self._window.clear()


class Registry:
    """Flat name → instrument map; get-or-create, kind-checked."""

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get_or_create(self, name: str, kind: str, factory):
        if not name or name != name.strip("."):
            raise ValueError(f"bad metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, "counter", lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, "gauge", lambda: Gauge(name))

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_BOUNDS,
                  window: int = 0) -> Histogram:
        m = self._get_or_create(
            name, "histogram", lambda: Histogram(name, bounds, window)
        )
        # drift guard, same spirit as the kind check: explicitly-requested
        # non-default params must match the existing instrument — a caller
        # asking for an exact-percentile window must not silently get a
        # windowless histogram someone else registered first (default-arg
        # calls are treated as pure gets)
        want_bounds = tuple(float(b) for b in bounds)
        if want_bounds != tuple(DEFAULT_BOUNDS) and want_bounds != m.bounds:
            raise ValueError(
                f"histogram {name!r} already registered with different "
                "bounds"
            )
        if window and (m._window is None or m._window.maxlen != window):
            raise ValueError(
                f"histogram {name!r} already registered with window="
                f"{None if m._window is None else m._window.maxlen}, "
                f"requested {window}"
            )
        return m

    # -- reading -------------------------------------------------------------
    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            names = list(self._metrics)
        return sorted(names)

    def instruments(self) -> list:
        with self._lock:
            metrics = dict(self._metrics)
        return [metrics[k] for k in sorted(metrics)]

    def snapshot(self) -> dict:
        """{name: scalar | histogram summary} — the debug dump."""
        out = {}
        for m in self.instruments():
            if m.kind == "histogram":
                out[m.name] = m.summary()
            else:
                out[m.name] = m.value
        return out

    def reset(self) -> None:
        for m in self.instruments():
            m.reset()


#: the process-wide registry (kernel wrappers, global_metrics)
_DEFAULT = Registry("default")


def default_registry() -> Registry:
    return _DEFAULT
