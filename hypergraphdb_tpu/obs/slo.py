"""Declarative SLOs: sliding-window error budgets + burn-rate alerts.

An :class:`Objective` states a contract ("99% of requests beat their
deadline") as a good/bad event ratio target over sliding windows; the
:class:`SLOMonitor` samples each objective's CUMULATIVE good/bad totals
(pulled from a source callable, or pushed via :meth:`SLOMonitor.record`),
keeps a bounded time-series per objective, and evaluates **multi-window
burn rates** — the Google-SRE alerting shape: the error-budget burn rate
is ``error_ratio / (1 - target)`` (1.0 = spending the budget exactly at
its sustainable pace), and an alert fires only when EVERY configured
window burns past its threshold (the short window proves the problem is
happening NOW, the long window proves it is not a blip).

Alerts are **flight-recorder incidents**: an alert edge calls
``FlightRecorder.incident("slo_burn_<name>", ...)`` — the same
rate-limited window-dump machinery every other incident producer uses,
so the minutes leading into a burn are on disk next to the breaker trips
and typed serve errors that usually explain it. Alerting is
edge-triggered with hysteresis (re-arms once every window drops below
its threshold), so a sustained burn costs one incident, not one per
evaluation.

The whole module is clock-injected and import-light (no jax, no HTTP):
the deterministic tier-1 tests drive windows with a fake clock, and the
fleet collector (:mod:`~hypergraphdb_tpu.obs.fleet`) ticks one monitor
per poll. :func:`fleet_objectives` wires the standard fleet trio —
deadline-hit ratio from the ``serve.*`` terminals, replication-lag bound
from replica healthz, availability from breaker/gate states — over a
:class:`~hypergraphdb_tpu.obs.fleet.FleetCollector`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from hypergraphdb_tpu.obs.flight import FlightRecorder, global_flight

#: default multi-window burn thresholds — (window_s, burn_rate): the
#: classic fast-burn pair scaled to serving-test time constants (a 1h/5m
#: page ladder makes no sense inside a CI smoke; deployments pass their
#: own windows)
DEFAULT_WINDOWS = ((60.0, 14.4), (300.0, 6.0))

#: a source yields (good_total, bad_total) CUMULATIVE counts
Source = Callable[[], tuple]


@dataclass(frozen=True)
class Objective:
    """One declarative SLO.

    ``target`` is the good-ratio contract (0.99 = 1% error budget);
    ``windows`` the multi-window burn alert config: ``(seconds,
    burn_threshold)`` pairs — ALL windows must burn past their threshold
    to alert. Windows must be sorted ascending by span; the longest one
    is also the budget-remaining report window."""

    name: str
    target: float
    description: str = ""
    windows: tuple = DEFAULT_WINDOWS

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target {self.target} outside (0, 1)")
        if not self.windows:
            raise ValueError("at least one burn window required")
        spans = [w for w, _ in self.windows]
        if spans != sorted(spans):
            raise ValueError("windows must ascend by span")


@dataclass
class _State:
    """Per-objective monitor state: bounded cumulative sample series +
    alert hysteresis."""

    objective: Objective
    source: Optional[Source]
    #: (t, good_total, bad_total) samples, oldest first
    samples: deque = field(default_factory=deque)
    alerting: bool = False
    alerts: int = 0
    last_incident_path: Optional[str] = None


def _window_delta(samples: deque, now: float, span: float):
    """(Δgood, Δbad) over the trailing ``span`` seconds: newest sample
    minus the latest sample at/before the window start (the window sees
    the whole gap a sparse poll cadence leaves). None before 2 samples."""
    if len(samples) < 2:
        return None
    t1, g1, b1 = samples[-1]
    base = None
    cutoff = now - span
    for t, g, b in samples:
        if t <= cutoff:
            base = (t, g, b)
        else:
            break
    if base is None:
        base = samples[0]
    _, g0, b0 = base
    return max(0, g1 - g0), max(0, b1 - b0)


class SLOMonitor:
    """The evaluator. Thread-safe: ``tick`` runs on the collector's poll
    thread while ``snapshot`` serves HTTP scrapes."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 flight: Optional[FlightRecorder] = None,
                 max_samples: int = 4096):
        self.clock = clock or time.monotonic
        self.flight = flight if flight is not None else global_flight()
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._states: dict[str, _State] = {}

    # -- registration --------------------------------------------------------
    def add(self, objective: Objective,
            source: Optional[Source] = None) -> "SLOMonitor":
        """Register one objective. ``source`` (optional) is pulled on
        every :meth:`tick`; push totals with :meth:`record` otherwise.
        Re-adding a name replaces the objective but KEEPS its series
        (live reconfiguration must not blind the windows)."""
        with self._lock:
            st = self._states.get(objective.name)
            if st is None:
                self._states[objective.name] = _State(objective, source)
            else:
                st.objective = objective
                if source is not None:
                    st.source = source
        return self

    def objectives(self) -> list[Objective]:
        with self._lock:
            return [st.objective for st in self._states.values()]

    # -- feeding -------------------------------------------------------------
    def record(self, name: str, good_total: int, bad_total: int,
               t: Optional[float] = None) -> None:
        """Append one cumulative sample for ``name`` (unknown names are
        ignored — a fleet node may advertise objectives this monitor
        does not carry)."""
        with self._lock:
            st = self._states.get(name)
            if st is None:
                return
            st.samples.append((self.clock() if t is None else float(t),
                               int(good_total), int(bad_total)))
            while len(st.samples) > self.max_samples:
                st.samples.popleft()

    def tick(self) -> dict:
        """Pull every sourced objective once, evaluate ALL objectives,
        fire incident on alert edges. Returns the evaluation snapshot
        (same shape as :meth:`snapshot`)."""
        with self._lock:
            pulls = [(name, st.source) for name, st in self._states.items()
                     if st.source is not None]
        for name, source in pulls:
            try:
                good, bad = source()
            except Exception:  # noqa: BLE001 - a broken source ≠ dead monitor
                continue
            self.record(name, good, bad)
        return self._evaluate()

    # -- evaluation ----------------------------------------------------------
    def _evaluate(self, mutate: bool = True) -> dict:
        now = self.clock()
        fire: list[tuple] = []
        out: dict = {}
        with self._lock:
            for name, st in self._states.items():
                obj = st.objective
                budget = 1.0 - obj.target
                wins = []
                all_burning = True
                any_burning = False
                for span, threshold in obj.windows:
                    d = _window_delta(st.samples, now, span)
                    if d is None or (d[0] + d[1]) == 0:
                        # no events in window: not burning (an idle
                        # fleet must not page), and not alert-worthy
                        wins.append({"window_s": span, "events": 0,
                                     "error_ratio": None, "burn_rate": None,
                                     "threshold": threshold,
                                     "burning": False})
                        all_burning = False
                        continue
                    good, bad = d
                    ratio = bad / (good + bad)
                    burn = ratio / budget
                    burning = burn >= threshold
                    all_burning = all_burning and burning
                    any_burning = any_burning or burning
                    wins.append({"window_s": span, "events": good + bad,
                                 "error_ratio": round(ratio, 6),
                                 "burn_rate": round(burn, 4),
                                 "threshold": threshold,
                                 "burning": burning})
                if mutate and all_burning and not st.alerting:
                    st.alerting = True
                    st.alerts += 1
                    fire.append((st, dict(
                        objective=name, target=obj.target,
                        **{f"burn_{int(w['window_s'])}s": w["burn_rate"]
                           for w in wins},
                    )))
                elif mutate and st.alerting and not any_burning:
                    # hysteresis re-arm only once EVERY window recovers:
                    # a sustained burn whose short window flaps (one
                    # clean burst, then burning again) stays ONE alert,
                    # not one incident per oscillation
                    st.alerting = False
                long_ratio = next(
                    (w["error_ratio"] for w in reversed(wins)
                     if w["error_ratio"] is not None), None,
                )
                out[name] = {
                    "target": obj.target,
                    "description": obj.description,
                    "windows": wins,
                    "alerting": st.alerting,
                    "alerts_total": st.alerts,
                    "budget_remaining": (
                        None if long_ratio is None
                        else round(1.0 - long_ratio / budget, 4)
                    ),
                    "last_incident": st.last_incident_path,
                }
        # incidents OUTSIDE the lock: the recorder writes files
        for st, fields in fire:
            path = self.flight.incident(
                "slo_burn_" + st.objective.name, **fields
            )
            if path is not None:
                with self._lock:
                    st.last_incident_path = path
                out[st.objective.name]["last_incident"] = path
        return out

    def snapshot(self) -> dict:
        """The ``/fleet/slo`` body: every objective's windows, burn
        rates, alert state, and budget remaining — a READ: no new
        samples, no alert-edge transitions, no incidents (scrapes must
        not fire or re-arm alerts; only :meth:`tick` does)."""
        return self._evaluate(mutate=False)


# ------------------------------------------------------- fleet standard trio


def fleet_objectives(collector, monitor: Optional[SLOMonitor] = None,
                     deadline_target: float = 0.99,
                     lag_target: float = 0.999,
                     availability_target: float = 0.999,
                     perf_target: float = 0.999,
                     sub_target: float = 0.999,
                     windows: tuple = DEFAULT_WINDOWS) -> SLOMonitor:
    """Wire the standard fleet SLO set over a
    :class:`~hypergraphdb_tpu.obs.fleet.FleetCollector`:

    - ``serve_deadline`` — deadline-hit ratio from the ``serve.*``
      terminals (good = completed, bad = shed past deadline), summed
      across every node's scrape;
    - ``replication_lag`` — per poll, each replica whose advertised lag
      exceeds its own advertised bound is one bad event;
    - ``availability`` — per poll, each node unreachable, unhealthy, or
      with an OPEN serve breaker is one bad event;
    - ``perf_drift`` — per poll, each node whose perf sentinel
      (``obs.perf.PerfSentinel``, advertised as the ``perf`` healthz
      section) reports ANY lane or skew violation is one bad event —
      the fleet-level error budget over the hgperf verdicts. Nodes
      without a sentinel don't vote (absent ≠ healthy);
    - ``sub_staleness`` — per poll, each node whose hgsub subscription
      tier (the ``sub`` healthz section) reports a standing query dirty
      past its staleness bound is one bad event — the freshness budget
      of the streaming tier. Nodes without subscriptions don't vote.

    Returns the monitor (created on the collector's clock when not
    passed) — attach it with ``FleetCollector(..., slo=monitor)`` or
    ``collector.slo = monitor``."""
    mon = monitor or SLOMonitor(clock=collector.clock,
                                flight=collector.flight)

    def deadline_source():
        good = collector.metric_total("serve_completed_total")
        bad = collector.metric_total("serve_shed_deadline_total")
        return int(good), int(bad)

    # level-triggered objectives accumulate poll verdicts here (sources
    # must yield CUMULATIVE totals)
    acc = {"lag": [0, 0], "avail": [0, 0], "perf": [0, 0],
           "sub": [0, 0]}

    def lag_source():
        good, bad = 0, 0
        for scrape in collector.node_scrapes().values():
            h = scrape.health or {}
            if h.get("role") != "replica":
                continue
            lag, bound = h.get("replication_lag"), h.get("lag_bound")
            if lag is None or bound is None:
                continue
            if int(lag) > int(bound):
                bad += 1
            else:
                good += 1
        acc["lag"][0] += good
        acc["lag"][1] += bad
        return tuple(acc["lag"])

    def avail_source():
        good, bad = 0, 0
        for scrape in collector.node_scrapes().values():
            h = scrape.health or {}
            down = (not scrape.ok or not scrape.healthy
                    or int(h.get("breaker_worst", 0) or 0) >= 2)
            if down:
                bad += 1
            else:
                good += 1
        acc["avail"][0] += good
        acc["avail"][1] += bad
        return tuple(acc["avail"])

    def perf_source():
        good, bad = 0, 0
        for scrape in collector.node_scrapes().values():
            p = (scrape.health or {}).get("perf")
            if not isinstance(p, dict):
                continue  # no sentinel on this node: it doesn't vote
            if p.get("violating"):
                bad += 1
            else:
                good += 1
        acc["perf"][0] += good
        acc["perf"][1] += bad
        return tuple(acc["perf"])

    def sub_source():
        good, bad = 0, 0
        for scrape in collector.node_scrapes().values():
            s = (scrape.health or {}).get("sub")
            if not isinstance(s, dict):
                continue  # no subscription tier here: it doesn't vote
            if s.get("violating"):
                bad += 1
            else:
                good += 1
        acc["sub"][0] += good
        acc["sub"][1] += bad
        return tuple(acc["sub"])

    mon.add(Objective("serve_deadline", deadline_target,
                      "requests resolved within their deadline",
                      windows), deadline_source)
    mon.add(Objective("replication_lag", lag_target,
                      "replicas inside their advertised lag bound",
                      windows), lag_source)
    mon.add(Objective("availability", availability_target,
                      "nodes reachable, healthy, breakers not open",
                      windows), avail_source)
    mon.add(Objective("perf_drift", perf_target,
                      "nodes with every lane inside its perf baseline",
                      windows), perf_source)
    mon.add(Objective("sub_staleness", sub_target,
                      "nodes with every standing query inside its "
                      "staleness bound", windows), sub_source)
    return mon
