"""hgobs — the unified observability subsystem.

One surface every layer reports into, replacing the reference's scatter
of ad-hoc counters (``HGStats`` / ``TxMonitor`` / ``HGIndexStats``) that
this repro had faithfully reproduced as ``utils.metrics.Metrics`` vs
``serve.stats.ServeStats``:

- **tracing** (:mod:`~hypergraphdb_tpu.obs.trace`): bounded span trees
  with explicit parenting and injectable clocks. A served request emits
  ``submit → queue_wait → batch_form → launch → device → collect →
  resolve`` (or ``shed`` / ``host_fallback``); a compaction pass emits
  ``compact → buffer_drain → device_swap``; a query emits
  ``compile → plan → execute``;
- **metrics** (:mod:`~hypergraphdb_tpu.obs.registry`): one registry of
  counters/gauges/log-bucketed histograms under dotted namespaces
  (``serve.*``, ``graph.*``, ``compact.*``, ``query.*``, ``tx.*``);
- **device timing** (:mod:`~hypergraphdb_tpu.obs.device`): opt-in
  launch→ready wall deltas, per-dispatch profiler annotations, and a
  gated ``jax.profiler`` session;
- **export** (:mod:`~hypergraphdb_tpu.obs.export`): Prometheus text and
  schema-versioned JSONL traces;
- **flight recorder** (:mod:`~hypergraphdb_tpu.obs.flight`): an
  always-on bounded ring of recent structured events (span terminals,
  fault firings, breaker transitions, retries, compaction swaps) that
  dumps its window to JSONL on incident;
- **HTTP endpoint** (:mod:`~hypergraphdb_tpu.obs.http`): ``/metrics``
  (Prometheus scrape), ``/healthz`` (per-key breaker states + queue
  depth + staleness), ``/debug/traces``, ``/debug/flight``;
- **fleet plane** (:mod:`~hypergraphdb_tpu.obs.fleet`): one collector
  over every process behind the front door — per-node-labelled metric
  merges, cross-process span-tree assembly on the 128-bit trace ids,
  remote incident-window retention, and per-request EXPLAIN cost
  attribution;
- **SLOs** (:mod:`~hypergraphdb_tpu.obs.slo`): declarative objectives
  over sliding windows with multi-window error-budget burn-rate alerts
  that fire as flight-recorder incidents;
- **perf sentinel** (:mod:`~hypergraphdb_tpu.obs.perf`): per-lane
  rolling digests vs the committed ``PERF_BASELINE.json``, multi-window
  drift detection with auto-captured incident profiles, and mesh
  skew/straggler attribution — the runtime twin of hgverify's HV401
  static cost gate.

Cross-process tracing: trace contexts propagate over peer messages
(``peer/messages.attach_trace``), so a replication push or snapshot
transfer is ONE span tree spanning sender and receiver — see
``obs.trace`` and README "Distributed tracing & operations".

Overhead contract: with tracing DISABLED (the default), every
instrumentation site costs one attribute read and allocates nothing —
regression-tested by ``tests/test_obs_serving.py``. With tracing ON,
head-based per-root-kind sampling (``tracer().set_sample_rate``) plus
the always-sample overrides (errors, sheds, breaker trips) keep the
finished-trace buffer bounded at production qps.

Usage::

    from hypergraphdb_tpu import obs

    obs.enable()                      # tracing on, process-wide
    obs.tracer().set_sample_rate("serve.request", 0.01)
    ... serve / query / compact ...
    print(obs.export.prometheus_text(rt.stats.registry))
    for t in obs.tracer().drain():
        ...
"""

from hypergraphdb_tpu.obs import device, export, fleet, flight, http, perf, slo
from hypergraphdb_tpu.obs.device import annotate, block_timed, profile
from hypergraphdb_tpu.obs.export import (
    TRACE_SCHEMA_VERSION,
    merge_expositions,
    parse_traces_jsonl,
    prometheus_text,
    relabel_exposition,
    sample_value,
    trace_to_dict,
    traces_to_jsonl,
    write_telemetry,
)
from hypergraphdb_tpu.obs.fleet import (
    FleetCollector,
    HTTPNodeSource,
    LocalNodeSource,
    explain_record,
)
from hypergraphdb_tpu.obs.flight import (
    FlightRecorder,
    global_flight,
    install_sigterm_dump,
    parse_flight_jsonl,
)
from hypergraphdb_tpu.obs.http import (
    TelemetryServer,
    breaker_key_label,
    composite_health,
    runtime_health,
)
from hypergraphdb_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
)
from hypergraphdb_tpu.obs.perf import (
    PerfSentinel,
    load_baseline,
    seed_baseline,
    shard_skew,
)
from hypergraphdb_tpu.obs.slo import Objective, SLOMonitor, fleet_objectives
from hypergraphdb_tpu.obs.trace import Clock, Span, Trace, Tracer, global_tracer


def tracer() -> Tracer:
    """The process-wide tracer (disabled until :func:`enable`)."""
    return global_tracer()


def enable(clock=None) -> Tracer:
    """Turn process-wide tracing on; returns the tracer."""
    return global_tracer().enable(clock)


def disable() -> Tracer:
    """Turn process-wide tracing off (already-open traces still finish)."""
    return global_tracer().disable()


__all__ = [
    "Clock",
    "Counter",
    "FleetCollector",
    "FlightRecorder",
    "Gauge",
    "HTTPNodeSource",
    "Histogram",
    "LocalNodeSource",
    "Objective",
    "PerfSentinel",
    "Registry",
    "SLOMonitor",
    "Span",
    "TRACE_SCHEMA_VERSION",
    "TelemetryServer",
    "Trace",
    "Tracer",
    "annotate",
    "block_timed",
    "breaker_key_label",
    "composite_health",
    "default_registry",
    "device",
    "disable",
    "enable",
    "explain_record",
    "export",
    "fleet",
    "fleet_objectives",
    "flight",
    "global_flight",
    "global_tracer",
    "http",
    "install_sigterm_dump",
    "load_baseline",
    "merge_expositions",
    "parse_flight_jsonl",
    "parse_traces_jsonl",
    "perf",
    "profile",
    "prometheus_text",
    "relabel_exposition",
    "runtime_health",
    "sample_value",
    "seed_baseline",
    "shard_skew",
    "slo",
    "trace_to_dict",
    "tracer",
    "traces_to_jsonl",
    "write_telemetry",
]
