"""hgperf: continuous performance observability — the runtime twin of
hgverify's HV401 static cost gate.

hgverify gates STATIC cost drift (a kernel's compiled flops/bytes moving
past its committed budget) and hgfleet reports AVAILABILITY SLOs; neither
would notice a serve lane silently getting 3× slower at runtime. This
module closes that gap:

- **PerfBaseline** (``PERF_BASELINE.json``): the committed per-lane
  performance contract — p50/p99 latency, qps, device-seconds/request —
  seeded from the ``BENCH_C*`` smoke records (``bench.py
  --seed-baseline``) and hand-tightened by operators once real-hardware
  numbers exist. :func:`load_baseline` is the version-checking reader.
- **PerfSentinel**: per-lane rolling digests fed by the serving
  runtime's completion path (``ServeConfig(perf=sentinel)``), evaluated
  against the baseline with **multi-window drift detection** in the
  ``obs.slo.SLOMonitor`` style: a lane alerts only when EVERY configured
  window is degraded (the short window proves the problem is happening
  NOW, the long one that it is not a blip), edge-triggered with
  hysteresis (re-arms only once every window clears), so a sustained
  degradation costs ONE incident, not one per evaluation. A firing
  detector raises a flight-recorder incident (``perf_drift_<lane>``) AND
  auto-opens a bounded :func:`~hypergraphdb_tpu.obs.device.profile`
  session around the degraded lane, so the profiler trace lands beside
  the flight window dump — the "incident profile" an operator needs to
  answer WHY, captured before anyone asks.
- **Skew/straggler attribution** (:func:`shard_skew`): per-shard gauges
  from ``ShardedExecutor.mesh_report()`` (HBM occupancy where the
  backend reports it, gid-ownership spans always, per-shard
  device-seconds once a real-hardware path provides them) rolled into
  max/mean skew ratios with the straggler shard named; a skew ratio
  sustained past ``skew_ratio_max`` raises its own edge-triggered
  ``perf_skew_<key>`` incident.

The whole module is clock-injected and import-light (no jax at module
scope; the profiler hook imports jax only when a session actually
opens — and is itself injectable, so the deterministic tier-1 tests run
jax-free). Aggregation across the fleet rides the existing planes: the
sentinel's :meth:`~PerfSentinel.health_summary` is embedded in
``/healthz`` by ``obs.http.runtime_health``, merged at the door by
``FleetCollector.fleet_perf()`` (``GET /fleet/perf``), and
``obs.slo.fleet_objectives`` wires a ``perf_drift`` error-budget
objective over the per-node verdicts.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from hypergraphdb_tpu.obs.flight import FlightRecorder, global_flight
from hypergraphdb_tpu.obs.registry import Registry

#: committed baseline file schema (the reader rejects unknown versions)
BASELINE_SCHEMA_VERSION = 1

#: PROFILE.json capture-manifest format (see _write_manifest)
MANIFEST_SCHEMA_VERSION = 1

#: default committed baseline filename (next to the repo's BENCH_C* files)
BASELINE_FILENAME = "PERF_BASELINE.json"

#: default drift windows (seconds): short proves NOW, long proves
#: not-a-blip — serving-test time constants, deployments pass their own
DEFAULT_WINDOWS = (30.0, 120.0)

#: default tolerance factors: observed metric > baseline × factor ⇒
#: degraded. Generous by default — the seeded CPU-smoke baselines are
#: coarse anchors; operators tighten once real-hardware numbers exist.
DEFAULT_FACTORS = {"p50_s": 3.0, "p99_s": 3.0, "device_s_per_req": 3.0}

#: the baseline metrics the sentinel gates on (qps/occupancy ride the
#: digests as attribution context but never page — qps tracks OFFERED
#: load, and a quiet service must not read as a slow one)
GATED_METRICS = ("p50_s", "p99_s", "device_s_per_req")

#: latency contracts are checked as BREACH FRACTIONS — the share of a
#: window's samples slower than ``baseline × factor`` — not as the
#: window's own percentile: a long window's raw p99 jumps on a 3-sample
#: blip (percentiles never dilute tails), which would defeat the
#: "long window proves it is not a blip" contract. A p50 contract is
#: violated when >50% of the window breaches its limit, a p99 contract
#: when >5% breaches (a 5× overdraft of the 1% tail budget — the
#: burn-rate idea translated to latency limits). ``device_s_per_req``
#: is a window AGGREGATE (Σ device-seconds / Σ real lanes), which
#: dilutes blips naturally.
BREACH_ALLOWANCES = {"p50_s": 0.5, "p99_s": 0.05}

#: per-shard report keys whose max/mean skew is gate-worthy (structural
#: keys like gid_span are reported for attribution but never page)
DEFAULT_SKEW_GATE_KEYS = ("hbm_bytes_in_use", "device_seconds")


# ------------------------------------------------------------- baseline file


def default_baseline_path() -> str:
    """Where the committed baseline lives by default: next to
    ``bench.py`` at the repo root (``--seed-baseline``'s default
    ``out_path``), overridable with ``HG_PERF_BASELINE`` — deployments
    that install the package point this at their own seeded record."""
    env = os.environ.get("HG_PERF_BASELINE")
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), BASELINE_FILENAME)


def load_baseline(path: str) -> dict:
    """The version-checking reader for ``PERF_BASELINE.json``. Raises
    ``ValueError`` on unknown schema versions or a record without the
    ``lanes`` contract — a sentinel must never run against a file whose
    shape it merely guessed."""
    with open(path) as f:
        rec = json.load(f)
    v = rec.get("schema_version")
    if v != BASELINE_SCHEMA_VERSION:
        raise ValueError(
            f"{path}: perf baseline schema {v!r} != "
            f"{BASELINE_SCHEMA_VERSION} (re-seed with bench.py "
            "--seed-baseline)"
        )
    if not isinstance(rec.get("lanes"), dict):
        raise ValueError(f"{path}: perf baseline has no 'lanes' mapping")
    return rec


def save_baseline(record: dict, path: str) -> str:
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def _bench_candidates(bench_dirs, prefix: str) -> list:
    """Every readable bench record for one config prefix across
    ``bench_dirs``, as ``(recorded_unix, path, record)`` triples."""
    out = []
    seen = set()
    for bench_dir in bench_dirs:
        try:
            names = sorted(os.listdir(bench_dir))
        except OSError:
            continue
        for name in names:
            if not (name.startswith(prefix + "_")
                    and name.endswith(".json")):
                continue
            path = os.path.join(bench_dir, name)
            # dedup by REAL path, never by basename: a fresh
            # BENCH_C6_local.json under BENCH_RECORD_DIR must compete
            # with (and, being newer, beat) the committed one — only the
            # literal same file is skipped
            real = os.path.realpath(path)
            if real in seen:
                continue
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            seen.add(real)
            out.append((int(rec.get("recorded_unix") or 0), path, rec))
    return out


def seed_baseline(bench_dirs=".", out_path: Optional[str] = None,
                  factors: Optional[dict] = None) -> dict:
    """Seed a ``PERF_BASELINE.json`` record from the recorded
    ``BENCH_C*`` files in ``bench_dirs`` (one dir or a sequence — the
    bench CLI passes both the repo dir and ``BENCH_RECORD_DIR``):

    - ``bfs``     ← ``BENCH_C6_*`` (open-loop serving: real latency
      percentiles + served qps);
    - ``range``   ← ``BENCH_C9_*`` (same shape);
    - ``pattern`` + ``sub`` ← ``BENCH_C10_*`` — one c10 run carries
      BOTH the ad-hoc open-loop pattern percentiles and the standing
      tier's notification-latency percentiles (ingest-dirty →
      delta-enqueued, the ``sub`` lane the manager feeds the sentinel);
    - ``join``    ← ``BENCH_C11_*`` (open-loop join serving: REAL
      latency percentiles + served qps, same shape as c6/c9/c10),
      falling back to ``BENCH_C7_*`` when no c11 record exists — c7 is
      closed-loop THROUGHPUT, so its latency anchor is the per-anchor
      mean (``1 / triangle.device_anchors_per_sec``) with ``p99_s`` a
      4× heuristic, recorded as such in the lane's ``note``.

    Per config the NEWEST record wins (``recorded_unix``): the
    documented re-seed flow — run a real-hardware sweep under a new
    tag, then seed — must pick the fresh run over the committed smokes,
    whatever its tag. Lanes with no bench record are omitted — the
    sentinel only gates lanes the baseline names. Writes ``out_path``
    when given; returns the record either way."""
    if isinstance(bench_dirs, str):
        bench_dirs = (bench_dirs,)
    lanes: dict = {}
    sources: list = []
    backends: list = []
    for prefix, key, build in (
        ("BENCH_C6", "c6_serving", _lanes_from_serving),
        ("BENCH_C9", "c9_value_index", _lanes_from_serving),
        ("BENCH_C10", "c10_pattern", _lanes_from_pattern),
        ("BENCH_C7", "c7_pattern_join", _lanes_from_join),
        # AFTER the c7 entry on purpose: both seed the ``join`` lane,
        # and last-writer-wins is the fallback order — c11's measured
        # open-loop percentiles beat c7's throughput proxy whenever a
        # c11 record exists at all
        ("BENCH_C11", "c11_join", _lanes_from_join_open),
    ):
        candidates = sorted(_bench_candidates(bench_dirs, prefix),
                            key=lambda t: t[0], reverse=True)
        for _, path, rec in candidates:
            payload = rec.get(key)
            if not isinstance(payload, dict):
                continue
            built = [(name, lane) for name, lane in build(payload)
                     if lane]
            if built:
                for lane_name, lane in built:
                    # per-lane provenance: a partial re-record (only c6
                    # on real hardware, the rest still CPU smokes) must
                    # not masquerade as a uniform contract
                    lane["backend"] = str(rec.get("backend") or "unknown")
                    lanes[lane_name] = lane
                    backends.append(lane["backend"])
                sources.append(os.path.basename(path))
                break
    uniq = sorted(set(backends))
    record = {
        "schema_version": BASELINE_SCHEMA_VERSION,
        "recorded_unix": int(time.time()),
        # "mixed" flags a cross-backend seed loudly (bench_diff's
        # backend_differs discipline) — the per-lane fields say which
        "backend": (uniq[0] if len(uniq) == 1 else
                    "mixed" if uniq else "unknown"),
        "source": sources,
        "factors": dict(factors or DEFAULT_FACTORS),
        "lanes": lanes,
    }
    if out_path is not None:
        save_baseline(record, out_path)
    return record


def _serving_lane(payload: dict) -> dict:
    """The shared open-loop serving shape (c6/c9/c10): latency
    percentiles in ms + served qps."""
    lane = {}
    p50, p99 = payload.get("latency_ms_p50"), payload.get("latency_ms_p99")
    if p50:
        lane["p50_s"] = round(float(p50) / 1e3, 6)
    if p99:
        lane["p99_s"] = round(float(p99) / 1e3, 6)
    if payload.get("served_qps"):
        lane["qps"] = float(payload["served_qps"])
    return lane


def _lanes_from_serving(payload: dict):
    lane_name = "bfs" if "batched_vs_unbatched" in payload else "range"
    return [(lane_name, _serving_lane(payload))]


def _lanes_from_pattern(payload: dict):
    """One c10 record seeds TWO lanes: the ad-hoc ``pattern`` serving
    percentiles and the standing-subscription ``sub`` lane, whose
    latency samples are notification deliveries (ingest-dirty →
    delta-enqueued), fed to the sentinel by the SubscriptionManager."""
    out = [("pattern", _serving_lane(payload))]
    sub = payload.get("sub") or {}
    lane = {}
    p50, p99 = sub.get("notify_ms_p50"), sub.get("notify_ms_p99")
    if p50:
        lane["p50_s"] = round(float(p50) / 1e3, 6)
    if p99:
        lane["p99_s"] = round(float(p99) / 1e3, 6)
    if lane:
        lane["note"] = ("standing-subscription notification latency "
                        "(dirty -> delta enqueued)")
    out.append(("sub", lane))
    return out


def _lanes_from_join_open(payload: dict):
    """The c11 open-loop join record: the same serving shape as
    c6/c9/c10 — measured latency percentiles under queueing + served
    qps — for the lane c7 could only proxy from closed-loop
    throughput."""
    lane = _serving_lane(payload)
    if lane:
        lane["note"] = ("open-loop join serving percentiles "
                        "(c11: Poisson arrivals under concurrent ingest)")
    return [("join", lane)]


def _lanes_from_join(payload: dict):
    tri = payload.get("triangle") or {}
    qps = tri.get("device_anchors_per_sec")
    if not qps or qps <= 0:
        return [("join", {})]
    p50 = 1.0 / float(qps)
    return [("join", {
        "p50_s": round(p50, 6),
        "p99_s": round(4.0 * p50, 6),
        "qps": float(qps),
        "note": "closed-loop c7 throughput proxy (per-anchor mean; "
                "p99 is a 4x heuristic)",
    })]


# --------------------------------------------------------- skew attribution

#: per-shard report keys that are identity/structure, not load gauges
_SHARD_IDENTITY_KEYS = ("device", "gid_lo", "gid_hi")


def shard_skew(mesh_report: dict) -> dict:
    """Roll a ``ShardedExecutor.mesh_report()`` into max/mean skew
    ratios per per-shard gauge, naming the straggler shard.

    Every numeric per-shard field is summarized (``hbm_bytes_in_use``
    where the backend reports allocator stats; ``device_seconds`` once a
    real-hardware path measures it; anything a future report adds),
    plus the structural ``gid_span`` derived from the partition ranges.
    Shape: ``{key: {"max", "mean", "ratio", "straggler"}}`` where
    ``straggler`` is the device id owning the max. Empty dict when the
    report carries no shards."""
    shards = mesh_report.get("shards") or ()
    series: dict[str, list] = {}
    for s in shards:
        dev = s.get("device")
        lo, hi = s.get("gid_lo"), s.get("gid_hi")
        if lo is not None and hi is not None:
            series.setdefault("gid_span", []).append((float(hi - lo), dev))
        for k, v in s.items():
            if k in _SHARD_IDENTITY_KEYS or not isinstance(v, (int, float)):
                continue
            series.setdefault(k, []).append((float(v), dev))
    out = {}
    for key, vals in series.items():
        mean = sum(v for v, _ in vals) / len(vals)
        mx, straggler = max(vals, key=lambda p: p[0])
        if mean <= 0:
            continue
        out[key] = {
            "max": mx,
            "mean": round(mean, 6),
            "ratio": round(mx / mean, 4),
            "straggler": straggler,
        }
    return out


# ----------------------------------------------------------------- sentinel


class _Lane:
    """One lane's rolling digest + alert hysteresis."""

    __slots__ = ("samples", "batches", "alerting", "alerts",
                 "last_incident", "last_profile")

    def __init__(self, max_samples: int):
        #: (t, latency_s, served_on_host) completion samples
        self.samples: deque = deque(maxlen=max_samples)
        #: (t, device_s, n_real, n_total) measured device batches
        self.batches: deque = deque(maxlen=max_samples)
        self.alerting = False
        self.alerts = 0
        self.last_incident: Optional[str] = None
        self.last_profile: Optional[str] = None


def _window(samples: list, batches: list, ring_full: bool,
            now: float, span: float,
            limits: Optional[dict] = None,
            min_samples: int = 0, min_breaches: int = 3) -> dict:
    """One lane's digest over the trailing ``span`` seconds — count,
    qps, p50/p99 latency, host-serve fraction, device-seconds/request
    and pad occupancy — plus the window's three-state verdict:

    - ``unknown`` — no baseline limits, or fewer than ``min_samples``
      samples: not enough evidence to call the window either way (an
      idle lane must neither page nor count as recovered);
    - ``degraded`` — some gated metric exceeded: a latency limit
      breached by more than its :data:`BREACH_ALLOWANCES` share AND by
      at least ``min_breaches`` samples (a single outlier in a small
      window is a blip, not a page), or the aggregate
      device-seconds/request over its limit;
    - ``clear`` — enough samples, nothing exceeded.

    A window whose span outruns the bounded sample ring is
    ``unknown`` too (``span_truncated``): at high qps the deque evicts
    history faster than the long window's span, and a sub-second burst
    filling the whole ring would otherwise read as "the long window is
    degraded" — exactly the blip the multi-window design must not page
    on. Size ``max_samples ≥ qps × longest window`` to keep long
    windows verdict-capable.

    Operates on ring SNAPSHOTS (``samples``/``batches`` lists plus the
    ``ring_full`` flag, captured under the sentinel lock) so the sorts
    run without blocking the dispatch-thread observe path."""
    cutoff = now - span
    lats: list = []
    hosts = 0
    crossed = False
    for t, lat, host in reversed(samples):
        if t <= cutoff:
            crossed = True
            break
        lats.append(lat)
        hosts += 1 if host else 0
    # ring at capacity with every retained sample inside the span: the
    # evicted history was younger than the window start, so the window
    # cannot honestly speak for its full span
    truncated = not crossed and ring_full
    n = len(lats)
    out: dict = {"n": n, "qps": round(n / span, 4) if span > 0 else None}
    if n:
        ordered = sorted(lats)
        out["p50_s"] = round(ordered[(n - 1) // 2], 6)
        out["p99_s"] = round(ordered[min(n - 1, (99 * n) // 100)], 6)
        out["host_fraction"] = round(hosts / n, 4)
    dev_s = real = total = 0.0
    for t, ds, nr, nt in reversed(batches):
        if t <= cutoff:
            break
        dev_s += ds
        real += nr
        total += nt
    if real:
        out["device_s_per_req"] = round(dev_s / real, 6)
        if total:
            out["occupancy"] = round(real / total, 4)
    exceeded: list = []
    known = bool(limits) and n >= min_samples and not truncated
    if truncated:
        out["span_truncated"] = True
    if known:
        for metric, allowance in BREACH_ALLOWANCES.items():
            limit = limits.get(metric)
            if limit is None:
                continue
            breaches = sum(1 for lat in lats if lat > limit)
            out[f"breach_{metric}"] = round(breaches / n, 4)
            if breaches / n > allowance and breaches >= min_breaches:
                exceeded.append(metric)
        limit = limits.get("device_s_per_req")
        observed = out.get("device_s_per_req")
        if limit is not None and observed is not None and observed > limit:
            exceeded.append("device_s_per_req")
    out["exceeded"] = exceeded
    out["status"] = ("unknown" if not known
                     else "degraded" if exceeded else "clear")
    return out


class _ProfileSession:
    """An open bounded profiler capture: the context manager, its
    output dir, and the deadline the next tick closes it at."""

    __slots__ = ("cm", "logdir", "lane", "until", "active")

    def __init__(self, cm, logdir: str, lane: str, until: float,
                 active: bool):
        self.cm = cm
        self.logdir = logdir
        self.lane = lane
        self.until = until
        self.active = active


#: reservation marker for the one-session-at-a-time incident profiler —
#: held in ``_profile`` between the check and the (lockless) session open
_PENDING_PROFILE = _ProfileSession(None, "", "", float("inf"), False)


class PerfSentinel:
    """The runtime perf sentinel: rolling per-lane digests vs the
    committed baseline, multi-window drift alerts as flight-recorder
    incidents with auto-captured profiler sessions, and mesh skew
    attribution.

    Feeding: wire ``ServeConfig(perf=sentinel)`` — the runtime pushes
    :meth:`observe` per completed request and :meth:`observe_batch` per
    ``block_timed``-measured device batch (``device_timing=True``), then
    rate-limits an evaluation through :meth:`maybe_tick` (no thread of
    its own). Use the SAME clock as the runtime: samples are stamped on
    it and the windows are cut against it.

    Evaluation (:meth:`tick`) is the mutating edge — scrapes read
    :meth:`snapshot` / :meth:`health_summary`, which never fire or
    re-arm alerts (the SLO monitor's discipline). Thread-safe.

    **Window sizing**: a window only renders a verdict once it holds
    ``min_samples`` — below that it is ``unknown``, which neither fires
    nor re-arms (no evidence ≠ recovered). Size spans so the lane's
    completion rate keeps windows populated even UNDER the slowdowns
    you want to catch: a closed-loop caller 10× slower completes 10×
    fewer requests per span, so ``span ≥ min_samples / (qps /
    slowdown_factor)`` — too-short windows go silent (``unknown``)
    during a catastrophic degradation rather than guessing."""

    def __init__(self, baseline=None,
                 clock: Optional[Callable[[], float]] = None,
                 flight: Optional[FlightRecorder] = None,
                 windows=DEFAULT_WINDOWS,
                 min_samples: int = 8,
                 min_breaches: int = 3,
                 eval_interval_s: float = 1.0,
                 profile_s: float = 2.0,
                 profiler: Optional[Callable] = None,
                 registry: Optional[Registry] = None,
                 mesh_source: Optional[Callable[[], dict]] = None,
                 skew_ratio_max: float = 1.5,
                 skew_gate_keys=DEFAULT_SKEW_GATE_KEYS,
                 max_samples: int = 4096):
        if isinstance(baseline, str):
            baseline = load_baseline(baseline)
        self.baseline = baseline or {"lanes": {}}
        self.factors = dict(DEFAULT_FACTORS)
        self.factors.update(self.baseline.get("factors") or {})
        self.clock = clock or time.monotonic
        self.flight = flight if flight is not None else global_flight()
        self.windows = tuple(float(w) for w in windows)
        if not self.windows or list(self.windows) != sorted(self.windows):
            raise ValueError("windows must be non-empty, ascending by span")
        # clamped ≥ 1: a window verdict over zero samples is undefined
        # (and min_samples=0 would divide by zero in the breach math)
        self.min_samples = max(1, int(min_samples))
        self.min_breaches = int(min_breaches)
        self.eval_interval_s = float(eval_interval_s)
        self.profile_s = float(profile_s)
        self._profiler = profiler  # None → obs.device.profile, bound lazily
        self.registry = registry if registry is not None else Registry("perf")
        self.mesh_source = mesh_source
        self.skew_ratio_max = float(skew_ratio_max)
        self.skew_gate_keys = tuple(skew_gate_keys)
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._lanes: dict[str, _Lane] = {}
        self._last_eval: Optional[float] = None
        self._profile: Optional[_ProfileSession] = None
        self._skew: Optional[dict] = None
        self._skew_alerting = False
        self._skew_alerts = 0
        self._alerts = self.registry.counter("perf.alerts")
        self._lane_gauges: dict = {}
        # baseline lanes register their gauges eagerly so a scrape (and
        # the fleet view) sees every watched lane at 0 before traffic —
        # and a lane whose baseline qps would overrun the sample ring
        # inside the longest window is called out NOW: it would sit
        # permanently span_truncated → unknown, silently un-alertable
        # (the failure mode this sentinel exists to catch)
        for kind, lane in (self.baseline.get("lanes") or {}).items():
            self._gauges_for(kind)
            qps = lane.get("qps") if isinstance(lane, dict) else None
            if qps and qps * self.windows[-1] > self.max_samples:
                import logging

                logging.getLogger("hypergraphdb_tpu.obs").warning(
                    "perf sentinel lane %r: baseline qps %.0f over the "
                    "%.0fs window needs ~%d samples but max_samples=%d — "
                    "windows will report span_truncated/unknown at that "
                    "rate; raise max_samples or shrink the windows",
                    kind, qps, self.windows[-1],
                    int(qps * self.windows[-1]), self.max_samples,
                )

    # -- feeding (dispatch-thread hot path) ----------------------------------
    def observe(self, kind: str, latency_s: float, path: str = "device",
                t: Optional[float] = None) -> None:
        """One completed request on lane ``kind``: end-to-end latency +
        which executor path answered (host fallbacks feed the SAME
        digest — a lane degrading INTO its host path is exactly the
        drift this sentinel exists to catch; the window's
        ``host_fraction`` is the attribution)."""
        t = self.clock() if t is None else float(t)
        with self._lock:
            lane = self._lanes.get(kind)
            if lane is None:
                lane = self._lanes[kind] = _Lane(self.max_samples)
            lane.samples.append((t, float(latency_s), path == "host"))

    def observe_batch(self, kind: str, device_s: float, n_real: int = 0,
                      n_total: int = 0, t: Optional[float] = None) -> None:
        """One ``block_timed``-measured device batch: launch→ready wall
        seconds, real lane count, and the TOTAL bucket width including
        padding (``occupancy = Σ n_real / Σ n_total`` and
        ``device_s_per_req`` in the windows)."""
        t = self.clock() if t is None else float(t)
        with self._lock:
            lane = self._lanes.get(kind)
            if lane is None:
                lane = self._lanes[kind] = _Lane(self.max_samples)
            lane.batches.append((t, float(device_s), int(n_real),
                                 int(n_total)))

    def maybe_tick(self) -> Optional[dict]:
        """Rate-limited :meth:`tick` for the runtime's completion path:
        evaluates at most once per ``eval_interval_s``. Returns the
        snapshot when an evaluation ran, else None."""
        now = self.clock()
        with self._lock:
            due = (self._last_eval is None
                   or now - self._last_eval >= self.eval_interval_s)
            if due:
                self._last_eval = now
        return self.tick() if due else None

    # -- evaluation ----------------------------------------------------------
    def tick(self) -> dict:
        """Evaluate every lane + the mesh skew, fire incidents and
        open/close profile sessions on alert edges. Returns the
        snapshot."""
        return self._evaluate(mutate=True)

    def snapshot(self) -> dict:
        """A pure READ of the current evaluation state: no samples, no
        alert-edge transitions, no incidents, no profile churn (scrapes
        must not fire or re-arm alerts; only :meth:`tick` does)."""
        return self._evaluate(mutate=False)

    def _evaluate(self, mutate: bool) -> dict:
        now = self.clock()
        skew = self._eval_skew(now, mutate)
        base_lanes = self.baseline.get("lanes") or {}
        # phase 1 — snapshot the rings under the lock (O(n) copies, no
        # sorting): the dispatch-thread hot path (observe) must never
        # wait behind a digest computation
        with self._lock:
            if mutate:
                self._last_eval = now
            lane_snaps: dict = {}
            for kind in set(self._lanes) | set(base_lanes):
                lane = self._lanes.get(kind)
                if lane is None:
                    lane = self._lanes[kind] = _Lane(self.max_samples)
                lane_snaps[kind] = (
                    lane, list(lane.samples), list(lane.batches),
                    len(lane.samples) == (lane.samples.maxlen or 0),
                )
        # phase 2 — window digests (and ALL sorting, including the lane
        # ordering itself) OUTSIDE the lock
        verdicts: list = []
        out_lanes: dict = {}
        for kind in sorted(lane_snaps):
            lane, samples, batches, ring_full = lane_snaps[kind]
            base = base_lanes.get(kind)
            limits = None
            if base is not None:
                limits = {
                    m: base[m] * self.factors.get(m, 3.0)
                    for m in GATED_METRICS if base.get(m)
                }
            wins = []
            all_bad = bool(limits)
            all_clear = bool(limits)
            for span in self.windows:
                w = _window(samples, batches, ring_full, now, span,
                            limits=limits, min_samples=self.min_samples,
                            min_breaches=self.min_breaches)
                all_bad = all_bad and w["status"] == "degraded"
                all_clear = all_clear and w["status"] == "clear"
                wins.append(dict(w, span_s=span,
                                 degraded=w["status"] == "degraded"))
            verdicts.append((kind, lane, base, all_bad, all_clear))
            out_lanes[kind] = {
                "baseline": base,
                "watched": base is not None,
                "windows": wins,
            }
        # phase 3 — alert-state transitions back under the lock
        fire: list = []
        with self._lock:
            for kind, lane, base, all_bad, all_clear in verdicts:
                if mutate and base is not None:
                    if all_bad and not lane.alerting:
                        lane.alerting = True
                        lane.alerts += 1
                        short = out_lanes[kind]["windows"][0]
                        metric = (short["exceeded"] or GATED_METRICS)[0]
                        fire.append((kind, lane, {
                            "lane": kind,
                            "metric": metric,
                            "observed": short.get(metric),
                            "baseline": base.get(metric),
                            "factor": self.factors.get(metric, 3.0),
                            "host_fraction": short.get("host_fraction"),
                        }))
                    elif lane.alerting and all_clear:
                        # hysteresis: re-arm only once EVERY window is
                        # affirmatively CLEAR — a long window still
                        # digesting the degraded period keeps the lane
                        # armed-off (a flapping short window stays ONE
                        # incident), and so does an idle/sparse window
                        # (no evidence ≠ recovered: a stall right after
                        # the alert must not reset the edge)
                        lane.alerting = False
                out_lanes[kind].update(
                    violating=lane.alerting,
                    alerts_total=lane.alerts,
                    last_incident=lane.last_incident,
                    last_profile=lane.last_profile,
                )
            alerts_total = (sum(ln.alerts for ln in self._lanes.values())
                            + self._skew_alerts)
        # instrument writes + incident/profile IO OUTSIDE the lock (the
        # SLO monitor's discipline: the recorder writes files)
        for kind, rec in out_lanes.items():
            g = self._gauges_for(kind)
            g["violating"].set(1 if rec["violating"] else 0)
            short = rec["windows"][0]
            if short.get("p99_s") is not None:
                g["p99_s"].set(short["p99_s"])
            if short.get("qps") is not None:
                g["qps"].set(short["qps"])
        for kind, lane, fields in fire:
            # a rate-limited/unconfigured dump returns None — keep the
            # pointer to the previous REAL evidence rather than nulling
            # the only path an operator has
            path = self.flight.incident("perf_drift_" + kind, **fields)
            self._alerts.inc()
            logdir = self._open_profile(kind, now)
            with self._lock:
                lane.last_incident = path or lane.last_incident
                lane.last_profile = logdir or lane.last_profile
                out_lanes[kind]["last_incident"] = lane.last_incident
                out_lanes[kind]["last_profile"] = lane.last_profile
        # reap an EXPIRED profile session on snapshots too: closing a
        # session past its deadline enforces the already-decided bound,
        # it is not an alert-state mutation — and completions may have
        # stopped exactly because of the incident that opened it, so the
        # scrape path may be the only caller left ticking
        self._close_expired_profile(now)
        return {
            "lanes": out_lanes,
            "skew": skew,
            "alerts_total": alerts_total,
            "profile_open": self._profile is not None,
        }

    def _eval_skew(self, now: float, mutate: bool) -> Optional[dict]:
        if self.mesh_source is None:
            return self._skew
        try:
            report = self.mesh_source()
            skew = shard_skew(report or {})
        except Exception:  # noqa: BLE001 - a broken source ≠ dead sentinel
            return self._skew
        worst_key, worst = None, None
        for key in self.skew_gate_keys:
            d = skew.get(key)
            if d is not None and (worst is None or d["ratio"] > worst):
                worst_key, worst = key, d["ratio"]
        violating = worst is not None and worst > self.skew_ratio_max
        fire_fields = None
        with self._lock:
            if mutate:
                if violating and not self._skew_alerting:
                    self._skew_alerting = True
                    self._skew_alerts += 1
                    d = skew[worst_key]
                    fire_fields = {
                        "key": worst_key, "ratio": d["ratio"],
                        "straggler": d["straggler"],
                        "ratio_max": self.skew_ratio_max,
                    }
                elif self._skew_alerting and not violating:
                    self._skew_alerting = False
            self._skew = dict(skew, violating=self._skew_alerting,
                              alerts_total=self._skew_alerts)
            snap = self._skew
        for key, d in skew.items():
            g = self._lane_gauges.get(("skew", key))
            if g is None:
                g = self._lane_gauges[("skew", key)] = self.registry.gauge(
                    f"perf.skew.{key}"
                )
            g.set(d["ratio"])
        if fire_fields is not None:
            self.flight.incident("perf_skew_" + fire_fields["key"],
                                 **fire_fields)
            self._alerts.inc()
        return snap

    def _gauges_for(self, kind: str) -> dict:
        g = self._lane_gauges.get(kind)
        if g is None:
            g = self._lane_gauges[kind] = {
                "violating": self.registry.gauge(
                    f"perf.lane.{kind}.violating"),
                "p99_s": self.registry.gauge(f"perf.lane.{kind}.p99_s"),
                "qps": self.registry.gauge(f"perf.lane.{kind}.qps"),
            }
        return g

    # -- incident profiles ---------------------------------------------------
    def _profile_cm(self, logdir: str):
        if self._profiler is not None:
            return self._profiler(logdir)
        from hypergraphdb_tpu.obs.device import profile

        return profile(logdir)

    def _open_profile(self, kind: str, now: float) -> Optional[str]:
        """Auto-capture: open ONE bounded profiler session per incident
        window, writing beside the flight dumps
        (``<incident_dir>/profile_<n>_<lane>/``). A session already
        open (another lane fired inside the bound) is left to finish —
        the profile covers the degraded period either way. Returns the
        session dir, or None (no incident_dir / open failed)."""
        root = self.flight.incident_dir
        if root is None:
            return None
        with self._lock:
            if self._profile is not None:
                return None
            # check-and-RESERVE in one lock hold: two alert edges racing
            # here must not both open a profiler session (the loser's cm
            # would never be exited — a leaked session for the rest of
            # the process)
            self._profile = _PENDING_PROFILE
            n = sum(ln.alerts for ln in self._lanes.values())
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in kind)[:32]
        logdir = os.path.join(root, f"profile_{n:04d}_{safe}")
        try:
            os.makedirs(logdir, exist_ok=True)
            cm = self._profile_cm(logdir)
            active = bool(cm.__enter__())
        except Exception:  # noqa: BLE001 - a dead profiler ≠ lost incident
            with self._lock:
                if self._profile is _PENDING_PROFILE:
                    self._profile = None
            return None
        session = _ProfileSession(cm, logdir, kind, now + self.profile_s,
                                  active)
        self._write_manifest(session, t0=now)
        with self._lock:
            self._profile = session
        return logdir

    def _close_expired_profile(self, now: float) -> None:
        with self._lock:
            session = self._profile
            if session is None or now < session.until:
                return
            self._profile = None
        self._finish_profile(session, now)

    def close(self) -> None:
        """Close any open profile session (shutdown path)."""
        with self._lock:
            session, self._profile = self._profile, None
        if session is not None and session is not _PENDING_PROFILE:
            self._finish_profile(session, self.clock())

    def _finish_profile(self, session: _ProfileSession, now: float) -> None:
        try:
            session.cm.__exit__(None, None, None)
        except Exception:  # noqa: BLE001  # hglint: disable=HG1005
            pass  # teardown: a torn session ≠ crash; the manifest below
            # still records what the profile DID capture
        self._write_manifest(session, t1=now)

    def _write_manifest(self, session: _ProfileSession,
                        t0: Optional[float] = None,
                        t1: Optional[float] = None) -> None:
        """``PROFILE.json`` beside the profiler's own trace files: which
        lane fired, the capture bounds, and whether a real profiler
        session actually opened (False on backends without one — the
        manifest still marks the window)."""
        path = os.path.join(session.logdir, "PROFILE.json")
        rec = {"lane": session.lane, "profiler_active": session.active,
               "bound_s": self.profile_s}
        try:
            if t0 is None and os.path.exists(path):
                with open(path) as f:
                    rec.update(json.load(f))
        except (OSError, ValueError):
            pass
        if t0 is not None:
            rec["t0"] = t0
        if t1 is not None:
            rec["t1"] = t1
        # stamped AFTER the merge so a pre-versioning manifest on disk
        # cannot strip the stamp from the rewrite
        rec["schema_version"] = MANIFEST_SCHEMA_VERSION
        try:
            with open(path, "w") as f:
                json.dump(rec, f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError:
            pass

    # -- fleet surface -------------------------------------------------------
    def health_summary(self) -> dict:
        """The compact per-node verdict ``/healthz`` embeds (and
        ``FleetCollector.fleet_perf`` merges): which lanes are in
        violation, the watched set, total alerts, and the skew ratios.
        A pure read — never drives evaluation."""
        # snapshot under the lock, sort/shape outside: /healthz must not
        # queue the dispatch thread's observe() behind a digest
        with self._lock:
            alerting = [k for k, ln in self._lanes.items() if ln.alerting]
            skew_alerting = self._skew_alerting
            watched = list(self.baseline.get("lanes") or ())
            alerts_total = (sum(ln.alerts for ln in self._lanes.values())
                            + self._skew_alerts)
            skew = dict(self._skew) if self._skew else None
            profile_open = self._profile is not None
        violating = sorted(alerting)
        if skew_alerting:
            violating.append("skew")
        return {
            "violating": violating,
            "watched": sorted(watched),
            "alerts_total": alerts_total,
            "skew": ({k: d["ratio"] for k, d in skew.items()
                      if isinstance(d, dict)}
                     if skew else None),
            "profile_open": profile_open,
        }
