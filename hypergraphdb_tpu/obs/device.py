"""Device-timing hooks: dispatch wall-clock + profiler trace sessions.

JAX dispatch is asynchronous: ``launch`` returns array HANDLES and the
host only learns how long the device actually ran when something blocks
on them. The serving runtime exploits that for pipelining — which means
naive timestamps around ``launch`` measure host assembly, not device
execution. :func:`block_timed` is the one honest measurement available
without a profiler: block until the handles are ready and report the
launch→ready wall delta, attributed to the batch's ``device`` span by the
caller. It is OPT-IN (``ServeConfig.device_timing``) because the block
itself serializes the pipeline's collect side a little earlier than a
plain download would.

**Per-hop attribution via the profiler**: within one double-buffered
dispatch pipeline, ``block_timed`` can only see whole-batch wall deltas —
what ran INSIDE the kernel (and which pipeline slot a batch occupied) is
the profiler's to answer. :func:`profile` opens a ``jax.profiler`` trace
session; while one is active (``profiling()``), the serving executor
wraps every kernel dispatch in :func:`annotate` — a named
``jax.profiler.TraceAnnotation`` carrying the batch kind, bucket, and
double-buffer slot — so the profile's device timeline is attributable
per batch: which slot launched it, what overlapped it, how long the
device actually ran. Each ``device`` span likewise carries its ``slot``
(dispatch sequence mod 2), closing the host-side half of the PR-5
"per-hop device spans need profiler integration" follow-up.

Both hooks are clean no-ops when jax/profiling is unavailable — call
sites carry the knob unconditionally.

No module-level jax import: the deterministic tier-1 tests import obs
with zero device work.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Callable, Optional

#: True while a profile() session is open — the serving executor gates
#: its per-dispatch TraceAnnotations on (device_timing or this), so a
#: plain run pays nothing for annotation support
_PROFILING = False


def profiling() -> bool:
    """Whether an ``obs.profile`` session is currently active."""
    return _PROFILING


@contextmanager
def annotate(name: str):
    """A named ``jax.profiler.TraceAnnotation`` around a host-side
    dispatch (NOT inside jit — purity of the traced graph is hgverify
    HV1xx territory); a no-op when the profiler is unavailable."""
    try:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
    except Exception:
        yield False
        return
    with ann:
        yield True


def block_timed(handles, clock: Callable[[], float]) -> tuple:
    """Block until ``handles`` (any pytree of jax arrays) are ready;
    returns ``(handles, t_ready)``. Against a launch timestamp taken on
    the same clock, ``t_ready`` gives the launch→ready wall delta — the
    per-dispatch device attribution (see ``serve/runtime.py``)."""
    import jax

    jax.block_until_ready(handles)
    return handles, clock()


@contextmanager
def profile(logdir: Optional[str]):
    """A ``jax.profiler`` trace session writing to ``logdir``; a no-op
    context when ``logdir`` is falsy or the profiler is unavailable (CPU
    CI images without profiling support must not error). Sets the
    :func:`profiling` flag so dispatch sites turn their per-batch
    :func:`annotate` markers on for the session's duration."""
    global _PROFILING

    if not logdir:
        yield False
        return
    try:
        import jax

        jax.profiler.start_trace(logdir)
    except Exception:
        yield False
        return
    _PROFILING = True
    try:
        yield True
    finally:
        _PROFILING = False
        try:
            jax.profiler.stop_trace()
        except Exception:  # hglint: disable=HG1005
            pass  # teardown: a torn session must not mask the workload error
