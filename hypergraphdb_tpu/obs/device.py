"""Device-timing hooks: dispatch wall-clock + profiler trace sessions.

JAX dispatch is asynchronous: ``launch`` returns array HANDLES and the
host only learns how long the device actually ran when something blocks
on them. The serving runtime exploits that for pipelining — which means
naive timestamps around ``launch`` measure host assembly, not device
execution. :func:`block_timed` is the one honest measurement available
without a profiler: block until the handles are ready and report the
launch→ready wall delta, attributed to the batch's ``device`` span by the
caller. It is OPT-IN (``ServeConfig.device_timing``) because the block
itself serializes the pipeline's collect side a little earlier than a
plain download would.

:func:`profile` wraps ``jax.profiler.trace`` as a context manager that is
a clean no-op when given no directory (or when jax/profiling is
unavailable) — so call sites can carry a profile knob unconditionally.

No module-level jax import: the deterministic tier-1 tests import obs
with zero device work.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Callable, Optional


def block_timed(handles, clock: Callable[[], float]) -> tuple:
    """Block until ``handles`` (any pytree of jax arrays) are ready;
    returns ``(handles, t_ready)``. Against a launch timestamp taken on
    the same clock, ``t_ready`` gives the launch→ready wall delta — the
    per-dispatch device attribution (see ``serve/runtime.py``)."""
    import jax

    jax.block_until_ready(handles)
    return handles, clock()


@contextmanager
def profile(logdir: Optional[str]):
    """A ``jax.profiler`` trace session writing to ``logdir``; a no-op
    context when ``logdir`` is falsy or the profiler is unavailable (CPU
    CI images without profiling support must not error)."""
    if not logdir:
        yield False
        return
    try:
        import jax

        jax.profiler.start_trace(logdir)
    except Exception:
        yield False
        return
    try:
        yield True
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:  # a torn session must not mask the workload error
            pass
