"""HTTP telemetry endpoint: the scrape surface a replicated tier needs.

A tiny stdlib ``http.server`` thread exposing the process's telemetry —
no framework, no dependency, safe to run beside the serving runtime:

- ``GET /metrics``       — every wired registry in Prometheus exposition
  text (``obs.export.prometheus_text``), the scrape endpoint ROADMAP
  item 3's replica tier fronts with;
- ``GET /healthz``       — JSON health: per-batch-key breaker states,
  admission queue depth, delta staleness lag. 200 while healthy, 503
  when any breaker gate is OPEN or the runtime stopped admitting (load
  balancers speak status codes, humans read the body);
- ``GET /debug/traces``  — recent finished traces as JSONL (a PEEK —
  the exporter's ``drain()`` is not consumed);
- ``GET /debug/flight``  — the flight recorder's current window as
  JSONL.

Usage::

    rt = ServeRuntime(graph, cfg)
    srv = TelemetryServer(
        registries=[rt.stats.registry, graph.metrics.registry],
        tracer=cfg.tracer, health=runtime_health(rt),
    ).start()
    ... requests hit http://127.0.0.1:{srv.port}/metrics ...
    srv.stop()

Handlers only READ (registry instruments lock themselves; ``peek`` and
``records`` are snapshots), so a scrape can never stall the dispatch
thread. No jax imports.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Optional, Tuple

from hypergraphdb_tpu.obs.export import prometheus_text, traces_to_jsonl
from hypergraphdb_tpu.obs.flight import FlightRecorder, global_flight
from hypergraphdb_tpu.obs.registry import Registry
from hypergraphdb_tpu.obs.trace import Tracer, global_tracer

#: health probe contract: () -> (healthy, payload dict)
HealthProbe = Callable[[], Tuple[bool, dict]]


def runtime_health(rt) -> HealthProbe:
    """The standard ``/healthz`` probe over one ``ServeRuntime``:
    per-batch-key breaker states (the one-gauge worst-state view made
    per-key — the ROADMAP "too coarse" follow-up), queue depth, and the
    pinned snapshot's staleness lag in delta edges. Tolerant of fake
    executors (no incremental manager → lag omitted)."""

    def probe() -> Tuple[bool, dict]:
        states = rt.breaker.states()
        payload = {
            "breakers": {breaker_key_label(k): v for k, v in states.items()},
            "breaker_worst": rt.breaker.worst_code(),
            "queue_depth": rt.queue.depth(),
            "accepting": not rt.queue.closed,
        }
        mgr = getattr(rt.executor, "mgr", None)
        if mgr is not None:
            payload["staleness_lag_edges"] = int(mgr.delta_edges)
        mesh_rep = getattr(rt.executor, "mesh_report", None)
        if callable(mesh_rep):
            # a multi-chip pod advertises its mesh shape, gid-range
            # partition map, and per-shard HBM occupancy — the fields
            # shard-aware FrontDoor placement reads
            try:
                payload["mesh"] = mesh_rep()
            except Exception:  # noqa: BLE001 - health must not 500 on it
                # a silently-missing field is indistinguishable from a
                # single-chip node; name the torn enrichment instead
                payload.setdefault("degraded", []).append("mesh")
        perf = getattr(rt, "perf", None)
        if perf is not None:
            # the hgperf sentinel's verdict (violating lanes, alerts,
            # skew) — what FleetCollector.fleet_perf merges. A pure
            # read: scrapes must not drive evaluation. Perf drift is
            # degraded-not-down: it never flips the health verdict.
            try:
                payload["perf"] = perf.health_summary()
            except Exception:  # noqa: BLE001 - health must not 500 on it
                payload.setdefault("degraded", []).append("perf")
        planner = getattr(rt, "planner", None)
        if planner is not None:
            # the hgplan planner's correction state (active per-shape
            # corrections, guard vetoes) — what FleetCollector.fleet_plan
            # merges. Same discipline as perf: pure read, degraded-not-
            # down, never flips the verdict.
            try:
                payload["plan"] = planner.health_summary()
            except Exception:  # noqa: BLE001 - health must not 500 on it
                payload.setdefault("degraded", []).append("plan")
        healthy = (payload["accepting"]
                   and all(v != "open" for v in states.values()))
        return healthy, payload

    return probe


def composite_health(*probes: HealthProbe) -> HealthProbe:
    """AND-combine health probes into ONE ``/healthz`` surface: healthy
    iff every probe is, payloads merged in order (later keys win). The
    replica tier stacks its replication-lag probe on top of
    :func:`runtime_health` this way — one endpoint, one JSON body, both
    stories."""

    def probe() -> Tuple[bool, dict]:
        ok = True
        payload: dict = {}
        for p in probes:
            healthy, part = p()
            ok = ok and healthy
            payload.update(part)
        return ok, payload

    return probe


def breaker_key_label(key) -> str:
    """One stable label per batch key: ``("bfs", 2)`` → ``"bfs_2"`` —
    shared by ``/healthz`` and the per-key ``serve.breaker.*``
    instruments, so the two views join by name."""
    if isinstance(key, (tuple, list)):
        return "_".join(str(p) for p in key)
    return str(key)


class _Handler(BaseHTTPRequestHandler):
    # the server thread must never block on a slow/half-open client
    timeout = 10

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        srv: "TelemetryServer" = self.server.telemetry  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = prometheus_text(*srv.registries).encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = 200
            elif path == "/healthz":
                healthy, payload = (srv.health() if srv.health is not None
                                    else (True, {}))
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                ctype = "application/json"
                status = 200 if healthy else 503
            elif path == "/debug/traces":
                traces = (srv.tracer.peek(srv.debug_traces_limit)
                          if srv.tracer is not None else [])
                body = traces_to_jsonl(traces).encode("utf-8")
                ctype = "application/jsonl"
                status = 200
            elif path == "/debug/flight":
                body = srv.flight.to_jsonl().encode("utf-8")
                ctype = "application/jsonl"
                status = 200
            else:
                body = b"not found\n"
                ctype = "text/plain"
                status = 404
        except Exception as e:  # a broken probe must not kill the server
            body = f"telemetry handler error: {type(e).__name__}\n".encode()
            ctype = "text/plain"
            status = 500
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args) -> None:  # scrapes are not news
        pass


class TelemetryServer:
    """The telemetry endpoint thread. ``port=0`` binds an ephemeral port
    (read it back from ``.port``); ``start()``/``stop()`` or use as a
    context manager."""

    def __init__(self, registries: Iterable[Registry] = (),
                 tracer: Optional[Tracer] = None,
                 flight: Optional[FlightRecorder] = None,
                 health: Optional[HealthProbe] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 debug_traces_limit: int = 64):
        self.registries = tuple(registries)
        self.tracer = tracer if tracer is not None else global_tracer()
        self.flight = flight if flight is not None else global_flight()
        self.health = health
        self.debug_traces_limit = int(debug_traces_limit)
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._server.telemetry = self  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "TelemetryServer":
        # check-and-set under the lock (transport.py's start discipline):
        # two racing start() calls must not spawn two serve loops — and a
        # start after stop() must FAIL LOUDLY, not serve_forever on a
        # closed socket (the listener died with stop(); make a new server)
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "TelemetryServer was stopped (port released); "
                    "construct a new one"
                )
            if self._thread is not None:
                return self
            self._thread = t = threading.Thread(
                target=self._server.serve_forever,
                name=f"hg-telemetry-{self.port}", daemon=True,
            )
        t.start()
        return self

    def stop(self) -> None:
        """Stop serving and RELEASE the port — also when ``start()`` was
        never called (the listening socket binds in ``__init__``)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            t, self._thread = self._thread, None
        if t is not None:
            # shutdown() only returns once serve_forever exits — never
            # call it for a loop that never ran (it would wait forever)
            self._server.shutdown()
            t.join(timeout=5)
        self._server.server_close()

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
