"""Fleet observability: one collector over every process behind the door.

PRs 9–12 made the deployment a genuine fleet — primary + replicas +
router + sharded mesh — while telemetry stayed strictly per-process:
every node runs its own ``/metrics``, ``/debug/traces``, and flight
recorder, and nobody joins them. This module is the join:

- **FleetCollector** scrapes every registered node source (in-process
  references or a remote node's
  :class:`~hypergraphdb_tpu.obs.http.TelemetryServer` URL), keeps the
  latest scrape per node, and serves the merged views the front door
  exposes: ``fleet_metrics()`` (per-node-labelled exposition — the
  ``prometheus_text(labels=...)`` / :func:`~.export.merge_expositions`
  machinery keeps identically-named series distinct),
  ``fleet_healthz()`` (worst-of verdict + per-node detail), and the SLO
  monitor tick (:mod:`~hypergraphdb_tpu.obs.slo`).
- **Cross-process trace assembly**: the sender/receiver trace halves
  that ``peer/messages.attach_trace`` correlates by 128-bit trace id
  (PR 11 widened the ids precisely so a multi-process pod could be
  joined behind one collector) are folded into a per-trace-id store as
  scrapes arrive; :meth:`FleetCollector.fleet_trace` stitches all of a
  trace id's spans — wherever they were recorded — into ONE tree, each
  span tagged with its node, queryable as ``GET /fleet/traces/<tid>``
  on the door.
- **Incident visibility**: a flight-recorder incident on any node
  (breaker trip, typed serve error, SLO burn) is detected from the
  scraped flight window and the node's window at that moment is
  retained on the collector — an operator asks the DOOR what broke,
  not N processes.
- **Per-request cost attribution**: :func:`explain_record` turns a
  finished request trace into the EXPLAIN dict ``submit_*(explain=True)``
  and ``POST /submit {"explain": true}`` return — serving lane, bucket
  and pad occupancy, device seconds, retries, breaker state, trace id —
  assembled from the ticket's own span tree, so the record can never
  disagree with the trace an operator later pulls from the fleet view.

No jax imports; HTTP scraping uses stdlib urllib. Everything is
clock-injected so tier-1 tests drive polls deterministically.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Iterable, Optional

from hypergraphdb_tpu.obs.export import (
    merge_expositions,
    parse_traces_jsonl,
    prometheus_text,
    sample_value,
    trace_to_dict,
)
from hypergraphdb_tpu.obs.flight import (
    FlightRecorder,
    global_flight,
    parse_flight_jsonl,
)
from hypergraphdb_tpu.obs.registry import Registry


@dataclass
class NodeScrape:
    """One node's telemetry at one poll: the unit the fleet views merge."""

    node_id: str
    role: str = "node"
    ok: bool = False                 # the scrape itself succeeded
    healthy: bool = False            # the node's own health verdict
    health: dict = field(default_factory=dict)
    metrics_text: str = ""
    traces: list = field(default_factory=list)   # trace records (dicts)
    flight: list = field(default_factory=list)   # flight records (dicts)
    t: float = 0.0
    error: Optional[str] = None


class LocalNodeSource:
    """An in-process node (the router itself, a test harness, a primary
    living in the door's process): direct references, no sockets."""

    def __init__(self, node_id: str, registries: Iterable[Registry] = (),
                 tracer=None, flight: Optional[FlightRecorder] = None,
                 health=None, role: str = "node"):
        self.node_id = str(node_id)
        self.role = role
        self.registries = tuple(registries)
        self.tracer = tracer
        self.flight = flight
        self.health = health

    def scrape(self, traces_limit: int = 64) -> NodeScrape:
        out = NodeScrape(self.node_id, self.role, ok=True)
        out.metrics_text = prometheus_text(*self.registries)
        if self.tracer is not None:
            out.traces = [trace_to_dict(t)
                          for t in self.tracer.peek(traces_limit)]
        if self.flight is not None:
            # round-trip through the ONE committed serialization so the
            # local and HTTP sources can never drift on record shape
            out.flight = parse_flight_jsonl(self.flight.to_jsonl())
        if self.health is not None:
            out.healthy, out.health = self.health()
        else:
            out.healthy = True
        return out


class HTTPNodeSource:
    """A remote node behind its
    :class:`~hypergraphdb_tpu.obs.http.TelemetryServer` base URL — the
    deployment shape: one scrape per endpoint per poll."""

    def __init__(self, node_id: str, url: str, role: str = "node",
                 timeout_s: float = 5.0):
        self.node_id = str(node_id)
        self.url = url.rstrip("/")
        self.role = role
        self.timeout_s = float(timeout_s)

    def _get(self, route: str) -> tuple:
        """(status, text) — non-2xx bodies are still telemetry (a 503
        ``/healthz`` carries the unhealthy payload)."""
        try:
            with urllib.request.urlopen(self.url + route,
                                        timeout=self.timeout_s) as r:
                return r.status, r.read().decode("utf-8")
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode("utf-8", "replace")

    def _get_ok(self, route: str) -> str:
        """Body of a route that MUST answer 200 — an error body is not
        telemetry (kept as text it would corrupt the merged exposition
        page / the trace-record reader), so non-200 fails the scrape."""
        status, text = self._get(route)
        if status != 200:
            raise ValueError(f"{route} answered {status}")
        return text

    def scrape(self, traces_limit: int = 64) -> NodeScrape:
        out = NodeScrape(self.node_id, self.role)
        try:
            out.metrics_text = self._get_ok("/metrics")
            out.traces = parse_traces_jsonl(
                self._get_ok("/debug/traces")
            )[-traces_limit:]
            out.flight = parse_flight_jsonl(self._get_ok("/debug/flight"))
            # /healthz is the one route where non-200 IS the payload
            # (503 = an unhealthy node's own verdict)
            status, health_text = self._get("/healthz")
            try:
                out.health = json.loads(health_text)
            except ValueError:
                out.health = {}
            out.healthy = status == 200
            out.ok = True
        except (OSError, ValueError) as e:
            out.error = f"{type(e).__name__}: {e}"
        return out


class FleetCollector:
    """The fleet's telemetry brain: poll every node, keep the latest
    scrape, fold trace records into the per-trace-id store, watch flight
    windows for incidents, tick the SLO monitor.

    Thread-safe: the poll loop writes under one lock while the door's
    handler threads read merged views. ``poll_interval_s=0`` disables
    the background thread (tests call :meth:`poll` directly)."""

    def __init__(self, sources: Iterable = (), clock=None,
                 flight: Optional[FlightRecorder] = None,
                 poll_interval_s: float = 0.25, traces_limit: int = 64,
                 max_traces: int = 512, slo=None):
        self.sources = list(sources)
        self.clock = clock or time.monotonic
        #: the collector's OWN recorder — SLO burn incidents and
        #: node-incident sightings land here (and dump, if configured)
        self.flight = flight if flight is not None else global_flight()
        self.poll_interval_s = float(poll_interval_s)
        self.traces_limit = int(traces_limit)
        self.max_traces = int(max_traces)
        #: optional hgobs SLO monitor, ticked once per poll
        self.slo = slo
        self.registry = Registry("fleet")
        self._polls = self.registry.counter("fleet.polls")
        self._scrape_errors = self.registry.counter("fleet.scrape_errors")
        self._incidents_seen = self.registry.counter("fleet.incidents_seen")
        self._nodes_up = self.registry.gauge("fleet.nodes_up")
        self._nodes_total = self.registry.gauge("fleet.nodes_total")
        self._traces_held = self.registry.gauge("fleet.traces_assembled")
        self._lock = threading.Lock()
        self._scrapes: dict[str, NodeScrape] = {}
        #: trace id → {dedupe key: trace record + "node"} (insertion-LRU)
        self._trace_store: OrderedDict = OrderedDict()
        #: node id → newest flight-incident timestamp already accounted
        self._incident_marks: dict[str, float] = {}
        #: node id → retained window snapshot of its latest incident
        self._incident_windows: dict[str, dict] = {}
        #: one sweep at a time: a direct poll() racing the background
        #: loop would double-count incident sightings (the per-node
        #: mark check is check-then-act) and race the SLO sources'
        #: cumulative accumulators — serialize instead
        self._poll_gate = threading.Lock()
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def add_source(self, source) -> "FleetCollector":
        with self._lock:
            self.sources = [s for s in self.sources
                            if s.node_id != source.node_id] + [source]
        return self

    def start(self) -> "FleetCollector":
        self.poll()
        t = None
        if self.poll_interval_s > 0:
            with self._lock:      # check-and-set: two start()s, one loop
                if self._poll_thread is None:
                    self._poll_stop.clear()
                    self._poll_thread = t = threading.Thread(
                        target=self._poll_loop, name="fleet-collector",
                        daemon=True,
                    )
        if t is not None:
            t.start()
        return self

    def stop(self) -> None:
        self._poll_stop.set()
        with self._lock:
            t, self._poll_thread = self._poll_thread, None
        if t is not None:
            t.join(timeout=5)

    def __enter__(self) -> "FleetCollector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception:  # noqa: BLE001 - the loop must survive
                import logging

                logging.getLogger("hypergraphdb_tpu.obs").warning(
                    "fleet poll failed", exc_info=True
                )

    # -- polling -------------------------------------------------------------
    def poll(self) -> dict:
        """One scrape sweep over every source; returns {node_id: ok}.
        Serialized: a direct call landing while the background loop
        sweeps WAITS for its turn (bounded by one sweep) rather than
        interleaving with it."""
        with self._poll_gate:
            # deliberate blocking-under-lock: the gate's whole job is to
            # make a second caller WAIT for the in-progress sweep (which
            # joins its scrape workers) rather than interleave with it
            return self._poll_once()  # hglint: disable=HG702

    def _poll_once(self) -> dict:
        with self._lock:
            sources = list(self.sources)
        now = self.clock()
        results: dict[str, NodeScrape] = {}

        def run(src):
            try:
                results[src.node_id] = src.scrape(self.traces_limit)
            except Exception as e:  # noqa: BLE001 - one bad node ≠ no poll
                results[src.node_id] = NodeScrape(
                    src.node_id, getattr(src, "role", "node"),
                    error=f"{type(e).__name__}: {e}",
                )

        # scrape CONCURRENTLY (the front door's probe-sweep discipline):
        # the sweep waits for the slowest single node, not the sum — one
        # hung telemetry port must not stall incident detection and SLO
        # ticks for every healthy node behind it
        if len(sources) <= 1:
            for src in sources:
                run(src)
        else:
            threads = [
                threading.Thread(target=run, args=(src,),
                                 name=f"fleet-scrape-{src.node_id}",
                                 daemon=True)
                for src in sources
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        verdicts = {}
        up = 0
        for src in sources:
            scrape = results[src.node_id]
            scrape.t = now
            verdicts[src.node_id] = scrape.ok
            if scrape.ok:
                up += 1
            else:
                self._scrape_errors.inc()
                with self._lock:
                    prev = self._scrapes.get(scrape.node_id)
                # a failed scrape KEEPS the node's last-good metrics
                # page: the SLO counter sources read cumulative totals
                # off these pages, and letting a down node's sum drop
                # to zero would clamp the burn windows empty — muting
                # the deadline alert fleet-wide exactly mid-incident.
                # ok/healthy stay False, so health verdicts are honest.
                if prev is not None:
                    scrape.metrics_text = prev.metrics_text
            self._fold_traces(scrape)
            self._watch_incidents(scrape)
            with self._lock:
                self._scrapes[scrape.node_id] = scrape
        self._polls.inc()
        self._nodes_up.set(up)
        self._nodes_total.set(len(sources))
        with self._lock:
            self._traces_held.set(len(self._trace_store))
        if self.slo is not None:
            self.slo.tick()
        return verdicts

    def _fold_traces(self, scrape: NodeScrape) -> None:
        """Fold one scrape's trace records into the per-trace-id store.
        ``/debug/traces`` is a PEEK, so the same record arrives on every
        poll until it ages out of the node's buffer — records dedupe on
        (node, root name, t0, first span id). Bounded: the store keeps
        the most recently TOUCHED ``max_traces`` trace ids."""
        if not scrape.traces:
            return
        with self._lock:
            for rec in scrape.traces:
                tid = rec.get("trace_id")
                if tid is None:
                    continue
                spans = rec.get("spans") or []
                key = (scrape.node_id, rec.get("name"), rec.get("t0"),
                       spans[0]["span_id"] if spans else None)
                bucket = self._trace_store.get(tid)
                if bucket is None:
                    bucket = self._trace_store[tid] = {}
                else:
                    self._trace_store.move_to_end(tid)
                bucket[key] = dict(rec, node=scrape.node_id)
            while len(self._trace_store) > self.max_traces:
                self._trace_store.popitem(last=False)

    def _watch_incidents(self, scrape: NodeScrape) -> None:
        """Detect NEW ``incident`` records in a node's scraped flight
        window (per-node timestamps — flight clocks are per-process) and
        retain that node's window: the collector pulls the remote
        context the moment something fired, so the door's fleet view can
        show it even after the node's own ring rolls over."""
        incidents = [r for r in scrape.flight if r.get("kind") == "incident"]
        if not incidents:
            return
        newest = max(r["t"] for r in incidents)
        mark = self._incident_marks.get(scrape.node_id)
        if mark is not None and newest <= mark:
            return
        fresh = [r for r in incidents if mark is None or r["t"] > mark]
        self._incident_marks[scrape.node_id] = newest
        self._incidents_seen.inc(len(fresh))
        last = fresh[-1]
        with self._lock:
            self._incident_windows[scrape.node_id] = {
                "t": last["t"],
                "reason": last.get("reason"),
                "incidents_new": len(fresh),
                "seen_at": scrape.t,
                # the PULLED window: the node's recent history at the
                # moment the collector noticed
                "window": list(scrape.flight),
            }
        self.flight.record("fleet.incident_seen", node=scrape.node_id,
                           reason=str(last.get("reason")))

    # -- reading: nodes ------------------------------------------------------
    def node_scrapes(self) -> dict:
        """{node_id: latest NodeScrape} — what SLO sources read."""
        with self._lock:
            return dict(self._scrapes)

    def metric_total(self, sample_name: str) -> float:
        """Sum one exposition sample across every node's latest scrape
        (absent samples count 0) — fleet-wide counter totals."""
        total = 0.0
        for scrape in self.node_scrapes().values():
            v = sample_value(scrape.metrics_text, sample_name)
            if v is not None:
                total += v
        return total

    def incidents(self) -> dict:
        """{node_id: retained incident window snapshot}."""
        with self._lock:
            return {k: dict(v) for k, v in self._incident_windows.items()}

    # -- reading: merged views -----------------------------------------------
    def fleet_metrics(self) -> str:
        """The door's ``/fleet/metrics`` body: every node's exposition
        page stamped ``node="<id>"`` plus the collector's own counters,
        merged into one valid page."""
        pages = [({"node": "fleet"}, prometheus_text(self.registry))]
        for node_id, scrape in sorted(self.node_scrapes().items()):
            pages.append(({"node": node_id}, scrape.metrics_text))
        return merge_expositions(pages)

    def fleet_healthz(self) -> tuple:
        """(healthy, payload): worst-of verdict — healthy iff every node
        scraped AND reported healthy — with per-node detail and the
        retained incident summaries beside it."""
        nodes = {}
        ok = True
        scrapes = self.node_scrapes()
        for node_id, scrape in sorted(scrapes.items()):
            node_ok = scrape.ok and scrape.healthy
            ok = ok and node_ok
            nodes[node_id] = {
                "role": scrape.role,
                "scraped": scrape.ok,
                "healthy": scrape.healthy,
                "detail": scrape.health,
            }
            if scrape.error:
                nodes[node_id]["error"] = scrape.error
        incidents = {
            node_id: {k: v for k, v in snap.items() if k != "window"}
            for node_id, snap in self.incidents().items()
        }
        ok = ok and bool(scrapes)
        return ok, {
            "role": "fleet",
            "healthy_nodes": sum(
                1 for n in nodes.values() if n["scraped"] and n["healthy"]
            ),
            "nodes_total": len(nodes),
            "nodes": nodes,
            "incidents": incidents,
        }

    def fleet_perf(self) -> dict:
        """The door's ``/fleet/perf`` body: every node's perf-sentinel
        verdict (the ``perf`` section ``obs.http.runtime_health`` embeds
        in ``/healthz``) merged into one view — per-node summaries, the
        ``{node: [lanes]}`` violation map (so "which lane on which node
        drifted" is one GET), fleet-total alerts, and how many nodes
        report a sentinel at all (a node without one is absent, not
        healthy-by-omission)."""
        nodes: dict = {}
        violating: dict = {}
        alerts = 0
        for node_id, scrape in sorted(self.node_scrapes().items()):
            p = (scrape.health or {}).get("perf")
            if not isinstance(p, dict):
                continue
            nodes[node_id] = p
            v = p.get("violating") or []
            if v:
                violating[node_id] = list(v)
            alerts += int(p.get("alerts_total") or 0)
        return {
            "role": "fleet",
            "nodes": nodes,
            "violating": violating,
            "alerts_total": alerts,
            "nodes_reporting": len(nodes),
        }

    def fleet_plan(self) -> dict:
        """The door's ``/fleet/plan`` body: every node's hgplan
        correction state (the ``plan`` section ``obs.http.runtime_health``
        embeds in ``/healthz``) merged into one view — per-node
        summaries, fleet totals of active corrections and sentinel-guard
        vetoes, and how many nodes report a planner at all (same
        absent-not-healthy discipline as ``fleet_perf``)."""
        nodes: dict = {}
        corrections = 0
        vetoes = 0
        for node_id, scrape in sorted(self.node_scrapes().items()):
            p = (scrape.health or {}).get("plan")
            if not isinstance(p, dict):
                continue
            nodes[node_id] = p
            corrections += int(p.get("corrections_active") or 0)
            vetoes += int(p.get("guard_vetoes") or 0)
        return {
            "role": "fleet",
            "nodes": nodes,
            "corrections_active": corrections,
            "guard_vetoes": vetoes,
            "nodes_reporting": len(nodes),
        }

    # -- reading: assembled traces -------------------------------------------
    def fleet_traces(self) -> list:
        """Summaries of every assembled trace id, most recent last:
        ``{"trace_id", "processes", "n_processes", "n_spans", "names"}``."""
        with self._lock:
            items = [(tid, list(bucket.values()))
                     for tid, bucket in self._trace_store.items()]
        out = []
        for tid, recs in items:
            out.append({
                "trace_id": tid,
                "processes": sorted({r["node"] for r in recs}),
                "n_processes": len({r["node"] for r in recs}),
                "n_spans": sum(len(r.get("spans") or ()) for r in recs),
                "names": sorted({r.get("name") for r in recs}),
            })
        return out

    def fleet_trace(self, trace_id: int) -> Optional[dict]:
        """ONE stitched fleet trace: all of ``trace_id``'s spans from
        every node, joined into a single tree — the receiver half's
        parentless spans hang under the sender's propagated span id
        exactly as recorded, so the cross-process edges need no
        heuristics, just the union of span records. None when the id is
        unknown."""
        with self._lock:
            bucket = self._trace_store.get(int(trace_id))
            recs = list(bucket.values()) if bucket else None
        if not recs:
            return None
        spans = []
        for rec in recs:
            for sp in rec.get("spans") or ():
                spans.append(dict(sp, node=rec["node"],
                                  root_name=rec.get("name")))
        spans.sort(key=lambda s: (s.get("t0") or 0.0, s["span_id"]))
        ids = {sp["span_id"] for sp in spans}
        children: dict = {}
        roots = []
        for sp in spans:
            pid = sp.get("parent_id")
            if pid in ids:
                children.setdefault(pid, []).append(sp)
            else:
                roots.append(sp)

        def nest(sp, seen):
            node = {k: sp[k] for k in ("span_id", "parent_id", "name",
                                       "t0", "t1", "attrs", "node")}
            kids = []
            for ch in children.get(sp["span_id"], ()):
                if ch["span_id"] in seen:
                    continue  # malformed cycle: never recurse forever
                seen.add(ch["span_id"])
                kids.append(nest(ch, seen))
            if kids:
                node["children"] = kids
            return node

        seen = {sp["span_id"] for sp in roots}
        tree = [nest(sp, seen) for sp in roots]
        processes = sorted({r["node"] for r in recs})
        return {
            "trace_id": int(trace_id),
            "processes": processes,
            "n_processes": len(processes),
            "names": sorted({r.get("name") for r in recs}),
            "n_spans": len(spans),
            "spans": spans,
            "tree": tree,
        }


# ------------------------------------------------------------------ explain


def explain_record(trace, result=None, lane_path: Optional[str] = None,
                   breaker_state: Optional[str] = None,
                   shard_owner: Optional[int] = None,
                   node_id: Optional[str] = None,
                   join: Optional[dict] = None) -> dict:
    """The per-request cost-attribution (EXPLAIN) record, assembled from
    a FINISHED request trace's own span tree — the one source of truth,
    so the record can never disagree with the trace an operator later
    fetches from ``/fleet/traces/<trace_id>``.

    ``lane_path`` names the executor path that answered (``device`` /
    ``sharded`` / ``host``); when absent it is derived from the span
    tree (a ``host_fallback`` span → host, else device). ``result``
    (a ServeResult/JoinResult) contributes count/epoch/truncation."""

    def span_named(name):
        return trace.find(name)

    def dur(sp):
        return None if sp is None or sp.t1 is None else sp.t1 - sp.t0

    bf = span_named("batch_form")
    launch = span_named("launch")
    device = span_named("device")
    path = lane_path
    if path is None:
        path = "host" if span_named("host_fallback") is not None else "device"
    kind = trace.attrs.get("kind")
    bucket = None if bf is None else bf.attrs.get("bucket")
    n_real = None if bf is None else bf.attrs.get("n_real")
    rec = {
        "trace_id": trace.trace_id,
        "kind": kind,
        "lane": f"{kind}/{path}" if kind else path,
        "queue_wait_s": dur(span_named("queue_wait")),
        "bucket": bucket,
        "lanes_real": n_real,
        "lanes_padded": None if bf is None else bf.attrs.get("n_pad"),
        "occupancy": (
            None if not bucket else round(n_real / bucket, 4)
        ),
        "launch_s": dur(launch),
        "retries": None if launch is None else launch.attrs.get("retries"),
        "device_s": dur(device),
        "device_slot": None if device is None else device.attrs.get("slot"),
        "collect_s": dur(span_named("collect")),
        "total_s": None if trace.t1 is None else trace.t1 - trace.t0,
        "breaker": breaker_state,
        "shard_owner": shard_owner,
        "n_spans": len(trace.spans()),
        "dropped_spans": trace.dropped,
    }
    if node_id is not None:
        rec["node"] = node_id
    if join is not None:
        # join-engine attribution (plan shape flat/bushy/hub/host, hub
        # dispatches, partial memtable corrections) — assembled by the
        # runtime from the launched batch, batch-level by construction
        rec["join"] = dict(join)
    if result is not None:
        rec["served_by"] = getattr(result, "served_by", None)
        rec["count"] = int(getattr(result, "count", 0))
        rec["truncated"] = bool(getattr(result, "truncated", False))
        rec["epoch"] = int(getattr(result, "epoch", 0))
    return rec
