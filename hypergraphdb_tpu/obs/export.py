"""Exportable telemetry: Prometheus text format + JSONL span traces.

Two committed wire formats:

- :func:`prometheus_text` renders one or more registries in the
  Prometheus exposition format (``# TYPE`` comments; dots in metric
  names become underscores; counters get the conventional ``_total``
  suffix; histograms emit cumulative ``_bucket{le=...}`` series plus
  ``_sum``/``_count``). Line-parseable — covered by a format test.
- :func:`traces_to_jsonl` serializes finished traces one-per-line with an
  explicit ``schema_version`` (:data:`TRACE_SCHEMA_VERSION`); span
  attributes are restricted to scalars at record time (``Span.set``), so
  serialization never fails mid-export. :func:`parse_traces_jsonl` is the
  committed reader — round-tripped by tests, version-checked so a future
  v2 cannot be misread silently.

:func:`write_telemetry` is the one-call dump ``bench.py --telemetry``
uses: ``<prefix>.prom`` + ``<prefix>.trace.jsonl`` next to the results.
"""

from __future__ import annotations

import json
import math
import re
from typing import Iterable, Optional

from hypergraphdb_tpu.obs.registry import Registry
from hypergraphdb_tpu.obs.trace import Trace, Tracer

#: bump on ANY change to the JSONL trace record shape.
#: v2: trace/span ids grew to full 128 bits (multi-chip pods put many
#: processes behind one collector; the v1 62-bit space could collide on
#: the join key). A v1 file's ids are not comparable with v2 ids, so the
#: reader REJECTS v1 instead of silently mixing the two spaces.
TRACE_SCHEMA_VERSION = 2

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def prometheus_text(*registries: Registry) -> str:
    """Render registries as Prometheus exposition text. Duplicate names
    across registries render once (first registry wins) — merged dumps of
    per-graph + per-runtime registries stay valid exposition."""
    lines: list[str] = []
    seen: set[str] = set()
    for reg in registries:
        for m in reg.instruments():
            pname = _prom_name(m.name)
            if pname in seen:
                continue
            seen.add(pname)
            if m.kind == "counter":
                lines.append(f"# TYPE {pname}_total counter")
                lines.append(f"{pname}_total {m.value}")
            elif m.kind == "gauge":
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {_fmt(m.value)}")
            else:  # histogram
                lines.append(f"# TYPE {pname} histogram")
                # one locked read: _bucket/_sum/_count stay mutually
                # consistent within a scrape
                buckets, total, count = m.export_state()
                for edge, cum in buckets:
                    lines.append(
                        f'{pname}_bucket{{le="{_fmt(edge)}"}} {cum}'
                    )
                lines.append(f"{pname}_sum {_fmt(total)}")
                lines.append(f"{pname}_count {count}")
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------------ traces


def trace_to_dict(trace: Trace) -> dict:
    """One trace as a plain dict (the JSONL record body)."""
    return {
        "schema_version": TRACE_SCHEMA_VERSION,
        "trace_id": trace.trace_id,
        "name": trace.name,
        "t0": trace.t0,
        "t1": trace.t1,
        "dropped_spans": trace.dropped,
        "attrs": dict(trace.attrs),
        "spans": [
            {
                "span_id": sp.span_id,
                "parent_id": sp.parent_id,
                "name": sp.name,
                "t0": sp.t0,
                "t1": sp.t1,
                "attrs": dict(sp.attrs),
            }
            for sp in trace.spans()
        ],
    }


def traces_to_jsonl(traces: Iterable[Trace]) -> str:
    """Finished traces, one JSON object per line."""
    return "".join(
        json.dumps(trace_to_dict(t), sort_keys=True) + "\n" for t in traces
    )


def parse_traces_jsonl(text: str) -> list[dict]:
    """The committed reader: parses + version-checks every line."""
    out = []
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        rec = json.loads(line)
        ver = rec.get("schema_version")
        if ver != TRACE_SCHEMA_VERSION:
            raise ValueError(
                f"trace line {i}: schema_version {ver!r} != "
                f"{TRACE_SCHEMA_VERSION}"
            )
        for key in ("trace_id", "name", "t0", "spans"):
            if key not in rec:
                raise ValueError(f"trace line {i}: missing {key!r}")
        out.append(rec)
    return out


def write_telemetry(prefix: str, registries: Iterable[Registry] = (),
                    tracer: Optional[Tracer] = None) -> dict:
    """Dump ``<prefix>.prom`` and ``<prefix>.trace.jsonl``; returns the
    paths written (the bench records them next to its results)."""
    prom_path = prefix + ".prom"
    jsonl_path = prefix + ".trace.jsonl"
    with open(prom_path, "w") as f:
        f.write(prometheus_text(*registries))
    traces = tracer.drain() if tracer is not None else []
    with open(jsonl_path, "w") as f:
        f.write(traces_to_jsonl(traces))
    return {"prometheus": prom_path, "traces": jsonl_path,
            "n_traces": len(traces)}
