"""Exportable telemetry: Prometheus text format + JSONL span traces.

Two committed wire formats:

- :func:`prometheus_text` renders one or more registries in the
  Prometheus exposition format (``# TYPE`` comments; dots in metric
  names become underscores; counters get the conventional ``_total``
  suffix; histograms emit cumulative ``_bucket{le=...}`` series plus
  ``_sum``/``_count``). Line-parseable — covered by a format test.
  ``labels={"node": "r1"}`` stamps every sample line with a constant
  label set, which is what keeps a FLEET-merged scrape's per-node
  series distinct instead of dedupe-colliding on identical names;
  :func:`relabel_exposition` applies the same stamping to exposition
  TEXT scraped from a remote node, and :func:`merge_expositions` joins
  several nodes' pages into one valid page (``# TYPE`` emitted once per
  metric, first node wins — the multi-registry dedupe rule, fleet
  edition). :func:`sample_value` is the matching tiny reader (the SLO
  monitor's counter source pulls totals out of scraped pages with it).
- :func:`traces_to_jsonl` serializes finished traces one-per-line with an
  explicit ``schema_version`` (:data:`TRACE_SCHEMA_VERSION`); span
  attributes are restricted to scalars at record time (``Span.set``), so
  serialization never fails mid-export. :func:`parse_traces_jsonl` is the
  committed reader — round-tripped by tests, version-checked so a future
  v2 cannot be misread silently.

:func:`write_telemetry` is the one-call dump ``bench.py --telemetry``
uses: ``<prefix>.prom`` + ``<prefix>.trace.jsonl`` next to the results.
"""

from __future__ import annotations

import json
import math
import re
from typing import Iterable, Optional

from hypergraphdb_tpu.obs.registry import Registry
from hypergraphdb_tpu.obs.trace import Trace, Tracer

#: bump on ANY change to the JSONL trace record shape.
#: v2: trace/span ids grew to full 128 bits (multi-chip pods put many
#: processes behind one collector; the v1 62-bit space could collide on
#: the join key). A v1 file's ids are not comparable with v2 ids, so the
#: reader REJECTS v1 instead of silently mixing the two spaces.
TRACE_SCHEMA_VERSION = 2

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def _label_value(v) -> str:
    """Escape one label value per the exposition format."""
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _label_body(labels: Optional[dict]) -> str:
    """The inside of a ``{...}`` label set (no braces), sorted for a
    stable wire format; empty string for no labels."""
    if not labels:
        return ""
    return ",".join(
        f'{_NAME_RE.sub("_", str(k))}="{_label_value(v)}"'
        for k, v in sorted(labels.items())
    )


def prometheus_text(*registries: Registry,
                    labels: Optional[dict] = None) -> str:
    """Render registries as Prometheus exposition text. Duplicate names
    across registries render once (first registry wins) — merged dumps of
    per-graph + per-runtime registries stay valid exposition. ``labels``
    stamps a constant label set onto every sample line (``node="r1"`` is
    the fleet collector's per-node tag), merged before ``le`` on
    histogram buckets."""
    lines: list[str] = []
    seen: set[str] = set()
    lb = _label_body(labels)
    sfx = "{" + lb + "}" if lb else ""
    for reg in registries:
        for m in reg.instruments():
            pname = _prom_name(m.name)
            if pname in seen:
                continue
            seen.add(pname)
            if m.kind == "counter":
                lines.append(f"# TYPE {pname}_total counter")
                lines.append(f"{pname}_total{sfx} {m.value}")
            elif m.kind == "gauge":
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname}{sfx} {_fmt(m.value)}")
            else:  # histogram
                lines.append(f"# TYPE {pname} histogram")
                # one locked read: _bucket/_sum/_count stay mutually
                # consistent within a scrape
                buckets, total, count = m.export_state()
                pre = lb + "," if lb else ""
                for edge, cum in buckets:
                    lines.append(
                        f'{pname}_bucket{{{pre}le="{_fmt(edge)}"}} {cum}'
                    )
                lines.append(f"{pname}_sum{sfx} {_fmt(total)}")
                lines.append(f"{pname}_count{sfx} {count}")
    return "\n".join(lines) + ("\n" if lines else "")


#: one exposition sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$"
)


def relabel_exposition(text: str, labels: dict) -> str:
    """Stamp ``labels`` onto every sample line of exposition TEXT (what a
    remote node's ``/metrics`` scrape returns) — the collector-side twin
    of ``prometheus_text(labels=...)``. Comment/blank lines pass through;
    existing labels (``le``) are preserved after the stamped ones."""
    lb = _label_body(labels)
    if not lb:
        return text
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            out.append(line)  # foreign line: never corrupt it
            continue
        name, existing, value = m.groups()
        inner = existing[1:-1] if existing else ""
        merged = lb + ("," + inner if inner else "")
        out.append(f"{name}{{{merged}}} {value}")
    return "\n".join(out) + ("\n" if out else "")


def merge_expositions(pages: Iterable[tuple]) -> str:
    """Join several nodes' exposition pages into ONE valid page:
    ``pages`` is an iterable of ``(labels, text)``; every sample line is
    stamped with its page's labels and ``# TYPE`` comments are emitted
    once per metric (first page wins — conflicting redeclarations from a
    skewed node are dropped, not duplicated)."""
    lines: list[str] = []
    typed: set[str] = set()
    for labels, text in pages:
        for line in relabel_exposition(text, labels).splitlines():
            if line.startswith("# TYPE "):
                metric = line.split()[2] if len(line.split()) > 2 else ""
                if metric in typed:
                    continue
                typed.add(metric)
            lines.append(line)
    return "\n".join(lines) + ("\n" if lines else "")


def sample_value(text: str, name: str,
                 labels: Optional[dict] = None) -> Optional[float]:
    """The first sample of ``name`` in exposition text whose label set
    CONTAINS ``labels`` (subset match; None matches any) — the tiny
    reader the SLO monitor's counter sources pull scraped totals with.
    None when absent."""
    want = None if labels is None else {
        (str(k), str(v)) for k, v in labels.items()
    }
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None or m.group(1) != name:
            continue
        if want is not None:
            inner = (m.group(2) or "{}")[1:-1]
            have = set()
            for part in inner.split(","):
                if "=" in part:
                    k, _, v = part.partition("=")
                    have.add((k.strip(), v.strip().strip('"')))
            if not want <= have:
                continue
        try:
            return float(m.group(3))
        except ValueError:
            return None
    return None


# ------------------------------------------------------------------ traces


def trace_to_dict(trace: Trace) -> dict:
    """One trace as a plain dict (the JSONL record body)."""
    return {
        "schema_version": TRACE_SCHEMA_VERSION,
        "trace_id": trace.trace_id,
        "name": trace.name,
        "t0": trace.t0,
        "t1": trace.t1,
        "dropped_spans": trace.dropped,
        "attrs": dict(trace.attrs),
        "spans": [
            {
                "span_id": sp.span_id,
                "parent_id": sp.parent_id,
                "name": sp.name,
                "t0": sp.t0,
                "t1": sp.t1,
                "attrs": dict(sp.attrs),
            }
            for sp in trace.spans()
        ],
    }


def traces_to_jsonl(traces: Iterable[Trace]) -> str:
    """Finished traces, one JSON object per line."""
    return "".join(
        json.dumps(trace_to_dict(t), sort_keys=True) + "\n" for t in traces
    )


def parse_traces_jsonl(text: str) -> list[dict]:
    """The committed reader: parses + version-checks every line."""
    out = []
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        rec = json.loads(line)
        ver = rec.get("schema_version")
        if ver != TRACE_SCHEMA_VERSION:
            raise ValueError(
                f"trace line {i}: schema_version {ver!r} != "
                f"{TRACE_SCHEMA_VERSION}"
            )
        for key in ("trace_id", "name", "t0", "spans"):
            if key not in rec:
                raise ValueError(f"trace line {i}: missing {key!r}")
        out.append(rec)
    return out


def write_telemetry(prefix: str, registries: Iterable[Registry] = (),
                    tracer: Optional[Tracer] = None) -> dict:
    """Dump ``<prefix>.prom`` and ``<prefix>.trace.jsonl``; returns the
    paths written (the bench records them next to its results)."""
    prom_path = prefix + ".prom"
    jsonl_path = prefix + ".trace.jsonl"
    with open(prom_path, "w") as f:
        f.write(prometheus_text(*registries))
    traces = tracer.drain() if tracer is not None else []
    with open(jsonl_path, "w") as f:
        f.write(traces_to_jsonl(traces))
    return {"prometheus": prom_path, "traces": jsonl_path,
            "n_traces": len(traces)}
