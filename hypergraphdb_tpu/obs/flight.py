"""Always-on flight recorder: a bounded ring of recent structured events.

Metrics tell you the rates; traces tell you one request; neither answers
"what were the last few hundred things this process did before it broke".
The :class:`FlightRecorder` does: a fixed-size ring of small structured
events — span terminals, fault firings, breaker transitions, serve
retries, compaction swaps — that costs ONE bounded ring append per event
on the healthy path (a ``deque(maxlen=...)`` append, GIL-atomic, no lock,
no IO, no growth) and, on **incident**, dumps its full window to a JSONL
file so the minutes before a breaker trip or typed serve error are on
disk before anyone asks.

Wired producers (each behind one ``enabled`` attribute read):

- ``obs.trace.Trace.finish_terminal`` — every trace terminal;
- ``fault.registry.FaultRegistry.check`` — every injected-fault fire
  (so every injected-fault test doubles as a flight-recorder fixture);
- ``fault.breaker.CircuitBreaker`` — every gate transition, trips as
  incidents;
- ``serve/runtime.py`` — retry-ladder steps; typed batch errors as
  incidents;
- ``ops/incremental.py`` — compaction device swaps;
- ``ops/checkpoint.py`` — corrupt-sidecar triage on reopen (the
  recovery-after-crash signal) as an incident.

Incident dumps are rate-limited (``min_dump_interval_s``) and written
only when an ``incident_dir`` is configured — incidents are always
COUNTED either way. A dump is a point-in-time snapshot of the ring; the
dump path is returned and remembered (``last_dump_path``).

No jax imports; records are scalars-only dicts, so JSONL serialization
never fails mid-incident.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

#: default ring capacity: at ~100 B/event this is <1 MB of history
DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """Bounded ring of ``(t, kind, fields)`` events + incident dumping.

    ``enabled`` is the zero-ish-cost gate (a plain attribute, the
    ``Tracer.enabled`` discipline) — ON by default: the healthy-path
    cost is one tuple allocation and one atomic deque append per event,
    cheap enough to leave running in production, which is the point of a
    flight recorder."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock: Optional[Callable[[], float]] = None,
                 incident_dir: Optional[str] = None,
                 min_dump_interval_s: float = 1.0):
        self.enabled = True
        self.clock = clock or time.monotonic
        self.incident_dir = incident_dir
        self.min_dump_interval_s = float(min_dump_interval_s)
        #: the ring: deque.append with maxlen is one GIL-atomic op — the
        #: healthy path takes NO lock (same discipline as the replication
        #: worker's pending queue)
        self._ring: deque = deque(maxlen=int(capacity))
        # incident bookkeeping only (rare path) lives behind the lock.
        # REENTRANT: the SIGTERM dump hook runs incident() from a signal
        # handler, which CPython executes on the main thread at the next
        # bytecode — possibly while that same thread already holds this
        # lock (a plain Lock would self-deadlock the orderly kill)
        self._lock = threading.RLock()
        self._incidents = 0
        self._dumps = 0
        self._last_dump_t: Optional[float] = None
        self.last_dump_path: Optional[str] = None

    # -- the hot path --------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one event. Scalars only (the JSONL dump contract);
        non-scalars are stringified rather than rejected — a recorder
        must never throw from an error path."""
        if not self.enabled:
            return
        self._ring.append((self.clock(), kind, fields))

    # -- configuration -------------------------------------------------------
    def configure(self, incident_dir: Optional[str] = None,
                  capacity: Optional[int] = None,
                  min_dump_interval_s: Optional[float] = None) -> None:
        """Point incident dumps at a directory / resize the ring (resize
        starts a fresh ring — history is bounded, not durable)."""
        with self._lock:
            if incident_dir is not None:
                self.incident_dir = incident_dir
            if min_dump_interval_s is not None:
                self.min_dump_interval_s = float(min_dump_interval_s)
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=int(capacity))

    def reset(self) -> None:
        """Clear the ring and incident counters (test isolation)."""
        with self._lock:
            self._ring.clear()
            self._incidents = 0
            self._dumps = 0
            self._last_dump_t = None
            self.last_dump_path = None

    # -- reading -------------------------------------------------------------
    def records(self) -> list[tuple]:
        """Snapshot of the ring, oldest first."""
        return list(self._ring)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    @property
    def incidents(self) -> int:
        with self._lock:
            return self._incidents

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    # -- incidents -----------------------------------------------------------
    def incident(self, reason: str, **fields) -> Optional[str]:
        """Record the incident event, then dump the full window to
        ``<incident_dir>/flight_<n>_<reason>.jsonl`` — rate-limited so an
        error storm costs one file per interval, not one per error.
        Returns the dump path (None when not configured / rate-limited).
        Never raises: an unwritable dir must not turn one incident into
        two."""
        self.record("incident", reason=reason, **fields)
        with self._lock:
            self._incidents += 1
            if self.incident_dir is None:
                return None
            now = self.clock()
            if (self._last_dump_t is not None
                    and now - self._last_dump_t < self.min_dump_interval_s):
                return None
            self._last_dump_t = now
            self._dumps += 1
            n = self._dumps
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        path = os.path.join(self.incident_dir, f"flight_{n:04d}_{safe}.jsonl")
        try:
            self.dump(path)
        except OSError:
            return None
        with self._lock:
            self.last_dump_path = path
        return path

    def to_jsonl(self) -> str:
        """The current window as JSONL text (one ``{"t", "kind", ...}``
        object per line, oldest first) — the ONE serialization both
        incident dumps and the ``/debug/flight`` endpoint emit, so the
        two views can never drift apart."""
        lines = []
        for t, kind, fields in self.records():
            rec = {"t": t, "kind": kind}
            for k, v in fields.items():
                rec[k] = (v if isinstance(v, (bool, int, float, str,
                                              type(None))) else str(v))
            lines.append(json.dumps(rec, sort_keys=True))
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str) -> str:
        """Write :meth:`to_jsonl` to ``path``."""
        text = self.to_jsonl()
        with open(path, "w") as f:
            f.write(text)
        return path


def parse_flight_jsonl(text: str) -> list[dict]:
    """The committed reader for dump files: every line must carry
    ``t`` and ``kind``."""
    out = []
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        rec = json.loads(line)
        for key in ("t", "kind"):
            if key not in rec:
                raise ValueError(f"flight line {i}: missing {key!r}")
        out.append(rec)
    return out


#: the process-wide recorder every in-tree site binds at import (the
#: fault-registry singleton contract: sites cache the reference)
_GLOBAL = FlightRecorder()


def global_flight() -> FlightRecorder:
    return _GLOBAL


def install_sigterm_dump(recorder: Optional[FlightRecorder] = None,
                         signum: Optional[int] = None) -> Callable[[], None]:
    """OPT-IN: dump the flight window when the process is killed orderly.

    Installs a SIGTERM handler (overridable via ``signum``) that records
    ``FlightRecorder.incident("sigterm")`` — writing the window to the
    recorder's ``incident_dir`` if one is configured — and then hands the
    signal on, PRESERVING the prior disposition: a previously-installed
    Python handler is invoked; a process that explicitly ignored the
    signal (``SIG_IGN``) keeps ignoring it (dump only, no death); with
    the default disposition the handler re-raises the signal against the
    process with ``SIG_DFL`` restored, so the kill still kills (operators
    get the window, supervisors still see a SIGTERM death).

    Must be called from the main thread (CPython restricts
    ``signal.signal``). Returns an uninstall callable restoring the prior
    handler. NOT installed automatically anywhere — a library must never
    repurpose a process's signals behind the operator's back; wire it
    from your entrypoint (or from ``tools/``-style harnesses).
    """
    import signal as _signal

    rec = recorder or _GLOBAL
    signum = _signal.SIGTERM if signum is None else signum
    prev = _signal.getsignal(signum)

    def _handler(num, frame):
        rec.incident("sigterm", signal=int(num))
        if prev == _signal.SIG_IGN:
            return  # the operator chose to survive this signal; honor it
        if callable(prev) and prev != _signal.SIG_DFL:
            prev(num, frame)
            return
        # default (or unknowable C-installed) disposition: restore
        # SIG_DFL and re-deliver, so the process still dies with the
        # conventional -SIGTERM status
        _signal.signal(num, _signal.SIG_DFL)
        os.kill(os.getpid(), num)

    _signal.signal(signum, _handler)

    def uninstall():
        if _signal.getsignal(signum) is not _handler:
            # someone installed their own handler AFTER ours (it chains
            # to us via its own getsignal) — restoring `prev` here would
            # silently remove THEIR handler; leave the chain alone
            import logging

            logging.getLogger("hypergraphdb_tpu.obs").warning(
                "sigterm dump hook is no longer the active handler for "
                "signal %s — leaving the current disposition in place",
                signum,
            )
            return
        # getsignal returns None for a handler installed from C — it
        # cannot be re-installed from Python, so fall back to SIG_DFL
        # (at least detaching the recorder) instead of raising
        _signal.signal(
            signum, prev if prev is not None else _signal.SIG_DFL
        )

    return uninstall
