"""hgplan: the cost-based cross-lane query planner.

Public surface:

- :class:`~hypergraphdb_tpu.plan.stats.CardinalityEstimator` — exact-
  for-free cardinalities off the pinned base (window widths, degrees,
  type counts);
- :class:`~hypergraphdb_tpu.plan.planner.QueryPlanner` /
  :class:`~hypergraphdb_tpu.plan.planner.PlanChoice` — candidate
  enumeration + costed lane choice for a mixed ``And(...)``;
- :class:`~hypergraphdb_tpu.plan.feedback.PlanFeedback` — the bounded
  per-shape est-vs-actual drift digest feeding corrections back into
  costing.

Wire a planner into a runtime with ``ServeRuntime.attach_planner`` and
submit through ``ServeRuntime.submit_planned``; standalone use (offline
EXPLAIN, tests) needs only a graph.
"""

from .feedback import PlanFeedback
from .planner import (PlanCandidate, PlanChoice, PlannedResult, QueryPlanner,
                      SHAPE_LANES)
from .stats import CardinalityEstimator, DegreeStats, Estimate

__all__ = [
    "CardinalityEstimator",
    "DegreeStats",
    "Estimate",
    "PlanCandidate",
    "PlanChoice",
    "PlanFeedback",
    "PlannedResult",
    "QueryPlanner",
    "SHAPE_LANES",
]
