"""hgplan cardinality estimation: exact-for-free stats off the pinned base.

The reference's cost-based compiler prices conditions with per-index
``HGIndexStats`` counters kept transactionally beside the data
(``query/HGQuery.java``); the TPU-native twin reads everything it needs
off columns the serve tier ALREADY maintains — no new bookkeeping, no
device work, refreshed once per compaction epoch:

- **range windows** — per-kind ``(value_rank, value_rank2)`` columns of
  the base snapshot, sorted once per epoch; a range predicate's window
  width under 128-bit searchsorted IS its cardinality (exact whenever
  the column and bounds are rank-exact: always for fixed-width kinds,
  and for str/bytes whose keys fit the 16-byte rank prefix NUL-free —
  the hgindex tie-break contract, ``storage/value_index``);
- **degree stats** — the incidence CSR's row widths: per-type mean /
  max / hub count, plus the exact incidence-set size of any single atom
  (``Incident(a)``'s cardinality at the base, no estimate involved);
- **type counts** — ``by_type``-equivalent bincounts over ``type_of``.

All reads are host numpy over the IMMUTABLE base snapshot: estimates
describe the compacted graph; the memtable residual is bounded by the
serve tier's ``max_lag_edges`` discipline and compensated downstream by
the planner's feedback corrections, never here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from hypergraphdb_tpu.utils.ordered_bytes import rank64


@dataclass(frozen=True)
class DegreeStats:
    """Incidence-degree summary of one type (or the whole graph):
    ``hubs`` counts atoms whose degree reaches ``hub_threshold`` — the
    same degree-skew signal the join engine's hub split keys on."""

    n: int
    mean: float
    max: int
    hubs: int
    hub_threshold: int


@dataclass(frozen=True)
class Estimate:
    """One cardinality estimate plus its honesty bit: ``exact`` means
    the number is a count, not a model — the planner's costing treats
    exact estimates as immune to feedback correction."""

    rows: float
    exact: bool


class CardinalityEstimator:
    """Epoch-cached, device-free cardinality reads for one graph.

    Pass the serve tier's ``SnapshotManager`` (``ServeRuntime.mgr``) so
    estimates track compaction epochs; standalone (tests, offline
    EXPLAIN) the estimator packs its own base per graph mutation
    counter. Every public method is O(log N) or O(types) against arrays
    built once per epoch.
    """

    def __init__(self, graph, mgr=None, hub_factor: float = 8.0):
        self.graph = graph
        self.mgr = mgr
        self.hub_factor = float(hub_factor)
        self._epoch: Optional[int] = None
        self._snap = None
        self._kind_cols: dict = {}       # kind -> (r1 sorted, r2 sorted)
        self._kind_ambig: dict = {}      # kind -> column has ambiguous keys
        self._type_counts: dict = {}
        self._degrees: Optional[np.ndarray] = None
        self._live: Optional[np.ndarray] = None
        self._deg_stats: dict = {}

    # -- epoch management ----------------------------------------------------
    def _current_epoch(self) -> int:
        if self.mgr is not None:
            return int(self.mgr.compactions)
        return int(getattr(self.graph, "_mutations", 0))

    def _base(self):
        if self.mgr is not None:
            return self.mgr.base
        from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

        return CSRSnapshot.pack(self.graph)

    def refresh(self) -> int:
        """Re-read the base snapshot if the epoch moved; returns the
        epoch the estimator now describes. Cheap no-op when current."""
        epoch = self._current_epoch()
        if epoch == self._epoch and self._snap is not None:
            return epoch
        snap = self._base()
        N = snap.num_atoms
        live = snap.type_of[:N] >= 0
        self._snap = snap
        self._live = live
        self._epoch = epoch
        self._kind_cols = {}
        self._kind_ambig = {}
        self._deg_stats = {}
        degrees = (snap.inc_offsets[1:N + 1]
                   - snap.inc_offsets[:N]).astype(np.int64)
        self._degrees = degrees
        th = snap.type_of[:N][live]
        if len(th):
            uniq, counts = np.unique(th, return_counts=True)
            self._type_counts = {int(t): int(c)
                                 for t, c in zip(uniq.tolist(),
                                                 counts.tolist())}
        else:
            self._type_counts = {}
        return epoch

    def _ensure(self):
        self.refresh()

    # -- simple exact reads --------------------------------------------------
    @property
    def epoch(self) -> Optional[int]:
        return self._epoch

    def n_atoms(self) -> int:
        """Live atoms at the base (exact)."""
        self._ensure()
        return int(self._live.sum())

    def type_count(self, type_handle: int) -> int:
        """Atoms of one type at the base (exact)."""
        self._ensure()
        return self._type_counts.get(int(type_handle), 0)

    def degree(self, h: int) -> int:
        """Incidence-set size of atom ``h`` at the base — EXACTLY
        ``Incident(h)``'s base cardinality (0 beyond the id space)."""
        self._ensure()
        if 0 <= int(h) < len(self._degrees):
            return int(self._degrees[int(h)])
        return 0

    def degree_stats(self, type_handle: Optional[int] = None) -> DegreeStats:
        """Mean / max / hub-count of incidence degrees, over one type's
        atoms or (``None``) all live atoms. The hub threshold is
        ``max(8, hub_factor × mean)`` — relative, so uniform graphs
        report zero hubs whatever their density."""
        self._ensure()
        key = None if type_handle is None else int(type_handle)
        cached = self._deg_stats.get(key)
        if cached is not None:
            return cached
        snap = self._snap
        N = snap.num_atoms
        if key is None:
            sel = self._live
        else:
            sel = snap.type_of[:N] == np.int32(key)
        deg = self._degrees[sel]
        if len(deg) == 0:
            out = DegreeStats(0, 0.0, 0, 0, 8)
        else:
            mean = float(deg.mean())
            thr = max(8, int(np.ceil(mean * self.hub_factor)))
            out = DegreeStats(len(deg), mean, int(deg.max()),
                              int((deg >= thr).sum()), thr)
        self._deg_stats[key] = out
        return out

    # -- range windows -------------------------------------------------------
    def _kind_column(self, kind: int):
        """The kind's (rank, rank2) pair sorted lexicographically, built
        once per epoch — the estimator's own twin of the device column,
        minus the upload."""
        kind = int(kind)
        col = self._kind_cols.get(kind)
        if col is not None:
            return col
        snap = self._snap
        N = snap.num_atoms
        sel = (snap.value_kind[:N] == np.uint8(kind)) & self._live
        r1 = snap.value_rank[:N][sel].astype(np.uint64)
        r2_col = getattr(snap, "value_rank2", None)
        if r2_col is not None and len(r2_col) >= N:
            r2 = r2_col[:N][sel].astype(np.uint64)
        else:
            r2 = np.zeros(len(r1), dtype=np.uint64)
        order = np.lexsort((r2, r1))
        col = (r1[order], r2[order])
        self._kind_cols[kind] = col
        ambig_col = getattr(snap, "value_ambig", None)
        from hypergraphdb_tpu.storage.value_index import FIXED_WIDTH_KINDS

        if kind in FIXED_WIDTH_KINDS:
            self._kind_ambig[kind] = False
        elif ambig_col is not None and len(ambig_col) >= N:
            self._kind_ambig[kind] = bool(ambig_col[:N][sel].any())
        else:
            self._kind_ambig[kind] = bool(len(r1))  # no rank2: be honest
        return col

    @staticmethod
    def _searchsorted128(r1: np.ndarray, r2: np.ndarray, q1: int, q2: int,
                         side: str) -> int:
        """Host 128-bit lexicographic searchsorted: position of the
        (q1, q2) bound in the sorted (r1, r2) pair — numpy binary search
        on the high word, then on the low word inside the tie run."""
        lo = int(np.searchsorted(r1, np.uint64(q1), side="left"))
        hi = int(np.searchsorted(r1, np.uint64(q1), side="right"))
        return lo + int(np.searchsorted(r2[lo:hi], np.uint64(q2), side=side))

    def _value_rank128(self, value) -> tuple:
        """(kind, rank, rank2, clean) of one query value via the
        typesystem — the bridge's key derivation, estimator edition.
        ``clean`` means the payload fits the 16-byte rank NUL-free, so
        128-bit comparisons against a clean column are exact."""
        vt = self.graph.typesystem.infer(value)
        if vt is None:
            raise ValueError(f"value {value!r} has no registered type")
        key = vt.to_key(value)
        payload = key[1:]
        clean = len(payload) <= 16 and b"\x00" not in payload[:16]
        return key[0], rank64(payload), rank64(payload[8:16]), clean

    def range_window(self, lo=None, hi=None, lo_op: str = "gte",
                     hi_op: str = "lte") -> Estimate:
        """Width of the ``[lo, hi]`` window in the bounds' kind column —
        the range predicate's cardinality. Exact when both the column
        and the bounds are rank-exact (fixed-width kinds always;
        variable-width under the 16-byte NUL-free tie-break contract);
        otherwise the width is still the device window's honest size,
        flagged ``exact=False``."""
        self._ensure()
        if lo is None and hi is None:
            raise ValueError("range_window needs at least one bound")
        from hypergraphdb_tpu.storage.value_index import FIXED_WIDTH_KINDS

        kind = None
        bounds_clean = True
        lo_r = hi_r = None
        if lo is not None:
            kind, r1, r2, clean = self._value_rank128(lo)
            bounds_clean &= clean
            lo_r = (r1, r2)
        if hi is not None:
            k2, r1, r2, clean = self._value_rank128(hi)
            if kind is not None and k2 != kind:
                raise ValueError("mixed-kind range bounds")
            kind = k2
            bounds_clean &= clean
            hi_r = (r1, r2)
        c1, c2 = self._kind_column(kind)
        if lo_r is None:
            lo_idx = 0
        else:
            side = "right" if lo_op == "gt" else "left"
            lo_idx = self._searchsorted128(c1, c2, lo_r[0], lo_r[1], side)
        if hi_r is None:
            hi_idx = len(c1)
        else:
            side = "right" if hi_op == "lte" else "left"
            hi_idx = self._searchsorted128(c1, c2, hi_r[0], hi_r[1], side)
        width = max(0, hi_idx - lo_idx)
        exact = (kind in FIXED_WIDTH_KINDS
                 or (bounds_clean and not self._kind_ambig[int(kind)]))
        return Estimate(float(width), exact)

    # -- composite estimates -------------------------------------------------
    def incident_count(self, target: int) -> Estimate:
        """``Incident(target)``'s base cardinality — the incidence-set
        size, exact by construction."""
        return Estimate(float(self.degree(target)), True)

    def coincident_count(self, other: int) -> Estimate:
        """``CoIncident(other)`` estimate: atoms sharing a link with
        ``other`` ≈ Σ (arity − 1) over other's incident links — an
        upper bound that overcounts only multi-link co-neighbours, so
        its relative error is bounded by the co-neighbour multiplicity
        (small on everything but pathological multigraphs)."""
        self._ensure()
        snap = self._snap
        h = int(other)
        if not (0 <= h < snap.num_atoms):
            return Estimate(0.0, True)
        s, e = int(snap.inc_offsets[h]), int(snap.inc_offsets[h + 1])
        links = snap.inc_links[s:e]
        if len(links) == 0:
            return Estimate(0.0, True)
        est = float(np.maximum(
            snap.arity[links].astype(np.int64) - 1, 0).sum())
        return Estimate(est, False)

    def bfs_frontier(self, seed: int, hops: int) -> Estimate:
        """Reachable-set estimate for a ``hops``-bounded BFS from
        ``seed``: seed degree compounded by the mean degree per extra
        hop, capped by the live-atom count — a growth model, never
        exact (the planner treats it as the coarsest input it has)."""
        self._ensure()
        d0 = float(self.degree(seed))
        if hops <= 0 or d0 == 0.0:
            return Estimate(0.0, False)
        mean = max(1.0, self.degree_stats().mean)
        est = d0 * (mean ** max(0, int(hops) - 1))
        return Estimate(min(est, float(self.n_atoms())), False)
