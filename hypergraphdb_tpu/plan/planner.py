"""hgplan planner: candidate enumeration + costed lane choice for And(...).

The serve tier has four fast lanes (bfs / pattern / join / range) and a
bridge that translates a condition into AT MOST ONE of them — a mixed
``And(...)`` outside the bridge's shapes is flatly Unservable, and even
inside them the bridge never asks which lane is CHEAPEST. This module is
the missing chooser, the TPU-native twin of the reference's cost-based
condition compiler: classify the conjunction's clauses, enumerate every
lane that can carry a subset of them (the rest riding along as a host
residual filter), price each candidate with

    cost = lane latency prior  (PERF_BASELINE p50, bench-seeded)
         + corrected est_rows × per-row gather cost
         + corrected est_rows × residual clauses × per-row filter cost
         + overflow penalty    (est beyond the lane's top-k / result cap
                                forces the exact host re-serve, so the
                                candidate must carry that cost honestly)

and emit a typed :class:`PlanChoice` the runtime dispatches
(``ServeRuntime.submit_planned``). Estimates come from
``plan/stats.CardinalityEstimator`` (window widths, degrees, type
counts); NON-exact estimates are multiplied by the per-shape feedback
correction (``plan/feedback.PlanFeedback``) before costing.

Candidate shapes (``PlanChoice.shape``):

- ``range_first`` — push 1-2 same-kind value bounds (plus ≤1 type, ≤1
  incident anchor) into the range lane, host-filter the rest;
- ``pattern``    — push the incident anchors (+type) into the pattern
  intersection lane, host-filter values and the rest;
- ``join``       — hand the adjacency conjunction to the join executor
  (the only lane that can carry ``CoIncident``);
- ``bfs``        — anchor the traversal at the RAREST seed among the
  BFS clauses, everything else residual;
- ``host``       — the exact brute-force scan, always enumerable, so
  the planner can never be WORSE than having no planner: a lane only
  wins by beating it.

Safety valve: a learned correction may re-rank candidates, but if the
corrected winner differs from the uncorrected one AND the perf sentinel
currently flags the corrected winner's lane as breaching its baseline,
the planner keeps the uncorrected choice and counts a guard veto
(``plan.guard_vetoes``) — telemetry never gets to steer traffic INTO a
lane that is already on fire.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from hypergraphdb_tpu.query import bridge, conditions as c
from hypergraphdb_tpu.serve.types import Unservable

from .feedback import PlanFeedback
from .stats import CardinalityEstimator, Estimate

#: shape -> serve lane kind (host has no lane; it is priced from N)
SHAPE_LANES: Dict[str, str] = {
    "range_first": "range",
    "pattern": "pattern",
    "join": "join",
    "bfs": "bfs",
}

#: fallback per-lane latency priors (seconds) when PERF_BASELINE has no
#: entry for the lane — deliberately coarse, bench-seeded values win
DEFAULT_LANE_PRIOR_S: Dict[str, float] = {
    "range": 2e-3,
    "pattern": 2e-3,
    "join": 4e-3,
    "bfs": 3e-3,
}

#: per-row costs (seconds): device-window gather / host residual filter
#: per clause / host brute-force scan per atom per clause, plus the flat
#: host setup. Constants stay fixed; the feedback loop corrects the ROW
#: estimates they multiply, which is where the real variance lives.
GATHER_S = 2e-7
FILTER_S = 2e-6
HOST_SCAN_S = 2e-6
HOST_BASE_S = 5e-4


@dataclass(frozen=True)
class PlanCandidate:
    """One enumerable strategy: the lane request carrying the pushed
    clauses (None = pure host), the residual clauses the runtime
    filters with ``.satisfies``, and the raw (uncorrected) estimate of
    rows the lane returns BEFORE the residual."""

    shape: str
    request: object
    residual: Tuple[c.HGQueryCondition, ...]
    est: Estimate


@dataclass(frozen=True)
class PlanChoice:
    """The planner's verdict for one condition — everything the runtime
    needs to dispatch, and everything EXPLAIN needs to record."""

    shape: str
    request: object
    residual: Tuple[c.HGQueryCondition, ...]
    condition: c.HGQueryCondition
    est_rows: float
    exact_est: bool
    cost: float
    correction: float
    guard_vetoed: bool
    epoch: Optional[int]
    alternatives: Tuple[Dict[str, float], ...] = field(default=())

    def explain(self) -> Dict[str, object]:
        """The ``plan`` sub-dict of an EXPLAIN record (actual_rows is
        stamped by the runtime once the result lands)."""
        return {
            "shape": self.shape,
            "est_rows": round(self.est_rows, 3),
            "exact_est": self.exact_est,
            "cost": round(self.cost, 9),
            "correction": round(self.correction, 6),
            "guard_vetoed": self.guard_vetoed,
            "epoch": self.epoch,
            "alternatives": list(self.alternatives),
        }


@dataclass(frozen=True, eq=False)
class PlannedResult:
    """A planned request's answer: the lane (or host) rows AFTER the
    residual filter, ascending atom ids. ``lane_kind``/``served_by``
    attribute the execution (``host``/``host`` for the brute-force
    shape); ``plan`` is the EXPLAIN sub-dict with ``actual_rows``
    stamped (the LANE's pre-residual row count — what the feedback
    digest compares against ``est_rows``)."""

    kind: str               # always "planned"
    count: int
    matches: tuple          # int ascending
    truncated: bool
    epoch: Optional[int]
    lane_kind: str
    served_by: str
    plan: Dict[str, object]


class QueryPlanner:
    """Cost-based chooser over the serve lanes for one graph.

    ``baseline`` is the parsed ``PERF_BASELINE.json`` record (or its
    ``lanes`` mapping); ``lane_degraded`` is a predicate over lane
    kinds, normally bound to the perf sentinel's violating set by
    ``ServeRuntime.attach_planner``. ``stats`` (a ``ServeStats``) is
    also bound there; standalone planners simply skip the metrics.
    """

    def __init__(self, graph, estimator: Optional[CardinalityEstimator] = None,
                 feedback: Optional[PlanFeedback] = None,
                 baseline: Optional[dict] = None,
                 stats=None,
                 lane_degraded: Optional[Callable[[str], bool]] = None,
                 default_max_hops: int = 2, top_r: int = 8):
        self.graph = graph
        self.estimator = estimator or CardinalityEstimator(graph)
        self.feedback = feedback or PlanFeedback()
        self.stats = stats
        self.lane_degraded = lane_degraded
        self.default_max_hops = int(default_max_hops)
        self.top_r = int(top_r)
        self._priors = dict(DEFAULT_LANE_PRIOR_S)
        lanes = None
        if isinstance(baseline, dict):
            lanes = baseline.get("lanes", baseline)
        if isinstance(lanes, dict):
            for kind in SHAPE_LANES.values():
                lane = lanes.get(kind)
                if isinstance(lane, dict):
                    p50 = lane.get("p50_s")
                    if isinstance(p50, (int, float)) and p50 > 0:
                        self._priors[kind] = float(p50)
        self._guard_vetoes = 0

    @classmethod
    def from_committed_baseline(cls, graph, path: Optional[str] = None,
                                **kw) -> "QueryPlanner":
        """A planner priced from the committed ``PERF_BASELINE.json`` —
        the SAME record ``bench.py --seed-baseline`` writes and the perf
        sentinel gates on, so the join lane's prior is the c11 open-loop
        p50, not a hardcoded guess. ``path`` defaults to
        ``obs.perf.default_baseline_path()`` (repo root /
        ``HG_PERF_BASELINE``); a missing or unreadable file degrades to
        the coarse ``DEFAULT_LANE_PRIOR_S`` table rather than failing —
        a fresh checkout without a seeded baseline still plans."""
        from hypergraphdb_tpu.obs.perf import (
            default_baseline_path,
            load_baseline,
        )

        baseline = None
        try:
            baseline = load_baseline(path or default_baseline_path())
        except (OSError, ValueError):
            pass
        return cls(graph, baseline=baseline, **kw)

    # -- clause classification -----------------------------------------------
    @staticmethod
    def _clauses(condition: c.HGQueryCondition) -> Tuple[c.HGQueryCondition, ...]:
        if isinstance(condition, c.And):
            return tuple(condition.clauses)
        return (condition,)

    def _type_handle(self, cl: c.AtomType) -> Optional[int]:
        try:
            return int(cl.type_handle(self.graph))
        except Exception:
            return None

    # -- per-clause estimates ------------------------------------------------
    def _clause_estimate(self, cl) -> Optional[Estimate]:
        """Base cardinality of ONE clause's match set, or None when the
        clause has no estimator (residual-only vocabulary) — the
        intersection estimate simply ignores it (a sound upper bound)."""
        est = self.estimator
        try:
            if isinstance(cl, c.AtomValue):
                if cl.op == "eq":
                    return est.range_window(lo=cl.value, hi=cl.value)
                lo = cl.value if cl.op in ("gt", "gte") else None
                hi = cl.value if cl.op in ("lt", "lte") else None
                return est.range_window(lo=lo, hi=hi,
                                        lo_op=cl.op if lo is not None else "gte",
                                        hi_op=cl.op if hi is not None else "lte")
            if isinstance(cl, c.TypedValue):
                return self._clause_estimate(c.AtomValue(cl.value, cl.op))
            if isinstance(cl, c.AtomType):
                th = self._type_handle(cl)
                if th is None:
                    return None
                return Estimate(float(est.type_count(th)), True)
            if isinstance(cl, c.Incident):
                return est.incident_count(int(cl.target))
            if isinstance(cl, c.TypedIncident):
                return est.incident_count(int(cl.target))
            if isinstance(cl, c.CoIncident):
                return est.coincident_count(int(cl.other))
            if isinstance(cl, c.BFS):
                hops = cl.max_distance
                if hops is None:
                    hops = self.default_max_hops
                return est.bfs_frontier(int(cl.start), int(hops))
        except (ValueError, Unservable):
            return None
        return None

    def _intersection_estimate(self, clauses) -> Estimate:
        """Upper-bound estimate of the conjunction: the MINIMUM of the
        clauses' individual cardinalities (an intersection can never
        exceed its smallest member). Exact only when the binding
        minimum clause is exact AND it is the only clause."""
        best: Optional[Estimate] = None
        n = 0
        for cl in clauses:
            e = self._clause_estimate(cl)
            if e is None:
                continue
            n += 1
            if best is None or e.rows < best.rows:
                best = e
        if best is None:
            return Estimate(float(self.estimator.n_atoms()), False)
        return Estimate(best.rows, best.exact and n == 1 and len(clauses) == 1)

    # -- candidate enumeration -----------------------------------------------
    def _candidates(self, condition) -> List[PlanCandidate]:
        clauses = self._clauses(condition)
        out: List[PlanCandidate] = []

        # host: the exact scan, always available
        out.append(PlanCandidate(
            "host", None, clauses,
            Estimate(self._intersection_estimate(clauses).rows, False)))

        # range_first: 1-2 same-kind value bounds (+ ≤1 type, ≤1 anchor)
        rng = self._range_candidate(clauses)
        if rng is not None:
            out.append(rng)

        # pattern: incident anchors (+ one consistent type)
        pat = self._pattern_candidate(clauses)
        if pat is not None:
            out.append(pat)

        # join: the adjacency conjunction (needs a CoIncident — without
        # one the join lane degenerates to the pattern intersection and
        # only adds executor overhead)
        jn = self._join_candidate(condition, clauses)
        if jn is not None:
            out.append(jn)

        # bfs: anchor the traversal at the rarest-degree seed
        bf = self._bfs_candidate(clauses)
        if bf is not None:
            out.append(bf)
        return out

    def _range_candidate(self, clauses) -> Optional[PlanCandidate]:
        vals = [cl for cl in clauses if isinstance(cl, c.AtomValue)]
        if not vals or len(vals) > 2:
            return None
        types = [cl for cl in clauses if isinstance(cl, c.AtomType)]
        incs = [cl for cl in clauses if isinstance(cl, c.Incident)]
        pushed: List[c.HGQueryCondition] = list(vals)
        type_h = None
        if len(types) == 1:
            type_h = self._type_handle(types[0])
            if type_h is not None:
                pushed.append(types[0])
        anchor = None
        if incs:
            # push the RAREST anchor: the device filter then prunes the
            # window hardest, the denser anchors stay residual
            rare = min(incs, key=lambda i: self.estimator.degree(int(i.target)))
            anchor = int(rare.target)
            pushed.append(rare)
        try:
            sub = c.And(*pushed) if len(pushed) > 1 else pushed[0]
            req = bridge.to_request(self.graph, sub,
                                    default_max_hops=self.default_max_hops)
        except Unservable:
            return None
        residual = tuple(cl for cl in clauses
                         if not any(cl is p for p in pushed))
        # the window width prices the lane; pushed type/anchor only
        # shrink what comes back, so the width stays the honest driver.
        # Both bounds estimate as ONE window (the exact-for-free claim:
        # its searchsorted width IS the conjunction's cardinality), not
        # as the min of two half-open windows
        win = self._window_estimate(vals)
        return PlanCandidate("range_first", req, residual, win)

    def _window_estimate(self, vals) -> Estimate:
        """The COMBINED window of 1-2 value bounds: eq collapses to
        [v, v]; a lower (gt/gte) and an upper (lt/lte) bound close one
        window. Falls back to the per-clause min only for same-direction
        pairs (which the bridge rejects anyway)."""
        if len(vals) == 1:
            e = self._clause_estimate(vals[0])
            return e if e is not None else Estimate(
                float(self.estimator.n_atoms()), False)
        lows = [v for v in vals if v.op in ("gt", "gte")]
        highs = [v for v in vals if v.op in ("lt", "lte")]
        if len(lows) == 1 and len(highs) == 1:
            try:
                return self.estimator.range_window(
                    lo=lows[0].value, hi=highs[0].value,
                    lo_op=lows[0].op, hi_op=highs[0].op)
            except (ValueError, Unservable):
                pass
        return self._intersection_estimate(vals)

    def _pattern_candidate(self, clauses) -> Optional[PlanCandidate]:
        pushed = [cl for cl in clauses
                  if isinstance(cl, (c.Incident, c.TypedIncident, c.AtomType))]
        if not any(isinstance(cl, (c.Incident, c.TypedIncident))
                   for cl in pushed):
            return None
        try:
            sub = c.And(*pushed) if len(pushed) > 1 else pushed[0]
            req = bridge.to_request(self.graph, sub,
                                    default_max_hops=self.default_max_hops)
        except Unservable:
            return None
        residual = tuple(cl for cl in clauses
                         if not any(cl is p for p in pushed))
        return PlanCandidate("pattern", req, residual,
                             self._intersection_estimate(pushed))

    def _join_candidate(self, condition, clauses) -> Optional[PlanCandidate]:
        if not any(isinstance(cl, c.CoIncident) for cl in clauses):
            return None
        pushed = [cl for cl in clauses
                  if isinstance(cl, (c.CoIncident, c.Incident,
                                     c.TypedIncident, c.AtomType, c.Link))]
        try:
            sub = c.And(*pushed) if len(pushed) > 1 else pushed[0]
            req = bridge.to_join_request(self.graph, {"x": sub},
                                         distinct=False)
        except Unservable:
            return None
        residual = tuple(cl for cl in clauses
                         if not any(cl is p for p in pushed))
        return PlanCandidate("join", req, residual,
                             self._intersection_estimate(pushed))

    def _bfs_candidate(self, clauses) -> Optional[PlanCandidate]:
        bfss = [cl for cl in clauses if isinstance(cl, c.BFS)]
        if not bfss:
            return None
        # the rarer end: smallest seed degree compounds to the smallest
        # frontier, every other clause (including other BFS legs)
        # filters the smaller set on the host
        seed_cl = min(bfss,
                      key=lambda b: self.estimator.degree(int(b.start)))
        try:
            req = bridge.to_request(self.graph, seed_cl,
                                    default_max_hops=self.default_max_hops)
        except Unservable:
            return None
        residual = tuple(cl for cl in clauses if cl is not seed_cl)
        return PlanCandidate("bfs", req, residual,
                             self._clause_estimate(seed_cl)
                             or Estimate(float(self.estimator.n_atoms()),
                                         False))

    # -- costing -------------------------------------------------------------
    def _cost(self, cand: PlanCandidate, rows: float) -> float:
        if cand.shape == "host":
            n = float(self.estimator.n_atoms())
            return HOST_BASE_S + n * max(1, len(cand.residual)) * HOST_SCAN_S
        cost = self._priors[SHAPE_LANES[cand.shape]]
        cost += rows * GATHER_S
        cost += rows * len(cand.residual) * FILTER_S
        if cand.shape == "range_first" and rows > self.top_r:
            # a window wider than the lane's top-k truncates on device
            # and the runtime must re-serve exactly on the host — price
            # the candidate as if it were the scan it will become
            n = float(self.estimator.n_atoms())
            cost += HOST_BASE_S + n * HOST_SCAN_S
        return cost

    def _corrected_rows(self, cand: PlanCandidate) -> Tuple[float, float]:
        """(rows for costing, correction applied). Exact estimates are
        counts — correcting them could only make them wrong."""
        if cand.est.exact or self.feedback is None:
            return cand.est.rows, 1.0
        corr = self.feedback.correction(cand.shape)
        return cand.est.rows * corr, corr

    # -- the verdict ---------------------------------------------------------
    def shapes_for(self, condition) -> List[str]:
        """The enumerable shapes for ``condition`` — the differential
        suite iterates this to force-execute every candidate."""
        return [cand.shape for cand in self._candidates(condition)]

    def plan(self, condition, force_shape: Optional[str] = None) -> PlanChoice:
        """Choose the cheapest candidate for ``condition``.

        ``force_shape`` bypasses costing and picks the named candidate
        (ValueError if it is not enumerable for this condition) — the
        hook the differential suite and the ≥2×-vs-worst smoke use."""
        self.estimator.refresh()
        cands = self._candidates(condition)
        scored = []
        for cand in cands:
            rows, corr = self._corrected_rows(cand)
            scored.append((cand, rows, corr,
                           self._cost(cand, rows),          # corrected
                           self._cost(cand, cand.est.rows)))  # raw

        if force_shape is not None:
            for cand, rows, corr, cost, _raw in scored:
                if cand.shape == force_shape:
                    return self._choice(condition, cand, rows, corr, cost,
                                        scored, guard_vetoed=False)
            raise ValueError(
                f"shape {force_shape!r} is not a candidate for this "
                f"condition (have {[s[0].shape for s in scored]})")

        best = min(scored, key=lambda s: s[3])
        best_raw = min(scored, key=lambda s: s[4])
        guard = False
        if best[0].shape != best_raw[0].shape and self.lane_degraded is not None:
            lane = SHAPE_LANES.get(best[0].shape)
            if lane is not None and self.lane_degraded(lane):
                # the learned correction steered the argmin onto a lane
                # the perf sentinel says is breaching its baseline: veto
                best = best_raw
                guard = True
                self._guard_vetoes += 1
                if self.stats is not None:
                    self.stats.record_plan_guard_veto()
        cand, rows, corr, cost, _ = best
        return self._choice(condition, cand, rows, corr, cost, scored,
                            guard_vetoed=guard)

    def _choice(self, condition, cand: PlanCandidate, rows: float,
                corr: float, cost: float, scored,
                guard_vetoed: bool) -> PlanChoice:
        alts = tuple(
            {"shape": s[0].shape, "cost": round(s[3], 9),
             "est_rows": round(s[1], 3)}
            for s in sorted(scored, key=lambda s: s[3])
        )
        # the original full condition travels on the choice so the
        # runtime's exactness escape hatch (truncated lane results)
        # can re-serve it brute-force without re-deriving it
        choice = PlanChoice(
            shape=cand.shape, request=cand.request, residual=cand.residual,
            condition=condition,
            est_rows=rows, exact_est=cand.est.exact, cost=cost,
            correction=corr, guard_vetoed=guard_vetoed,
            epoch=self.estimator.epoch, alternatives=alts)
        if self.stats is not None:
            self.stats.record_plan_request(cand.shape, rows, cost)
        return choice

    # -- feedback + observability --------------------------------------------
    def observe(self, choice: PlanChoice, actual_rows: int) -> None:
        """Close the loop for one executed choice: feed est-vs-actual
        into the drift digest (non-exact estimates only — an exact
        window width matching its actual teaches nothing) and the
        ``plan.*`` metrics."""
        if self.stats is not None:
            self.stats.record_plan_actual(choice.est_rows, actual_rows)
        if choice.exact_est or self.feedback is None:
            return
        stored = self.feedback.observe(choice.shape, choice.est_rows,
                                       float(actual_rows))
        if stored is not None and self.stats is not None:
            self.stats.record_plan_feedback_update(
                clamped=(stored != actual_rows / choice.est_rows))

    def health_summary(self) -> Dict[str, object]:
        """The ``plan`` payload of ``/healthz`` and ``/fleet/plan``:
        correction state + guard-veto count, JSON-safe."""
        fb = self.feedback.snapshot() if self.feedback is not None else {}
        return {
            "enabled": bool(fb.get("enabled", False)),
            "corrections_active": (self.feedback.corrections_active()
                                   if self.feedback is not None else 0),
            "guard_vetoes": self._guard_vetoes,
            "shapes": fb.get("shapes", {}),
            "updates": fb.get("updates", 0),
        }
