"""hgplan feedback: bounded per-plan-shape drift digest over est-vs-actual.

Every planned request's EXPLAIN record carries ``plan.est_rows`` and
``plan.actual_rows``; this module is the loop that closes them. Per plan
shape (the coarse strategy key — ``range_first``, ``join``, ``bfs``,
``pattern``, ``host``) it keeps a bounded window of ``actual / est``
ratios and serves their clamped median as a multiplicative correction
the planner applies to NON-exact estimates before costing. Medians over
clamped windows make the digest robust to the two failure modes a
naive mean would amplify: a single pathological query (one huge ratio)
and systematic zero-actuals (est correction driven to the floor).

Discipline, mirroring every other adaptive surface in the repo
(admission controller, breaker ladder, subscription tier):

- **bounded** — at most ``max_shapes`` shapes × ``window`` samples;
  overflow evicts the least-recently-updated shape, never grows;
- **gated** — ``enabled=False`` (or fewer than ``min_samples``
  observations) serves the identity correction, so the planner without
  telemetry is exactly the planner with the loop switched off;
- **observable** — :meth:`snapshot` feeds the ``/fleet/plan`` surface
  and the ``plan.feedback.*`` metrics; nothing is learned silently.

The sentinel guard lives in the PLANNER, not here: a correction that
would flip the argmin onto a lane the perf sentinel currently flags is
vetoed at costing time (``plan.guard_vetoes``) — the digest still
learns, it just doesn't get to steer into a known-degraded lane.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple


class PlanFeedback:
    """Bounded per-shape multiplicative-correction store.

    ``clamp`` bounds each STORED ratio (and therefore the served
    median), keeping one absurd observation from ever dominating;
    ``min_samples`` is the warm-up gate below which the correction is
    identity.
    """

    def __init__(self, max_shapes: int = 64, window: int = 128,
                 clamp: Tuple[float, float] = (0.25, 4.0),
                 min_samples: int = 8, enabled: bool = True):
        if max_shapes <= 0 or window <= 0:
            raise ValueError("max_shapes and window must be positive")
        lo, hi = float(clamp[0]), float(clamp[1])
        if not (0.0 < lo <= 1.0 <= hi):
            raise ValueError("clamp must bracket 1.0 with a positive floor")
        self.max_shapes = int(max_shapes)
        self.window = int(window)
        self.clamp = (lo, hi)
        self.min_samples = int(min_samples)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # shape -> deque of clamped actual/est ratios; OrderedDict as an
        # LRU so eviction drops the staletest shape, not an arbitrary one.
        self._ratios: "OrderedDict[str, deque]" = OrderedDict()
        self._updates = 0
        self._clamped = 0

    # -- learning ------------------------------------------------------------
    def observe(self, shape: str, est_rows: float,
                actual_rows: float) -> Optional[float]:
        """Record one est-vs-actual pair for ``shape``; returns the
        clamped ratio stored, or None when the pair is unusable (est
        non-finite or ≤ 0 gives the ratio no denominator — a zero
        estimate that materialized rows is a MODEL bug the oracle tests
        catch, not a scale error a multiplier can fix)."""
        try:
            est = float(est_rows)
            actual = float(actual_rows)
        except (TypeError, ValueError):
            return None
        if not (est > 0.0) or actual < 0.0 or est != est or actual != actual:
            return None
        lo, hi = self.clamp
        raw = actual / est
        ratio = min(hi, max(lo, raw))
        with self._lock:
            dq = self._ratios.get(shape)
            if dq is None:
                while len(self._ratios) >= self.max_shapes:
                    self._ratios.popitem(last=False)
                dq = deque(maxlen=self.window)
                self._ratios[shape] = dq
            else:
                self._ratios.move_to_end(shape)
            dq.append(ratio)
            self._updates += 1
            if ratio != raw:
                self._clamped += 1
        return ratio

    # -- serving -------------------------------------------------------------
    @staticmethod
    def _median(values) -> float:
        ordered = sorted(values)
        n = len(ordered)
        mid = n // 2
        if n % 2:
            return ordered[mid]
        return 0.5 * (ordered[mid - 1] + ordered[mid])

    def correction(self, shape: str) -> float:
        """The multiplicative correction for ``shape``: clamped median
        of its ratio window, or 1.0 while disabled / warming up."""
        if not self.enabled:
            return 1.0
        with self._lock:
            dq = self._ratios.get(shape)
            if dq is None or len(dq) < self.min_samples:
                return 1.0
            return self._median(dq)

    def corrections_active(self) -> int:
        """Shapes currently past warm-up and serving a non-identity
        correction — surfaced through the planner's health section and
        the fleet ``corrections_active`` rollup (not a registry name)."""
        if not self.enabled:
            return 0
        with self._lock:
            return sum(1 for dq in self._ratios.values()
                       if len(dq) >= self.min_samples
                       and self._median(dq) != 1.0)

    # -- observability -------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """JSON-safe state for ``/fleet/plan`` and tests: per-shape
        sample counts + served corrections, plus the update/clamp
        totals."""
        with self._lock:
            shapes = {
                shape: {
                    "samples": len(dq),
                    "correction": round(
                        self._median(dq), 6)
                    if self.enabled and len(dq) >= self.min_samples else 1.0,
                }
                for shape, dq in self._ratios.items()
            }
            return {
                "enabled": self.enabled,
                "shapes": shapes,
                "updates": self._updates,
                "clamped": self._clamped,
                "window": self.window,
                "min_samples": self.min_samples,
            }

    def reset(self) -> None:
        with self._lock:
            self._ratios.clear()
            self._updates = 0
            self._clamped = 0
