"""Atom utilities: refs, directed hyperedges, typed relations, subsumption.

Re-expression of the reference's ``atom/`` package (SURVEY §2.1 "Atom
utilities"):

- :class:`HGAtomRef` — a *value* referencing another atom with a mode
  (``atom/HGAtomRef.java:68-99``): **hard** refs pin the referent (it cannot
  be removed while referenced), **symbolic** refs may dangle, **floating**
  refs follow replacement (handles are stable here, so floating = symbolic
  that survives value replacement — the dense-handle design gives this for
  free).
- :class:`HGBergeLink` — a directed hyperedge with head/tail target sets
  (``atom/HGBergeLink.java:28``): stored as an ordinary link whose value
  records the head count, so the device plane sees a normal CSR row.
- :class:`HGRel` / :func:`define_rel_type` — named typed relations
  (``HGRel``/``HGRelType``).
- :func:`declare_subsumes` — the ``HGSubsumes`` link: persisted as a
  2-arity link AND registered with the type system's subsumption closure.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from hypergraphdb_tpu.core.errors import HGException
from hypergraphdb_tpu.core.handles import HGHandle

# ref modes (HGAtomRef.Mode)
HARD = "hard"
SYMBOLIC = "symbolic"
FLOATING = "floating"

#: index: referent handle (encoded) -> referring atoms holding a HARD ref
IDX_HARD_REFS = "hg.atomref.hard"


@dataclass(frozen=True)
class HGAtomRef:
    """A reference-to-atom value. Store it (possibly inside a record) and
    the kernel maintains the hard-ref pin index."""

    target: int
    mode: str = HARD

    def deref(self, graph):
        """Resolve to the referent's value; symbolic/floating refs return
        None when dangling, hard refs raise (they cannot dangle)."""
        if graph.contains(self.target):
            return graph.get(self.target)
        if self.mode == HARD:
            raise HGException(f"hard ref target {self.target} is missing")
        return None


def _hard_ref_key(target: int) -> bytes:
    from hypergraphdb_tpu.utils.ordered_bytes import encode_int

    return encode_int(int(target))


def scan_refs(value) -> list[HGAtomRef]:
    """Find HGAtomRef values inside an atom value (top-level, dataclass
    fields, list/tuple/dict containers — the projection surface)."""
    out: list[HGAtomRef] = []

    def visit(v, depth=0):
        if depth > 4:
            return
        if isinstance(v, HGAtomRef):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x, depth + 1)
        elif isinstance(v, dict):
            for x in v.values():
                visit(x, depth + 1)
        else:
            import dataclasses

            if dataclasses.is_dataclass(v) and not isinstance(v, type):
                for f in dataclasses.fields(v):
                    visit(getattr(v, f.name), depth + 1)

    visit(value)
    return out


def install_ref_maintenance(graph) -> None:
    """Wire hard-ref pinning into the graph's event stream: validates refs
    BEFORE the write (propose/replace-request phase, so an invalid atom is
    never persisted), adds/releases pin index entries after commit, and
    vetoes removal — including cascade removal — of pinned atoms. (The
    reference bakes this into ``AtomRefType``; here it is an opt-in kernel
    extension kept out of the hot path.)"""
    from hypergraphdb_tpu.core import events as ev

    idx = graph.store.get_index(IDX_HARD_REFS)
    #: handle -> hard-ref targets of the value being replaced (request phase
    #: stash, consumed by the post-commit replaced event)
    pending_replace: dict[int, list[int]] = {}

    def _hard_targets(value) -> list[int]:
        return [r.target for r in scan_refs(value) if r.mode == HARD]

    def _validate(g, value) -> None:
        for t in _hard_targets(value):
            if not g.contains(t):
                raise HGException(f"hard ref to missing atom {t}")

    def on_propose(g, event):
        _validate(g, event.atom)

    def on_added(g, event):
        for t in _hard_targets(event.atom):
            idx.add_entry(_hard_ref_key(t), int(event.handle))

    def on_replace_request(g, event):
        h = int(event.handle)
        _validate(g, event.atom)
        try:
            old = g.get(h)
            old = getattr(old, "value", old)
        except Exception:
            old = None
        pending_replace[h] = _hard_targets(old)

    def on_replaced(g, event):
        h = int(event.handle)
        for t in pending_replace.pop(h, ()):
            idx.remove_entry(_hard_ref_key(t), h)
        for t in _hard_targets(event.atom):
            idx.add_entry(_hard_ref_key(t), h)

    def on_remove_request(g, event):
        if len(idx.find(_hard_ref_key(int(event.handle)))):
            return ev.HGListener.CANCEL
        # dropping the referrer releases its pins
        try:
            val = g.get(int(event.handle))
            val = getattr(val, "value", val)
        except Exception:
            return None
        for t in _hard_targets(val):
            idx.remove_entry(_hard_ref_key(t), int(event.handle))
        return None

    graph.events.add_listener(ev.HGAtomProposeEvent, on_propose)
    graph.events.add_listener(ev.HGAtomAddedEvent, on_added)
    graph.events.add_listener(ev.HGAtomReplaceRequestEvent, on_replace_request)
    graph.events.add_listener(ev.HGAtomReplacedEvent, on_replaced)
    graph.events.add_listener(ev.HGAtomRemoveRequestEvent, on_remove_request)


# ------------------------------------------------------------------ Berge links


@dataclass(frozen=True)
class BergeValue:
    """Stored value of a Berge link: payload + head-count split."""

    head_count: int
    payload: object = None


class HGBergeLink:
    """Directed hyperedge view: targets[:head_count] are the head set,
    the rest the tail (``HGBergeLink.java:28``)."""

    def __init__(self, graph, handle: HGHandle):
        self.graph = graph
        self.handle = int(handle)

    @staticmethod
    def add(graph, head: Sequence[int], tail: Sequence[int],
            payload=None) -> "HGBergeLink":
        targets = [int(h) for h in head] + [int(t) for t in tail]
        h = graph.add_link(targets, value=BergeValue(len(head), payload))
        return HGBergeLink(graph, h)

    def _value(self) -> BergeValue:
        v = self.graph.get(self.handle)
        return v.value if hasattr(v, "value") else v

    @property
    def head(self) -> tuple[int, ...]:
        ts = self.graph.get_targets(self.handle)
        return tuple(int(t) for t in ts[: self._value().head_count])

    @property
    def tail(self) -> tuple[int, ...]:
        ts = self.graph.get_targets(self.handle)
        return tuple(int(t) for t in ts[self._value().head_count :])

    @property
    def payload(self):
        return self._value().payload


# ------------------------------------------------------------------ relations


@dataclass(frozen=True)
class RelTypeValue:
    """Value of a relation-type atom: name + arity (HGRelType)."""

    name: str
    arity: int


@dataclass(frozen=True)
class RelValue:
    """Value of a relation instance (HGRel): its relation-type handle."""

    rel_type: int
    name: str = ""


def define_rel_type(graph, name: str, arity: int) -> HGHandle:
    """Create (or find) a named relation type atom."""
    from hypergraphdb_tpu.query import dsl as q

    t = graph.typesystem.infer(RelTypeValue("", 0))
    existing = q.find_one(
        graph, q.and_(q.type_(t.name), q.part("name", name),
                      q.part("arity", arity))
    )
    if existing is not None:
        return existing
    return graph.add(RelTypeValue(name, arity))


def add_rel(graph, rel_type: HGHandle, *targets: int) -> HGHandle:
    """Instantiate a relation over targets; arity-checked."""
    rt = graph.get(int(rel_type))
    rt = rt.value if hasattr(rt, "value") else rt
    if not isinstance(rt, RelTypeValue):
        raise HGException(f"{rel_type} is not a relation type atom")
    if len(targets) != rt.arity:
        raise HGException(
            f"relation {rt.name} wants {rt.arity} targets, got {len(targets)}"
        )
    return graph.add_link([int(t) for t in targets],
                          value=RelValue(int(rel_type), rt.name))


# ------------------------------------------------------------------ subsumption


@dataclass(frozen=True)
class SubsumesValue:
    """Marker value of a subsumption link (HGSubsumes)."""


def declare_subsumes(graph, general_type: str, specific_type: str) -> HGHandle:
    """Persist ``general subsumes specific`` as a 2-arity link between the
    two type atoms and register it with the type system (powers TypePlus
    expansion, ``cond2qry/ExpressionBasedQuery.java:603``)."""
    gh = graph.typesystem.handle_of(general_type)
    sh = graph.typesystem.handle_of(specific_type)
    graph.typesystem.declare_subtype(specific_type, general_type)
    return graph.add_link([int(gh), int(sh)], value=SubsumesValue())


def declared_specifics(graph, general: int) -> frozenset:
    """All atoms with a persisted ``HGSubsumes`` link ``(general, x)`` —
    ONE incidence scan, memoized per graph version, so a ``Subsumed``
    query over N candidates costs one scan instead of N (each
    ``satisfies`` call would otherwise re-walk the incidence set)."""
    from hypergraphdb_tpu.types.record import _qualname

    # inside a transaction the incidence read merges the tx OVERLAY —
    # neither usable from nor storable into the committed-state memo
    # (an aborted tx would leave phantom subsumptions behind)
    in_tx = graph.txman.current() is not None
    version = graph._mutations
    cache = getattr(graph, "_subsumes_cache", None)
    if cache is None or cache[0] != version:
        th = graph._find_type_atom(_qualname(SubsumesValue))
        cache = (version, th, {})
        if not in_tx:
            graph._subsumes_cache = cache
    _, th, memo = cache
    if in_tx:
        memo = {}  # throwaway: overlay-tainted results must never be shared
    if th is None:
        return frozenset()
    general = int(general)
    if not in_tx:
        hit = memo.get(general)
        if hit is not None:
            return hit
    out = set()
    try:
        inc = graph.get_incidence_set(general).array()
    except Exception:
        memo[general] = frozenset()
        return memo[general]
    for l in inc.tolist():
        try:
            if int(graph.get_type_handle_of(l)) != int(th):
                continue
            ts_ = graph.get_targets(l)
        except Exception:
            continue
        if len(ts_) == 2 and int(ts_[0]) == general:
            out.add(int(ts_[1]))
    memo[general] = frozenset(out)
    return memo[general]


def subsumes_declared(graph, general: int, specific: int) -> bool:
    """Is there a persisted ``HGSubsumes`` link ``(general, specific)``?
    The declared-subsumption primitive of ``SubsumesImpl.declaredSubsumption``
    (And(type=HGSubsumes, OrderedLink(general, specific)) in the ref)."""
    return int(specific) in declared_specifics(graph, general)


def load_subsumptions(graph) -> int:
    """Reopen path: re-register persisted subsumption links with the type
    system; returns how many were loaded. Called automatically at graph
    open (a database must not forget its hierarchy — VERDICT r2 item 4)."""
    from hypergraphdb_tpu.query import dsl as q
    from hypergraphdb_tpu.types.record import _qualname

    # peek WITHOUT registering: a fresh store has no subsumption links and
    # must not grow a type atom just from being opened
    if graph._find_type_atom(_qualname(SubsumesValue)) is None:
        return 0
    t = graph.typesystem.infer(SubsumesValue())
    if t is None:
        return 0
    n = 0
    ts = graph.typesystem
    for h in q.find_all(graph, q.type_(t.name)):
        gh, sh = graph.get_targets(h)
        # the endpoint types may not be REGISTERED yet this session — adopt
        # their persisted name↔handle mappings so TypePlus resolves
        gname = ts.adopt_type_atom(int(gh))
        sname = ts.adopt_type_atom(int(sh))
        if gname is None or sname is None:
            continue
        ts.declare_subtype(sname, gname)
        n += 1
    return n
