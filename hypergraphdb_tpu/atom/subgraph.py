"""Named subgraphs with a membership index.

Re-expression of the reference's ``HGSubgraph`` (``atom/HGSubgraph.java:36``):
a subgraph is itself an atom; membership is tracked in a dedicated storage
index (subgraph handle → member handles) so ``SubgraphMember`` queries are
index lookups, and a subgraph scopes add/remove operations on its graph.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Optional

import numpy as np

from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.utils.ordered_bytes import encode_int

#: storage index: key = encoded subgraph handle, values = member handles
IDX_SUBGRAPH = "hg.subgraph"


def member_key(handle: HGHandle) -> bytes:
    """Index key of a subgraph's member list — the ONE key encoding shared
    by membership ops here and the purge in ``HyperGraph.remove``."""
    return encode_int(int(handle))


@dataclass
class SubgraphValue:
    """The stored value of a subgraph atom."""

    name: str = ""


class HGSubgraph:
    """A view over a graph restricted to an indexed member set."""

    def __init__(self, graph, handle: HGHandle):
        self.graph = graph
        self.handle = int(handle)

    # -- lifecycle -----------------------------------------------------------
    @staticmethod
    def create(graph, name: str = "") -> "HGSubgraph":
        h = graph.add(SubgraphValue(name=name))
        return HGSubgraph(graph, h)

    @staticmethod
    def of(graph, handle: HGHandle) -> "HGSubgraph":
        return HGSubgraph(graph, handle)

    @staticmethod
    def find_by_name(graph, name: str) -> Optional["HGSubgraph"]:
        from hypergraphdb_tpu.query import dsl as hg

        t = graph.typesystem.infer(SubgraphValue())
        h = graph.find_one(hg.and_(hg.type_(t.name), hg.part("name", name)))
        return None if h is None else HGSubgraph(graph, h)

    # -- membership ----------------------------------------------------------
    def _key(self) -> bytes:
        return member_key(self.handle)

    def _index(self):
        return self.graph.store.get_index(IDX_SUBGRAPH)

    def add_member(self, atom: HGHandle) -> None:
        self._index().add_entry(self._key(), int(atom))

    def remove_member(self, atom: HGHandle) -> None:
        self._index().remove_entry(self._key(), int(atom))

    def is_member(self, atom: HGHandle) -> bool:
        return int(atom) in self._index().find(self._key())

    def members(self) -> np.ndarray:
        return self._index().find(self._key()).array()

    def __iter__(self) -> Iterator[int]:
        return iter(self.members().tolist())

    def __len__(self) -> int:
        return len(self.members())

    # -- scoped operations (HGSubgraph.add/remove delegate + auto-member) -----
    def add(self, value: Any = None, **kw) -> HGHandle:
        h = self.graph.add(value, **kw)
        self.add_member(h)
        return h

    def remove(self, atom: HGHandle) -> bool:
        self.remove_member(atom)
        return self.graph.remove(atom)


def member_index_plan(graph, subgraph_handle: HGHandle):
    """Physical plan for ``SubgraphMember``: a direct index lookup."""
    from hypergraphdb_tpu.query.compiler import Plan

    class _MembersPlan(Plan):
        def __init__(self, h: int):
            self.h = int(h)

        def run(self, g):
            return g.store.get_index(IDX_SUBGRAPH).find(member_key(self.h)).array()

        def estimate(self, g):
            return float(g.store.get_index(IDX_SUBGRAPH).count(member_key(self.h)))

        def describe(self):
            return f"subgraph({self.h})"

    return _MembersPlan(subgraph_handle)
