"""hypergraphdb_tpu — a TPU-native hypergraph database framework.

A from-scratch rebuild of the capabilities of HyperGraphDB (the reference
Java implementation: an embedded, transactional, extensible hypergraph
database — see /root/reference, ``core/src/java/org/hypergraphdb/HyperGraph.java:64-75``)
re-designed TPU-first:

- **Host plane** (Python + C++ extension): columnar atom store, type system,
  MVCC transactions, ingest, indexing, p2p services.
- **Device plane** (JAX/XLA/Pallas): immutable CSR snapshots of the
  incidence structure; query and traversal hot loops run as batched
  gather/scatter + sorted-set-intersection kernels on TPU, sharded over a
  ``jax.sharding.Mesh`` for multi-chip scale.

Public entry points mirror the reference's API surface:

    >>> import hypergraphdb_tpu as hg
    >>> graph = hg.HyperGraph()          # HGEnvironment.get() equivalent
    >>> h = graph.add("hello")
    >>> link = graph.add_link((h, graph.add("world")))
    >>> snap = graph.snapshot()          # device CSR snapshot
"""

from hypergraphdb_tpu.core.handles import (
    HGHandle,
    NULL_HANDLE,
    HandleFactory,
    SequentialHandleFactory,
    UUIDHandleFactory,
)
from hypergraphdb_tpu.core.config import HGConfiguration
from hypergraphdb_tpu.core.errors import (
    HGException,
    TransactionConflict,
    NotFoundError,
)
from hypergraphdb_tpu.core.graph import HyperGraph, HGLink
from hypergraphdb_tpu.core.environment import HGEnvironment

__version__ = "0.1.0"

__all__ = [
    "HGHandle",
    "NULL_HANDLE",
    "HandleFactory",
    "SequentialHandleFactory",
    "UUIDHandleFactory",
    "HGConfiguration",
    "HGException",
    "TransactionConflict",
    "NotFoundError",
    "HyperGraph",
    "HGLink",
    "HGEnvironment",
]
