"""Exception hierarchy.

Mirrors the reference's ``HGException`` / ``TransactionConflictException``
surface (``core/src/java/org/hypergraphdb/HGException.java``,
``transaction/TransactionConflictException.java``) with Python idioms.
"""


class HGException(Exception):
    """Base class for all hypergraphdb_tpu errors."""


class NotFoundError(HGException, KeyError):
    """An atom, link or datum was not found for the given handle."""


class TransactionConflict(HGException):
    """Commit-time validation failed; the transaction should be retried.

    Equivalent of the reference's ``TransactionConflictException`` raised in
    ``HGTransaction.validateCommit`` (``transaction/HGTransaction.java:96-108``).
    """


class TransactionAborted(HGException):
    """The transaction was explicitly aborted."""


class StorageError(HGException):
    """Low-level storage failure."""


class TypeError_(HGException):
    """Type-system violation (bad value for type, unknown type...)."""


class QueryError(HGException):
    """Malformed or uncompilable query condition."""
