"""Atom identity: handles and handle factories.

The reference models identity as 16-byte UUIDs with pluggable factories
(``core/src/java/org/hypergraphdb/handle/UUIDPersistentHandle.java:26``,
``SequentialUUIDHandleFactory.java:19``, ``LongHandleFactory.java:8``,
``IntHandleFactory.java:23``). The existence of the int/long factories proves
UUIDs are not semantically required — so the TPU-native design makes the
*dense integer* the primary handle: atom ids index directly into columnar
host tables and device CSR arrays, which is what lets query/traversal hot
loops run as vectorized gathers instead of hash lookups.

UUIDs survive only as an optional *exchange format* (``UUIDHandleFactory``)
for p2p interop, mapped bidirectionally to dense ids.
"""

from __future__ import annotations

import itertools
import threading
import uuid
from typing import Iterable, Optional

# A handle is a plain Python int (dense, non-negative). -1 is the null handle,
# matching the padding sentinel used by device-side CSR arrays.
HGHandle = int

NULL_HANDLE: HGHandle = -1


def is_null(h: HGHandle) -> bool:
    return h is None or h < 0


class HandleFactory:
    """Allocates fresh persistent handles.

    Equivalent of the reference's ``HGHandleFactory``; concrete factories
    below parallel its UUID/sequential/long/int family.
    """

    def make(self) -> HGHandle:
        raise NotImplementedError

    def make_many(self, n: int) -> range:
        """Bulk allocation for ingest hot paths (no reference analogue —
        the columnar design makes contiguous id ranges valuable)."""
        raise NotImplementedError

    @property
    def null_handle(self) -> HGHandle:
        return NULL_HANDLE

    def reset(self, next_id: int) -> None:
        """Fast-forward the allocator (used when reopening a persisted store)."""
        raise NotImplementedError


class SequentialHandleFactory(HandleFactory):
    """Dense sequential ids — the default.

    Analogue of ``IntHandleFactory``/``LongHandleFactory`` and of the
    locality intent behind ``SequentialUUIDHandleFactory.java:19`` (sequential
    keys give B-tree locality there; here they give direct array indexing).
    Thread-safe.
    """

    def __init__(self, start: int = 0):
        self._lock = threading.Lock()
        self._next = start

    def make(self) -> HGHandle:
        with self._lock:
            h = self._next
            self._next += 1
            return h

    def make_many(self, n: int) -> range:
        with self._lock:
            first = self._next
            self._next += n
            return range(first, first + n)

    def reset(self, next_id: int) -> None:
        with self._lock:
            if next_id > self._next:
                self._next = next_id

    @property
    def peek(self) -> int:
        return self._next


class UUIDHandleFactory(HandleFactory):
    """Dense ids + a bidirectional UUID alias table.

    Keeps the reference's wire/exchange identity (16-byte UUIDs,
    ``UUIDPersistentHandle.java:26``) available for p2p replication and
    import/export, while all in-process identity stays dense.
    """

    def __init__(self, start: int = 0):
        self._seq = SequentialHandleFactory(start)
        self._lock = threading.Lock()
        self._to_uuid: dict[int, uuid.UUID] = {}
        self._from_uuid: dict[uuid.UUID, int] = {}

    def make(self) -> HGHandle:
        h = self._seq.make()
        u = uuid.uuid4()
        with self._lock:
            self._to_uuid[h] = u
            self._from_uuid[u] = h
        return h

    def make_many(self, n: int) -> range:
        r = self._seq.make_many(n)
        with self._lock:
            for h in r:
                u = uuid.uuid4()
                self._to_uuid[h] = u
                self._from_uuid[u] = h
        return r

    def reset(self, next_id: int) -> None:
        self._seq.reset(next_id)

    def uuid_of(self, h: HGHandle) -> Optional[uuid.UUID]:
        return self._to_uuid.get(h)

    def handle_of(self, u: uuid.UUID) -> Optional[HGHandle]:
        return self._from_uuid.get(u)

    def bind(self, h: HGHandle, u: uuid.UUID) -> None:
        """Register a foreign (replicated) atom's exchange identity."""
        with self._lock:
            self._to_uuid[h] = u
            self._from_uuid[u] = h


def pack_handles(handles: Iterable[HGHandle]) -> bytes:
    """Serialize a handle tuple as little-endian int64s.

    The wire analogue of the reference's concatenated 16-byte handle layout
    (``storage/bdb-je/.../LinkBinding.java:28``) at 8 bytes per handle.
    """
    import struct

    hs = list(handles)
    return struct.pack(f"<{len(hs)}q", *hs)


def unpack_handles(data: bytes) -> tuple[HGHandle, ...]:
    import struct

    n = len(data) // 8
    return struct.unpack(f"<{n}q", data)
