"""Bulk loader: the high-throughput ingest path for dataset-scale imports.

The per-atom add path buffers every write in the transaction overlay, then
replays it at commit — correct, but ~30 Python-level calls per atom. At the
benchmark scales (BASELINE configs 3-4: 10M atoms) that tax dominates
ingest. ``bulk_import`` is the loader the reference would call a batch
load: one type resolution, one commit batch, direct backend writes, bulk
index appends.

Semantics and caveats (documented, deliberate):

- atomicity/durability: all writes go through ONE backend commit batch, so
  a crash mid-load replays nothing (all-or-nothing on durable backends);
- isolation: the loader requires that no transaction is active on the
  calling thread and takes the commit lock for its whole run — concurrent
  committers queue behind it exactly like behind a large commit;
- events fire per atom only if someone is listening (same rule as the
  bulk add APIs); user indexers run through the normal ``maybe_index``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from hypergraphdb_tpu.core.errors import HGException


def bulk_import(
    graph,
    values: Optional[Sequence[Any]] = None,
    target_lists: Optional[Sequence[Sequence[int]]] = None,
    type: Optional[Any] = None,  # noqa: A002 - mirrors kernel naming
) -> range:
    """Load ``values[i]`` (and, for links, ``target_lists[i]``) in one batch.

    All atoms must share one type (pass ``type`` or let the first value
    infer it). Returns the contiguous handle range. Falls back to the
    normal bulk APIs when a transaction is open on this thread."""
    from hypergraphdb_tpu.core import events as ev
    from hypergraphdb_tpu.core.graph import (
        _FLAG_LINK,
        IDX_BY_TYPE,
        IDX_BY_VALUE,
        _type_key,
    )
    from hypergraphdb_tpu.indexing.manager import indexers_of, maybe_index

    n = len(target_lists) if target_lists is not None else len(values)
    if n == 0:
        return range(0, 0)
    if values is not None and target_lists is not None \
            and len(values) != len(target_lists):
        raise HGException("values and target_lists length mismatch")

    if graph.txman.current() is not None:
        # inside a transaction the overlay semantics must hold — use the
        # buffered path
        if target_lists is None:
            return graph.add_nodes_bulk(values, type=type)
        return graph.add_links_bulk(target_lists, values=values, type=type)

    graph._check_open()
    sample = values[0] if values is not None else None
    type_handle = int(graph._resolve_type_handle(sample, type))
    atype = graph.typesystem.get_type(type_handle)
    backend = graph.backend
    has_indexers = bool(indexers_of(graph, type_handle))

    with graph.txman._commit_lock:
        r = graph.handles.make_many(n)
        backend.commit_batch_begin()
        try:
            by_type = backend.get_index(IDX_BY_TYPE)
            by_value = backend.get_index(IDX_BY_VALUE)
            tkey = _type_key(type_handle)
            flags = _FLAG_LINK if target_lists is not None else 0
            value_keys: set = set()
            touched_targets: set = set()
            touched_user_idx: set = set()
            for i, h in enumerate(r):
                v = values[i] if values is not None else None
                vkey = atype.to_key(v)
                if v is None and atype.name == "null":
                    value_handle = -1
                else:
                    value_handle = graph.handles.make()
                    backend.store_data(value_handle, atype.store(v))
                if target_lists is not None:
                    targets = tuple(int(t) for t in target_lists[i])
                else:
                    targets = ()
                backend.store_link(h, (type_handle, value_handle, flags)
                                   + targets)
                by_type.add_entry(tkey, h)
                by_value.add_entry(vkey, h)
                value_keys.add(vkey)
                for t in targets:
                    backend.add_incidence_link(t, h)
                    touched_targets.add(t)
                if has_indexers:
                    maybe_index(graph, h, type_handle, v, targets or None,
                                touched=touched_user_idx)
        except BaseException:
            backend.commit_batch_abort()
            raise
        else:
            backend.commit_batch_end()
        # one clock tick for the whole batch, but EVERY cell the batch
        # touched gets the version bump — an open transaction that read any
        # of these (a value key it expects absent, a target's incidence
        # set) must fail commit-time validation, not silently miss the
        # bulk write (ADVICE r2: bulk_import isolation gap)
        graph.txman._clock += 1
        clock = graph.txman._clock
        versions = graph.txman._versions
        versions[("idx", IDX_BY_TYPE, tkey)] = clock
        for vk in value_keys:
            versions[("idx", IDX_BY_VALUE, vk)] = clock
        for name, key in touched_user_idx:
            versions[("idx", name, key)] = clock
        for t in touched_targets:
            versions[("inc", t)] = clock

    def fire() -> None:
        if graph.events.has_listeners_for(ev.HGAtomAddedEvent):
            for i, h in enumerate(r):
                v = values[i] if values is not None else None
                graph._committed_mutation(ev.HGAtomAddedEvent(h, v))
        else:
            graph._mutations += n
            graph.metrics.incr("graph.mutations", n)

    fire()
    return r
