"""Bulk loader: the high-throughput ingest path for dataset-scale imports.

The per-atom add path buffers every write in the transaction overlay, then
replays it at commit — correct, but ~30 Python-level calls per atom. At the
benchmark scales (BASELINE configs 3-4: 10M atoms) that tax dominates
ingest. ``bulk_import`` is the loader the reference would call a batch
load: one type resolution, one commit batch, direct backend writes, bulk
index appends.

Semantics and caveats (documented, deliberate):

- atomicity/durability: all writes go through ONE backend commit batch, so
  a crash mid-load replays nothing (all-or-nothing on durable backends);
- isolation: the loader requires that no transaction is active on the
  calling thread and takes the commit lock for its whole run — concurrent
  committers queue behind it exactly like behind a large commit;
- events fire per atom only if someone is listening (same rule as the
  bulk add APIs); user indexers run through the normal ``maybe_index``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from hypergraphdb_tpu.core.errors import HGException


def bulk_import(
    graph,
    values: Optional[Sequence[Any]] = None,
    target_lists: Optional[Sequence[Sequence[int]]] = None,
    type: Optional[Any] = None,  # noqa: A002 - mirrors kernel naming
) -> range:
    """Load ``values[i]`` (and, for links, ``target_lists[i]``) in one batch.

    All atoms must share one type (pass ``type`` or let the first value
    infer it). Returns the contiguous handle range. Falls back to the
    normal bulk APIs when a transaction is open on this thread."""
    from hypergraphdb_tpu.core import events as ev
    from hypergraphdb_tpu.core.graph import (
        _FLAG_LINK,
        IDX_BY_TYPE,
        IDX_BY_VALUE,
        _type_key,
    )
    from hypergraphdb_tpu.indexing.manager import indexers_of, maybe_index

    n = len(target_lists) if target_lists is not None else len(values)
    if n == 0:
        return range(0, 0)
    if values is not None and target_lists is not None \
            and len(values) != len(target_lists):
        raise HGException("values and target_lists length mismatch")

    if graph.txman.current() is not None:
        # inside a transaction the overlay semantics must hold — use the
        # buffered path
        if target_lists is None:
            return graph.add_nodes_bulk(values, type=type)
        return graph.add_links_bulk(target_lists, values=values, type=type)

    graph._check_open()
    sample = values[0] if values is not None else None
    type_handle = int(graph._resolve_type_handle(sample, type))
    atype = graph.typesystem.get_type(type_handle)
    backend = graph.backend
    has_indexers = bool(indexers_of(graph, type_handle))

    with graph.txman._commit_lock:
        r = graph.handles.make_many(n)
        # MVCC pre-image capture (ADVICE r4): a transaction begun BEFORE
        # this load must keep its begin-time view of every inc/idx cell the
        # load touches (its reads go straight to the backend when the
        # history chain is empty). Mirror _capture_history lazily: full
        # pre-image per cell, recorded before the first write, tagged with
        # the clock tick this batch will commit as. begin() also takes the
        # commit lock, so the active-set check cannot race a new reader.
        # Scope is inc/idx only, like the version bumps below: the
        # link/data cells all belong to handles minted by THIS load, and a
        # snapshot reader can only discover those through the captured
        # index cells — per-atom link/data pre-images would cost O(load)
        # history memory to cover handles no snapshot can reach.
        txman = graph.txman
        # no current() tx on this thread — any active tx is a reader
        capturing = bool(txman._active)
        vnext = txman._clock + 1
        captured: set = set()

        def cap(cell, read_pre):
            if not capturing or cell in captured:
                return
            captured.add(cell)
            txman._history.setdefault(cell, []).append(
                (vnext, ("full", read_pre()))
            )

        def cap_user_idx(storage_name, key, idx):
            cap(("idx", storage_name, key),
                lambda: idx.find(key).array().copy())

        backend.commit_batch_begin()
        try:
            by_type = backend.get_index(IDX_BY_TYPE)
            by_value = backend.get_index(IDX_BY_VALUE)
            tkey = _type_key(type_handle)
            cap(("idx", IDX_BY_TYPE, tkey),
                lambda: by_type.find(tkey).array().copy())
            flags = _FLAG_LINK if target_lists is not None else 0
            value_keys: set = set()
            touched_targets: set = set()
            touched_user_idx: set = set()
            for i, h in enumerate(r):
                v = values[i] if values is not None else None
                vkey = atype.to_key(v)
                if v is None and atype.name == "null":
                    value_handle = -1
                else:
                    value_handle = graph.handles.make()
                    backend.store_data(value_handle, atype.store(v))
                if target_lists is not None:
                    targets = tuple(int(t) for t in target_lists[i])
                else:
                    targets = ()
                backend.store_link(h, (type_handle, value_handle, flags)
                                   + targets)
                by_type.add_entry(tkey, h)
                if capturing:
                    cap(("idx", IDX_BY_VALUE, vkey),
                        lambda k=vkey: by_value.find(k).array().copy())
                by_value.add_entry(vkey, h)
                value_keys.add(vkey)
                for t in targets:
                    if capturing:
                        cap(("inc", t),
                            lambda a=t: backend.get_incidence_set(a)
                            .array().copy())
                    backend.add_incidence_link(t, h)
                    touched_targets.add(t)
                if has_indexers:
                    maybe_index(graph, h, type_handle, v, targets or None,
                                touched=touched_user_idx,
                                before_write=(cap_user_idx if capturing
                                              else None))
        except BaseException:
            backend.commit_batch_abort()
            # Direct backend writes already applied are NOT rolled back on
            # memory backends (commit_batch_abort is a durability marker),
            # so the error path must still honor both isolation promises:
            # KEEP the captured pre-images (snapshot readers reconstruct
            # their begin-time view through them) and consume the `vnext`
            # tick + bump the captured cells (open readers of the
            # half-applied state fail commit validation instead of
            # committing on top of it).
            if captured:
                txman._clock = vnext
                for cell in captured:
                    txman._versions[cell] = vnext
            raise
        else:
            backend.commit_batch_end()
        # one clock tick for the whole batch, but EVERY cell the batch
        # touched gets the version bump — an open transaction that read any
        # of these (a value key it expects absent, a target's incidence
        # set) must fail commit-time validation, not silently miss the
        # bulk write (ADVICE r2: bulk_import isolation gap)
        graph.txman._clock += 1
        clock = graph.txman._clock
        versions = graph.txman._versions
        versions[("idx", IDX_BY_TYPE, tkey)] = clock
        for vk in value_keys:
            versions[("idx", IDX_BY_VALUE, vk)] = clock
        for name, key in touched_user_idx:
            versions[("idx", name, key)] = clock
        for t in touched_targets:
            versions[("inc", t)] = clock

    def fire() -> None:
        if graph.events.has_listeners_for(ev.HGAtomAddedEvent):
            for i, h in enumerate(r):
                v = values[i] if values is not None else None
                graph._committed_mutation(ev.HGAtomAddedEvent(h, v))
        else:
            graph._mutations += n
            graph.metrics.incr("graph.mutations", n)

    fire()
    return r
