"""HGStore — the transaction-aware store façade.

Re-expression of the reference's ``HGStore`` (``core/src/java/org/hypergraphdb/
HGStore.java:42-416``): the single object through which the graph kernel talks
to storage — link records, value payloads, incidence sets and named indices —
with every read/write routed through the current transaction's overlay
(read-your-writes + commit-time validation, see ``tx/manager.py``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from hypergraphdb_tpu.core.handles import HGHandle
from hypergraphdb_tpu.storage.api import (
    HGBidirectionalIndex,
    HGIndex,
    HGSortedResultSet,
    StorageBackend,
)
from hypergraphdb_tpu.tx.manager import (
    _TOMBSTONE,
    _IdxDelta,
    _IncDelta,
    HGTransactionManager,
)


class HGStore:
    def __init__(self, backend: StorageBackend, txman: HGTransactionManager,
                 incidence_cache_entries: int = 0,
                 max_cached_incidence_set_size: int = 0):
        self.backend = backend
        self.tx = txman
        # incidence-set LRU (the reference wires an LRUCache with a
        # maxCachedIncidenceSetSize cap at HyperGraph.java:316-323 /
        # HGConfiguration.java:39): entries are (cell_version, readonly
        # array) — version-validated, so invalidation is free
        from hypergraphdb_tpu.utils.cache import LRUCache

        self._inc_cache = (
            LRUCache(incidence_cache_entries)
            if incidence_cache_entries > 0 else None
        )
        self._inc_cache_max = max_cached_incidence_set_size

    def _committed_incidence(self, atom: int, sv: Optional[int]) -> np.ndarray:
        """The committed incidence array for ``atom`` as of snapshot ``sv``
        (None = latest), through the capped LRU when possible.

        Misses ALWAYS go through the MVCC reconstruction (``tx.inc_at``
        reads the backend first and then undoes newer history — the
        race-free order, see ``_value_at`` in tx/manager.py), pinned at
        ``sv`` for snapshot readers and at the observed ``ver`` otherwise.
        A raw backend read is NOT safe to cache: commit applies backend
        writes before bumping ``_versions``, so a read that straddles
        ``_apply`` can pair a post-commit array with the pre-commit
        version and survive the version re-check (ADVICE r4). The
        reconstruction undoes exactly that in-flight commit, so the array
        is the value at ``ver`` by construction; the re-check below only
        guards the completed-commit + history-GC window."""
        cache = self._inc_cache
        ver = self.tx.cell_version(("inc", atom))
        if cache is not None and (sv is None or ver <= sv):
            hit = cache.get(atom)
            if hit is not None and hit[0] == ver:
                return hit[1]
        arr = self.tx.inc_at(atom, sv if sv is not None else ver)
        if (
            cache is not None
            and len(arr) <= self._inc_cache_max
            and (sv is None or ver <= sv)
            and self.tx.cell_version(("inc", atom)) == ver
        ):
            arr.setflags(write=False)  # shared across readers
            cache.put(atom, (ver, arr))
        return arr

    # ---- links --------------------------------------------------------------
    def store_link(self, h: HGHandle, targets: Sequence[HGHandle]) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.store_link(h, targets)
        else:
            tx.links[int(h)] = tuple(int(t) for t in targets)

    def get_link(self, h: HGHandle) -> Optional[tuple[HGHandle, ...]]:
        h = int(h)
        tx = self.tx.current()
        while tx is not None:
            if h in tx.links:
                v = tx.links[h]
                return None if v is _TOMBSTONE else v
            tx = tx.parent
        cur = self.tx.current()
        if cur is None:
            return self.backend.get_link(h)
        cur.note_read(("link", h))
        # begin-time snapshot read (VBox.java:28): concurrent commits after
        # our start_version are invisible
        return self.tx.link_at(h, cur.start_version)

    def remove_link(self, h: HGHandle) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.remove_link(int(h))
        else:
            tx.links[int(h)] = _TOMBSTONE

    def contains_link(self, h: HGHandle) -> bool:
        return self.get_link(h) is not None

    # ---- data ---------------------------------------------------------------
    def store_data(self, h: HGHandle, data: bytes) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.store_data(int(h), data)
        else:
            tx.data[int(h)] = bytes(data)

    def get_data(self, h: HGHandle) -> Optional[bytes]:
        h = int(h)
        tx = self.tx.current()
        while tx is not None:
            if h in tx.data:
                v = tx.data[h]
                return None if v is _TOMBSTONE else v
            tx = tx.parent
        cur = self.tx.current()
        if cur is None:
            return self.backend.get_data(h)
        cur.note_read(("data", h))
        return self.tx.data_at(h, cur.start_version)

    def remove_data(self, h: HGHandle) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.remove_data(int(h))
        else:
            tx.data[int(h)] = _TOMBSTONE

    # ---- incidence ----------------------------------------------------------
    def add_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.add_incidence_link(int(atom), int(link))
        else:
            tx.inc.setdefault(int(atom), _IncDelta()).add(int(link))

    def remove_incidence_link(self, atom: HGHandle, link: HGHandle) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.remove_incidence_link(int(atom), int(link))
        else:
            tx.inc.setdefault(int(atom), _IncDelta()).remove(int(link))

    def remove_incidence_set(self, atom: HGHandle) -> None:
        tx = self.tx.current()
        if tx is None:
            self.backend.remove_incidence_set(int(atom))
        else:
            tx.inc.setdefault(int(atom), _IncDelta()).clear()

    def get_incidence_set(self, atom: HGHandle) -> HGSortedResultSet:
        atom = int(atom)
        tx = self.tx.current()
        if tx is not None:
            tx.note_read(("inc", atom))
            base = self._committed_incidence(atom, tx.start_version)
        else:
            base = self._committed_incidence(atom, None)
        # merge overlay deltas, innermost-last
        deltas: list[_IncDelta] = []
        t = tx
        while t is not None:
            d = t.inc.get(atom)
            if d is not None:
                deltas.append(d)
            t = t.parent
        if not deltas:
            return HGSortedResultSet(base)
        added: set[int] = set()
        removed: set[int] = set()
        cleared = False
        for d in reversed(deltas):  # outermost first
            if d.cleared:
                cleared, added, removed = True, set(), set()
            added |= d.added
            added -= d.removed
            removed |= d.removed
            removed -= d.added
        vals = set() if cleared else set(base.tolist())
        vals -= removed
        vals |= added
        return HGSortedResultSet(np.asarray(sorted(vals), dtype=np.int64))

    def incidence_count(self, atom: HGHandle) -> int:
        return len(self.get_incidence_set(atom))

    # ---- indices ------------------------------------------------------------
    def get_index(self, name: str, create: bool = True) -> Optional["TxIndexView"]:
        idx = self.backend.get_index(name, create=create)
        if idx is None:
            return None
        return TxIndexView(self, name, idx)

    def remove_index(self, name: str) -> None:
        self.backend.remove_index(name)

    def index_names(self) -> list[str]:
        return self.backend.index_names()


class TxIndexView(HGBidirectionalIndex):
    """Transaction-aware view over a backend index."""

    def __init__(self, store: HGStore, name: str, backing: HGBidirectionalIndex):
        self.name = name
        self._store = store
        self._backing = backing

    def _tx(self):
        return self._store.tx.current()

    def add_entry(self, key: bytes, value: HGHandle) -> None:
        tx = self._tx()
        if tx is None:
            self._backing.add_entry(key, int(value))
        else:
            tx.idx.setdefault((self.name, bytes(key)), _IdxDelta()).add(int(value))

    def remove_entry(self, key: bytes, value: HGHandle) -> None:
        tx = self._tx()
        if tx is None:
            self._backing.remove_entry(key, int(value))
        else:
            tx.idx.setdefault((self.name, bytes(key)), _IdxDelta()).remove(int(value))

    def remove_all_entries(self, key: bytes) -> None:
        tx = self._tx()
        if tx is None:
            self._backing.remove_all_entries(key)
        else:
            d = tx.idx.setdefault((self.name, bytes(key)), _IdxDelta())
            d.added.clear()
            d.removed.clear()
            d.removed_all = True

    def _deltas_for(self, key: bytes) -> list[_IdxDelta]:
        out = []
        t = self._tx()
        while t is not None:
            d = t.idx.get((self.name, key))
            if d is not None:
                out.append(d)
            t = t.parent
        return out

    def find(self, key: bytes) -> HGSortedResultSet:
        key = bytes(key)
        tx = self._tx()
        if tx is not None:
            tx.note_read(("idx", self.name, key))
            base = self._store.tx.idx_at(self.name, key, tx.start_version)
        else:
            base = self._backing.find(key).array()
        deltas = self._deltas_for(key)
        if not deltas:
            return HGSortedResultSet(base)
        vals: set[int] = set()
        wiped = False
        added: set[int] = set()
        removed: set[int] = set()
        for d in reversed(deltas):
            if d.removed_all:
                wiped, added, removed = True, set(), set()
            added |= d.added
            added -= d.removed
            removed |= d.removed
            removed -= d.added
        vals = set() if wiped else set(base.tolist())
        vals -= removed
        vals |= added
        return HGSortedResultSet(np.asarray(sorted(vals), dtype=np.int64))

    def key_count(self) -> int:
        return self._backing.key_count()

    def scan_keys(self):
        # keys to re-check against the merged/snapshot view: the tx's own
        # writes AND keys other commits moved past the snapshot (a
        # post-snapshot key must not surface here while find() reports it
        # empty — the phantom find_range already suppresses)
        touched = set()
        t = self._tx()
        while t is not None:
            for (nm, k), d in t.idx.items():
                if nm == self.name and (d.added or d.removed or d.removed_all):
                    touched.add(k)
            t = t.parent
        tx = self._tx()
        if tx is not None:
            touched.update(
                self._store.tx.idx_keys_changed_since(
                    self.name, tx.start_version
                )
            )
        if not touched:
            yield from self._backing.scan_keys()
            return
        seen = set()
        for k in self._backing.scan_keys():
            seen.add(k)
            if k not in touched or len(self.find(k)):
                yield k
        for k in sorted(touched - seen):
            if len(self.find(k)):
                yield k

    def find_range(
        self,
        lo: Optional[bytes] = None,
        hi: Optional[bytes] = None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = False,
    ) -> HGSortedResultSet:
        base = self._backing.find_range(lo, hi, lo_inclusive, hi_inclusive).array()
        tx = self._tx()
        if tx is None:
            return HGSortedResultSet(base)

        def in_range(k: bytes) -> bool:
            if lo is not None and (k < lo or (k == lo and not lo_inclusive)):
                return False
            if hi is not None and (k > hi or (k == hi and not hi_inclusive)):
                return False
            return True

        # keys to re-check: this tx's own writes PLUS keys other commits
        # moved past our snapshot (their current committed membership is
        # in `base` but must not be visible)
        touched: set[bytes] = set()
        t = tx
        while t is not None:
            for (nm, k) in t.idx:
                if nm == self.name and in_range(k):
                    touched.add(k)
            t = t.parent
        for k in self._store.tx.idx_keys_changed_since(
            self.name, tx.start_version
        ):
            if in_range(k):
                touched.add(k)
        if not touched:
            return HGSortedResultSet(base)
        vals = set(base.tolist())
        for k in touched:
            committed = set(self._backing.find(k).array().tolist())
            merged = set(self.find(k).array().tolist())
            vals -= committed - merged
            vals |= merged
        return HGSortedResultSet(np.asarray(sorted(vals), dtype=np.int64))

    def find_by_value(self, value: HGHandle) -> list[bytes]:
        keys = set(self._backing.find_by_value(int(value)))
        t = self._tx()
        while t is not None:
            for (nm, k), d in t.idx.items():
                if nm != self.name:
                    continue
                if int(value) in d.added:
                    keys.add(k)
                elif int(value) in d.removed or d.removed_all:
                    keys.discard(k)
            t = t.parent
        return sorted(keys)
