"""Configuration tree.

Single dataclass config, JSON-loadable (the reference splits this across
``HGConfiguration.java:32-46``, ``HGQueryConfiguration``, backend config
beans and a JSON peer config; here it is one tree — see SURVEY §5 "Config").
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class QueryConfig:
    """Query-compiler knobs (reference: ``query/HGQueryConfiguration.java``)."""

    parallel_or: bool = False          # async union of Or branches
    prefer_device: bool = True         # plan onto TPU snapshot when possible
    #: smallest-child estimate below which ONE-SHOT dispatches stay on
    #: host cursors (planner duality). MEASURED on tunneled TPU hardware
    #: (CALIBRATION.md §2): a single ad-hoc device dispatch costs
    #: 130-800 ms there, so the host wins through at least 262K rows.
    #: Batched serving (plan_pattern/execute_pattern) is NOT gated by
    #: this. Co-located chips should lower it (re-run
    #: tools/calibrate_duality.py).
    device_min_batch: int = 262_144
    contract_conjunctions: bool = True
    #: cost cap for range-scan cardinality estimates: counts are exact up
    #: to this many entries, then clamped (HGIndexStats.java:37 analogue)
    range_estimate_cap: int = 4096


@dataclass
class CacheConfig:
    """Host-side cache sizing (reference wires 0.9/0.3 memory fractions at
    ``HyperGraph.java:316-323``; we use explicit entry counts)."""

    atom_cache_size: int = 1 << 20
    incidence_cache_entries: int = 1 << 16
    max_cached_incidence_set_size: int = 1 << 20
    #: RSS threshold (bytes) above which caches shrink; 0 disables the
    #: watcher (MemoryWarningSystem analogue)
    memory_warning_bytes: int = 0
    memory_warning_interval_s: float = 5.0


@dataclass
class SnapshotConfig:
    """Device snapshot build policy."""

    auto_refresh: bool = False         # re-pack CSR on snapshot() if stale
    delta_threshold: float = 0.15      # fraction of dirty atoms triggering full re-pack
    pad_multiple: int = 128            # pad CSR arrays to lane multiples
    dtype: str = "int32"               # device id dtype


@dataclass
class PeerConfig:
    """P2P peer settings (reference: JSON config consumed by
    ``peer/HyperGraphPeer.java:337-353``)."""

    name: str = ""
    transport: str = "loopback"        # "loopback" | "grpc"
    bootstrap: list = field(default_factory=list)
    replicate: bool = False
    listen_address: str = ""


@dataclass
class HGConfiguration:
    """Top-level configuration (reference: ``HGConfiguration.java:32-46``)."""

    transactional: bool = True
    keep_incident_links_on_removal: bool = False
    store_backend: str = "memory"      # "memory" | "native" | "partitioned"
    location: Optional[str] = None     # directory for persistent backends
    n_partitions: int = 4              # partitioned backend: child count
    handle_factory: str = "sequential"  # "sequential" | "uuid"
    query: QueryConfig = field(default_factory=QueryConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    snapshot: SnapshotConfig = field(default_factory=SnapshotConfig)
    peer: PeerConfig = field(default_factory=PeerConfig)

    @staticmethod
    def from_json(text: str) -> "HGConfiguration":
        raw = json.loads(text)
        return HGConfiguration.from_dict(raw)

    @staticmethod
    def from_dict(raw: dict) -> "HGConfiguration":
        kwargs: dict[str, Any] = {}
        for f in dataclasses.fields(HGConfiguration):
            if f.name not in raw:
                continue
            v = raw[f.name]
            if dataclasses.is_dataclass(f.type) or f.name in (
                "query", "cache", "snapshot", "peer",
            ):
                sub = {"query": QueryConfig, "cache": CacheConfig,
                       "snapshot": SnapshotConfig, "peer": PeerConfig}[f.name]
                v = sub(**v)
            kwargs[f.name] = v
        return HGConfiguration(**kwargs)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)
