"""HGEnvironment — registry of open databases.

Re-expression of ``core/src/java/org/hypergraphdb/HGEnvironment.java:37,93``:
a process-wide map from location → open ``HyperGraph``, with idempotent
``get`` and an atexit hook standing in for the reference's JVM shutdown hook
(``HGEnvironment.java:256-283``).
"""

from __future__ import annotations

import atexit
import threading
from typing import Optional

from hypergraphdb_tpu.core.config import HGConfiguration
from hypergraphdb_tpu.core.graph import HyperGraph

_lock = threading.Lock()
_open: dict[str, HyperGraph] = {}


def _native_available() -> bool:
    try:
        from hypergraphdb_tpu.storage import native  # noqa: F401

        return True
    except ImportError:
        return False


def get(location: str, config: Optional[HGConfiguration] = None) -> HyperGraph:
    """Open (or return the already-open) database at ``location``.

    A real filesystem location selects the persistent native backend when the
    C++ extension is built; otherwise it falls back to the in-memory backend
    with a warning (never mutating the caller's config object).
    """
    with _lock:
        g = _open.get(location)
        if g is not None:
            return g
        import copy

        cfg = copy.deepcopy(config) if config is not None else HGConfiguration()
        cfg.location = location
        if cfg.store_backend == "memory" and location not in ("", ":memory:"):
            if _native_available():
                cfg.store_backend = "native"
            else:
                import logging

                logging.getLogger(__name__).warning(
                    "native storage backend unavailable; opening %s in-memory "
                    "(non-durable)", location,
                )
        g = HyperGraph(cfg)
        _open[location] = g
        return g


def is_open(location: str) -> bool:
    with _lock:
        return location in _open


def close(location: str) -> None:
    with _lock:
        g = _open.pop(location, None)
    if g is not None:
        g.close()


def close_all() -> None:
    with _lock:
        graphs = list(_open.items())
        _open.clear()
    for _, g in graphs:
        g.close()


atexit.register(close_all)


class HGEnvironment:
    """Namespace-style façade matching the reference's static API."""

    get = staticmethod(get)
    is_open = staticmethod(is_open)
    close = staticmethod(close)
    close_all = staticmethod(close_all)
