"""Event system: synchronous listener dispatch per event class.

Re-expression of the reference's ``event/`` package
(``event/HGDefaultEventManager.java`` — dispatch walks the event class and
its superclasses; events for atom added/removed/replaced/loaded, veto
"propose" events, tx boundaries, open/close — SURVEY §2.1 Events).
A listener returning ``HGListener.CANCEL`` vetoes the operation (the
reference's propose/refuse protocol).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from hypergraphdb_tpu.core.handles import HGHandle


class HGEvent:
    pass


@dataclass
class HGAtomEvent(HGEvent):
    handle: HGHandle
    atom: Any = None


class HGAtomProposeEvent(HGAtomEvent):
    """Fired before an add; a CANCEL veto aborts the add."""


class HGAtomAddedEvent(HGAtomEvent):
    pass


class HGAtomRemoveRequestEvent(HGAtomEvent):
    """Fired before a remove; a CANCEL veto aborts it."""


class HGAtomRemovedEvent(HGAtomEvent):
    pass


class HGAtomReplaceRequestEvent(HGAtomEvent):
    pass


class HGAtomReplacedEvent(HGAtomEvent):
    pass


class HGAtomLoadedEvent(HGAtomEvent):
    pass


class HGAtomAccessedEvent(HGAtomEvent):
    pass


@dataclass
class HGOpenedEvent(HGEvent):
    graph: Any = None


@dataclass
class HGClosingEvent(HGEvent):
    graph: Any = None


@dataclass
class HGTransactionStartedEvent(HGEvent):
    tx: Any = None


@dataclass
class HGTransactionEndedEvent(HGEvent):
    tx: Any = None
    success: bool = True


class HGListener:
    CONTINUE = 0
    CANCEL = 1


Listener = Callable[[Any, HGEvent], int]


class HGEventManager:
    """Synchronous dispatch; listeners keyed by event class, superclass
    listeners also fire (``HGDefaultEventManager`` semantics)."""

    def __init__(self) -> None:
        self._listeners: dict[type, list[Listener]] = {}

    def add_listener(self, event_class: type, listener: Listener) -> None:
        self._listeners.setdefault(event_class, []).append(listener)

    def remove_listener(self, event_class: type, listener: Listener) -> None:
        ls = self._listeners.get(event_class)
        if ls and listener in ls:
            ls.remove(listener)

    def clear(self) -> None:
        self._listeners.clear()

    def dispatch(self, graph: Any, event: HGEvent) -> int:
        if not self._listeners:  # bulk-ingest fast path: no subscribers
            return HGListener.CONTINUE
        for cls in type(event).__mro__:
            if not (isinstance(cls, type) and issubclass(cls, HGEvent)):
                continue
            for l in list(self._listeners.get(cls, ())):
                if l(graph, event) == HGListener.CANCEL:
                    return HGListener.CANCEL
        return HGListener.CONTINUE

    def has_listeners_for(self, event_class: type) -> bool:
        """True if any listener would see an event of this class — lets hot
        paths skip constructing per-atom events entirely."""
        if not self._listeners:
            return False
        # dispatch walks event_class.__mro__, so a listener sees the event
        # iff it subscribed to event_class or one of its superclasses
        return any(
            issubclass(event_class, cls) and self._listeners[cls]
            for cls in self._listeners
        )
