"""HyperGraph — the graph kernel: atom CRUD + incidence maintenance.

Re-expression of the reference kernel (``core/src/java/org/hypergraphdb/
HyperGraph.java:92`` — ``add`` :641, ``get`` :784, ``addNode`` :1563,
``addLink`` :1589, incidence maintenance :1882) on the TPU-native columnar
design:

- Every datum is an **atom** identified by a dense int handle. A **link** is
  an atom that additionally holds an ordered tuple of target atoms (arity
  ≥ 0, links may target links — the hypergraph property,
  ``HyperGraph.java:64-75``).
- The stored atom record is ``(type_handle, value_handle, flags, *targets)``
  — the direct analogue of the reference layout ``[type, value, targets...]``
  (``HyperGraph.java:1571-1607``) plus a flags word to distinguish 0-arity
  links from nodes without an instanceof check.
- Two system indices are maintained on every add/replace/remove: by-type and
  by-value (``HyperGraph.java:110-114`` HGATOMTYPE/HGATOMVALUE), feeding the
  query planner and the device CSR type index.
- Reads are cached in a bounded LRU (the reference's ``WeakRefAtomCache``
  role); incidence sets are cached at the storage layer as sorted numpy
  snapshots that double as CSR pack input.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from hypergraphdb_tpu.core import events as ev
from hypergraphdb_tpu.core.config import HGConfiguration
from hypergraphdb_tpu.core.errors import HGException, NotFoundError
from hypergraphdb_tpu.core.handles import (
    NULL_HANDLE,
    HandleFactory,
    HGHandle,
    SequentialHandleFactory,
    UUIDHandleFactory,
)
from hypergraphdb_tpu.core.store import HGStore
from hypergraphdb_tpu.storage.api import HGSortedResultSet, StorageBackend
from hypergraphdb_tpu.tx.manager import HGTransactionManager
from hypergraphdb_tpu.types.system import HGTypeSystem
from hypergraphdb_tpu.utils.cache import LRUCache

_FLAG_LINK = 1

#: partitions.json on-disk layout marker; pre-versioning markers parse as 1
_PARTITION_MARKER_VERSION = 1

#: index names for the two system indices
IDX_BY_TYPE = "hg.bytype"
IDX_BY_VALUE = "hg.byvalue"
#: persistent type-name → type-atom-handle index (reopen recovery)
IDX_TYPE_NAME = "hg.typename"


@dataclass(frozen=True)
class HGLink:
    """A loaded link atom: its value + ordered targets.

    The reference models links as Java objects implementing ``HGLink``;
    here a link is plain data (functional style — nothing mutates in place).
    """

    targets: tuple[HGHandle, ...]
    value: Any = None

    @property
    def arity(self) -> int:
        return len(self.targets)

    def target_at(self, i: int) -> HGHandle:
        return self.targets[i]


class HyperGraph:
    """An open hypergraph database instance."""

    def __init__(
        self,
        config: Optional[HGConfiguration] = None,
        backend: Optional[StorageBackend] = None,
    ):
        self.config = config or HGConfiguration()
        if backend is None:
            backend = self._make_backend(self.config)
        self.backend = backend
        backend.startup()
        # captured BEFORE bootstrap creates type atoms: a truly fresh store
        # is stamped with the current format, never migrated
        self._fresh_store = backend.max_handle() == 0
        self.txman = HGTransactionManager(backend, enabled=self.config.transactional)
        self.store = HGStore(
            backend, self.txman,
            incidence_cache_entries=self.config.cache.incidence_cache_entries,
            max_cached_incidence_set_size=(
                self.config.cache.max_cached_incidence_set_size
            ),
        )
        if self.config.handle_factory == "uuid":
            self.handles: HandleFactory = UUIDHandleFactory()
        else:
            self.handles = SequentialHandleFactory()
        self.handles.reset(backend.max_handle())
        self.events = ev.HGEventManager()
        self._atom_cache: LRUCache = LRUCache(self.config.cache.atom_cache_size)
        from hypergraphdb_tpu.utils.metrics import Metrics

        # the tx manager mirrors its commit/abort/conflict counters into
        # this graph's registry (tx.* namespace); attached BEFORE the
        # typesystem bootstrap so the mirror counts the bootstrap commits
        # the legacy `txman.committed` attribute counts — no permanent
        # offset between the two surfaces
        self.metrics = Metrics()
        self.txman.metrics = self.metrics
        self.typesystem = HGTypeSystem(self)
        self.typesystem.bootstrap()
        self.stats = HGStats()
        self._snapshot_cache = None
        self._snapshot_mgr = None  # incremental mode (enable_incremental)
        self._mutations = 0  # bumped on every committed structural change
        self._memwatch = None
        if self.config.cache.memory_warning_bytes > 0:
            from hypergraphdb_tpu.utils.cache import MemoryWarningSystem

            self._memwatch = MemoryWarningSystem(
                self.config.cache.memory_warning_bytes,
                self.config.cache.memory_warning_interval_s,
            )
            self._memwatch.add_listener(self._atom_cache.clear)
            if self.store._inc_cache is not None:
                self._memwatch.add_listener(self.store._inc_cache.clear)
            self._memwatch.start()
        self._open = True
        try:
            # on-disk format check + migration chain (the reference's
            # maintenance upgrades) — BEFORE the loaders below, so a
            # migration may rewrite registry formats they then read
            from hypergraphdb_tpu.maintenance.migration import migrate

            migrate(self)
            # restore the database's self-knowledge from the store (the
            # reference's HGIndexManager.loadIndexers + class↔type index
            # recovery at open, HGTypeSystem.java:97-98): registered
            # indexers answer queries and the subtype closure is intact
            # after reopen
            from hypergraphdb_tpu.indexing.manager import load_indexers

            load_indexers(self)
            from hypergraphdb_tpu.atom.utilities import load_subsumptions

            load_subsumptions(self)
        except BaseException:
            # the deliberately-reachable refuse-to-open path (e.g. a
            # NEWER-format database) must not leak the started backend's
            # store lock or the memwatch thread
            if self._memwatch is not None:
                self._memwatch.stop()
                self._memwatch = None
            self._open = False
            self.backend.shutdown()
            raise
        self.events.dispatch(self, ev.HGOpenedEvent(graph=self))

    @staticmethod
    def _make_backend(config: HGConfiguration) -> StorageBackend:
        if config.store_backend == "native":
            try:
                from hypergraphdb_tpu.storage.native import NativeStorage
            except ImportError as e:
                raise HGException(
                    "the native (persistent) storage backend is not available "
                    "in this build; use store_backend='memory'"
                ) from e
            return NativeStorage(config.location or ".hgdb")
        if config.store_backend == "partitioned":
            # the hazelstore role: record/key-routed storage over N child
            # partitions (native WAL stores when a location is given)
            from hypergraphdb_tpu.storage.partitioned import PartitionedStorage

            if config.location:
                import json
                import os

                from hypergraphdb_tpu.storage.native import NativeStorage

                loc = config.location
                # the partition count is part of the on-disk layout:
                # handle routing is h % n, so reopening with a different n
                # would silently mis-route every record. First open
                # records it; later opens USE the recorded count.
                os.makedirs(loc, exist_ok=True)
                marker = os.path.join(loc, "partitions.json")
                if os.path.exists(marker):
                    with open(marker, encoding="utf-8") as f:
                        rec = json.load(f)
                    # pre-versioning markers (no stamp) parse as 1; an
                    # UNKNOWN layout version must hard-fail — guessing
                    # n here would silently mis-route every record
                    if rec.get("schema_version", 1) != _PARTITION_MARKER_VERSION:
                        raise HGException(
                            f"unsupported partition-marker schema in "
                            f"{marker}; this build reads version "
                            f"{_PARTITION_MARKER_VERSION}"
                        )
                    n = int(rec["n_partitions"])
                else:
                    n = int(config.n_partitions)
                    with open(marker, "w", encoding="utf-8") as f:
                        json.dump({
                            "schema_version": _PARTITION_MARKER_VERSION,
                            "n_partitions": n,
                        }, f)
                return PartitionedStorage(
                    n_partitions=n,
                    factory=lambda i: NativeStorage(
                        os.path.join(loc, f"part{i}")
                    ),
                )
            return PartitionedStorage(n_partitions=config.n_partitions)
        from hypergraphdb_tpu.storage.memstore import MemStorage

        return MemStorage()

    # ------------------------------------------------------------------ lifecycle
    def close(self) -> None:
        if not getattr(self, "_open", False):
            return
        self.events.dispatch(self, ev.HGClosingEvent(graph=self))
        if self._memwatch is not None:
            self._memwatch.stop()
            self._memwatch = None
        if self._snapshot_mgr is not None:
            self._snapshot_mgr.close()
            self._snapshot_mgr = None
        if getattr(self, "_type_column", None) is not None:
            self._type_column.close()
            self._type_column = None
        self.backend.shutdown()
        self._open = False

    def __enter__(self) -> "HyperGraph":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ add
    def add(
        self,
        value: Any = None,
        type: Optional[Any] = None,  # noqa: A002 - mirrors reference naming
        targets: Sequence[HGHandle] = (),
    ) -> HGHandle:
        """Add an atom (``HyperGraph.add`` :641). ``targets`` non-empty (or an
        ``HGLink`` value) makes it a link."""
        if isinstance(value, HGLink):
            targets = value.targets
            value = value.value
            return self.add_link(targets, value, type)
        if targets:
            return self.add_link(targets, value, type)
        return self.add_node(value, type)

    def add_node(self, value: Any, type: Optional[Any] = None) -> HGHandle:  # noqa: A002
        return self._add_atom(value, type, None)

    def add_link(
        self,
        targets: Sequence[HGHandle],
        value: Any = None,
        type: Optional[Any] = None,  # noqa: A002
    ) -> HGHandle:
        return self._add_atom(value, type, tuple(int(t) for t in targets))

    def _resolve_type_handle(self, value: Any, type_: Optional[Any]) -> HGHandle:
        if type_ is None:
            if value is None:
                return self.typesystem.handle_of("null")
            return self.typesystem.get_type_handle(value)
        if isinstance(type_, str):
            return self.typesystem.handle_of(type_)
        return int(type_)

    def _check_open(self) -> None:
        if not getattr(self, "_open", True):
            raise HGException("database is closed")

    def _add_atom(
        self, value: Any, type_: Optional[Any], targets: Optional[tuple[int, ...]]
    ) -> HGHandle:
        self._check_open()
        if (
            self.events.dispatch(self, ev.HGAtomProposeEvent(NULL_HANDLE, value))
            == ev.HGListener.CANCEL
        ):
            raise HGException("atom add vetoed by listener")
        type_handle = self._resolve_type_handle(value, type_)

        def run() -> HGHandle:
            h = self.handles.make()
            self._write_atom(h, type_handle, value, targets)
            return h

        h = self.txman.ensure_transaction(run)
        self._after_commit(lambda: self._committed_mutation(
            ev.HGAtomAddedEvent(h, value)))
        return h

    def _after_commit(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` now, or defer it to the enclosing transaction's commit
        (so listeners never observe atoms that never commit)."""
        tx = self.txman.current()
        if tx is None:
            fn()
        else:
            tx.on_commit.append(fn)

    def _committed_mutation(self, event: ev.HGEvent, n: int = 1) -> None:
        self._mutations += n
        self.metrics.incr("graph.mutations", n)
        self.events.dispatch(self, event)

    def _write_atom(
        self,
        h: HGHandle,
        type_handle: HGHandle,
        value: Any,
        targets: Optional[tuple[int, ...]],
    ) -> None:
        """The write path of ``addNode``/``addLink`` (:1563/:1589): store the
        value payload, the atom record, the system index entries and the
        per-target incidence entries."""
        atype = self.typesystem.get_type(type_handle)
        if value is None and atype.name == "null":
            value_handle = NULL_HANDLE
        else:
            value_handle = self.handles.make()
            self.store.store_data(value_handle, atype.store(value))
        flags = _FLAG_LINK if targets is not None else 0
        record = (int(type_handle), int(value_handle), flags) + (targets or ())
        self.store.store_link(h, record)
        by_type = self.store.get_index(IDX_BY_TYPE)
        by_type.add_entry(_type_key(type_handle), h)
        by_value = self.store.get_index(IDX_BY_VALUE)
        by_value.add_entry(atype.to_key(value), h)
        if targets:
            for t in targets:
                self.store.add_incidence_link(t, h)
        from hypergraphdb_tpu.indexing.manager import maybe_index

        maybe_index(self, h, type_handle, value, targets)

    def _find_type_atom(self, name: str) -> Optional[HGHandle]:
        """Look up a persisted type atom by name (reopen path: the class↔type
        index dbs of the reference, ``HGTypeSystem.java:97-98``)."""
        idx = self.store.get_index(IDX_TYPE_NAME, create=False)
        if idx is None:
            return None
        return idx.find_first(name.encode("utf-8"))

    def _add_type_atom(self, name: str) -> HGHandle:
        """Bootstrap-time creation of a type atom; the top type atom is its
        own type (the reference's Top, ``type/Top.java:25``)."""

        def run() -> HGHandle:
            h = self.handles.make()
            if name == "top":
                type_handle = h  # self-typed root
            else:
                type_handle = self.typesystem.handle_of("top")
            top = self.typesystem.top
            value_handle = self.handles.make()
            self.store.store_data(value_handle, top.store(name))
            record = (int(type_handle), int(value_handle), 0)
            self.store.store_link(h, record)
            self.store.get_index(IDX_BY_TYPE).add_entry(_type_key(type_handle), h)
            self.store.get_index(IDX_BY_VALUE).add_entry(top.to_key(name), h)
            self.store.get_index(IDX_TYPE_NAME).add_entry(name.encode("utf-8"), h)
            return h

        return self.txman.ensure_transaction(run)

    # ------------------------------------------------------------------ get
    def get(self, handle: HGHandle) -> Any:
        """Load an atom's runtime value (``HyperGraph.get`` :784): links load
        as ``HGLink``, nodes load as their bare value."""
        h = int(handle)
        # the shared cache holds committed state only: inside a transaction
        # reads bypass it entirely (the tx overlay may shadow the committed
        # value, and tx-local values must never leak into the cache)
        in_tx = self.txman.current() is not None
        if not in_tx and h in self._atom_cache:
            self.stats.atom_accesses += 1
            return self._atom_cache.get(h)
        rec = self.store.get_link(h)
        if rec is None:
            raise NotFoundError(h)
        type_handle, value_handle, flags = rec[0], rec[1], rec[2]
        atype = self.typesystem.get_type(type_handle)
        if value_handle == NULL_HANDLE:
            value = None
        else:
            data = self.store.get_data(value_handle)
            value = None if data is None else atype.make(data)
        if flags & _FLAG_LINK:
            value = HGLink(targets=tuple(rec[3:]), value=value)
        if not in_tx:
            self._atom_cache.put(h, value)
        self.stats.atom_loads += 1
        self.events.dispatch(self, ev.HGAtomLoadedEvent(h, value))
        return value

    def get_one(self, condition) -> Any:
        h = self.find_one(condition)
        return None if h is None else self.get(h)

    def get_type_handle_of(self, handle: HGHandle) -> HGHandle:
        rec = self.store.get_link(int(handle))
        if rec is None:
            raise NotFoundError(handle)
        return rec[0]

    def get_targets(self, handle: HGHandle) -> tuple[HGHandle, ...]:
        rec = self.store.get_link(int(handle))
        if rec is None:
            raise NotFoundError(handle)
        return tuple(rec[3:])

    def arity(self, handle: HGHandle) -> int:
        return len(self.get_targets(handle))

    def is_link(self, handle: HGHandle) -> bool:
        rec = self.store.get_link(int(handle))
        if rec is None:
            raise NotFoundError(handle)
        return bool(rec[2] & _FLAG_LINK)

    def contains(self, handle: HGHandle) -> bool:
        return self.store.contains_link(int(handle))

    # ------------------------------------------------------------------ replace
    def replace(
        self, handle: HGHandle, value: Any, type: Optional[Any] = None  # noqa: A002
    ) -> None:
        """Replace an atom's value in place, keeping identity and incidence
        (``HyperGraph.replace`` semantics). Targets of links are immutable —
        like the reference, changing structure means remove + add."""
        h = int(handle)
        if (
            self.events.dispatch(self, ev.HGAtomReplaceRequestEvent(h, value))
            == ev.HGListener.CANCEL
        ):
            raise HGException("atom replace vetoed by listener")

        def run() -> None:
            rec = self.store.get_link(h)
            if rec is None:
                raise NotFoundError(h)
            old_type_handle, old_value_handle, flags = rec[0], rec[1], rec[2]
            targets = tuple(rec[3:])
            old_type = self.typesystem.get_type(old_type_handle)
            if old_value_handle != NULL_HANDLE:
                data = self.store.get_data(old_value_handle)
                old_value = None if data is None else old_type.make(data)
            else:
                old_value = None
            inner = value.value if isinstance(value, HGLink) else value
            new_type_handle = self._resolve_type_handle(inner, type)
            new_type = self.typesystem.get_type(new_type_handle)
            # remove old value + index entries
            by_value = self.store.get_index(IDX_BY_VALUE)
            by_value.remove_entry(old_type.to_key(old_value), h)
            if old_value_handle != NULL_HANDLE:
                self.store.remove_data(old_value_handle)
            if new_type_handle != old_type_handle:
                by_type = self.store.get_index(IDX_BY_TYPE)
                by_type.remove_entry(_type_key(old_type_handle), h)
                by_type.add_entry(_type_key(new_type_handle), h)
            # store new value
            if inner is None and new_type.name == "null":
                new_value_handle = NULL_HANDLE
            else:
                new_value_handle = self.handles.make()
                self.store.store_data(new_value_handle, new_type.store(inner))
            by_value.add_entry(new_type.to_key(inner), h)
            record = (int(new_type_handle), int(new_value_handle), flags) + targets
            self.store.store_link(h, record)
            from hypergraphdb_tpu.indexing.manager import maybe_unindex, maybe_index

            maybe_unindex(self, h, old_type_handle, old_value, targets or None)
            maybe_index(self, h, new_type_handle, inner, targets or None)

        self.txman.ensure_transaction(run)
        self._atom_cache.invalidate(h)
        self._after_commit(lambda: self._committed_mutation(
            ev.HGAtomReplacedEvent(h, value)))

    # ------------------------------------------------------------------ remove
    def remove(self, handle: HGHandle, keep_incident_links: Optional[bool] = None) -> bool:
        """Remove an atom (``HyperGraph.remove``). By default incident links
        are removed recursively (reference default,
        ``HGConfiguration.keepIncidentLinksOnRemoval=false``); with
        ``keep_incident_links`` the atom is replaced by NULL in each
        incident link's target list? — No: like the reference, the incident
        links simply keep a dangling target cleared to null. We instead drop
        the atom from incident links' target tuples."""
        h = int(handle)
        self._check_open()
        if not self.store.contains_link(h):
            return False
        if self.typesystem.is_type_handle(h):
            # the reference likewise refuses to remove a type atom in use
            # (HGTypeSystem.remove checks instance/subtype indices)
            raise HGException(
                f"handle {h} is a registered type atom; types in use cannot "
                "be removed"
            )
        keep = (
            self.config.keep_incident_links_on_removal
            if keep_incident_links is None
            else keep_incident_links
        )

        removed: set[int] = set()
        rewritten: set[int] = set()
        vetoed: list[bool] = []

        def run() -> None:
            removed.clear()  # retry-safe
            rewritten.clear()
            vetoed.clear()
            # the remove-request veto runs INSIDE the removal transaction:
            # a listener guarding an atom (e.g. HGAtomRef pin counting,
            # atom/utilities.py) must see transactionally-consistent state,
            # and no commit may interleave between its verdict and the
            # removal itself (ADVICE r2: pin-release invariant break)
            if (
                self.events.dispatch(self, ev.HGAtomRemoveRequestEvent(h))
                == ev.HGListener.CANCEL
            ):
                vetoed.append(True)
                return
            self._remove_rec(h, keep, removed, rewritten)

        self.txman.ensure_transaction(run)
        if vetoed:
            return False

        def fire() -> None:
            # one event per removed atom (cascade included) — delta overlays
            # and replication listeners need to see every tombstone
            self._committed_mutation(ev.HGAtomRemovedEvent(h))
            for other in removed - {h}:
                self._committed_mutation(ev.HGAtomRemovedEvent(other))
            # keep_incident_links rewrote these links' target tuples in
            # place: snapshot overlays must learn their columns are stale
            for link in rewritten - removed:
                self._committed_mutation(ev.HGAtomReplacedEvent(link))

        self._after_commit(fire)
        return True

    def _remove_rec(self, h: int, keep: bool, seen: set[int],
                    rewritten: Optional[set[int]] = None,
                    root: bool = True) -> None:
        if rewritten is None:
            rewritten = set()
        if h in seen:
            return
        seen.add(h)
        rec = self.store.get_link(h)
        if rec is None:
            return
        # cascaded atoms get the same veto chance as the root (the root's
        # event fired in remove()); a veto mid-cascade aborts the whole
        # removal — partial cascades would leave dangling targets
        if not root and (
            self.events.dispatch(self, ev.HGAtomRemoveRequestEvent(h))
            == ev.HGListener.CANCEL
        ):
            raise HGException(
                f"cascade removal of atom {h} vetoed by listener"
            )
        type_handle, value_handle, flags = rec[0], rec[1], rec[2]
        targets = tuple(rec[3:])
        # incident links: either cascade-remove or rewrite their target lists
        incident = self.store.get_incidence_set(h).array().tolist()
        for link in incident:
            if not keep:
                self._remove_rec(int(link), keep, seen, rewritten, root=False)
            else:
                link = int(link)
                lrec = self.store.get_link(link)
                if lrec is None:
                    continue
                old_targets = tuple(lrec[3:])
                newt = tuple(t for t in old_targets if t != h)
                # re-run user indexers: target positions shift
                lvalue = self._load_value(lrec)
                from hypergraphdb_tpu.indexing.manager import (
                    maybe_index,
                    maybe_unindex,
                )

                maybe_unindex(self, link, lrec[0], lvalue, old_targets)
                self.store.store_link(link, lrec[:3] + newt)
                maybe_index(self, link, lrec[0], lvalue, newt)
                self._atom_cache.invalidate(link)
                rewritten.add(link)
        # de-index
        atype = self.typesystem.get_type(type_handle)
        if value_handle != NULL_HANDLE:
            data = self.store.get_data(value_handle)
            value = None if data is None else atype.make(data)
            self.store.remove_data(value_handle)
        else:
            value = None
        self.store.get_index(IDX_BY_TYPE).remove_entry(_type_key(type_handle), h)
        self.store.get_index(IDX_BY_VALUE).remove_entry(atype.to_key(value), h)
        from hypergraphdb_tpu.indexing.manager import maybe_unindex

        maybe_unindex(self, h, type_handle, value, targets or None)
        # purge subgraph memberships (member entries AND, if the atom is
        # itself a subgraph, its whole member list)
        from hypergraphdb_tpu.atom.subgraph import IDX_SUBGRAPH, member_key

        sub_idx = self.store.get_index(IDX_SUBGRAPH, create=False)
        if sub_idx is not None:
            for key in sub_idx.find_by_value(h):
                sub_idx.remove_entry(key, h)
            sub_idx.remove_all_entries(member_key(h))
        # un-link from target incidence sets
        for t in targets:
            self.store.remove_incidence_link(t, h)
        self.store.remove_incidence_set(h)
        self.store.remove_link(h)
        self._atom_cache.invalidate(h)

    def _load_value(self, rec: tuple) -> Any:
        """Deserialize the bare value of a stored atom record."""
        type_handle, value_handle = rec[0], rec[1]
        atype = self.typesystem.get_type(type_handle)
        if value_handle == NULL_HANDLE:
            return None
        data = self.store.get_data(value_handle)
        return None if data is None else atype.make(data)

    # ------------------------------------------------------------------ incidence
    def get_incidence_set(self, handle: HGHandle) -> HGSortedResultSet:
        """All links pointing at ``handle`` (``HyperGraph.getIncidenceSet``
        :1415), sorted — the primitive BFS and joins build on."""
        return self.store.get_incidence_set(int(handle))

    # ------------------------------------------------------------------ queries
    @staticmethod
    def _compiler():
        try:
            from hypergraphdb_tpu.query.compiler import compile_query
        except ImportError as e:  # pragma: no cover - build gating
            raise HGException("query engine not available in this build") from e
        return compile_query

    def find_all(self, condition) -> list[HGHandle]:
        return list(self._compiler()(self, condition).execute())

    def find_one(self, condition) -> Optional[HGHandle]:
        for h in self._compiler()(self, condition).execute():
            return h
        return None

    def count(self, condition) -> int:
        return self._compiler()(self, condition).count()

    # ------------------------------------------------------------------ scans
    def atoms(self) -> Iterator[HGHandle]:
        """All atom handles, ascending (committed state)."""
        ids, _, _ = self.backend.bulk_links()
        tx = self.txman.current()
        if tx is None:
            yield from ids.tolist()
            return
        from hypergraphdb_tpu.tx.manager import _TOMBSTONE

        # merge the whole tx chain, outermost first (inner shadows outer)
        overlay: dict[int, Any] = {}
        chain = []
        t = tx
        while t is not None:
            chain.append(t)
            t = t.parent
        for t in reversed(chain):
            overlay.update(t.links)
        extra = {h for h, v in overlay.items() if v is not _TOMBSTONE}
        dead = {h for h, v in overlay.items() if v is _TOMBSTONE}
        merged = sorted((set(ids.tolist()) - dead) | extra)
        yield from merged

    def atom_count(self) -> int:
        return sum(1 for _ in self.atoms())

    # ------------------------------------------------------------------ bulk ingest
    def add_nodes_bulk(self, values: Sequence[Any], type: Optional[Any] = None) -> range:  # noqa: A002
        """Contiguous-id bulk node ingest (TPU fast path, no reference
        analogue — dense ids make contiguous ranges valuable for CSR)."""

        def run() -> range:
            r = self.handles.make_many(len(values))
            for h, v in zip(r, values):
                th = self._resolve_type_handle(v, type)
                self._write_atom(h, th, v, None)
            return r

        r = self.txman.ensure_transaction(run)

        def fire() -> None:
            if self.events.has_listeners_for(ev.HGAtomAddedEvent):
                for h, v in zip(r, values):
                    self._committed_mutation(ev.HGAtomAddedEvent(h, v))
            else:  # bulk fast path: one counter bump, no per-atom events
                self._mutations += len(values)
                self.metrics.incr("graph.mutations", len(values))

        self._after_commit(fire)
        return r

    def add_links_bulk(
        self,
        target_lists: Sequence[Sequence[HGHandle]],
        values: Optional[Sequence[Any]] = None,
        type: Optional[Any] = None,  # noqa: A002
    ) -> range:
        def run() -> range:
            r = self.handles.make_many(len(target_lists))
            for i, (h, ts) in enumerate(zip(r, target_lists)):
                v = values[i] if values is not None else None
                th = self._resolve_type_handle(v, type)
                self._write_atom(h, th, v, tuple(int(t) for t in ts))
            return r

        r = self.txman.ensure_transaction(run)

        def fire() -> None:
            if self.events.has_listeners_for(ev.HGAtomAddedEvent):
                for i, h in enumerate(r):
                    v = values[i] if values is not None else None
                    self._committed_mutation(ev.HGAtomAddedEvent(h, v))
            else:  # bulk fast path: one counter bump, no per-atom events
                self._mutations += len(target_lists)
                self.metrics.incr("graph.mutations", len(target_lists))

        self._after_commit(fire)
        return r

    def bulk_import(self, values=None, target_lists=None, type=None):  # noqa: A002
        """High-throughput single-type batch ingest (see ``core/bulkload``)."""
        from hypergraphdb_tpu.core.bulkload import bulk_import

        return bulk_import(self, values=values, target_lists=target_lists,
                           type=type)

    # ------------------------------------------------------------------ device snapshot
    def enable_incremental(self, headroom: float = 2.0,
                           compact_ratio: float = 0.5,
                           background: bool = True, **kw):
        """Switch to incremental snapshot mode (BASELINE config 5): from
        now on ``snapshot()`` returns the current immutable BASE of an
        (base, delta) pair maintained by a :class:`SnapshotManager` — no
        full repack on mutation. Device query plans merge the delta at
        read time (LSM model), so query answers stay exact while ingest
        runs. Returns the manager."""
        if self._snapshot_mgr is None:
            from hypergraphdb_tpu.ops.incremental import SnapshotManager

            self._snapshot_mgr = SnapshotManager(
                self, headroom=headroom, compact_ratio=compact_ratio,
                background=background, **kw,
            )
        return self._snapshot_mgr

    @property
    def incremental(self):
        """The active SnapshotManager, or None (exact-snapshot mode)."""
        return self._snapshot_mgr

    def type_column(self):
        """The hot host-side handle→type column (lazily built; see
        ``utils/typecolumn.py`` — the typed-incidence annotation of the
        reference's bdb-native extension)."""
        if getattr(self, "_type_column", None) is None:
            from hypergraphdb_tpu.utils.typecolumn import TypeColumn

            self._type_column = TypeColumn(self)
        return self._type_column

    def snapshot(self, refresh: bool = False):
        """Pack (or return the cached) immutable device CSR snapshot — a
        long-lived read transaction living in HBM (SURVEY §7). In
        incremental mode the current base is returned (bounded-stale;
        pair with ``graph.incremental.correction()`` for exact reads —
        the query planner's device plans do this automatically)."""
        try:
            from hypergraphdb_tpu.ops.snapshot import CSRSnapshot
        except ImportError as e:  # pragma: no cover - build gating
            raise HGException("device snapshots not available in this build") from e

        if self._snapshot_mgr is not None and not refresh:
            self.metrics.incr("snapshot.cache_hits")
            return self._snapshot_mgr.base

        snap = self._snapshot_cache
        if snap is not None and not refresh and snap.version == self._mutations:
            self.metrics.incr("snapshot.cache_hits")
            return snap
        with self.metrics.timer("snapshot.pack"):
            snap = CSRSnapshot.pack(self, version=self._mutations)
        self.metrics.gauge("snapshot.num_atoms", snap.num_atoms)
        self.metrics.gauge("snapshot.incidence_edges", snap.n_edges_inc)
        self._snapshot_cache = snap
        return snap

    # ------------------------------------------------------------------ misc
    def type_handle(self, name_or_class) -> HGHandle:
        if isinstance(name_or_class, type):
            t = self.typesystem.infer(name_or_class())  # pragma: no cover
            return self.typesystem.handle_of(t.name)
        return self.typesystem.handle_of(name_or_class)


@dataclass
class HGStats:
    """Access counters (reference: ``atom/HGStats.java:20``)."""

    atom_accesses: int = 0
    atom_loads: int = 0


def _type_key(type_handle: HGHandle) -> bytes:
    from hypergraphdb_tpu.utils.ordered_bytes import encode_int

    return encode_int(int(type_handle))
