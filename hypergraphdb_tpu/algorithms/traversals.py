"""Graph traversals: BFS/DFS iterators + adjacency-list generators.

Re-expression of the reference's ``algorithms/`` package:
``HGTraversal`` — an iterator of (parent-link, atom) pairs
(``algorithms/HGTraversal.java:36``), ``HGBreadthFirstTraversal.java:29``
(queue + examined map, advance :49-66), ``HGDepthFirstTraversal.java:28``,
and the adjacency generators ``HGALGenerator``/``SimpleALGenerator.java:27``/
``DefaultALGenerator.java:73`` (link & sibling predicates, ordered-link
direction options, generate :504-509).

These are the *host-plane* semantics oracle. The device plane runs the same
frontier expansion as batched CSR message passing (``ops/frontier.py``);
``TraversalPlan`` in the query compiler picks between them.
"""

from __future__ import annotations

import heapq
from collections import deque
from typing import Callable, Iterator, Optional

from hypergraphdb_tpu.core.errors import NotFoundError
from hypergraphdb_tpu.core.handles import HGHandle

LinkPredicate = Callable[["HyperGraph", HGHandle], bool]  # noqa: F821
AtomPredicate = Callable[["HyperGraph", HGHandle], bool]  # noqa: F821


class HGALGenerator:
    """Adjacency-list generator: for an atom, yield (link, neighbor) pairs."""

    def generate(self, atom: HGHandle) -> Iterator[tuple[HGHandle, HGHandle]]:
        raise NotImplementedError


class SimpleALGenerator(HGALGenerator):
    """All siblings through all incident links (``SimpleALGenerator.java:27``)."""

    def __init__(self, graph):
        self.graph = graph

    def generate(self, atom):
        atom = int(atom)
        for link in self.graph.get_incidence_set(atom):
            for t in self.graph.get_targets(link):
                if t != atom:
                    yield (int(link), int(t))


class DefaultALGenerator(HGALGenerator):
    """Filtered/directed adjacency (``DefaultALGenerator.java:73``):

    - ``link_predicate`` filters which incident links are followed,
    - ``sibling_predicate`` filters which neighbors are yielded,
    - ``return_preceeding``/``return_succeeding`` restrict, for *ordered*
      links, to targets before/after the source atom's position (the
      directed-hyperedge options),
    - ``reverse_order`` walks a link's targets backwards.
    """

    def __init__(
        self,
        graph,
        link_predicate: Optional[LinkPredicate] = None,
        sibling_predicate: Optional[AtomPredicate] = None,
        return_preceeding: bool = True,
        return_succeeding: bool = True,
        reverse_order: bool = False,
    ):
        self.graph = graph
        self.link_predicate = link_predicate
        self.sibling_predicate = sibling_predicate
        self.return_preceeding = return_preceeding
        self.return_succeeding = return_succeeding
        self.reverse_order = reverse_order

    def generate(self, atom):
        g = self.graph
        atom = int(atom)
        for link in g.get_incidence_set(atom):
            link = int(link)
            if self.link_predicate is not None and not self.link_predicate(g, link):
                continue
            targets = g.get_targets(link)
            # positions of the source atom in the link (may repeat)
            pos = [i for i, t in enumerate(targets) if t == atom]
            if not pos:
                continue
            lo, hi = min(pos), max(pos)
            order = range(len(targets) - 1, -1, -1) if self.reverse_order else range(
                len(targets)
            )
            for i in order:
                t = targets[i]
                if t == atom:
                    continue
                if not self.return_preceeding and i < hi:
                    continue
                if not self.return_succeeding and i > lo:
                    continue
                if self.sibling_predicate is not None and not self.sibling_predicate(
                    g, t
                ):
                    continue
                yield (link, int(t))


class HGTraversal:
    """Base traversal iterator of (parent_link, atom) pairs; the start atom
    itself is not yielded (reference contract)."""

    def __init__(
        self,
        graph,
        start: HGHandle,
        generator: Optional[HGALGenerator] = None,
        max_distance: Optional[int] = None,
    ):
        self.graph = graph
        self.start = int(start)
        self.generator = generator or SimpleALGenerator(graph)
        self.max_distance = max_distance

    def __iter__(self) -> Iterator[tuple[Optional[HGHandle], HGHandle]]:
        raise NotImplementedError


class HGBreadthFirstTraversal(HGTraversal):
    """Queue-based BFS (``HGBreadthFirstTraversal.java:29``)."""

    def __iter__(self):
        visited = {self.start}
        q: deque[tuple[int, int]] = deque([(self.start, 0)])
        while q:
            atom, dist = q.popleft()
            if self.max_distance is not None and dist >= self.max_distance:
                continue
            for link, nbr in self.generator.generate(atom):
                if nbr in visited:
                    continue
                visited.add(nbr)
                yield (link, nbr)
                q.append((nbr, dist + 1))


class HGDepthFirstTraversal(HGTraversal):
    """Stack-based DFS (``HGDepthFirstTraversal.java:28``)."""

    def __iter__(self):
        if self.max_distance is not None and self.max_distance <= 0:
            return
        visited = {self.start}
        # stack of (parent_link, atom, distance); yield on pop = preorder DFS
        stack: list[tuple[int, int, int]] = [
            (link, nbr, 1)
            for link, nbr in reversed(list(self.generator.generate(self.start)))
        ]
        while stack:
            link, atom, dist = stack.pop()
            if atom in visited:
                continue
            visited.add(atom)
            yield (link, atom)
            if self.max_distance is None or dist < self.max_distance:
                nbrs = list(self.generator.generate(atom))
                for l, n in reversed(nbrs):
                    if n not in visited:
                        stack.append((l, n, dist + 1))


class HyperTraversal:
    """Link-as-node flattened traversal (``HyperTraversal.java:33``): yields
    both atoms and the links between them as visited nodes."""

    def __init__(self, graph, start: HGHandle, max_distance: Optional[int] = None):
        self.graph = graph
        self.start = int(start)
        self.max_distance = max_distance

    def __iter__(self):
        visited = {self.start}
        q: deque[tuple[int, int]] = deque([(self.start, 0)])
        while q:
            node, dist = q.popleft()
            if self.max_distance is not None and dist >= self.max_distance:
                continue
            neighbors: list[tuple[int, int]] = []
            for link in self.graph.get_incidence_set(node):
                neighbors.append((int(link), int(link)))
            try:
                for t in self.graph.get_targets(node):
                    neighbors.append((node, int(t)))
            except NotFoundError:
                pass  # a plain atom in the frontier has no targets —
                # anything ELSE (storage fault, evaluation bug) propagates
            for parent, nbr in neighbors:
                if nbr in visited:
                    continue
                visited.add(nbr)
                yield (parent, nbr)
                q.append((nbr, dist + 1))


# ---------------------------------------------------------------- classics


def dijkstra(
    graph,
    start: HGHandle,
    goal: HGHandle,
    generator: Optional[HGALGenerator] = None,
    weight: Optional[Callable[[HGHandle], float]] = None,
) -> Optional[list[HGHandle]]:
    """Shortest path (``GraphClassics.dijkstra`` :80). Returns the atom path
    start..goal or None. ``weight`` maps a link handle to its edge weight."""
    gen = generator or SimpleALGenerator(graph)
    start, goal = int(start), int(goal)
    dist: dict[int, float] = {start: 0.0}
    prev: dict[int, int] = {}
    heap: list[tuple[float, int]] = [(0.0, start)]
    done: set[int] = set()
    while heap:
        d, atom = heapq.heappop(heap)
        if atom in done:
            continue
        done.add(atom)
        if atom == goal:
            path = [goal]
            while path[-1] != start:
                path.append(prev[path[-1]])
            return list(reversed(path))
        for link, nbr in gen.generate(atom):
            w = 1.0 if weight is None else float(weight(link))
            nd = d + w
            if nd < dist.get(nbr, float("inf")):
                dist[nbr] = nd
                prev[nbr] = atom
                heapq.heappush(heap, (nd, nbr))
    return None


def has_cycles(graph, start: HGHandle, generator: Optional[HGALGenerator] = None) -> bool:
    """Cycle detection from a start atom (``GraphClassics.hasCycles`` :40),
    treating generated adjacency as directed edges."""
    gen = generator or SimpleALGenerator(graph)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict[int, int] = {}

    def visit(a: int) -> bool:
        color[a] = GRAY
        for _, nbr in gen.generate(a):
            st = color.get(nbr, WHITE)
            if st == GRAY:
                return True
            if st == WHITE and visit(nbr):
                return True
        color[a] = BLACK
        return False

    return visit(int(start))
