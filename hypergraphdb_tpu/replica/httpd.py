"""The HTTP skin of the replicated tier: ``POST /submit`` + ``GET /healthz``
(+ the hgsub subscription surface: ``POST /subscribe``,
``GET /notifications``).

One tiny stdlib server class worn twice:

- each **replica node** (and the primary) runs a :class:`SubmitServer`
  over its own serve runtime — the per-node submit surface the front
  door forwards to (:class:`~hypergraphdb_tpu.replica.router.HTTPBackend`);
- the **front door** runs a :class:`SubmitServer` whose submit function
  IS :meth:`~hypergraphdb_tpu.replica.router.FrontDoor.submit` — the
  one URL callers see.

**Subscriptions** ride the same port when a handler is wired
(``subscribe_fn`` / ``poll_fn``): ``POST /subscribe`` takes the
``sub/wire`` subscribe/unsubscribe envelopes, ``GET
/notifications?id=<sid>&timeout_s=<s>&max=<n>`` long-polls one
subscription's delta queue (the poll parks INSIDE the handler thread —
``ThreadingHTTPServer`` gives each poll its own; ``sub/wire`` clamps
the park below the handler's socket timeout). Nodes without the
subscription tier answer 404, which the front door reads as "route
elsewhere".

Status mapping (what :class:`~.router.HTTPBackend` keys its typed
errors off)::

    200  answered                      (JSON ServeResult shape)
    400  Unservable / malformed        (the REQUEST is the problem)
    503  AdmissionGated / QueueFull /  (the NODE is — re-route)
         RuntimeClosed
    504  DeadlineExceeded              (the budget is — propagate)
    500  anything else                 (bug — re-route + investigate)

Error bodies are JSON ``{"error": <type name>, "message": <str>}`` so
routers can distinguish a lag-gate refusal from a real failure without
string-matching prose. ``/metrics`` stays the
:class:`~hypergraphdb_tpu.obs.http.TelemetryServer`'s job — run one
beside this per process; ``/healthz`` is duplicated here because the
front door and load balancers need it ON the submit port.

**Fleet views** (``SubmitServer(fleet=FleetCollector(...))`` — the
front door wears them): ``GET /fleet/metrics`` (per-node-labelled
merged exposition), ``/fleet/healthz`` (worst-of + per-node detail),
``/fleet/slo`` (error-budget burn state), ``/fleet/perf`` (per-node
perf-sentinel verdicts + violation map), ``/fleet/plan`` (per-node
hgplan correction state), and ``/fleet/traces/<tid>``
(one cross-process span tree stitched from every node's half) ride the
same port as ``/submit``, so the fleet is observed through the URL
callers already use. ``POST /submit {"explain": true}`` adds the
answering node's per-request cost-attribution record to the response.

No jax imports; handlers hold no runtime locks (``submit`` blocks on
the request's future only), so a slow request never stalls a scrape.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from hypergraphdb_tpu.obs.http import HealthProbe
from hypergraphdb_tpu.serve.types import (
    AdmissionGated,
    DeadlineExceeded,
    QueueFull,
    RuntimeClosed,
    Unservable,
)

#: exception type → HTTP status (first match wins, order matters:
#: subclasses before ServeError-wide defaults). Coverage is statically
#: enforced: hglint HG1104 flags any in-tree subclass of a family root
#: mapped here that has no entry of its own — a new typed refusal must
#: be added or it degrades to the generic 500 and loses its round-trip.
_STATUS = (
    (AdmissionGated, 503),
    (QueueFull, 503),
    (RuntimeClosed, 503),
    (DeadlineExceeded, 504),
    (Unservable, 400),
    ((KeyError, ValueError, TypeError), 400),
)


def _status_of(exc: BaseException) -> int:
    for types, code in _STATUS:
        if isinstance(exc, types):
            return code
    return 500


class _Handler(BaseHTTPRequestHandler):
    timeout = 30  # never block the handler thread on a half-open client

    def _respond(self, status: int, payload: dict) -> None:
        body = (json.dumps(payload, sort_keys=True) + "\n").encode("utf-8")
        self._respond_raw(status, body, "application/json")

    def _respond_raw(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        srv: "SubmitServer" = self.server.submit_server  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if srv.fleet is not None and path.startswith("/fleet/"):
            try:
                self._do_fleet(srv.fleet, path)
            except Exception as e:  # noqa: BLE001 - a broken view ≠ dead door
                self._respond(500, {"error": type(e).__name__,
                                    "message": str(e)})
            return
        if path == "/notifications":
            if srv.poll_fn is None:
                self._respond(404, {"error": "NotFound",
                                    "message": "no subscription tier"})
                return
            from urllib.parse import parse_qs, urlsplit

            q = parse_qs(urlsplit(self.path).query)
            params = {k: v[0] for k, v in q.items() if v}
            try:
                result = srv.poll_fn(params)
            except BaseException as e:  # noqa: BLE001 - typed status map
                self._respond(_status_of(e), {"error": type(e).__name__,
                                              "message": str(e)})
                if not isinstance(e, Exception):
                    raise
                return
            self._respond(200, result)
            return
        if path != "/healthz":
            self._respond(404, {"error": "NotFound", "message": path})
            return
        try:
            healthy, payload = (srv.health() if srv.health is not None
                                else (True, {}))
        except Exception as e:  # noqa: BLE001 - a broken probe ≠ dead server
            self._respond(500, {"error": type(e).__name__,
                                "message": str(e)})
            return
        self._respond(200 if healthy else 503, payload)

    def _do_fleet(self, fleet, path: str) -> None:
        """The fleet views ON the door's port: the operator asks the one
        URL callers already use. ``fleet`` is a
        :class:`~hypergraphdb_tpu.obs.fleet.FleetCollector`."""
        if path == "/fleet/metrics":
            self._respond_raw(
                200, fleet.fleet_metrics().encode("utf-8"),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/fleet/healthz":
            healthy, payload = fleet.fleet_healthz()
            self._respond(200 if healthy else 503, payload)
        elif path == "/fleet/slo":
            if fleet.slo is None:
                self._respond(404, {"error": "NotFound",
                                    "message": "no SLO monitor attached"})
            else:
                self._respond(200, fleet.slo.snapshot())
        elif path == "/fleet/perf":
            self._respond(200, fleet.fleet_perf())
        elif path == "/fleet/plan":
            self._respond(200, fleet.fleet_plan())
        elif path == "/fleet/traces":
            self._respond(200, {"traces": fleet.fleet_traces()})
        elif path.startswith("/fleet/traces/"):
            tail = path[len("/fleet/traces/"):]
            try:
                tid = int(tail)
            except ValueError:
                self._respond(400, {"error": "ValueError",
                                    "message": f"bad trace id {tail!r}"})
                return
            joined = fleet.fleet_trace(tid)
            if joined is None:
                self._respond(404, {"error": "NotFound",
                                    "message": f"unknown trace {tid}"})
            else:
                self._respond(200, joined)
        else:
            self._respond(404, {"error": "NotFound", "message": path})

    def do_POST(self) -> None:  # noqa: N802
        srv: "SubmitServer" = self.server.submit_server  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/subscribe":
            fn = srv.subscribe_fn
        elif path == "/submit":
            fn = srv.submit_fn
        else:
            self._respond(404, {"error": "NotFound", "message": path})
            return
        if fn is None:
            self._respond(404, {"error": "NotFound",
                                "message": "no subscription tier"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n).decode("utf-8"))
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
        except Exception as e:  # noqa: BLE001 - unparsable body
            self._respond(400, {"error": type(e).__name__,
                                "message": str(e)})
            return
        try:
            result = fn(payload)
        except BaseException as e:  # noqa: BLE001 - typed status mapping
            self._respond(_status_of(e), {"error": type(e).__name__,
                                          "message": str(e)})
            if not isinstance(e, Exception):
                raise  # a real kill (InjectedCrash) must still kill
            return
        self._respond(200, result)

    def log_message(self, fmt, *args) -> None:  # requests are not news
        pass


class SubmitServer:
    """The submit endpoint thread (``port=0`` binds ephemeral; read it
    back from ``.port``). ``submit_fn`` takes the decoded JSON payload
    and returns the response dict — wire
    ``lambda p: submit_payload(node.runtime, p, timeout)`` for a node,
    or ``frontdoor.submit`` for the router. Lifecycle mirrors
    ``obs.http.TelemetryServer`` (start/stop or context manager; stop
    releases the port; no restart after stop)."""

    def __init__(self, submit_fn: Callable[[dict], dict],
                 health: Optional[HealthProbe] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 fleet=None,
                 subscribe_fn: Optional[Callable[[dict], dict]] = None,
                 poll_fn: Optional[Callable[[dict], dict]] = None):
        self.submit_fn = submit_fn
        self.health = health
        #: hgsub surface: ``POST /subscribe`` body → response envelope,
        #: and ``GET /notifications`` query params → poll envelope.
        #: None (the default) answers 404 on both paths.
        self.subscribe_fn = subscribe_fn
        self.poll_fn = poll_fn
        #: optional hgobs FleetCollector: serves /fleet/metrics,
        #: /fleet/healthz, /fleet/slo, /fleet/perf, /fleet/plan,
        #: /fleet/traces[/<tid>] ON this
        #: port — the front door wears it so the fleet is operated
        #: through the same URL callers submit to
        self.fleet = fleet
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._server.submit_server = self  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "SubmitServer":
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    "SubmitServer was stopped (port released); "
                    "construct a new one"
                )
            if self._thread is not None:
                return self
            self._thread = t = threading.Thread(
                target=self._server.serve_forever,
                name=f"hg-submit-{self.port}", daemon=True,
            )
        t.start()
        return self

    def stop(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            t, self._thread = self._thread, None
        if t is not None:
            self._server.shutdown()
            t.join(timeout=5)
        self._server.server_close()

    def __enter__(self) -> "SubmitServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def node_server(node, timeout_s: float = 30.0,
                host: str = "127.0.0.1", port: int = 0,
                authoritative: bool = False) -> SubmitServer:
    """A replica node's submit endpoint: runtime + health in one call.
    ``authoritative=True`` marks a PRIMARY's endpoint: an unknown gid
    answers 400 (the gid is wrong) instead of 503 (merely not here yet).
    Explain responses are stamped with the node's peer identity. When
    the node's runtime carries an hgsub ``SubscriptionManager``
    (``runtime.subscriptions``), the subscription surface is served
    beside ``/submit``."""
    from hypergraphdb_tpu.replica.router import submit_payload

    ident = getattr(getattr(node, "peer", None), "identity", None)
    subscribe_fn = poll_fn = None
    if getattr(node.runtime, "subscriptions", None) is not None:
        from hypergraphdb_tpu.sub.wire import (
            poll_payload,
            subscribe_payload,
        )

        subscribe_fn = (
            lambda p: subscribe_payload(node.runtime.subscriptions, p)
        )
        poll_fn = lambda p: poll_payload(node.runtime.subscriptions, p)
    return SubmitServer(
        lambda p: submit_payload(node.runtime, p, timeout_s,
                                 authoritative=authoritative,
                                 node_id=ident),
        health=node.health_probe(), host=host, port=port,
        subscribe_fn=subscribe_fn, poll_fn=poll_fn,
    )


def frontdoor_server(frontdoor, host: str = "127.0.0.1",
                     port: int = 0, fleet=None) -> SubmitServer:
    """The front door's public endpoint; pass a
    :class:`~hypergraphdb_tpu.obs.fleet.FleetCollector` as ``fleet`` to
    serve the ``/fleet/*`` views beside ``/submit``. Subscriptions are
    routed (and re-anchored across failover) by the door itself."""
    return SubmitServer(frontdoor.submit, health=frontdoor.health_probe(),
                        host=host, port=port, fleet=fleet,
                        subscribe_fn=frontdoor.subscribe,
                        poll_fn=frontdoor.poll)
