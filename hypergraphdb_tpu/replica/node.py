"""ReplicaNode: an ingest-following peer that SERVES.

Lifecycle (the bootstrap → follow → serve chain)::

    node = ReplicaNode(graph, peer, ReplicaConfig(primary="primary-id"))
    node.start()          # 1. bootstrap  2. follow  3. serve
    fut = node.runtime.submit_bfs(seed)     # reads, lag-bounded
    node.stop()

1. **bootstrap** — publish a full interest (pushes start flowing at
   once, applied idempotently), then pull the primary's whole graph via
   the resumable snapshot transfer (``peer/transfer`` + ``cact``). A
   node whose SeenMap already anchors the primary (a REJOIN after a
   crash or restart) skips the transfer and resumes by incremental
   catch-up — unless the primary's log truncated past it
   (``needs_full_sync``), which forces the clean re-bootstrap.
2. **follow** — replication pushes + gap-aware catch-up keep the local
   graph converging; a periodic anti-entropy digest probe is the
   backstop for losses no later push ever reveals.
3. **serve** — the node's own :class:`~hypergraphdb_tpu.serve.ServeRuntime`
   answers reads over the LOCAL graph. The runtime's ``admission_gate``
   is wired to the replication lag: a replica more than
   ``max_replication_lag`` log entries behind the primary refuses with
   :class:`~hypergraphdb_tpu.serve.AdmissionGated` — the cross-process
   mirror of the single-node ``max_lag_edges`` staleness contract
   (bounded-stale inside one process, bounded-lag across processes;
   both bound how far an answer may trail the ingest front).

``/healthz``: :meth:`ReplicaNode.health_probe` stacks the replica story
(role, advertised lag, lag bound, bootstrap state) on top of the
standard :func:`~hypergraphdb_tpu.obs.http.runtime_health` breaker/queue
view via :func:`~hypergraphdb_tpu.obs.http.composite_health` — the
fields the front door's placement reads (``replication_lag``,
``read_gate``) ride the same JSON body an operator already scrapes.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from hypergraphdb_tpu.obs.http import (
    HealthProbe,
    composite_health,
    runtime_health,
)
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime


@dataclass
class ReplicaConfig:
    """Knobs of one replica node."""

    #: the primary's peer identity (who to bootstrap from and follow)
    primary: str
    #: reads gate once the replica trails the primary's log by more
    #: than this many entries — the staleness contract across processes
    max_replication_lag: int = 256
    #: anti-entropy digest cadence (0 disables the loop; gap repair via
    #: contiguity tracking still runs on every apply cycle)
    anti_entropy_interval_s: float = 0.5
    bootstrap_page: int = 256
    bootstrap_timeout_s: float = 120.0
    #: snapshot-transfer stall watchdog: re-pull after this much silence,
    #: up to ``bootstrap_max_resumes`` consecutive no-progress resumes
    #: before the bootstrap fails typed (``TransientFault``) — the knobs
    #: of :meth:`~hypergraphdb_tpu.peer.peer.HyperGraphPeer.transfer_graph_from`
    bootstrap_retry_after_s: float = 1.0
    bootstrap_max_resumes: int = 8
    #: serving knobs for the node's own runtime (``admission_gate`` is
    #: overwritten with the replica's lag gate)
    serve: ServeConfig = field(default_factory=ServeConfig)
    #: stand up the node's standing-query tier (a
    #: :class:`~hypergraphdb_tpu.sub.SubscriptionManager` attached to
    #: the runtime, anchored at the replication log position so a
    #: subscription's resume seq is comparable across the tier)
    subscriptions: bool = True
    #: :class:`~hypergraphdb_tpu.sub.SubConfig` overrides (None =
    #: defaults)
    sub: Optional[object] = None


class ReplicaNode:
    """One replica: graph + following peer + lag-gated serve runtime.

    The ``peer`` is constructed by the caller (loopback for tests, TCP
    for deployments) and must NOT be started — :meth:`start` owns the
    whole lifecycle so the bootstrap ordering is right."""

    def __init__(self, graph, peer, config: ReplicaConfig):
        self.graph = graph
        self.peer = peer
        self.config = config
        self.runtime: Optional[ServeRuntime] = None
        #: the node's standing-query manager (None until started, or
        #: when ``config.subscriptions`` is off)
        self.subscriptions = None
        self.bootstrapped = False
        #: how the last bootstrap ran: "transfer" (full snapshot pull)
        #: or "resume" (incremental catch-up from the persisted clock)
        self.bootstrap_mode: Optional[str] = None
        self._ae_stop = threading.Event()
        self._ae_thread: Optional[threading.Thread] = None
        self._started = False
        #: at most one re-bootstrap RUN at a time (AE loop vs the read
        #: gate's lazy kick — whoever loses the race is a no-op)
        self._repair_gate = threading.Lock()
        #: guards only the spawn check-and-set (never held across the
        #: repair itself — the read gate must stay non-blocking)
        self._repair_spawn_lock = threading.Lock()
        self._repair_thread: Optional[threading.Thread] = None
        #: leaf lock for the node's shared state words (``bootstrapped``,
        #: ``bootstrap_mode``, ``runtime``, ``_started``, ``_ae_thread``)
        #: — written from the caller, AE, and repair threads; held only
        #: across the assignment, never across blocking work
        self._state_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaNode":
        if self._started:
            return self
        self._ae_stop.clear()  # a restarted node may kick repairs again
        self.peer.start()
        try:
            self._bootstrap()
            cfg = dataclasses.replace(self.config.serve,
                                      admission_gate=self._read_gate)
            rt = ServeRuntime(self.graph, cfg)
            with self._state_lock:
                self.runtime = rt
            if self.config.subscriptions:
                from hypergraphdb_tpu.sub import SubscriptionManager

                rep = self.peer.replication
                primary = self.config.primary
                # anchor standing queries at the REPLICATION log
                # position: the seq a notification carries is the same
                # coordinate on every node, which is what lets the
                # front door resume a subscription on another backend
                sub = SubscriptionManager(
                    self.graph, rt, self.config.sub,
                    seq_source=lambda: rep.last_seen.get(primary),
                )
                rt.attach_subscriptions(sub)
                with self._state_lock:
                    self.subscriptions = sub
        except BaseException:
            # a failed bootstrap must not leak a started peer (worker
            # threads, transport, a published interest the primary keeps
            # pushing to) — stop() is a no-op until _started flips
            try:
                if self.subscriptions is not None:
                    self.subscriptions.close()
                    with self._state_lock:
                        self.subscriptions = None
                if self.runtime is not None:
                    self.runtime.close(drain=False)
                    with self._state_lock:
                        self.runtime = None
            finally:
                self.peer.stop()
            raise
        t = None
        if self.config.anti_entropy_interval_s > 0:
            self._ae_stop.clear()
            t = threading.Thread(
                target=self._anti_entropy_loop,
                name=f"replica-ae-{self.peer.identity[:8]}", daemon=True,
            )
        with self._state_lock:
            self._ae_thread = t
            self._started = True
        if t is not None:
            t.start()
        return self

    def stop(self, drain: bool = True) -> None:
        with self._state_lock:
            if not self._started:
                return
            self._started = False
            ae, self._ae_thread = self._ae_thread, None
        self._ae_stop.set()
        if ae is not None:
            ae.join(timeout=5)
        with self._repair_spawn_lock:
            t = self._repair_thread
        if t is not None:
            # a kicked repair mid-flight: give it a bounded window; a
            # transfer that outlives it keeps running on the daemon
            # thread against the stopping peer and fails typed there
            t.join(timeout=5)
        if self.subscriptions is not None:
            # before the runtime: close wakes parked polls and stops new
            # evals, so the runtime's drain isn't fed by a dying tier
            self.subscriptions.close()
        if self.runtime is not None:
            self.runtime.close(drain=drain)
        self.peer.stop()

    def __enter__(self) -> "ReplicaNode":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- bootstrap -----------------------------------------------------------
    def _bootstrap(self) -> None:
        rep = self.peer.replication
        primary = self.config.primary
        # interest FIRST: pushes committed while the snapshot streams
        # arrive immediately and apply idempotently (gid write-through),
        # shrinking the catch-up tail to whatever raced the eof
        rep.publish_interest(None)
        resume = (rep.last_seen.get(primary) > 0
                  and primary not in rep.needs_full_sync)
        if resume:
            # rejoin: the persisted clock anchors incremental catch-up —
            # no multi-MB re-transfer for a bounced process
            with self._state_lock:
                self.bootstrap_mode = "resume"
            if not rep.catch_up(primary):
                # resume's ONLY wake-up signal: the request never left
                # (reliable-send budget spent). Swallowing it would park
                # the node gated at "head unknown" until an unrelated
                # push happens by — fail the bootstrap typed instead so
                # the caller's retry policy owns it
                from hypergraphdb_tpu.fault import TransientFault

                raise TransientFault(
                    f"resume catch-up could not reach {primary!r}")
        else:
            self.peer.transfer_graph_from(
                primary, page=self.config.bootstrap_page,
                timeout=self.config.bootstrap_timeout_s,
                retry_after_s=self.config.bootstrap_retry_after_s,
                max_resumes=self.config.bootstrap_max_resumes,
            )
            with self._state_lock:
                self.bootstrap_mode = "transfer"
            # the tail committed during the transfer: a lost send here is
            # non-fatal — the clock is anchored at the server's head, so
            # lag stays visible and pushes/anti-entropy heal the tail
            rep.catch_up(primary)
        with self._state_lock:
            self.bootstrapped = True

    # -- the staleness contract ----------------------------------------------
    @property
    def replication_lag(self) -> int:
        """Log entries the primary is known to be ahead of this replica."""
        return self.peer.replication.replication_lag(self.config.primary)

    def _read_gate(self) -> Optional[str]:
        """The serve runtime's admission gate: None admits; a reason
        string refuses typed. Bounded-lag reads are the contract — a
        refusal here is the router's cue to place the request on a
        fresher replica (or the primary), never a caller-visible error."""
        if self.config.primary in self.peer.replication.needs_full_sync:
            # the mark must be actionable even with the AE loop disabled
            # (anti_entropy_interval_s=0) — otherwise a truncated-past
            # replica wedges gated forever with nobody left to repair
            # it. Checked BEFORE ``bootstrapped`` so a FAILED repair
            # (mark survives, bootstrapped stays False) re-kicks on the
            # next gated read instead of wedging behind the
            # "bootstrapping" answer.
            self._kick_rebootstrap()
            return "replica diverged (primary log truncated); re-bootstrapping"
        if not self.bootstrapped:
            return "replica bootstrapping"
        if (self.bootstrap_mode == "resume" and self.config.primary
                not in self.peer.replication.peer_heads):
            # a resumed replica hasn't heard the primary's head THIS
            # incarnation (peer_heads is per-process; the resume
            # condition guarantees the primary's head is nonzero, so
            # push/catch-up/digest metadata will fill it) — until then
            # replication_lag reads 0 no matter how far behind we are,
            # and admitting would serve unboundedly stale data at an
            # advertised lag of 0. Transfer mode re-anchors at the
            # server's head on resolve, so it is exempt.
            return "replication head unknown since restart"
        lag = self.replication_lag
        if lag > self.config.max_replication_lag:
            return (f"replication lag {lag} exceeds bound "
                    f"{self.config.max_replication_lag}")
        return None

    def wait_converged(self, timeout: float = 30.0,
                       poll_s: float = 0.02) -> bool:
        """Block until the advertised lag reaches 0 and both replication
        pipelines are drained (tests / controlled failover)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # the full read gate (not just lag == 0): a resumed replica
            # reads lag 0 until the primary's head arrives — converged
            # means ADMITTING, at an actual lag of zero
            if (self._read_gate() is None and self.replication_lag == 0
                    and self.peer.replication.flush(timeout=max(
                        0.1, deadline - time.monotonic()))):
                if (self._read_gate() is None
                        and self.replication_lag == 0):
                    return True
            time.sleep(poll_s)
        return False

    # -- health ---------------------------------------------------------------
    def health_probe(self) -> HealthProbe:
        """The replica's ``/healthz`` surface: the standard runtime view
        (per-key breaker states, queue depth, delta staleness) PLUS the
        replica fields the front door's placement reads. Unhealthy while
        bootstrapping or past the lag bound — a load balancer sees 503
        exactly when the router would refuse to place reads here."""

        def replica_probe():
            lag = self.replication_lag
            gate = self._read_gate()
            payload = {
                "role": "replica",
                "primary": self.config.primary,
                "peer_id": self.peer.identity,
                "replication_lag": lag,
                "lag_bound": self.config.max_replication_lag,
                "bootstrapped": self.bootstrapped,
                "bootstrap_mode": self.bootstrap_mode,
            }
            if gate is not None:
                payload["read_gate"] = gate
            sub = self.subscriptions
            if sub is not None:
                # the standing-query story rides the same body the
                # fleet SLO tier scrapes (the ``sub_staleness``
                # objective reads ``sub.violating``)
                payload["sub"] = sub.health_section()
            return gate is None, payload

        if self.runtime is None:
            return replica_probe
        return composite_health(runtime_health(self.runtime), replica_probe)

    # -- fleet observability ---------------------------------------------------
    def fleet_source(self, node_id: Optional[str] = None):
        """This replica as a
        :class:`~hypergraphdb_tpu.obs.fleet.LocalNodeSource` (same-
        process fleets / tests; a real deployment registers the node's
        TelemetryServer URL as an
        :class:`~hypergraphdb_tpu.obs.fleet.HTTPNodeSource` instead):
        serve + graph registries, the runtime's tracer, the process
        flight recorder, and the composite health probe the front door
        already reads."""
        from hypergraphdb_tpu.obs.fleet import LocalNodeSource
        from hypergraphdb_tpu.obs.flight import global_flight

        rt = self.runtime
        regs = [] if rt is None else [rt.stats.registry]
        gm = getattr(self.graph, "metrics", None)
        if gm is not None:
            regs.append(gm.registry)
        return LocalNodeSource(
            node_id or self.peer.identity, registries=regs,
            tracer=None if rt is None else rt.tracer,
            flight=global_flight(), health=self.health_probe(),
            role="replica",
        )

    # -- follow ---------------------------------------------------------------
    def _anti_entropy_loop(self) -> None:
        """The backstop convergence prod: a digest probe every interval.
        Cheap enough to leave on (ints on the wire); the response path
        triggers catch-up only when the digest disagrees. When a digest
        (or empty catch-up page) reveals the primary truncated past us
        (``needs_full_sync``), the loop runs the clean re-bootstrap IN
        PLACE — without it a long-partitioned replica would wedge
        permanently gated, since :meth:`start` is the only other reader
        of the mark."""
        while not self._ae_stop.wait(self.config.anti_entropy_interval_s):
            try:
                if self.config.primary in \
                        self.peer.replication.needs_full_sync:
                    self._rebootstrap()
                else:
                    self.peer.replication.anti_entropy(self.config.primary)
            except Exception:  # noqa: BLE001 - the loop must survive
                import logging

                logging.getLogger("hypergraphdb_tpu.replica").warning(
                    "anti-entropy probe failed", exc_info=True
                )

    def _kick_rebootstrap(self) -> None:
        """Start :meth:`_rebootstrap` on a background thread unless one
        is already running — the read gate's path for a replica whose AE
        loop is disabled (and a harmless no-op when the loop exists and
        gets there first)."""
        with self._repair_spawn_lock:
            if not self._started or self._ae_stop.is_set():
                return  # stopping/stopped: no new repairs
            if (self._repair_thread is not None
                    and self._repair_thread.is_alive()):
                return
            self._repair_thread = t = threading.Thread(
                target=self._rebootstrap,
                name=f"replica-repair-{self.peer.identity[:8]}",
                daemon=True,
            )
        t.start()

    def _rebootstrap(self) -> None:
        """Runtime re-bootstrap (AE thread or the read gate's kick):
        incremental repair cannot converge once the primary's log
        truncated past us, so gate reads (``bootstrapped`` drives
        :meth:`_read_gate`) and pull a fresh snapshot. A completed
        transfer clears ``needs_full_sync`` and re-anchors the
        replication clock at the server's head; on failure the mark
        survives and the next tick (or gated read) retries — reads stay
        gated the whole time (a diverged replica must not serve). At
        most one runs at a time; a concurrent entrant no-ops."""
        if not self._repair_gate.acquire(blocking=False):
            return  # a repair is already in flight
        try:
            rep = self.peer.replication
            with self._state_lock:
                self.bootstrapped = False
            try:
                self.peer.transfer_graph_from(
                    self.config.primary, page=self.config.bootstrap_page,
                    timeout=self.config.bootstrap_timeout_s,
                    retry_after_s=self.config.bootstrap_retry_after_s,
                    max_resumes=self.config.bootstrap_max_resumes,
                )
                rep.catch_up(self.config.primary)
                with self._state_lock:
                    self.bootstrap_mode = "transfer"
            finally:
                with self._state_lock:
                    self.bootstrapped = (
                        self.config.primary not in rep.needs_full_sync)
        finally:
            self._repair_gate.release()
