"""hgreplica — the fault-tolerant replicated serving tier.

ROADMAP item 3's composition: the ``peer/*`` plane (replication push,
catch-up, snapshot transfer) finally MEETS the ``serve/*`` runtime, so
one process death no longer takes down all serving. Three parts:

- **node** (:mod:`~hypergraphdb_tpu.replica.node`): a
  :class:`ReplicaNode` composes an ingest-following peer (snapshot
  transfer to bootstrap, then replication push + gap-aware catch-up)
  with its OWN :class:`~hypergraphdb_tpu.serve.ServeRuntime`. Reads are
  pinned at a bounded replication lag — the cross-process twin of the
  single-node ``max_lag_edges`` staleness contract: a replica past its
  lag bound refuses admission (typed
  :class:`~hypergraphdb_tpu.serve.AdmissionGated`) instead of serving
  answers staler than it promised, and its ``/healthz`` advertises the
  lag so the router can see it coming;
- **router** (:mod:`~hypergraphdb_tpu.replica.router`): the
  :class:`FrontDoor` — ONE submit surface over the primary + N
  replicas. Placement spreads read load across healthy replicas by
  advertised lag (round-robin within the least-lagged group), a
  per-replica :class:`~hypergraphdb_tpu.fault.CircuitBreaker` re-routes
  a dead or degraded replica's load within a bounded number of probes,
  and the primary remains the exact-answer fallback — degraded, never
  down: zero caller-visible errors for in-budget requests;
- **httpd** (:mod:`~hypergraphdb_tpu.replica.httpd`): the stdlib HTTP
  skin — ``POST /submit`` + ``GET /healthz`` — worn by both a replica
  node and the front door, so the tier runs over real sockets
  (``tools/replica.sh`` smokes primary + 2 replicas + front door with
  curl) while tests drive the same objects in-process.

Underneath sits the gap-aware convergence this tier requires
(``peer/replication.py``): receiver-side applied-seq contiguity in the
SeenMap (ack = max *contiguous* seq), targeted catch-up repair of
detected holes, and a periodic anti-entropy digest as the backstop — a
push dropped past the redelivery budget is detected and repaired, never
a silent divergence. See README "Replicated serving tier".
"""

from hypergraphdb_tpu.replica.httpd import (
    SubmitServer,
    frontdoor_server,
    node_server,
)
from hypergraphdb_tpu.replica.node import ReplicaConfig, ReplicaNode
from hypergraphdb_tpu.replica.router import (
    FrontDoor,
    HTTPBackend,
    LocalBackend,
    RouterConfig,
    submit_payload,
)

__all__ = [
    "FrontDoor",
    "HTTPBackend",
    "LocalBackend",
    "ReplicaConfig",
    "ReplicaNode",
    "RouterConfig",
    "SubmitServer",
    "frontdoor_server",
    "node_server",
    "submit_payload",
]
